/* libec_jax.so — the native 'jax' erasure-code plugin shim.
 *
 * BASELINE.json's north star: register a 'jax' plugin under the
 * reference's ErasureCodePlugin registry so the C++ OSD's EC hot path
 * executes on the TPU.  The registry loads plugins by
 * dlopen("libec_<name>.so") and resolves __erasure_code_version /
 * __erasure_code_init (reference ErasureCodePlugin.cc:34-35,132-170);
 * this shim exports exactly those symbols, so the LOADING seam is
 * byte-compatible.  (The full ErasureCodeInterface vtable needs
 * ceph::bufferlist — unbuildable out of tree since the EC submodules
 * are empty in this checkout — so the codec surface is exported as a
 * plain-C chunk API, ec_jax_encode/ec_jax_decode, carrying the same
 * (k, m, chunk buffers) contract as encode_chunks/decode_chunks.)
 *
 * Data path: every call is framed over a unix socket to the TPU
 * sidecar (tpu_sidecar.py), which coalesces concurrent stripes into
 * fixed-size device batches — the pybind-sidecar architecture the
 * north star names.
 *
 * Build: g++ -O2 -fPIC -shared -o libec_jax.so libec_jax.cc
 */

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>

#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

namespace {

int g_fd = -1;

int sidecar_connect(const char *path) {
    int fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return -errno;
    sockaddr_un sa;
    memset(&sa, 0, sizeof(sa));
    sa.sun_family = AF_UNIX;
    strncpy(sa.sun_path, path, sizeof(sa.sun_path) - 1);
    if (connect(fd, (sockaddr *)&sa, sizeof(sa)) != 0) {
        int e = errno;
        close(fd);
        return -e;
    }
    return fd;
}

int write_all(int fd, const void *buf, size_t n) {
    const char *p = (const char *)buf;
    while (n) {
        ssize_t w = write(fd, p, n);
        if (w <= 0) return -EIO;
        p += w;
        n -= (size_t)w;
    }
    return 0;
}

int read_all(int fd, void *buf, size_t n) {
    char *p = (char *)buf;
    while (n) {
        ssize_t r = read(fd, p, n);
        if (r <= 0) return -EIO;
        p += r;
        n -= (size_t)r;
    }
    return 0;
}

/* one framed request/reply round-trip */
int sidecar_call(uint8_t op, const char *profile_json,
                 int k, int m, const uint8_t *erasures, int n_erasures,
                 uint32_t chunk, const uint8_t *chunks_in, int n_in,
                 uint8_t *chunks_out, int n_out) {
    if (g_fd < 0) return -ENOTCONN;
    uint16_t plen = (uint16_t)strlen(profile_json);
    uint32_t body = 1 + 2 + plen + 3 + (uint32_t)n_erasures + 4 +
                    (uint32_t)n_in * chunk;
    std::string req;
    req.reserve(4 + body);
    uint32_t len = body;
    req.append((char *)&len, 4);
    req.push_back((char)op);
    req.append((char *)&plen, 2);
    req.append(profile_json, plen);
    req.push_back((char)k);
    req.push_back((char)m);
    req.push_back((char)n_erasures);
    req.append((const char *)erasures, n_erasures);
    req.append((char *)&chunk, 4);
    req.append((const char *)chunks_in, (size_t)n_in * chunk);
    if (write_all(g_fd, req.data(), req.size()) != 0) return -EIO;

    uint32_t rlen;
    if (read_all(g_fd, &rlen, 4) != 0) return -EIO;
    std::string reply(rlen, 0);
    if (read_all(g_fd, &reply[0], rlen) != 0) return -EIO;
    if (reply.empty() || reply[0] != 0) return -EREMOTEIO;
    if (rlen - 1 != (uint32_t)n_out * chunk) return -EPROTO;
    memcpy(chunks_out, reply.data() + 1, rlen - 1);
    return 0;
}

}  // namespace

extern "C" {

/* The exact symbols the reference registry resolves
 * (ErasureCodePlugin.cc PLUGIN_VERSION_FUNCTION / PLUGIN_INIT_FUNCTION).
 * Version string: the registry compares against its build's
 * CEPH_GIT_NICE_VER; the driver passes the expected value through. */
const char *__erasure_code_version() { return "12.1.2"; }

int __erasure_code_init(const char *plugin_name, const char *directory) {
    (void)directory;
    if (strcmp(plugin_name, "jax") != 0) return -ENOENT;
    const char *sock = getenv("EC_JAX_SIDECAR");
    if (!sock) sock = "/tmp/ec_jax.sock";
    int fd = sidecar_connect(sock);
    if (fd < 0) return fd;
    g_fd = fd;
    /* ping: the init must fail loudly if the sidecar is not serving */
    uint8_t op = 3;
    uint32_t len = 1;
    if (write_all(g_fd, &len, 4) || write_all(g_fd, &op, 1)) return -EIO;
    uint32_t rlen;
    char buf[16];
    if (read_all(g_fd, &rlen, 4) || rlen > sizeof(buf) ||
        read_all(g_fd, buf, rlen))
        return -EIO;
    return 0;
}

/* chunk-API twins of encode_chunks/decode_chunks
 * (ErasureCodeInterface.h:170-462): data/coding laid out as contiguous
 * chunk-size buffers. */
int ec_jax_encode(const char *profile_json, int k, int m,
                  uint32_t chunk_size, const uint8_t *data /* k*chunk */,
                  uint8_t *parity /* m*chunk */) {
    return sidecar_call(1, profile_json, k, m, nullptr, 0, chunk_size,
                        data, k, parity, m);
}

int ec_jax_decode(const char *profile_json, int k, int m,
                  const uint8_t *erasures, int n_erasures,
                  uint32_t chunk_size,
                  const uint8_t *chunks /* (k+m)*chunk, erased zeroed */,
                  uint8_t *out /* n_erasures*chunk */) {
    return sidecar_call(2, profile_json, k, m, erasures, n_erasures,
                        chunk_size, chunks, k + m, out, n_erasures);
}

}  // extern "C"
