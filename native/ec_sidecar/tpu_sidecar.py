#!/usr/bin/env python
"""TPU sidecar: the process C++ erasure-code plugins delegate to.

BASELINE.json's north star: "the C++ OSD reaches the TPU via a …
sidecar that coalesces stripe requests into fixed-size device batches".
This is that sidecar: a unix-socket server speaking a tiny length-
prefixed binary protocol; libec_jax.cc (the native plugin shim built
against the reference's dlopen ABI) connects here, and every
encode/decode lands on the ceph_tpu batch engines.

Coalescing: requests arriving within a small window are merged into ONE
device dispatch per (profile, op, chunk-size) group — the fixed-size
device batching the north star describes — then the results fan back
out per request.

Protocol (little-endian):
  request:  u32 len | u8 op (1=encode 2=decode 3=ping) | u16 profile_len
            | profile json | u8 k | u8 m | u8 n_erasures | u8[] erasures
            | u32 chunk_size | chunk payloads (k for encode, k+m with
            erased zeroed for decode)
  reply:    u32 len | u8 status | payload (m parity chunks for encode,
            len(erasures) chunks for decode)
"""

from __future__ import annotations

import asyncio
import json
import struct
import sys
from collections import defaultdict
from typing import Dict, List, Tuple

import numpy as np


class Sidecar:
    def __init__(self, coalesce_window: float = 0.002):
        self._codecs: Dict[str, object] = {}
        self.window = coalesce_window
        self._queues: Dict[Tuple, List] = defaultdict(list)
        self._flushers: Dict[Tuple, asyncio.Task] = {}
        self.batches = 0
        self.requests = 0

    def codec(self, profile_json: str):
        c = self._codecs.get(profile_json)
        if c is None:
            from ceph_tpu.ec import factory

            c = factory(json.loads(profile_json))
            self._codecs[profile_json] = c
        return c

    async def handle(self, reader, writer):
        try:
            while True:
                hdr = await reader.readexactly(4)
                (n,) = struct.unpack("<I", hdr)
                payload = await reader.readexactly(n)
                resp = await self.dispatch(payload)
                writer.write(struct.pack("<I", len(resp)) + resp)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def dispatch(self, payload: bytes) -> bytes:
        op = payload[0]
        if op == 3:
            return b"\x00pong"
        (plen,) = struct.unpack_from("<H", payload, 1)
        off = 3
        profile = payload[off:off + plen].decode()
        off += plen
        k, m, ne = payload[off], payload[off + 1], payload[off + 2]
        off += 3
        erasures = tuple(payload[off:off + ne])
        off += ne
        (chunk,) = struct.unpack_from("<I", payload, off)
        off += 4
        nchunks = k if op == 1 else k + m
        data = np.frombuffer(
            payload, dtype=np.uint8, count=nchunks * chunk, offset=off
        ).reshape(nchunks, chunk)
        self.requests += 1
        out = await self._submit(profile, op, erasures, data)
        return b"\x00" + out.tobytes()

    async def _submit(self, profile, op, erasures, data) -> np.ndarray:
        """Queue into the coalescing window; one device dispatch serves
        every request that arrived in it."""
        key = (profile, op, erasures, data.shape[1])
        fut = asyncio.get_event_loop().create_future()
        self._queues[key].append((data, fut))
        if key not in self._flushers or self._flushers[key].done():
            self._flushers[key] = asyncio.get_event_loop().create_task(
                self._flush(key))
        return await fut

    async def _flush(self, key) -> None:
        await asyncio.sleep(self.window)
        batch = self._queues.pop(key, [])
        if not batch:
            return
        profile, op, erasures, _ = key
        codec = self.codec(profile)
        stack = np.stack([d for d, _ in batch])      # (B, nchunks, S)
        self.batches += 1
        try:
            if op == 1:
                out = np.asarray(codec.encode_batch(stack))
            else:
                out = np.asarray(codec.decode_batch(erasures, stack))
            for i, (_, fut) in enumerate(batch):
                if not fut.done():
                    fut.set_result(out[i])
        except Exception as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


async def main(path: str) -> None:
    sidecar = Sidecar()
    server = await asyncio.start_unix_server(sidecar.handle, path=path)
    print(f"sidecar listening on {path}", flush=True)
    async with server:
        await server.serve_forever()


if __name__ == "__main__":
    asyncio.run(main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/ec_jax.sock"))
