/* Driver: loads libec_jax.so exactly the way the reference registry
 * does (dlopen libec_<name>.so, check __erasure_code_version, call
 * __erasure_code_init — ErasureCodePlugin.cc:132-170), then runs the
 * north-star workload through the plugin: ISA-compatible RS k=8,m=4
 * encode over 4KiB stripes + single-erasure decode, round-trip
 * verified, throughput timed.  Exit 0 = the native seam works end to
 * end (C++ plugin -> unix socket -> TPU sidecar -> batched device
 * codec -> back).
 *
 * Build: g++ -O2 -o ec_jax_driver driver.cc -ldl
 * Run:   EC_JAX_SIDECAR=/tmp/ec_jax.sock ./ec_jax_driver ./libec_jax.so
 */

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <dlfcn.h>
#include <string>
#include <vector>

static double now_s() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + ts.tv_nsec * 1e-9;
}

int main(int argc, char **argv) {
    const char *so = argc > 1 ? argv[1] : "./libec_jax.so";
    void *lib = dlopen(so, RTLD_NOW);
    if (!lib) {
        fprintf(stderr, "dlopen: %s\n", dlerror());
        return 1;
    }
    auto version = (const char *(*)())dlsym(lib, "__erasure_code_version");
    auto init = (int (*)(const char *, const char *))dlsym(
        lib, "__erasure_code_init");
    if (!version || !init) {
        fprintf(stderr, "missing plugin symbols\n");
        return 1;
    }
    if (std::string(version()) != "12.1.2") {
        fprintf(stderr, "version mismatch: %s\n", version());
        return 1;
    }
    int r = init("jax", "/unused");
    if (r != 0) {
        fprintf(stderr, "__erasure_code_init: %d\n", r);
        return 1;
    }
    auto encode = (int (*)(const char *, int, int, uint32_t,
                           const uint8_t *, uint8_t *))
        dlsym(lib, "ec_jax_encode");
    auto decode = (int (*)(const char *, int, int, const uint8_t *, int,
                           uint32_t, const uint8_t *, uint8_t *))
        dlsym(lib, "ec_jax_decode");
    if (!encode || !decode) {
        fprintf(stderr, "missing codec symbols\n");
        return 1;
    }

    const char *profile = "{\"plugin\": \"isa\", \"k\": \"8\", \"m\": \"4\"}";
    const int k = 8, m = 4;
    const uint32_t chunk = 512;  /* 4KiB stripe / k */
    std::vector<uint8_t> data(k * chunk), parity(m * chunk);
    for (size_t i = 0; i < data.size(); i++)
        data[i] = (uint8_t)(i * 2654435761u >> 13);

    r = encode(profile, k, m, chunk, data.data(), parity.data());
    if (r != 0) {
        fprintf(stderr, "encode: %d\n", r);
        return 1;
    }

    /* erase data chunk 2, decode it back, byte-compare */
    std::vector<uint8_t> full((k + m) * chunk), out(chunk);
    memcpy(full.data(), data.data(), data.size());
    memcpy(full.data() + data.size(), parity.data(), parity.size());
    memset(full.data() + 2 * chunk, 0, chunk);
    uint8_t erasures[1] = {2};
    r = decode(profile, k, m, erasures, 1, chunk, full.data(), out.data());
    if (r != 0) {
        fprintf(stderr, "decode: %d\n", r);
        return 1;
    }
    if (memcmp(out.data(), data.data() + 2 * chunk, chunk) != 0) {
        fprintf(stderr, "round-trip MISMATCH\n");
        return 1;
    }

    /* throughput: the sidecar coalesces; serial from one client still
     * measures the full plugin->socket->device->back path */
    int iters = 200;
    double t0 = now_s();
    for (int i = 0; i < iters; i++)
        encode(profile, k, m, chunk, data.data(), parity.data());
    double dt = now_s() - t0;
    double gbps = (double)iters * k * chunk / dt / 1e9;
    printf("{\"native_seam\": \"ok\", \"encode_stripes_per_s\": %.0f, "
           "\"gbps\": %.4f}\n", iters / dt, gbps);
    return 0;
}
