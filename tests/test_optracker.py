"""OpTracker slow-op semantics, blocked-op accounting, and cross-layer
trace absorption (reference src/common/TrackedOp.cc +
osd_op_complaint_time health feed)."""

import time

from ceph_tpu.cluster.optracker import (
    CURRENT_OP,
    OpTracker,
    mark_current,
)


def test_slow_threshold_zero_disables():
    t = OpTracker(slow_threshold=0.0)
    for i in range(5):
        t.create(f"op{i}").finish()
    assert t.dump_historic_slow_ops()["num_ops"] == 0
    assert t.slow_in_flight() == (0, 0.0)


def test_slow_ring_admits_only_slow_ops():
    t = OpTracker(slow_threshold=0.02, slow_size=2)
    fast = t.create("fast")
    fast.finish()
    slows = []
    for i in range(3):
        op = t.create(f"slow{i}")
        op.start -= 0.05  # age it past the threshold
        op.finish()
        slows.append(op)
    dump = t.dump_historic_slow_ops()
    # ring keeps only slow_size ops, slowest first, fast op excluded
    assert dump["num_ops"] == 2
    assert all("slow" in o["description"] for o in dump["ops"])
    assert dump["ops"][0]["duration"] >= dump["ops"][1]["duration"]
    # history still has everything
    assert t.dump_historic_ops()["num_ops"] == 4


def test_slow_in_flight_counts_blocked_ops():
    t = OpTracker(slow_threshold=0.02)
    op = t.create("stuck")
    assert t.slow_in_flight() == (0, 0.0)
    op.start -= 0.1   # now blocked past the complaint time
    n, oldest = t.slow_in_flight()
    assert n == 1 and oldest >= 0.1
    op.finish()
    assert t.slow_in_flight() == (0, 0.0)
    # the completed stuck op landed in the slow ring
    assert t.dump_historic_slow_ops()["num_ops"] == 1


def test_trace_absorption_and_event_ordering():
    t = OpTracker()
    now = time.time()
    trace = {"id": "client.x#ab:op7",
             "events": [("objecter:submit", now - 0.02),
                        ("msgr:client.1:send", now - 0.01)]}
    op = t.create("osd_op(...)", trace=trace)
    op.mark("dispatched")
    op.mark("commit")
    op.finish()
    d = t.dump_historic_ops()["ops"][0]
    assert d["trace_id"] == "client.x#ab:op7"
    names = [e["event"] for e in d["type_data"]["events"]]
    # client-side events sort before OSD arrival/marks: the full
    # objecter -> messenger -> osd timeline in one dump
    assert names.index("objecter:submit") < \
        names.index("msgr:client.1:send") < names.index("initiated")
    assert names.index("initiated") < names.index("dispatched") < \
        names.index("commit") < names.index("done")
    times = [e["time"] for e in d["type_data"]["events"]]
    assert times == sorted(times)


def test_resize_applies_runtime_knobs():
    t = OpTracker(history_size=10, slow_size=10, slow_threshold=0.001)
    for i in range(8):
        op = t.create(f"op{i}")
        op.start -= 0.01
        op.finish()
    assert t.dump_historic_ops()["num_ops"] == 8
    t.resize(history_size=3, slow_size=2)
    hist = t.dump_historic_ops()
    assert hist["num_ops"] == 3   # newest kept
    assert hist["ops"][-1]["description"] == "op7"
    assert t.dump_historic_slow_ops()["num_ops"] == 2
    # growing works too
    t.resize(history_size=5)
    t.create("op8").finish()
    assert t.dump_historic_ops()["num_ops"] == 4


def test_mark_current_contextvar():
    t = OpTracker()
    mark_current("ignored")  # no current op: must be a no-op
    op = t.create("op")
    token = CURRENT_OP.set(op)
    try:
        mark_current("ec_encode")
        mark_current("commit")
    finally:
        CURRENT_OP.reset(token)
    mark_current("also_ignored")
    op.finish()
    names = [e["event"] for e in op.dump()["type_data"]["events"]]
    assert "ec_encode" in names and "commit" in names
    assert "ignored" not in names and "also_ignored" not in names
