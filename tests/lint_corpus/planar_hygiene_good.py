"""planar-conversion-hygiene GOOD corpus: seam-declared transitions
and reshape-only blob views (linted as if under ceph_tpu/cluster/)."""

from ceph_tpu.ec import planar_store


class GoodStore:
    def declared_relayout(self, blob):
        # a mixed-generation transition declaring which seam books it
        return planar_store.shard_to_planes(blob, seam="relayout")

    def declared_store_side(self, raw):
        # seam=None: the caller explicitly defers the booking to the
        # store op that lands the planes (still a declared decision)
        return planar_store.shard_to_planes(raw, seam=None)

    def reshape_only(self, blob, planes):
        # blob_to_planes / planes_to_blob are views of the SAME bytes,
        # not conversions — never flagged
        m = planar_store.blob_to_planes(blob)
        return planar_store.planes_to_blob(planes), m

    def pragma_suppressed_unseamed(self, planes):
        return planar_store.planes_to_shard(  # graftlint: ignore[planar-conversion-hygiene]
            planes, seam="unseamed")
