"""per-op-device-dispatch BAD corpus: device entry points reachable
per-op inside cluster/ async handlers (linted as if under
ceph_tpu/cluster/)."""

from ceph_tpu.ec import stripe as stripemod


class BadBackend:
    async def direct_planar_call(self, codec, batch):
        # direct device dispatch inside an async handler: every op pays
        # its own host/device round trip
        pb = codec.to_planar(batch)
        return codec.encode_planar(pb)

    async def executor_hop(self, codec, sinfo, data):
        # the dominant idiom: the device callable handed to an executor
        # wrapper — the hop does not change who pays the dispatch
        return await self._compute(
            stripemod.encode_stripes, codec, sinfo, data)

    async def per_op_crc(self, rows):
        from ceph_tpu.ops.crc32c import crc32c_batch

        return crc32c_batch(rows)
