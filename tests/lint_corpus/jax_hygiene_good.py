"""Clean device code: static args host-computed, lax control flow,
host tables built with np at module scope (host-side is fine)."""

import functools

import numpy as np

import jax
import jax.numpy as jnp

HOST_TABLE = np.arange(256, dtype=np.uint8)  # np at module scope: host


@functools.partial(jax.jit, static_argnums=(1,))
def scale(x, k):
    # k is static: a host int; np on it is host work at trace time
    table = np.asarray([k] * 4, dtype=np.uint8)
    if k > 2:  # static branch: resolved at trace time
        return x * jnp.asarray(table)[0]
    return x


@jax.jit
def clamp(x):
    # shape/dtype inspection is static under trace; lax.cond for the
    # tracer-valued decision
    if x.ndim != 1:
        raise ValueError("1-D only")
    return jax.lax.cond(jnp.all(x > 0), lambda v: v, lambda v: -v, x)


def loop(step, data):
    @jax.jit
    def run(d0):
        def body(d, _):
            return step(d), ()

        d, _ = jax.lax.scan(body, d0, None, length=8)
        return d

    return run(data)
