"""Historical-race fixture: PR 9's superseded-PGState ack-wait.

The bug this repo actually paid for (found then by a lucky chaos
seed): ``_advance_last_complete`` snapshotted a PGState, awaited the
shard-ack fan-out, and persisted the commit watermark through the
snapshot — but a crash-restart + re-peer during the ack wait had
REPLACED the registry entry, so the watermark landed on a PGState the
PG had already left, wedging last_complete behind last_update forever.

``buggy_pr9_shape`` is the pre-fix code shape — the await-atomicity
rule must convict it.  ``fixed_pr9_shape`` carries the shipped fix
(the ``pgs.get(pgid) is not st`` identity re-check) — the rule must
stay quiet on it.  Linted with relpath
ceph_tpu/cluster/awaitrace_hist_pgstate.py.
"""


class OSD:
    def __init__(self):
        self.pgs = {}

    async def buggy_pr9_shape(self, pgid, version, txn):
        st = self.pgs[pgid]
        await self._wait_shard_acks(st, version)
        # stale `st`: the ack wait yielded, a restart re-registered the
        # PG, and this persists the watermark onto the superseded state
        st.last_complete = version
        await self._persist_watermark(txn, version)

    async def fixed_pr9_shape(self, pgid, version, txn):
        st = self.pgs[pgid]
        await self._wait_shard_acks(st, version)
        pgs = self.pgs
        if pgs is not None and pgs.get(pgid) is not st:
            # superseded while we awaited: the NEW incarnation owns the
            # watermark now (the PR-9 fix)
            return None
        st.last_complete = version
        await self._persist_watermark(txn, version)

    async def _wait_shard_acks(self, st, version):
        return version

    async def _persist_watermark(self, txn, version):
        return version
