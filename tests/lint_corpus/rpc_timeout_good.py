"""rpc-timeout GOOD corpus: every RPC future wait is bounded."""

import asyncio


class Daemon:
    def __init__(self):
        self._pending = {}
        self.timeout = 5.0

    def _make_waiter(self, key, needed):
        fut = asyncio.get_event_loop().create_future()
        fut.needed = needed
        self._pending[key] = (fut, [])
        return fut

    async def wait_bounded(self, key):
        fut = self._make_waiter(key, 1)
        try:
            # bounded: wait_for carries the deadline
            return await asyncio.wait_for(fut, timeout=self.timeout)
        finally:
            self._pending.pop(key, None)

    async def poll_done(self, key):
        fut = asyncio.get_event_loop().create_future()
        if fut.done():
            return fut.result()  # poll, never a bare await
        return await asyncio.wait_for(fut, timeout=self.timeout)

    async def not_a_future(self, q):
        # awaiting other awaitables stays out of scope for the rule
        item = await q.get()
        return item
