"""task-spawn good corpus: every spawn has a bounded lifetime."""

import asyncio


class Daemon:
    def __init__(self):
        self._bg_tasks = set()
        self._timer = None
        self._retries = {}

    def _track(self, task):
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    async def handle_op(self):
        # handed to the self-discarding tracker — the callee owns it
        self._track(asyncio.get_event_loop().create_task(self._bg()))
        # replace-on-rearm attribute slot: at most one live task
        self._timer = asyncio.get_event_loop().create_task(self._bg())
        # keyed slot, same bounded shape
        self._retries["pg1"] = asyncio.get_event_loop().create_task(
            self._bg())
        # bound, then explicitly given a discard path
        t = asyncio.get_event_loop().create_task(self._bg())
        t.add_done_callback(lambda _t: None)
        # awaited: bounded by this coroutine
        await asyncio.get_event_loop().create_task(self._bg())

    async def _bg(self):
        await asyncio.sleep(0)
