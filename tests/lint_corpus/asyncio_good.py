"""Clean async daemon code: async sleep, named DepLock, sync IO kept
in sync helpers."""

import asyncio

from ceph_tpu.utils.lockdep import DepLock


class Daemon:
    def __init__(self):
        self.big_lock = DepLock("corpus.daemon")

    def _load(self, path):
        # sync helper: blocking IO before the loop starts is fine
        with open(path, "rb") as f:
            return f.read()

    async def tick(self):
        async with self.big_lock:
            await asyncio.sleep(0.1)
            return 1
