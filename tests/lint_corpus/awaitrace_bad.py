"""await-atomicity bad corpus: one conviction per rule variant.

Linted with relpath ceph_tpu/cluster/awaitrace_bad.py — the rule is
cluster/-scoped.  Every shape here is an await-interleaving race:
shared cluster state snapshotted, an await, then action on the stale
snapshot.
"""

from ceph_tpu.utils.lockdep import DepLock


class PG:
    def __init__(self):
        self.lock = DepLock("pg.lock")
        self.pgs = {}
        self.acting = []
        self.pipeline_pending = {}

    # variant (a): stale-snapshot-across-await — `st` is the PGState
    # this PG *was*; after the ack-wait await it may have been
    # superseded (the PR-9 bug shape), yet the watermark advance goes
    # through the stale snapshot with no revalidation
    async def stale_snapshot(self, pgid, version):
        st = self.pgs[pgid]
        await self._wait_acks(version)
        st.last_complete = version

    # variant (b): check-then-act-across-await — the absent check
    # passes, the await yields, ANOTHER task registers the entry, and
    # the insert clobbers it: the checked predicate no longer held
    # when the act ran
    async def check_then_act(self, pgid, entry):
        if entry not in self.pipeline_pending:
            await self._fan_out(entry)
            self.pipeline_pending[entry] = pgid
        return None

    # variant (c): lock-window-escape — `head` is consistent only
    # while pg.lock is held; flowing it past the lock release and
    # acting on it re-creates the race the lock existed to prevent
    async def lock_window_escape(self, pgid):
        async with self.lock:
            head = self.pipeline_pending[pgid]
        await self._sync(pgid)
        return head.version

    async def _wait_acks(self, version):
        return version

    async def _fan_out(self, entry):
        return entry

    async def _sync(self, pgid):
        return pgid
