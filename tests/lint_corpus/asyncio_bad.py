"""Event-loop stalls + a lock invisible to lockdep.  The module path
trick: the engine lints this file AS IF it lived under cluster/ via an
explicit path in the test (the Lock rule is cluster-scoped)."""

import asyncio
import subprocess
import time


class Daemon:
    def __init__(self):
        self.big_lock = asyncio.Lock()  # invisible to lockdep

    async def tick(self):
        time.sleep(0.1)  # stalls every op in flight
        with open("/tmp/x", "rb") as f:  # sync IO on the loop
            data = f.read()
        subprocess.run(["true"])  # blocks until the child exits
        return data
