"""Bad corpus for the swallowed-async-error rule: every shape fires."""

import asyncio


class Daemon:
    async def bad_bare_except(self, conn):
        try:
            await conn.send(b"x")
        except:  # noqa: E722  (also eats CancelledError)
            pass

    async def bad_broad_except(self, peers):
        for p in peers:
            try:
                await p.send_sub_write()
            except Exception:
                pass  # a lost sub-op failure = a leaked un-acked shard

    async def bad_gather_discarded(self, subs):
        await asyncio.gather(*subs, return_exceptions=True)

    async def bad_gather_unused_binding(self, subs):
        results = await asyncio.gather(*subs, return_exceptions=True)
        return None
