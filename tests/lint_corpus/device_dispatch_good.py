"""per-op-device-dispatch GOOD corpus: cluster/ async handlers that keep
device work behind the coalescer seam (linted as if under
ceph_tpu/cluster/)."""

import asyncio


class GoodBackend:
    async def _ec_write(self, codec, sinfo, data):
        # the sanctioned shape: the op submits its stripe range to the
        # tick coalescer; the batcher owns the device dispatch
        shards, crcs, tick = await self._ec_batcher.encode(
            codec, sinfo, data, True)
        return shards

    async def _plain_host_work(self, payload):
        # ordinary host calls (store, messenger) are not device entry
        # points and never match
        await asyncio.sleep(0)
        return payload[:10]

    def _sync_helper(self, codec, batch):
        # sync (non-handler) code is out of scope for this rule: the
        # per-op contract is about async dispatch paths
        return codec.encode_batch(batch)
