"""Interprocedural inversion: neither function nests two ``async
with`` directly — the cycle only exists through the awaited call."""

from ceph_tpu.utils.lockdep import DepLock


class Daemon:
    def __init__(self):
        self.map_lock = DepLock("corpus.CT_A")
        self.io_lock = DepLock("corpus.CT_B")

    async def _write(self):
        async with self.io_lock:
            return 1

    async def _remap(self):
        async with self.map_lock:
            return 2

    async def update(self):
        async with self.map_lock:
            return await self._write()     # A -> B

    async def flush(self):
        async with self.io_lock:
            return await self._remap()     # B -> A: cycle
