"""Symmetric struct codecs: every encoded field decoded, version
guards monotonic and bounded, wire dataclass fields all defaulted."""

import pickle
import struct
from dataclasses import dataclass


class Message:  # stand-in base
    pass


@dataclass
class MGood(Message):
    epoch: int = 0
    blob: bytes = b""


class HitSet:
    struct_v = 2

    def __init__(self):
        self.bits = b""
        self.count = 0

    def encode(self) -> bytes:
        return pickle.dumps((self.bits, self.count))

    @classmethod
    def decode(cls, blob, v=2):
        h = cls()
        h.bits, h.count = pickle.loads(blob)
        if v >= 1:
            pass
        if v >= 2:  # monotonic, <= struct_v
            pass
        return h


def _encode_frame(msg) -> bytes:
    if isinstance(msg, MGood):
        return struct.pack("<I", msg.epoch) + msg.blob
    raise TypeError(msg)


def _decode_frame(body: bytes):
    (epoch,) = struct.unpack_from("<I", body)
    return MGood(epoch=epoch, blob=body[4:])
