"""Every tracer/host-sync violation family: host materialization of a
traced value, wall-clock at trace time, host sync in a scan body,
Python branching on a tracer, and module-scope device compute."""

import time

import numpy as np

import jax
import jax.numpy as jnp

DEVICE_TABLE = jnp.arange(256)  # traces + compiles at import


@jax.jit
def bad_asarray(x):
    return np.asarray(x).sum()  # host materialization of a tracer


@jax.jit
def bad_float(x):
    return float(x) * 2.0  # scalar coercion forces a host sync


@jax.jit
def bad_clock(x):
    t0 = time.perf_counter()  # runs at TRACE time, not per step
    return x + t0


@jax.jit
def bad_branch(x):
    if x > 0:  # Python branch on a tracer
        return x
    return -x


def bad_scan_body(data):
    def body(d, _):
        d.block_until_ready()  # host sync inside the device loop
        return d, ()

    d, _ = jax.lax.scan(body, data, None, length=8)
    return d
