"""Inverted lock ordering across two functions: the classic latent
deadlock runtime lockdep only catches when BOTH paths happen to run."""

from ceph_tpu.utils.lockdep import DepLock


class Daemon:
    def __init__(self):
        self.map_lock = DepLock("corpus.A")
        self.io_lock = DepLock("corpus.B")

    async def update(self):
        async with self.map_lock:      # A -> B
            async with self.io_lock:
                return 1

    async def flush(self):
        async with self.io_lock:       # B -> A: cycle
            async with self.map_lock:
                return 2
