"""Historical-race fixture: PR 11's stale self-info roll-forward floor.

The peering round snapshotted its OWN log head at round start, then
pushed deltas to members — awaits that race pipelined commits
advancing the head.  The roll-forward floor then rested on the
round-start snapshot, pinning last_complete below entries every member
verifiably held: the round ended complete with the watermark wedged,
and nothing ever re-armed it.

``buggy_pr11_shape`` is the pre-fix shape — the await-atomicity rule
must convict it.  ``fixed_pr11_shape`` re-reads the current self state
after the awaits (the shipped fix) — the rule must stay quiet.
Linted with relpath ceph_tpu/cluster/awaitrace_hist_selfinfo.py.
"""


class Recovery:
    async def buggy_pr11_shape(self, st, members):
        # round-start snapshot of our own log head
        my_head = st.last_update
        for osd in members:
            # racing pipelined commits advance st.last_update under us
            await self._push_delta(osd, st)
        # roll-forward floor rests on the ROUND-START head: a stale
        # self info pins last_complete below entries every member holds
        floor = my_head
        if floor > st.last_complete:
            st.last_complete = floor

    async def fixed_pr11_shape(self, st, members):
        my_head = st.last_update
        for osd in members:
            await self._push_delta(osd, st)
        # the PR-11 fix: the floor rests on the CURRENT self state
        my_head = st.last_update
        floor = my_head
        if floor > st.last_complete:
            st.last_complete = floor

    async def _push_delta(self, osd, st):
        return osd
