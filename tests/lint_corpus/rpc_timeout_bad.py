"""rpc-timeout BAD corpus: bare awaits on RPC futures hang forever."""

import asyncio


class Daemon:
    def __init__(self):
        self._pending = {}

    def _make_waiter(self, key, needed):
        fut = asyncio.get_event_loop().create_future()
        fut.needed = needed
        self._pending[key] = (fut, [])
        return fut

    async def wait_unbounded_waiter(self, key):
        fut = self._make_waiter(key, 1)
        # BAD: if the peer dies, this hangs for the daemon's lifetime
        return await fut

    async def wait_unbounded_reply(self, tid):
        fut = asyncio.get_event_loop().create_future()
        self._pending[tid] = fut
        # BAD: reply waiter with no timeout and no deadline
        reply = await fut
        return reply

    async def wait_unbounded_annotated(self, tid):
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[tid] = fut
        # BAD: annotated binding is still a bare future await
        return await fut

    async def wait_unbounded_chained(self, tid):
        fut = self._round = asyncio.get_event_loop().create_future()
        # BAD: chained binding is still a bare future await
        return await fut
