"""fixed-sleep-in-tests bad corpus: every sleep here is a bare timing
guess.  Linted with relpath tests/fixed_sleep_bad.py — the rule is
tests/-scoped.
"""

import asyncio
import time


async def waits_a_guessed_duration():
    # 1: classic flake: hope 0.1 s outlasts the replica apply
    await asyncio.sleep(0.1)


async def waits_a_whole_second():
    # 2: bigger guess, same smell
    await asyncio.sleep(1)


def blocks_the_suite():
    # 3: synchronous flavour
    time.sleep(0.5)
