"""Asymmetric codecs: an encoded field the decoder forgets, a
non-monotonic version guard, a guard past struct_v, a message class
the decoder cannot rebuild, and a default-less wire field."""

import pickle
import struct
from dataclasses import dataclass


class Message:  # stand-in base
    pass


@dataclass
class MBad(Message):
    epoch: int = 0
    blob: bytes  # no default: an older peer omitting it breaks decode


@dataclass
class MOrphan(Message):
    tid: int = 0


class HitSet:
    struct_v = 2

    def __init__(self):
        self.bits = b""
        self.count = 0
        self.stamp = 0.0

    def encode(self) -> bytes:
        # writes bits, count AND stamp...
        return pickle.dumps((self.bits, self.count, self.stamp))

    @classmethod
    def decode(cls, blob, v=2):
        h = cls()
        # ...but only restores two of them
        h.bits, h.count = pickle.loads(blob)[:2]
        if v >= 3:   # exceeds struct_v=2
            pass
        if v >= 1:   # after v>=3: not monotonic
            pass
        return h


def _encode_frame(msg) -> bytes:
    if isinstance(msg, MBad):
        return struct.pack("<I", msg.epoch) + msg.blob
    if isinstance(msg, MOrphan):
        return struct.pack("<I", msg.tid)
    raise TypeError(msg)


def _decode_frame(body: bytes):
    # MBad loses its blob; MOrphan is never reconstructed at all
    (epoch,) = struct.unpack_from("<I", body)
    return MBad(epoch=epoch)
