"""Good corpus for the swallowed-async-error rule: zero findings."""

import asyncio


class Daemon:
    async def good_narrow_except(self, conn):
        try:
            await conn.send(b"x")
        except (ConnectionError, OSError):
            pass  # typed protocol decision, not a blanket swallow

    async def good_observed_broad(self, peers):
        for p in peers:
            try:
                await p.send_sub_write()
            except Exception:
                self.perf.inc("send_errors")

    async def good_gather_consumed(self, subs):
        results = await asyncio.gather(*subs, return_exceptions=True)
        return sum(1 for r in results if isinstance(r, BaseException))

    async def good_gather_raising(self, subs):
        # no return_exceptions: failures propagate, nothing swallowed
        await asyncio.gather(*subs)

    def good_sync_function(self):
        try:
            self.close()
        except Exception:
            pass  # sync scope: outside this rule's async-handler remit
