"""await-atomicity good corpus: the same three shapes, revalidated —
the rule must stay quiet on every one.

Linted with relpath ceph_tpu/cluster/awaitrace_good.py.
"""

from ceph_tpu.utils.lockdep import DepLock


class PG:
    def __init__(self):
        self.lock = DepLock("pg.lock")
        self.pgs = {}
        self.acting = []
        self.pipeline_pending = {}

    # (a) revalidated by the identity re-check (the PR-9 fix shape):
    # the test mentions both the snapshot name and its watched source
    async def snapshot_revalidated(self, pgid, version):
        st = self.pgs[pgid]
        await self._wait_acks(version)
        if self.pgs.get(pgid) is not st:
            return None
        st.last_complete = version

    # (a) revalidated by re-binding after the await
    async def snapshot_rebound(self, pgid, version):
        st = self.pgs[pgid]
        await self._wait_acks(version)
        st = self.pgs[pgid]
        st.last_complete = version

    # (a) no await between snapshot and use: plain sequential code
    async def snapshot_no_await(self, pgid, version):
        st = self.pgs[pgid]
        st.last_complete = version
        await self._wait_acks(version)

    # (a) the awaits sit in guard clauses that return — executions
    # that suspended never reach the use, so nothing goes stale
    async def snapshot_guard_clause(self, pgid, version):
        st = self.pgs[pgid]
        if st is None:
            await self._wait_acks(version)
            return None
        return st.last_update

    # (a) the "use" is an argument of the await expression itself:
    # it evaluates BEFORE the suspension
    async def snapshot_in_await_args(self, pgid, version):
        st = self.pgs[pgid]
        return await self._wait_acks(st.last_update)

    # (b) the conditional re-checks the watched state after the await,
    # before mutating through it
    async def check_act_rechecked(self, pgid, entry):
        if entry not in self.pipeline_pending:
            await self._fan_out(entry)
            if entry not in self.pipeline_pending:
                self.pipeline_pending[entry] = pgid
        return None

    # (c) the captured value is re-bound after the lock window closes
    async def lock_window_rebound(self, pgid):
        async with self.lock:
            head = self.pipeline_pending[pgid]
        await self._sync(pgid)
        head = self.pipeline_pending[pgid]
        return head.version

    # (c) the whole use stays inside the lock window
    async def lock_window_contained(self, pgid):
        async with self.lock:
            head = self.pipeline_pending[pgid]
            await self._sync(pgid)
            return head.version

    async def _wait_acks(self, version):
        return version

    async def _fan_out(self, entry):
        return entry

    async def _sync(self, pgid):
        return pgid
