"""Consistent lock ordering: A before B everywhere — no cycle."""

from ceph_tpu.utils.lockdep import DepLock


class Daemon:
    def __init__(self):
        self.map_lock = DepLock("corpus.A")
        self.io_lock = DepLock("corpus.B")

    async def update(self):
        async with self.map_lock:
            async with self.io_lock:
                return 1

    async def flush(self):
        async with self.map_lock:
            async with self.io_lock:
                return 2
