"""planar-conversion-hygiene BAD corpus: at-rest layout conversions
outside the sanctioned seams (linted as if under ceph_tpu/cluster/)."""

from ceph_tpu.ec import planar_store
from ceph_tpu.ops import gf8


class BadStore:
    def raw_transform_in_cluster(self, batch):
        # raw layout transform: belongs in the ec/ kernel seam modules
        return gf8.to_planar(batch)

    def raw_row_transform(self, rows):
        return planar_store.rows_to_planes(rows)

    def undeclared_seam(self, blob):
        # no seam= declaration: the silent convert-per-hop this rule
        # exists to catch
        return planar_store.shard_to_planes(blob)

    def undeclared_egress(self, planes):
        return planar_store.planes_to_shard(planes)

    def unseamed_byte_view(self, planes):
        # declared unseamed: books the PINNED counter — needs a pragma
        # and a story, like the store read() fallbacks
        return planar_store.planes_to_shard(planes, seam="unseamed")

    async def undeclared_in_async(self, blob):
        return planar_store.shard_to_planes(blob)
