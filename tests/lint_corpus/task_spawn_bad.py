"""task-spawn bad corpus: every per-op spawn here leaks.

Linted with relpath ceph_tpu/cluster/task_spawn_bad.py — the rule is
cluster/-scoped.
"""

import asyncio


class Daemon:
    def __init__(self):
        self._tasks = []
        self._running = set()

    async def handle_op(self):
        # 1: handle discarded outright — nothing can ever cancel or
        # observe this task, and a failure disappears silently
        asyncio.get_event_loop().create_task(self._bg())
        # 2: grow-only list — one dead Task per op for the daemon's life
        self._tasks.append(asyncio.get_event_loop().create_task(self._bg()))
        # 3: grow-only set (same leak, different container)
        self._running.add(asyncio.get_event_loop().create_task(self._bg()))
        # 4: bound to a name the function never touches again
        orphan = asyncio.get_event_loop().create_task(self._bg())  # noqa: F841
        # 5: ensure_future, same discard
        asyncio.ensure_future(self._bg())

    async def _bg(self):
        await asyncio.sleep(0)
