"""fixed-sleep-in-tests good corpus: the sanctioned shapes the rule
must stay quiet on.  Linted with relpath tests/fixed_sleep_good.py.
"""

import asyncio
import time


async def converge_poll(cond):
    # constant sleep INSIDE a while loop: the poll interval of a
    # wall-deadline converge-poll — the repo's sanctioned wait
    loop = asyncio.get_event_loop()
    deadline = loop.time() + 5.0
    while loop.time() < deadline and not cond():
        await asyncio.sleep(0.02)
    assert cond()


async def bounded_retry(cond):
    # for-loop polling: same shape, counted instead of wall-bounded
    for _ in range(100):
        if cond():
            break
        await asyncio.sleep(0.05)


async def pure_yield():
    # sleep(0) is a cooperative yield, not a wait
    await asyncio.sleep(0)


async def variable_duration(dt):
    # non-literal durations are the caller's contract, not a guess
    await asyncio.sleep(dt)


def paced_on_purpose():
    # genuinely time-semantic pacing carries the pragma + the reason:
    # two wall-clock stamps must differ for the assertion downstream
    time.sleep(0.01)  # graftlint: ignore[fixed-sleep-in-tests]
