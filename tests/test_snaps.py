"""Object snapshots & clones, end to end (round-4 item 1).

Reference seams: SnapContext (src/common/snap_types.h:41), SnapSet
(src/osd/osd_types.h:4431), clone-on-write in
PrimaryLogPG::make_writeable (src/osd/PrimaryLogPG.cc:7019), snap-read
resolution in find_object_context, snap trimming
(PrimaryLogPG::SnapTrimmer), and the librados snap API
(rados_ioctx_snap_create / selfmanaged twins / snap_set_read).
"""

import asyncio

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster.snaps import (
    SnapContext,
    SnapSet,
    clone_oid,
    is_snap_key,
)
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


EC_PROFILE = {"plugin": "jerasure", "technique": "reed_sol_van",
              "k": "2", "m": "1"}


# ---------------------------------------------------------------- unit tier

def test_snapset_clone_decision_and_resolution():
    ss = SnapSet()
    # snap 1 exists, object written under seq=1 -> clone of pre-write head
    snapc = SnapContext(seq=1, snaps=(1,))
    assert ss.needs_clone(snapc, head_exists=True)
    cid = ss.add_clone(snapc, head_size=10)
    assert cid == 1 and ss.seq == 1
    # snap 1 reads the clone; snap 2 (taken later, no writes) the head
    assert ss.resolve_read(1, head_exists=True) == ("clone", 1)
    assert ss.resolve_read(2, head_exists=True) == ("head", None)
    assert ss.resolve_read(None, head_exists=True) == ("head", None)
    # head deleted: snap 1 still resolves, HEAD/2 do not
    assert ss.resolve_read(1, head_exists=False) == ("clone", 1)
    assert ss.resolve_read(2, head_exists=False) == ("enoent", None)
    assert ss.resolve_read(None, head_exists=False) == ("enoent", None)


def test_snapset_trim():
    ss = SnapSet()
    ss.add_clone(SnapContext(seq=1, snaps=(1,)), 10)
    ss.add_clone(SnapContext(seq=3, snaps=(3, 2, 1)), 20)
    v = ss.version
    assert v >= 2                        # every mutation stamps a version
    dead, dirty = ss.trim({2})
    assert dirty and dead == []          # clone 3 still serves snap 3
    assert ss.version > v                # trims must bump it too (the
    v = ss.version                       # backfill gate keys off it)
    dead, dirty = ss.trim({1})
    assert dead == [1]                   # clone 1 served only snap 1
    dead, dirty = ss.trim({3})
    assert dead == [3]
    assert ss.clones == []
    assert ss.version > v


def test_snap_key_naming():
    assert is_snap_key(clone_oid("obj", 5))
    assert not is_snap_key("obj")
    assert not is_snap_key("obj@5")      # client oids with @ are fine


# ------------------------------------------------------------- cluster tier

def test_pool_snap_write_snap_overwrite_read_back_replicated():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rsnap", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            v1 = b"version-one" * 50
            v2 = b"VERSION-TWO!" * 77
            await io.write_full("obj", v1)
            sid = await io.snap_create("s1")
            await io.write_full("obj", v2)
            assert await io.read("obj") == v2
            assert await io.read("obj", snapid=sid) == v1
            # a second snap with no intervening write sees the head data
            sid2 = await io.snap_create("s2")
            assert await io.read("obj", snapid=sid2) == v2
            # snap_list + lookup
            assert io.snap_lookup("s1") == sid
            assert set(io.snap_list().values()) == {"s1", "s2"}
            # clones never leak into listings
            assert await io.list_objects() == ["obj"]
        finally:
            await cluster.stop()

    run(scenario())


def test_selfmanaged_snap_ec_pool_byte_exact():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("ecsnap", "erasure",
                                            pg_num=8,
                                            ec_profile=dict(EC_PROFILE))
            io = client.ioctx(pool)
            v1 = bytes(range(256)) * 40          # 10240 bytes
            v2 = bytes(reversed(range(256))) * 60
            await io.write_full("eobj", v1)
            sid = await io.selfmanaged_snap_create()
            io.set_snap_context(sid, [sid])
            await io.write_full("eobj", v2)
            assert await io.read("eobj") == v2
            assert await io.read("eobj", snapid=sid) == v1
            # partial overwrite (RMW path) after a second snap
            sid2 = await io.selfmanaged_snap_create()
            io.set_snap_context(sid2, [sid2, sid])
            await io.write("eobj", b"X" * 1000, offset=500)
            at2 = await io.read("eobj", snapid=sid2)
            assert at2 == v2
            head = await io.read("eobj")
            assert head[500:1500] == b"X" * 1000
            assert head[:500] == v2[:500]
            assert await io.read("eobj", snapid=sid) == v1
        finally:
            await cluster.stop()

    run(scenario())


def test_delete_after_snap_keeps_snap_readable():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("dsnap", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            payload = b"preserve-me" * 30
            await io.write_full("victim", payload)
            sid = await io.snap_create("keep")
            await io.remove("victim")
            with pytest.raises(FileNotFoundError):
                await io.read("victim")
            assert await io.read("victim", snapid=sid) == payload
            with pytest.raises(FileNotFoundError):
                await io.stat("victim")
            assert await io.stat("victim", snapid=sid) == len(payload)
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_snap_trim_removes_clone_objects():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("tsnap", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"old")
            sid = await io.snap_create("s1")
            await io.write_full("obj", b"new")
            assert await io.read("obj", snapid=sid) == b"old"
            pgid = client.objecter.object_pgid(pool, "obj")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            cname = clone_oid("obj", sid)
            _, _, acting, _ = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            assert all(cluster.osds[o].store.stat(coll, cname) is not None
                       for o in acting), "clone object missing pre-trim"
            await io.snap_remove("s1")
            # trimmer runs off the map-update path on every member
            for _ in range(100):
                if all(cluster.osds[o].store.stat(coll, cname) is None
                       for o in acting):
                    break
                await asyncio.sleep(0.1)
            assert all(cluster.osds[o].store.stat(coll, cname) is None
                       for o in acting), "trim left clone objects behind"
            with pytest.raises(FileNotFoundError):
                await io.read("obj", snapid=sid)
            assert await io.read("obj") == b"new"
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_ec_snap_survives_shard_loss():
    """Snap reads ride the same decode path as head reads: kill one OSD
    and the clone must still reconstruct."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("ecs2", "erasure",
                                            pg_num=4,
                                            ec_profile=dict(EC_PROFILE))
            io = client.ioctx(pool)
            v1 = b"snapdata" * 512
            await io.write_full("hot", v1)
            sid = await io.selfmanaged_snap_create()
            io.set_snap_context(sid, [sid])
            await io.write_full("hot", b"headdata" * 700)
            pgid = client.objecter.object_pgid(pool, "hot")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            victim = next(o for o in acting if o != primary)
            await cluster.osds[victim].stop()
            got = await io.read("hot", snapid=sid, timeout=60)
            assert got == v1
        finally:
            await cluster.stop()

    run(scenario())
