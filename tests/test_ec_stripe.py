"""Stripe tessellation tests: stripe_info_t math + batched object codecs."""

import numpy as np
import pytest

from ceph_tpu.ec import factory
from ceph_tpu.ec.stripe import (
    StripeInfo,
    decode_stripes,
    encode_stripes,
    merge_range,
)


def test_stripe_info_math():
    # k=4, unit=16: stripe_width=64 (mirrors reference ECUtil.h:31-84)
    s = StripeInfo(4, 16)
    assert s.stripe_width == 64
    assert s.chunk_size == 16
    assert s.logical_offset_is_stripe_aligned(128)
    assert not s.logical_offset_is_stripe_aligned(100)
    assert s.logical_to_prev_chunk_offset(100) == 16
    assert s.logical_to_next_chunk_offset(100) == 32
    assert s.logical_to_prev_stripe_offset(100) == 64
    assert s.logical_to_next_stripe_offset(100) == 128
    assert s.logical_to_next_stripe_offset(128) == 128
    assert s.aligned_logical_offset_to_chunk_offset(128) == 32
    assert s.aligned_chunk_offset_to_logical_offset(32) == 128
    assert s.offset_len_to_stripe_bounds(100, 20) == (64, 64)
    assert s.offset_len_to_stripe_bounds(60, 10) == (0, 128)
    assert s.object_stripes(0) == 0
    assert s.object_stripes(1) == 1
    assert s.object_stripes(64) == 1
    assert s.object_stripes(65) == 2
    assert s.shard_size(65) == 32


@pytest.fixture(scope="module")
def codec():
    return factory({"plugin": "isa", "k": "4", "m": "2"})


def test_encode_decode_roundtrip(codec):
    sinfo = StripeInfo(4, 32)
    data = bytes(range(256)) * 3  # 768 bytes = 6 stripes of 128
    shards = encode_stripes(codec, sinfo, data)
    assert shards.shape == (6, 6 * 32)
    avail = {s: shards[s] for s in range(6)}
    assert decode_stripes(codec, sinfo, avail, len(data)) == data


def test_decode_with_erasures(codec):
    sinfo = StripeInfo(4, 32)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, 1000, dtype=np.uint8).tobytes()  # padded
    shards = encode_stripes(codec, sinfo, data)
    # lose two shards (= m): decode from the remaining four
    avail = {s: shards[s] for s in (0, 2, 4, 5)}
    assert decode_stripes(codec, sinfo, avail, len(data)) == data
    # losing three is unrecoverable
    with pytest.raises(ValueError):
        decode_stripes(codec, sinfo, {s: shards[s] for s in (0, 2, 4)},
                       len(data))


def test_stripes_match_per_stripe_encode(codec):
    """The batched stripe path must equal encoding each stripe separately."""
    sinfo = StripeInfo(4, 32)
    rng = np.random.default_rng(6)
    data = rng.integers(0, 256, 4 * 32 * 3, dtype=np.uint8).tobytes()
    shards = encode_stripes(codec, sinfo, data)
    for stripe in range(3):
        block = np.frombuffer(
            data[stripe * 128: (stripe + 1) * 128],
            dtype=np.uint8).reshape(1, 4, 32)
        parity = np.asarray(codec.encode_batch(block))[0]
        for j in range(2):
            got = shards[4 + j, stripe * 32: (stripe + 1) * 32]
            assert np.array_equal(got, parity[j]), (stripe, j)


def test_merge_range():
    assert merge_range(b"abcdef", 6, 2, b"XY") == b"abXYef"
    assert merge_range(b"ab", 2, 4, b"Z") == b"ab\0\0Z"
    assert merge_range(b"", 0, 0, b"Q") == b"Q"
    # zero-extension of a short old buffer against a larger old_size
    assert merge_range(b"ab", 5, 1, b"Z") == b"aZ\0\0\0"


def test_zero_stripes_have_zero_parity(codec):
    """Linearity: zero data stripes encode to zero parity, so shard
    truncate-extension commutes with encode (the RMW gap-stripe invariant)."""
    sinfo = StripeInfo(4, 32)
    shards = encode_stripes(codec, sinfo, b"\0" * 256)
    assert not shards.any()
