"""Admin/observability surfaces: OpTracker, admin commands, mgr perf
streams, injectargs.

Reference: src/common/TrackedOp.cc (dump_historic_ops), AdminSocket
commands, MgrClient::send_report (src/mgr/MgrClient.cc:232), injectargs.
"""

import asyncio

import pytest

from ceph_tpu.cluster.optracker import OpTracker
from ceph_tpu.cluster.vstart import _fast_config, start_cluster


def run(coro):
    return asyncio.run(coro)


def test_optracker_unit():
    t = OpTracker(history_size=3)
    ops = []
    for i in range(5):
        op = t.create(f"op{i}")
        op.mark("queued")
        op.finish()
        ops.append(op)
    live = t.create("inflight")
    inflight = t.dump_ops_in_flight()
    assert inflight["num_ops"] == 1
    assert inflight["ops"][0]["description"] == "inflight"
    hist = t.dump_historic_ops()
    assert hist["num_ops"] == 3  # ring buffer keeps the newest 3
    assert [o["description"] for o in hist["ops"]] == ["op2", "op3", "op4"]
    assert all(o["duration"] is not None for o in hist["ops"])
    events = hist["ops"][0]["type_data"]["events"]
    assert [e["event"] for e in events] == ["initiated", "queued", "done"]
    live.finish()
    # fast ops never reach the slow ring: the 30s complaint-time default
    # only admits genuinely slow completions (a threshold of 0 used to
    # put EVERY op here — fixed round 6)
    slow = t.dump_historic_slow_ops()
    assert slow["num_ops"] == 0


def test_admin_commands_and_historic_ops():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("ap", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            for i in range(5):
                await io.write_full(f"o{i}", b"x" * 100)
                await io.read(f"o{i}")

            pgid = client.objecter.object_pgid(pool, "o0")
            _, _, _, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            addr = client.objecter.osdmap.osd_addrs[primary]

            # historic op dump shows real ops with event timelines
            hist = await client.objecter.daemon_command(
                addr, {"prefix": "dump_historic_ops"})
            assert hist["num_ops"] >= 1
            assert any("osd_op" in o["description"] for o in hist["ops"])
            # perf dump over the same channel
            perf = await client.objecter.daemon_command(
                addr, {"prefix": "perf dump"})
            assert perf[f"osd.{primary}"]["osd_client_ops"] >= 1
            # config show
            cfg = await client.objecter.daemon_command(
                addr, {"prefix": "config show"})
            assert "osd_heartbeat_interval" in cfg
            # remote scrub trigger
            rep = await client.objecter.daemon_command(
                addr, {"prefix": "scrub"}, timeout=30)
            assert isinstance(rep, dict)
        finally:
            await cluster.stop()

    run(scenario())


def test_injectargs_via_mon():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            before = cluster.osds[1].config.osd_recovery_delay_start
            await client.objecter.mon_command({
                "prefix": "injectargs", "who": "osd.1",
                "args": {"osd_recovery_delay_start": 7.5}})
            deadline = asyncio.get_event_loop().time() + 5
            while asyncio.get_event_loop().time() < deadline:
                if cluster.osds[1].config.osd_recovery_delay_start == 7.5:
                    break
                await asyncio.sleep(0.05)
            assert cluster.osds[1].config.osd_recovery_delay_start == 7.5
            # other osds untouched
            assert cluster.osds[0].config.osd_recovery_delay_start == before
        finally:
            await cluster.stop()

    run(scenario())


from tests._flaky import contention_retry


@contention_retry()
def test_mgr_receives_perf_streams():
    async def scenario():
        cfg = _fast_config()
        cluster = await start_cluster(3, config=cfg, with_mgr=True)
        try:
            client = await cluster.client()
            pool = await client.pool_create("mp", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"mgr" * 100)
            # wait for reports to stream in (every heartbeat tick)
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                if len(cluster.mgr.daemons) >= 3:
                    break
                await asyncio.sleep(0.1)
            assert len(cluster.mgr.daemons) >= 3

            status = await client.objecter.daemon_command(
                cluster.mgr_addr, {"prefix": "mgr status"})
            assert set(status["daemons"]) >= {"osd.0", "osd.1", "osd.2"}
            # the counter rides the NEXT report after the write: poll
            # instead of trusting one heartbeat tick (load-deflake
            # round 11 — the invariant stays, the clock relaxes)
            total_ops = 0
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                total_ops = await client.objecter.daemon_command(
                    cluster.mgr_addr,
                    {"prefix": "counter sum",
                     "counter": "osd_client_ops"})
                if total_ops >= 1:
                    break
                await asyncio.sleep(0.1)
            assert total_ops >= 1
        finally:
            await cluster.stop()

    run(scenario())


def test_pool_delete_rename_set():
    """Pool lifecycle admin (reference OSDMonitor pool ops): rename,
    set size/min_size, guarded delete that really removes the data."""
    import asyncio

    import pytest

    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("adm", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            await io.write_full("obj", b"data")
            # rename
            await client.pool_rename("adm", "renamed")
            assert "renamed" in client.pool_list()
            assert "adm" not in client.pool_list()
            # set size
            await client.pool_set("renamed", "size", 2)
            assert client.objecter.osdmap.pools[pool].size == 2
            with pytest.raises(RuntimeError):
                await client.pool_set("renamed", "pg_num", 4)  # shrink
            # ADVICE r4: invalid size/min_size must be EINVAL, never
            # committed (they would wedge all writes on the pool)
            for var, val in (("size", 0), ("size", -1), ("min_size", 0),
                             ("min_size", 3), ("size", "garbage")):
                with pytest.raises(RuntimeError):
                    await client.pool_set("renamed", var, val)
            assert client.objecter.osdmap.pools[pool].size == 2
            assert 1 <= client.objecter.osdmap.pools[pool].min_size <= 2
            # delete requires the sure gate
            with pytest.raises(RuntimeError):
                await client.pool_delete("renamed")
            await client.pool_delete("renamed", sure=True)
            assert "renamed" not in client.pool_list()
            # the data is gone from every OSD store — converge-poll to
            # a wall deadline (the deletion rides the map push; a fixed
            # beat raced it under host load)
            def _purged():
                return all(
                    not [c for c in osd.store.list_collections()
                         if c.startswith(f"pg_{pool}_")]
                    and not [p for p in osd.pgs if p.pool == pool]
                    for osd in cluster.osds.values())

            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline and \
                    not _purged():
                await asyncio.sleep(0.05)
            for osd in cluster.osds.values():
                assert not [c for c in osd.store.list_collections()
                            if c.startswith(f"pg_{pool}_")], \
                    f"osd.{osd.osd_id} kept deleted pool data"
                assert not [p for p in osd.pgs if p.pool == pool]
        finally:
            await cluster.stop()

    asyncio.run(scenario())


def test_health_and_df_commands():
    """'ceph health' / 'ceph df' analogs: health checks from the map,
    usage aggregated from OSD beacon statfs."""
    import asyncio

    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            h = await client.objecter.mon_command({"prefix": "health"})
            assert h["status"] == "HEALTH_OK", h
            pool = await client.pool_create("hdf", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"x" * 100_000)
            # wait for a beacon cycle to carry statfs
            for _ in range(100):
                df = await client.objecter.mon_command({"prefix": "df"})
                if df["used_bytes"] > 0 and len(df["osds"]) == 3:
                    break
                await asyncio.sleep(0.1)
            assert df["total_bytes"] > 0
            assert df["used_bytes"] >= 100_000  # replicated x2 somewhere
            # kill an OSD -> health degrades
            victim = next(iter(cluster.osds))
            await cluster.osds.pop(victim).stop()
            for _ in range(100):
                h = await client.objecter.mon_command({"prefix": "health"})
                # poll for the down mark itself: survivors report
                # transient PG_RECOVERING before the grace expires
                if "OSD_DOWN" in h["checks"]:
                    break
                await asyncio.sleep(0.1)
            assert h["status"] in ("HEALTH_WARN", "HEALTH_ERR")
            assert "OSD_DOWN" in h["checks"]
        finally:
            await cluster.stop()

    asyncio.run(scenario())


def test_unified_telemetry_end_to_end():
    """Round-6 tentpole acceptance: 'ceph daemon osd.N perf dump'
    returns schema'd counters including a histogram; an EC write's
    dump_historic_ops entry carries cross-layer trace events
    (objecter -> messenger -> osd -> store); the mon serves admin
    commands over the same path; the mgr renders Prometheus text."""
    async def scenario():
        cluster = await start_cluster(3, with_mgr=True)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "tele", "erasure", pg_num=8,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            await io.write_full("traced", b"\xa5" * 20000)
            assert (await io.read("traced"))[:4] == b"\xa5" * 4

            pgid = client.objecter.object_pgid(pool, "traced")
            _, _, _, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)

            # perf dump via the 'ceph daemon' path: schema'd counters
            # including at least one histogram, plus the process-wide
            # device-kernel section
            perf = await cluster.daemon_command(
                f"osd.{primary}", "perf dump")
            sec = perf[f"osd.{primary}"]
            assert sec["osd_client_ops"] >= 1
            assert sec["osd_op_lat"]["avgcount"] >= 1
            assert sec["osd_op_lat_hist"]["count"] >= 1
            assert sum(sec["osd_op_lat_hist"]["buckets"]) == \
                sec["osd_op_lat_hist"]["count"]
            assert "device_kernels" in perf
            # round 6: EC pool batches ride the bit-planar layout, so the
            # encode shows up as planar matmul + conversion counters (the
            # byte-path ec_matmul counters remain for non-planar routes)
            dk = perf["device_kernels"]
            # round 11: CPU backends run the coalesced write path on the
            # vectorized host GF engine (ec_host_matmul_*); device
            # backends keep the planar/byte matmul counters
            assert dk.get("planar_matmul_calls", 0) >= 1 \
                or dk.get("ec_matmul_calls", 0) >= 1 \
                or dk.get("ec_host_matmul_calls", 0) >= 1
            assert dk.get("planar_convert_to_planar_bytes", 0) >= 1 \
                or dk.get("ec_matmul_bytes", 0) >= 1 \
                or dk.get("ec_host_matmul_bytes", 0) >= 1
            schema = await cluster.daemon_command(
                f"osd.{primary}", "perf schema")
            assert schema[f"osd.{primary}"]["osd_op_lat_hist"]["type"] \
                == "histogram"
            hist = await cluster.daemon_command(
                f"osd.{primary}", "perf histogram dump")
            assert "osd_op_lat_hist" in hist[f"osd.{primary}"]

            # cross-layer trace: the historic entry for the EC write
            # shows client-side + messenger + osd + store events
            ops = await cluster.daemon_command(
                f"osd.{primary}", "dump_historic_ops")
            traced = [o for o in ops["ops"]
                      if "traced" in o["description"] and
                      "write_full" in o["description"]]
            assert traced, ops
            ev = [e["event"]
                  for e in traced[0]["type_data"]["events"]]
            assert "objecter:submit" in ev
            assert any(e.startswith("msgr:") for e in ev)
            assert "dispatched" in ev
            # coalesced tick marks (default config) or the per-op pair
            assert "batch_encoded" in ev or "ec_encode" in ev
            assert "store:journal_queued" in ev
            assert "commit" in ev
            enc = "batch_encoded" if "batch_encoded" in ev \
                else "ec_encode"
            assert ev.index("dispatched") < ev.index(enc) < \
                ev.index("commit")
            assert traced[0].get("trace_id")

            # the mon serves the same admin-command path
            mon_perf = await cluster.daemon_command("mon", "perf dump")
            assert "mon" in mon_perf
            q = await cluster.daemon_command("mon", "quorum_status")
            assert q["is_leader"] is True

            # mgr Prometheus exporter: daemon-labeled counters in text
            # exposition format (admin command + HTTP scrape endpoint)
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                if len(cluster.mgr.daemons) >= 3:
                    break
                await asyncio.sleep(0.1)
            text = await cluster.daemon_command(
                "mgr", "prometheus metrics")
            assert f'ceph_osd_client_ops{{daemon="osd.{primary}"}}' \
                in text
            assert "ceph_osd_op_lat_hist_bucket" in text
            host, port = await cluster.mgr.serve_exporter()
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(b"GET /metrics HTTP/1.1\r\n\r\n")
            await writer.drain()
            raw = await reader.read()
            writer.close()
            assert raw.startswith(b"HTTP/1.1 200")
            assert b"ceph_osd_client_ops" in raw

            # perf reset zeroes values but keeps schemas
            await cluster.daemon_command(f"osd.{primary}", "perf reset")
            perf = await cluster.daemon_command(
                f"osd.{primary}", "perf dump")
            assert perf[f"osd.{primary}"]["osd_client_ops"] == 0
        finally:
            await cluster.stop()

    run(scenario())


def test_slow_ops_health_warning_raises_and_clears():
    """A blocked op past osd_op_complaint_time raises the SLOW_OPS
    health warning ('N slow ops, oldest age X') through the beacon
    stream and the cluster log, and clears once the op completes."""
    async def scenario():
        cfg = _fast_config()
        cfg.osd_op_complaint_time = 0.2
        cluster = await start_cluster(3, config=cfg)
        try:
            client = await cluster.client()
            h = await client.objecter.mon_command({"prefix": "health"})
            assert "SLOW_OPS" not in h["checks"]
            # a deliberately-stuck op on osd.0 (the tracker is the
            # daemon's real blocked-op feed; ops created here age
            # exactly like a wedged client op)
            stuck = cluster.osds[0].tracker.create(
                "osd_op(client.test:1 wedged [write_full])")
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                h = await client.objecter.mon_command(
                    {"prefix": "health"})
                if "SLOW_OPS" in h["checks"]:
                    break
                await asyncio.sleep(0.05)
            assert "SLOW_OPS" in h["checks"], h
            assert h["status"] == "HEALTH_WARN"
            assert "slow ops, oldest age" in h["checks"]["SLOW_OPS"]
            # the complaint reached the Paxos-replicated cluster log
            deadline = asyncio.get_event_loop().time() + 10
            logged = []
            while asyncio.get_event_loop().time() < deadline:
                logged = await client.objecter.mon_command(
                    {"prefix": "log last", "num": 50})
                if any("slow ops" in e["msg"] for e in logged):
                    break
                await asyncio.sleep(0.05)
            assert any("slow ops" in e["msg"] and e["prio"] == "WRN"
                       for e in logged), logged
            # drain: the op completes, the warning clears with the next
            # beacon round
            stuck.finish()
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                h = await client.objecter.mon_command(
                    {"prefix": "health"})
                if "SLOW_OPS" not in h["checks"]:
                    break
                await asyncio.sleep(0.05)
            assert "SLOW_OPS" not in h["checks"], h
            # and the blocked interval is in the slow-op ring
            slow = await cluster.daemon_command(
                "osd.0", "dump_historic_slow_ops")
            assert any("wedged" in o["description"]
                       for o in slow["ops"])
        finally:
            await cluster.stop()

    run(scenario())
