"""PG splitting: pg_num growth under load (VERDICT r4 missing #2).

Reference seams: PG::split_colls / split_into (src/osd/PG.h:416-422,1436)
and OSDMonitor's pg_num/pgp_num handling — pg_num growth splits objects
and logs into child PGs colocated with their parents (pgp_num unchanged
keeps the placement seed folded), then a separate pgp_num increase
migrates children through the normal remap+recovery path.
"""

import asyncio

import pytest

from tests._flaky import contention_retry

from ceph_tpu.cluster.vstart import start_cluster
from ceph_tpu.osdmap.osdmap import PGid


def run(coro):
    return asyncio.run(coro)


@contention_retry(attempts=4)
def test_pg_split_doubles_under_load_and_scrubs_clean():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("split", "replicated",
                                            pg_num=4, size=3)
            io = client.ioctx(pool)
            objs = {f"obj-{i}": (b"payload-%d " % i) * 50
                    for i in range(24)}
            for k, v in objs.items():
                await io.write_full(k, v)
            # snapshot + overwrite so clones must follow their heads
            await io.snap_create("before")
            await io.write_full("obj-0", b"after-snap")

            async def writer():
                for i in range(10):
                    await io.write_full(f"live-{i}", b"during-split")
                    await asyncio.sleep(0.01)

            wtask = asyncio.get_event_loop().create_task(writer())
            await client.pool_set("split", "pg_num", 8)
            await wtask
            p = client.objecter.osdmap.pools[pool]
            assert p.pg_num == 8 and p.pgp_num == 4
            # wait until every OSD has advanced to the split map (fixed
            # sleeps flake on the 1-core driver)
            for _ in range(300):
                if all(o.osdmap.pools[pool].pg_num == 8
                       for o in cluster.osds.values() if not o._stopped):
                    break
                await asyncio.sleep(0.1)

            # every object still reads back
            for k, v in objs.items():
                want = b"after-snap" if k == "obj-0" else v
                assert await io.read(k, timeout=60) == want, k
            for i in range(10):
                assert await io.read(f"live-{i}", timeout=60) \
                    == b"during-split"
            # snap read resolves through the split
            snapid = client.objecter.osdmap.pools[pool].snaps
            sid = next(s for s, n in snapid.items() if n == "before")
            assert await io.read("obj-0", snapid=sid) == objs["obj-0"]

            # child PGs actually exist and hold objects
            seeds = {client.objecter.object_pgid(pool, k).seed
                     for k in objs}
            assert any(s >= 4 for s in seeds), "no object maps to a child"

            # scrub every PG clean on its primary
            for seed in range(8):
                pgid = PGid(pool, seed)
                _, _, acting, primary = \
                    client.objecter.osdmap.pg_to_up_acting_osds(pgid)
                st = cluster.osds[primary].pgs.get(pgid)
                if st is None:
                    continue
                report = await cluster.osds[primary].scrub_pg(st)
                assert report["inconsistent"] == [], (seed, report)

            # now move placements: pgp_num follows, children remap and
            # recover; data survives
            await client.pool_set("split", "pgp_num", 8)
            for _ in range(300):
                if all(o.osdmap.pools[pool].pgp_num == 8
                       for o in cluster.osds.values() if not o._stopped):
                    break
                await asyncio.sleep(0.1)
            for k, v in objs.items():
                want = b"after-snap" if k == "obj-0" else v
                assert await io.read(k, timeout=60) == want, k
            assert client.objecter.osdmap.pools[pool].pgp_num == 8
        finally:
            await cluster.stop()

    run(scenario())


def test_pg_num_validation():
    async def scenario():
        cluster = await start_cluster(2)
        try:
            client = await cluster.client()
            pool = await client.pool_create("v", "replicated",
                                            pg_num=4, size=2)
            with pytest.raises(RuntimeError):
                await client.pool_set("v", "pg_num", 4)     # no shrink/same
            with pytest.raises(RuntimeError):
                await client.pool_set("v", "pg_num", 2)
            with pytest.raises(RuntimeError):
                await client.pool_set("v", "pgp_num", 9)    # > pg_num
            ec = await client.pool_create(
                "ev", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            with pytest.raises(RuntimeError):
                await client.pool_set("ev", "pg_num", 8)    # EC refused
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_osd_down_across_split_splits_on_resume():
    """An OSD that missed the pg_num bump must split its parent
    collections when it rejoins (the split watermark persists on the
    PGMETA object, not in daemon memory)."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rsplit", "replicated",
                                            pg_num=4, size=3)
            io = client.ioctx(pool)
            for i in range(20):
                await io.write_full(f"r-{i}", b"resume-%d" % i)
            victim = next(iter(cluster.osds))
            await cluster.osds[victim].stop()
            await client.pool_set("rsplit", "pg_num", 8)
            # converge-poll: the SURVIVING daemons learn the split map
            # and split their collections before the victim resumes
            loop = asyncio.get_event_loop()
            deadline = loop.time() + 15.0
            while loop.time() < deadline:
                if all(o.osdmap.pools.get(pool) is not None and
                       o.osdmap.pools[pool].pg_num == 8
                       for o in cluster.osds.values()
                       if o.osd_id != victim):
                    break
                await asyncio.sleep(0.05)
            osd = await cluster.restart_osd(victim)
            # wait for the resumed OSD to advance to the split map
            for _ in range(300):
                if osd.osdmap.pools.get(pool) is not None and \
                        osd.osdmap.pools[pool].pg_num == 8:
                    break
                await asyncio.sleep(0.1)

            from ceph_tpu.cluster.pg import PGMETA, PGRB, _coll
            from ceph_tpu.ops.jenkins import str_hash_rjenkins
            from ceph_tpu.osdmap.osdmap import ceph_stable_mod

            def _no_stranded() -> bool:
                # collection splits run asynchronously after the map
                # advance — converge on the final no-child-objects-in-
                # parent condition, then assert it below
                p = osd.osdmap.pools[pool]
                for coll in osd.store.list_collections():
                    if not coll.startswith(f"pg_{pool}_"):
                        continue
                    seed = int(coll.split("_")[2])
                    for name in osd.store.list_objects(coll):
                        if name in (PGMETA, PGRB):
                            continue
                        want = ceph_stable_mod(
                            str_hash_rjenkins(name.encode()),
                            p.pg_num, p.pg_num_mask)
                        if want != seed:
                            return False
                return True

            deadline = loop.time() + 15.0
            while not _no_stranded() and loop.time() < deadline:
                await asyncio.sleep(0.05)
            for i in range(20):
                assert await io.read(f"r-{i}", timeout=60) \
                    == b"resume-%d" % i
            # the resumed OSD's parent collections hold no child objects
            p = osd.osdmap.pools[pool]
            for coll in osd.store.list_collections():
                if not coll.startswith(f"pg_{pool}_"):
                    continue
                seed = int(coll.split("_")[2])
                for name in osd.store.list_objects(coll):
                    if name in (PGMETA, PGRB):
                        continue
                    want = ceph_stable_mod(
                        str_hash_rjenkins(name.encode()),
                        p.pg_num, p.pg_num_mask)
                    assert want == seed, \
                        f"{name} stranded in {coll} (belongs to {want})"
        finally:
            await cluster.stop()

    run(scenario())
