"""BlueStore-analog: block layout, allocator, csum-on-read, durability
(round-4, VERDICT r3 missing #7).

Reference: src/os/bluestore/BlueStore.cc — block-device data placement
by an allocator, kv onode metadata, checksum verification on every read
(:9012,3703-3709), COW writes.
"""

import os
import pickle

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster.bluestore import BLOCK, BlueStore
from ceph_tpu.cluster.store import Transaction


def _store(tmp_path, **kw):
    s = BlueStore(str(tmp_path / "bs"), size=8 << 20, **kw)
    s.mount()
    return s


def test_write_read_roundtrip_and_partial(tmp_path):
    s = _store(tmp_path)
    payload = bytes(range(256)) * 40          # 10240: crosses blocks
    s.queue_transaction(Transaction().write("c", "o", 0, payload)
                        .set_version("c", "o", 7))
    assert s.read("c", "o") == payload
    assert s.stat("c", "o") == len(payload)
    assert s.get_version("c", "o") == 7
    # partial overwrite inside a block + across a block boundary
    s.queue_transaction(Transaction().write("c", "o", 4000, b"X" * 200))
    got = s.read("c", "o")
    assert got[4000:4200] == b"X" * 200
    assert got[:4000] == payload[:4000]
    assert got[4200:] == payload[4200:]
    # ranged read
    assert s.read("c", "o", 4100, 50) == b"X" * 50
    s.umount()


def test_csum_detects_silent_corruption(tmp_path):
    """Flipping bytes in the block FILE (silent media corruption) must
    surface as EIO on read — never as returned garbage."""
    s = _store(tmp_path)
    s.queue_transaction(Transaction().write("c", "o", 0, b"A" * BLOCK))
    blkno = s._onodes["c"]["o"].blocks[0]
    s.umount()
    # corrupt the raw device out-of-band
    path = os.path.join(str(tmp_path / "bs"), "block")
    with open(path, "r+b") as f:
        f.seek((16 + blkno) * BLOCK + 100)
        f.write(b"\xff\xfe\xfd")
    s2 = BlueStore(str(tmp_path / "bs"), size=8 << 20)
    s2.mount()
    with pytest.raises(IOError):
        s2.read("c", "o")
    s2.umount()


def test_allocator_reclaims_on_remove_and_overwrite(tmp_path):
    s = _store(tmp_path)
    free0 = s.alloc.n_free
    s.queue_transaction(Transaction().write("c", "o", 0, b"B" * (BLOCK * 4)))
    assert s.alloc.n_free == free0 - 4
    # COW overwrite: net usage unchanged (new blocks in, old freed)
    s.queue_transaction(Transaction().write("c", "o", 0, b"C" * (BLOCK * 4)))
    assert s.alloc.n_free == free0 - 4
    s.queue_transaction(Transaction().remove("c", "o"))
    assert s.alloc.n_free == free0
    # truncate releases the tail blocks
    s.queue_transaction(Transaction().write("c", "t", 0, b"D" * (BLOCK * 4)))
    s.queue_transaction(Transaction().truncate("c", "t", BLOCK))
    assert s.alloc.n_free == free0 - 1
    assert s.read("c", "t") == b"D" * BLOCK
    s.umount()


def test_device_full_is_enospc(tmp_path):
    s = BlueStore(str(tmp_path / "tiny"), size=64 * BLOCK)
    s.mount()
    with pytest.raises(OSError):
        s.queue_transaction(
            Transaction().write("c", "big", 0, b"x" * (100 * BLOCK)))
    s.umount()


def test_remount_durability_and_wal_replay(tmp_path):
    s = _store(tmp_path, checkpoint_every=10_000)  # nothing checkpoints
    s.queue_transaction(Transaction()
                        .write("c", "o", 0, b"persist-me" * 500)
                        .setattr("c", "o", "k", b"v")
                        .omap_set("c", "o", {"a": b"1"})
                        .set_version("c", "o", 9))
    s.queue_transaction(Transaction().clone("c", "o", "o2"))
    # hard stop WITHOUT checkpoint: remount must replay the kv WAL
    s._wal.flush()
    s._dev.flush()
    s._mounted = False
    s2 = BlueStore(str(tmp_path / "bs"), size=8 << 20)
    s2.mount()
    assert s2.read("c", "o") == b"persist-me" * 500
    assert s2.getattr("c", "o", "k") == b"v"
    assert s2.omap_get("c", "o") == {"a": b"1"}
    assert s2.get_version("c", "o") == 9
    assert s2.read("c", "o2") == b"persist-me" * 500
    # allocator rebuilt: no double-accounting after replay
    used = sum(1 for f in s2.alloc.free if not f)
    want = len([b for b in s2._onodes["c"]["o"].blocks if b >= 0]) + \
        len([b for b in s2._onodes["c"]["o2"].blocks if b >= 0])
    assert used == want
    s2.umount()


def test_wal_replay_never_clobbers_checkpointed_blocks(tmp_path):
    """Regression (round-4 review): the mount-time freelist must rebuild
    from the checkpointed onodes BEFORE WAL replay — otherwise replayed
    writes allocate from an all-free bitmap and overwrite committed
    objects' blocks."""
    s = _store(tmp_path, checkpoint_every=10_000)
    s.queue_transaction(Transaction().write("c", "A", 0, b"a" * BLOCK * 3))
    s.checkpoint()                       # A's blocks are checkpoint-owned
    s.queue_transaction(Transaction().write("c", "B", 0, b"b" * BLOCK * 2))
    s._wal.flush()
    s._dev.flush()
    s._mounted = False                   # crash: no umount checkpoint
    s2 = BlueStore(str(tmp_path / "bs"), size=8 << 20)
    s2.mount()                           # replays B's txn
    assert s2.read("c", "A") == b"a" * BLOCK * 3, \
        "WAL replay clobbered checkpointed data"
    assert s2.read("c", "B") == b"b" * BLOCK * 2
    s2.umount()


@contention_retry()
def test_full_cluster_on_bluestore(tmp_path):
    """vstart --bluestore analog: the whole cluster on BlueStore,
    including a full-cluster restart resume (the FileStore restart test's
    flagship-store twin)."""
    import asyncio

    from ceph_tpu.cluster.osd import OSDDaemon
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    async def scenario():
        cfg = _fast_config()
        cluster = await start_cluster(
            3, config=cfg,
            store_factory=lambda o: BlueStore(
                str(tmp_path / f"osd{o}"), size=64 << 20))
        try:
            client = await cluster.client()
            pool = await client.pool_create("bs", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"bluestore-cluster" * 100)
            assert await io.read("obj") == b"bluestore-cluster" * 100
            # bounce one OSD, keeping its store directory
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(
                    client.objecter.object_pgid(pool, "obj"))
            victim = acting[0]
            stopped = cluster.osds.pop(victim)
            await stopped.stop()
            osd = OSDDaemon(victim, cluster.mon_addr, config=cfg,
                            store=BlueStore(str(tmp_path / f"osd{victim}"),
                                            size=64 << 20))
            await osd.start()
            cluster.osds[victim] = osd
            for _ in range(100):
                if cluster.mon.osdmap.osd_up[victim]:
                    break
                await asyncio.sleep(0.05)
            assert await io.read("obj", timeout=60) == \
                b"bluestore-cluster" * 100
        finally:
            await cluster.stop()

    asyncio.run(scenario())


def test_snapshots_and_scrub_on_bluestore_ec_pool(tmp_path):
    """Cross-feature integration: EC pool + snapshots (shard-local COW
    clones) + scrub, all on the BlueStore flagship store — the stack a
    reference user actually runs."""
    import asyncio

    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    async def scenario():
        cfg = _fast_config()
        cluster = await start_cluster(
            3, config=cfg,
            store_factory=lambda o: BlueStore(
                str(tmp_path / f"bosd{o}"), size=64 << 20))
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "bsec", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            v1 = bytes(range(256)) * 32
            await io.write_full("obj", v1)
            sid = await io.selfmanaged_snap_create()
            io.set_snap_context(sid, [sid])
            await io.write_full("obj", b"HEAD" * 2048)
            assert await io.read("obj") == b"HEAD" * 2048
            assert await io.read("obj", snapid=sid) == v1
            # scrub finds the BlueStore-backed EC shards consistent
            for osd in cluster.osds.values():
                for st in list(osd.pgs.values()):
                    if st.primary == osd.osd_id:
                        rep = await osd.scrub_pg(st)
                        assert not rep["inconsistent"], rep
        finally:
            await cluster.stop()

    asyncio.run(scenario())
