"""Multi-monitor quorum: election, Paxos-replicated maps, leader failover.

The tier-3 mon_thrash analog (reference qa/tasks/mon_thrash.py): kill the
leader mid-workload and require the cluster to elect, converge, and keep
serving I/O.
"""

import asyncio

import pytest

from ceph_tpu.cluster.vstart import _fast_config, start_cluster


def run(coro):
    return asyncio.run(coro)


def test_three_mon_quorum_replicates_maps():
    async def scenario():
        cluster = await start_cluster(3, n_mons=3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("repl", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            await io.write_full("obj", b"quorum-payload" * 50)
            assert await io.read("obj") == b"quorum-payload" * 50

            # every monitor converges on the same committed map
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                epochs = {m.osdmap.epoch for m in cluster.mons}
                pools = [sorted(p.name for p in m.osdmap.pools.values())
                         for m in cluster.mons]
                if len(epochs) == 1 and all(p == pools[0] for p in pools):
                    break
                await asyncio.sleep(0.05)
            assert len({m.osdmap.epoch for m in cluster.mons}) == 1
            for m in cluster.mons:
                assert any(p.name == "repl" for p in m.osdmap.pools.values())
            # exactly one leader
            assert sum(1 for m in cluster.mons if m.is_leader) == 1
        finally:
            await cluster.stop()

    run(scenario())


def test_leader_failover_mid_pool_create():
    """Kill the leader while a pool create is in flight: a new leader is
    elected, the command succeeds (client failover + idempotent create),
    maps converge identically on the survivors, and OSDs keep serving."""
    async def scenario():
        cluster = await start_cluster(3, n_mons=3)
        try:
            client = await cluster.client()
            p1 = await client.pool_create("before", "replicated",
                                          pg_num=4, size=3)
            io1 = client.ioctx(p1)
            await io1.write_full("pre", b"pre-failover" * 40)

            leader = cluster.mon
            dead_rank = leader.rank

            async def create():
                return await client.pool_create("during", "replicated",
                                                pg_num=4, size=3)

            before = leader.perf.get("mon_proposals")
            task = asyncio.get_event_loop().create_task(create())
            # converge-poll (round-14 deflake): wait until the create
            # actually REACHED the leader's proposal path, then kill —
            # a fixed sleep raced the command under load (too early:
            # nothing in flight; too late: already committed)
            deadline = asyncio.get_event_loop().time() + 5
            while asyncio.get_event_loop().time() < deadline:
                if leader.perf.get("mon_proposals") > before or \
                        task.done():
                    break
                await asyncio.sleep(0.005)
            await cluster.kill_mon(dead_rank)

            p2 = await asyncio.wait_for(task, timeout=30)
            new_leader = await cluster.wait_for_leader(exclude=dead_rank)
            assert new_leader.rank != dead_rank

            survivors = [m for m in cluster.mons if m.rank != dead_rank]
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                epochs = {m.osdmap.epoch for m in survivors}
                if len(epochs) == 1 and all(
                        any(p.name == "during"
                            for p in m.osdmap.pools.values())
                        for m in survivors):
                    break
                await asyncio.sleep(0.05)
            names = [sorted(p.name for p in m.osdmap.pools.values())
                     for m in survivors]
            assert names[0] == names[1], names
            # the pool exists exactly ONCE despite the client retry
            assert sum(1 for p in survivors[0].osdmap.pools.values()
                       if p.name == "during") == 1

            # OSDs keep serving through the new quorum
            io2 = client.ioctx(p2)
            await io2.write_full("post", b"post-failover" * 40, timeout=60)
            assert await io2.read("post", timeout=60) == \
                b"post-failover" * 40
            assert await io1.read("pre") == b"pre-failover" * 40
        finally:
            await cluster.stop()

    run(scenario())


def test_peon_forwards_commands():
    """A command sent to a peon is forwarded to the leader and the reply
    relayed back (reference Monitor::forward_request_leader)."""
    async def scenario():
        cluster = await start_cluster(3, n_mons=3)
        try:
            leader = cluster.mon
            peon = next(m for m in cluster.mons if not m.is_leader)
            # point a client directly (and only) at the peon
            from ceph_tpu.cluster.objecter import RadosClient

            c = RadosClient([tuple(cluster.mon_addrs[peon.rank])],
                            name="peonclient", config=cluster.config)
            await c.connect()
            cluster.clients.append(c)
            pool = await c.pool_create("viapeon", "replicated",
                                       pg_num=4, size=3)
            assert any(p.name == "viapeon"
                       for p in leader.osdmap.pools.values())
            assert peon.perf.get("mon_commands_forwarded") >= 1
        finally:
            await cluster.stop()

    run(scenario())


def test_cluster_log_service():
    """Central cluster log (VERDICT r4 missing #4; reference LogMonitor,
    src/mon/LogMonitor.h:39): daemon and mon events Paxos-replicate into
    a queryable log; 'log last' shows an induced failure."""
    import asyncio

    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    async def scenario():
        cluster = await start_cluster(3, config=_fast_config())
        try:
            client = await cluster.client()
            await client.pool_create("clogp", "replicated",
                                     pg_num=4, size=2)
            victim = max(cluster.osds)
            await cluster.osds[victim].stop()
            # wait for failure detection to mark it down, then for the
            # mon tick to flush the clog buffer through Paxos
            deadline = 400
            entries = []
            for _ in range(deadline):
                await asyncio.sleep(0.1)
                r = await client.objecter.mon_command(
                    {"prefix": "log last", "num": 50})
                entries = r if isinstance(r, list) else []
                if any(f"osd.{victim}" in e["msg"] and "down" in e["msg"]
                       for e in entries):
                    break
            msgs = [e["msg"] for e in entries]
            assert any("pool 'clogp' created" in m for m in msgs), msgs
            assert any(f"osd.{victim}" in m and "down" in m
                       for m in msgs), msgs
            # entries carry who/stamp/prio
            assert all({"who", "stamp", "prio", "msg"} <= set(e)
                       for e in entries)
        finally:
            await cluster.stop()

    asyncio.run(scenario())
