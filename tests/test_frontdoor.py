"""Front-door crash consistency (round 15): RBD snapshot/clone/copyup,
RGW multipart, and MDS journal replay under named crash points, judged
by application-level invariants.

Layers tested here:

- the client-library interrupt seam (``chaos.points.maybe_interrupt``):
  arming, seeded skip, chain pop, one-shot, provable no-op;
- the three new invariants on SYNTHETIC histories (a torn snapshot
  read, an orphaned part, a half-visible complete, a lost metadata op
  each convict) — the checks are duck-typed, so fakes drive them
  without a cluster;
- the durable RGW multipart state machine end-to-end (orphan GC,
  completing roll-forward, abort finish, index repair);
- MDS replay hardening: a transient apply failure can never let the
  trim eat an unreplayed segment;
- the ``frontdoor-smoke`` builtin scenario (tier-1: one seeded run,
  schedule determinism, interrupts provably fired) and its slow
  double-run bit-identical-verdict twin + the slow scenario trio;
- graft-load plan determinism for the round-15 verbs.
"""

import asyncio

import pytest

from tests._flaky import contention_retry

from ceph_tpu.chaos.counters import CHAOS, chaos_total
from ceph_tpu.chaos.points import ChaosInterrupt, maybe_interrupt
from ceph_tpu.utils import Config


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------ interrupt seam unit


def test_interrupt_point_unarmed_is_noop():
    cfg = Config()
    before = chaos_total()
    maybe_interrupt(cfg, "rbd_snap_pre_header")   # unarmed: no-op
    assert chaos_total() == before


def test_interrupt_point_fires_one_shot_with_skip():
    cfg = Config(chaos_crash_point="rgw_part_mid",
                 chaos_crash_point_skip=2)
    maybe_interrupt(cfg, "rgw_part_mid")          # skip 2 -> 1
    maybe_interrupt(cfg, "rgw_complete_mid")      # name mismatch
    maybe_interrupt(cfg, "rgw_part_mid")          # skip 1 -> 0
    assert cfg.chaos_crash_point == "rgw_part_mid"
    with pytest.raises(ChaosInterrupt):
        maybe_interrupt(cfg, "rgw_part_mid")
    assert cfg.chaos_crash_point == ""            # one-shot: disarmed
    maybe_interrupt(cfg, "rgw_part_mid")          # and stays off


def test_interrupt_point_chain_pops_head():
    cfg = Config(chaos_crash_point="rgw_part_mid,rgw_complete_mid")
    maybe_interrupt(cfg, "rgw_complete_mid")      # not the head yet
    with pytest.raises(ChaosInterrupt):
        maybe_interrupt(cfg, "rgw_part_mid")
    assert cfg.chaos_crash_point == "rgw_complete_mid"
    with pytest.raises(ChaosInterrupt):
        maybe_interrupt(cfg, "rgw_complete_mid")
    assert cfg.chaos_crash_point == ""


# --------------------------------------- synthetic-history invariants


class _FakeImage:
    def __init__(self, content):
        self.content = content                    # (region, snap) -> bytes

    async def read(self, offset, length, snap_name=None, timeout=None):
        return self.content[(offset // length, snap_name)]


class _SnapFD:
    """Minimal duck-typed stand-in for FrontdoorState's rbd half."""

    def __init__(self, content, snaps, parent_pin=None,
                 clone_expect=None):
        self.region_size = 4
        self.image_name = "img"
        self.clone_name = "clone"
        self.parent_snap = "s0"
        self.snaps = snaps
        self.parent_pin = parent_pin or {}
        self.clone_expect = clone_expect or {}
        self._img = _FakeImage(content)

    async def open_image(self, name):
        return self._img


def test_snapshot_invariant_convicts_torn_read():
    from ceph_tpu.chaos.invariants import check_snapshot

    # snap s0 allows only gen-a in region 0; the store serves gen-b
    # (post-snap bytes: the COW-miss bug class)
    fd = _SnapFD(content={(0, "s0"): b"gnB!"},
                 snaps={"s0": {0: frozenset({b"gnA!"})}})
    failures = run(check_snapshot(fd, timeout=0.1))
    assert failures and "torn or post-snap" in failures[0]
    # ...and passes when the snap serves an allowed generation
    fd = _SnapFD(content={(0, "s0"): b"gnA!"},
                 snaps={"s0": {0: frozenset({b"gnA!"})}})
    assert run(check_snapshot(fd, timeout=0.1)) == []


def test_snapshot_invariant_convicts_mutated_parent_and_lost_copyup():
    from ceph_tpu.chaos.invariants import check_snapshot

    fd = _SnapFD(content={(0, "s0"): b"MUT!", (1, None): b"zzzz"},
                 snaps={},
                 parent_pin={0: b"pin!"},
                 clone_expect={1: frozenset({b"chld"})})
    failures = run(check_snapshot(fd, timeout=0.1))
    assert any("MUTATED" in f for f in failures)
    assert any("lost copy-up" in f for f in failures)


class _FakeMeta:
    def __init__(self, key):
        self.key = key


class _FakeListing:
    def __init__(self, keys):
        self.keys = [_FakeMeta(k) for k in keys]


class _FakeRGW:
    def __init__(self, objects):
        self.objects = objects                    # key -> bytes

    async def list_objects(self, bucket, prefix="", marker="",
                           max_keys=1000):
        return _FakeListing(sorted(self.objects))

    async def get_object(self, bucket, key, timeout=None):
        if key not in self.objects:
            raise FileNotFoundError(key)
        return _FakeMeta(key), self.objects[key]

    async def head_object(self, bucket, key, timeout=None):
        if key not in self.objects:
            raise FileNotFoundError(key)
        return _FakeMeta(key)


class _MpFD:
    def __init__(self, objects, completed=None, pending=None,
                 orphans=()):
        self.bucket = "b"
        self.rgw = _FakeRGW(objects)
        self.mp_completed = completed or {}
        self.mp_pending = pending or {}
        self._orphans = list(orphans)

    async def part_oids(self):
        return self._orphans


def test_multipart_invariant_convicts_orphans_and_half_visibility():
    from ceph_tpu.chaos.invariants import check_multipart

    # an orphaned part object survives the reclaim pass
    fd = _MpFD(objects={}, orphans=[".mp.1:b:0001.00001"])
    assert any("orphaned part" in f
               for f in run(check_multipart(fd, timeout=0.1)))
    # an interrupted complete that is LISTED but serves wrong bytes
    fd = _MpFD(objects={"k": b"wrong"}, pending={"k": b"right"})
    assert any("PARTIALLY visible" in f
               for f in run(check_multipart(fd, timeout=0.1)))
    # an acked complete that vanished
    fd = _MpFD(objects={}, completed={"k": b"payload"})
    failures = run(check_multipart(fd, timeout=0.1))
    assert any("unreadable" in f for f in failures)
    assert any("missing from the bucket listing" in f
               for f in failures)
    # all-or-nothing holds: invisible pending + clean acked pass
    fd = _MpFD(objects={"done": b"x"}, completed={"done": b"x"},
               pending={"gone": b"y"})
    assert run(check_multipart(fd, timeout=0.1)) == []


class _NsFD:
    def __init__(self, tree, model=None, gone=()):
        self.tree = tree                          # path -> kind
        self.ns_model = model or {}
        self.ns_gone = set(gone)

    async def fs_stat(self, path):
        if path not in self.tree:
            raise FileNotFoundError(path)

        class Ino:
            mode = self.tree[path]

        return Ino()

    async def fs_listdir(self, path):
        if path not in self.tree:
            raise FileNotFoundError(path)
        return []


def test_namespace_invariant_convicts_lost_and_resurrected():
    from ceph_tpu.chaos.invariants import check_namespace

    # an acked create lost post-replay (the trim-ate-a-segment class)
    fd = _NsFD(tree={"/fd": "dir"},
               model={"/fd": "dir", "/fd/f1": "file"})
    assert any("lost post-replay" in f
               for f in run(check_namespace(fd, timeout=0.1)))
    # a renamed-away source resurrected by replay
    fd = _NsFD(tree={"/fd": "dir", "/fd/old": "file"},
               model={"/fd": "dir"}, gone=["/fd/old"])
    assert any("resurrected" in f
               for f in run(check_namespace(fd, timeout=0.1)))
    # the clean model passes
    fd = _NsFD(tree={"/fd": "dir", "/fd/f1": "file"},
               model={"/fd": "dir", "/fd/f1": "file"})
    assert run(check_namespace(fd, timeout=0.1)) == []


# ------------------------------------------------ no-op + determinism


def test_frontdoor_paths_are_noop_without_armed_points():
    """The acceptance no-op proof for the round-15 seams: a full RBD
    snap/clone/copyup cycle + an RGW multipart + MDS metadata ops with
    no point armed never touch a chaos counter."""
    from ceph_tpu.cluster.mds import MDSClient
    from ceph_tpu.cluster.rbd import RBD
    from ceph_tpu.cluster.rgw import RGW
    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3)
        try:
            before = chaos_total()
            client = await cluster.client()
            pool = await client.pool_create("fdnoop", "replicated",
                                            pg_num=4, size=3)
            io = client.ioctx(pool)
            rbd = RBD(io)
            await rbd.create("i", 64 << 10, stripe_unit=8 << 10,
                             stripe_count=1, object_size=16 << 10)
            img = await rbd.open("i")
            await img.write(0, b"g1" * 8192)      # both object halves
            await img.snap_create("s")
            await rbd.clone("i", "s", "c")
            child = await rbd.open("c")
            await child.write(0, b"c" * 8192)      # copy-up traversal
            assert await child.read(8 << 10, 4) == b"g1g1"
            with pytest.raises(OSError):
                await img.snap_remove("s")          # pinned by the clone
            rgw = RGW(io)
            await rgw.create_bucket("b")
            uid = await rgw.create_multipart("b", "k")
            await rgw.upload_part("b", "k", uid, 1, b"p1" * 100)
            await rgw.upload_part("b", "k", uid, 2, b"p2" * 100)
            await rgw.complete_multipart("b", "k", uid)
            _, data = await rgw.get_object("b", "k")
            assert data == b"p1" * 100 + b"p2" * 100
            assert await rgw.list_multipart_uploads("b") == {}
            meta = await client.pool_create("fdnm", "replicated",
                                            pg_num=4, size=3)
            data_p = await client.pool_create("fdnd", "replicated",
                                             pg_num=4, size=3)
            await cluster.start_mds(meta, data_p)
            for _ in range(100):
                await client.objecter._refresh_map()
                if getattr(client.objecter.osdmap, "mds_addr", None):
                    break
                await asyncio.sleep(0.05)
            fs = MDSClient(client, data_p, meta_pool=meta)
            await fs.mkdir("/d")
            await fs.create("/d/f")
            assert chaos_total() == before
        finally:
            await cluster.stop()

    run(scenario())


def test_frontdoor_schedules_deterministic():
    """Every round-15 builtin resolves a bit-identical schedule from
    its seed; client/mds crash points never consume OSD bookkeeping."""
    from ceph_tpu.chaos.frontdoor import frontdoor_scenarios
    from ceph_tpu.chaos.scenario import build_schedule

    for name, sc in frontdoor_scenarios(1.0).items():
        s1, s2 = build_schedule(sc, 23), build_schedule(sc, 23)
        assert s1 == s2, name
        for e in s1:
            if e["action"] == "crash_point":
                assert "at" in e["args"], (name, e)
                assert e["target"] == "client" or \
                    e["target"].startswith("mds"), (name, e)


def test_graftlint_scopes_cover_frontdoor_files():
    """The task-spawn / swallowed-async-error / rpc-timeout rule scopes
    must keep every front-door library in range (the round-15 chaos
    seams and new chaos modules included) — a scope refactor that drops
    them would silently stop linting the very code this PR grew."""
    from ceph_tpu.analysis import async_errors, rpc_timeout, taskspawn

    frontdoor_files = [
        "ceph_tpu/cluster/rbd.py", "ceph_tpu/cluster/rgw.py",
        "ceph_tpu/cluster/rgw_http.py", "ceph_tpu/cluster/rgw_sync.py",
        "ceph_tpu/cluster/mds.py", "ceph_tpu/cluster/fs.py",
        "ceph_tpu/cluster/snaps.py", "ceph_tpu/chaos/frontdoor.py",
        "ceph_tpu/chaos/points.py", "ceph_tpu/load/driver.py",
        # round 16: the read coalescer, the scrub scheduler, and the
        # integrity scenario runner joined the tree — the rule scopes
        # must keep covering them (read-repair task spawns, the fill
        # runner's async phases, the batcher's parked futures)
        "ceph_tpu/cluster/batcher.py", "ceph_tpu/cluster/scrub.py",
        "ceph_tpu/chaos/integrity.py",
    ]
    for mod in (taskspawn, async_errors, rpc_timeout):
        for path in frontdoor_files:
            assert path.startswith(mod.SCOPE), (mod.RULE, path)


def test_load_plan_determinism_with_frontdoor_verbs():
    """Round-15 verbs ride the same plan contract: same seed -> same
    plan; and a spec WITHOUT the new verbs resolves exactly the plan it
    did before they existed (existing seeds must not shift)."""
    from ceph_tpu.load.driver import LoadSpec, build_plan, plan_key

    fd = LoadSpec(name="fdmix", clients=8, sessions=2, rate=2.0,
                  duration=1.0, objects=8,
                  verbs=(("write", 1.0), ("rbd_snap", 1.0),
                         ("rbd_clone_read", 1.0),
                         ("rgw_multipart", 1.0)))
    assert plan_key(build_plan(fd, 9)) == plan_key(build_plan(fd, 9))
    assert plan_key(build_plan(fd, 9)) != plan_key(build_plan(fd, 10))
    verbs = {op["verb"] for ops in build_plan(fd, 9) for op in ops}
    assert verbs & {"rbd_snap", "rbd_clone_read", "rgw_multipart"}
    # the old default mix is untouched by the new handlers: the plan is
    # a pure function of (spec, seed), and spec didn't change
    base = LoadSpec(name="base", clients=8, sessions=2, rate=2.0,
                    duration=1.0, objects=8)
    assert {op["verb"] for ops in build_plan(base, 9) for op in ops} <= \
        {"write", "read", "rmw", "append", "delete"}


# --------------------------------------------- multipart e2e reclaim


@contention_retry()
def test_multipart_reclaim_resolves_every_interrupted_state():
    """One cluster, all four reclaim duties: orphaned parts GC'd, an
    interrupted complete rolled FORWARD (visible exactly once, exact
    bytes), an interrupted abort finished, and a dangling index entry
    (payload removed, index not) repaired."""
    from ceph_tpu.cluster.rgw import RGW
    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("mprec", "replicated",
                                            pg_num=4, size=3)
            io = client.ioctx(pool)
            rgw = RGW(io)
            await rgw.create_bucket("b")

            # 1. orphaned part: payload landed, registry never updated
            uid1 = await rgw.create_multipart("b", "k1")
            io.objecter.config.set("chaos_crash_point", "rgw_part_mid")
            with pytest.raises(ChaosInterrupt):
                await rgw.upload_part("b", "k1", uid1, 1, b"orphan")
            # the client died; its upload is later deemed expired

            # 2. interrupted complete: payload + intent landed, index
            #    never updated -> invisible now, rolled forward by GC
            uid2 = await rgw.create_multipart("b", "k2")
            await rgw.upload_part("b", "k2", uid2, 1, b"AA" * 50)
            await rgw.upload_part("b", "k2", uid2, 2, b"BB" * 50)
            io.objecter.config.set("chaos_crash_point",
                                   "rgw_complete_mid")
            with pytest.raises(ChaosInterrupt):
                await rgw.complete_multipart("b", "k2", uid2)
            with pytest.raises(FileNotFoundError):
                await rgw.head_object("b", "k2")   # all-or-nothing

            # 3. interrupted abort: intent landed, parts not deleted
            uid3 = await rgw.create_multipart("b", "k3")
            await rgw.upload_part("b", "k3", uid3, 1, b"CC" * 50)
            io.objecter.config.set("chaos_crash_point", "rgw_abort_mid")
            with pytest.raises(ChaosInterrupt):
                await rgw.abort_multipart("b", "k3", uid3)

            # 4. dangling index entry: a client died mid-delete
            await rgw.put_object("b", "gone", b"dead payload")
            await io.remove(rgw._data_oid("b", "gone"))

            stats = await rgw.reclaim_multipart("b", abort_open=True)
            assert stats["rolled_forward"] == 1, stats
            assert stats["orphan_parts"] >= 1, stats
            assert stats["aborts_finished"] >= 1, stats
            assert stats["index_repaired"] == 1, stats
            # the rolled-forward complete is fully visible, exact bytes
            _, data = await rgw.get_object("b", "k2")
            assert data == b"AA" * 50 + b"BB" * 50
            # no part objects and no registry entries survive
            prefix = rgw._mp_prefix("b")
            assert [o for o in await io.list_objects()
                    if o.startswith(prefix)] == []
            assert await rgw.list_multipart_uploads("b") == {}
            # listing matches readable: the dangling entry is gone
            listed = [m.key for m in
                      (await rgw.list_objects("b")).keys]
            assert listed == ["k2"]
            with pytest.raises(FileNotFoundError):
                await rgw.head_object("b", "gone")

            # 5. crash mid-CLEANUP: index already flipped, one part
            #    already deleted, record still 'completing' — reclaim
            #    must detect the manifest etag in the index and finish
            #    the cleanup instead of failing to re-read dead parts
            uid4 = await rgw.create_multipart("b", "k4")
            await rgw.upload_part("b", "k4", uid4, 1, b"DD" * 50)
            await rgw.upload_part("b", "k4", uid4, 2, b"EE" * 50)
            real_remove = io.remove
            seen = {"n": 0}

            async def dying_remove(oid, timeout=None):
                if oid.startswith(rgw._mp_prefix("b")):
                    seen["n"] += 1
                    if seen["n"] == 2:
                        raise TimeoutError("client died mid-cleanup")
                return await real_remove(oid, timeout=timeout)

            io.remove = dying_remove
            with pytest.raises(TimeoutError):
                await rgw.complete_multipart("b", "k4", uid4)
            io.remove = real_remove
            stats = await rgw.reclaim_multipart("b", abort_open=True)
            assert stats["rolled_forward"] == 1, stats
            _, d4 = await rgw.get_object("b", "k4")
            assert d4 == b"DD" * 50 + b"EE" * 50
            assert await rgw.list_multipart_uploads("b") == {}
            assert [o for o in await io.list_objects()
                    if o.startswith(rgw._mp_prefix("b"))] == []
        finally:
            await cluster.stop()

    run(scenario())


# ------------------------------------------------- mds replay honesty


@contention_retry()
def test_mds_replay_transient_failure_never_trims_unreplayed():
    """A transient apply failure during replay must stop the watermark:
    the journal keeps the event, the boot fails loudly, and a later
    replay applies it — trim can never eat an unreplayed segment."""
    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3)
        try:
            admin = await cluster.client()
            meta = await admin.pool_create("rjm", "replicated",
                                           pg_num=4, size=2)
            data = await admin.pool_create("rjd", "replicated",
                                           pg_num=4, size=2)
            await cluster.start_mds(meta, data)
            mds = cluster.mds
            seq = mds._seq + 1
            await mds._journal_append(seq, ("create", "/victim"))

            real_create = mds.fs.create

            async def failing_create(path):
                raise IOError("transient meta-pool failure")

            mds.fs.create = failing_create
            with pytest.raises(IOError):
                await mds._replay_journal()
            # the event SURVIVED: not trimmed, watermark not advanced
            applied, events = await mds._journal_state()
            assert applied < seq
            assert f"{seq:016d}" in events
            # the next (healthy) replay applies it
            mds.fs.create = real_create
            await mds._replay_journal()
            assert "victim" in await mds.fs.listdir("/")
            applied, events = await mds._journal_state()
            assert applied >= seq
            assert f"{seq:016d}" not in events   # now safely trimmed
        finally:
            await cluster.stop()

    run(scenario())


# --------------------------------------------- the builtin scenarios


@pytest.mark.chaos
def test_frontdoor_smoke_scenario():
    """Tier-1 front-door gate: all three surfaces under one client
    interrupt or MDS crash per round — snapshot/multipart/namespace
    invariants all hold, the schedule resolves bit-identically, and
    the seams provably fired.  (The double-run verdict-replay gate is
    the slow twin below.)"""
    from ceph_tpu.chaos.frontdoor import frontdoor_scenarios, run_frontdoor
    from ceph_tpu.chaos.scenario import build_schedule

    sc = frontdoor_scenarios(1.0)["frontdoor-smoke"]
    s1 = build_schedule(sc, 7)
    assert s1 == build_schedule(sc, 7)
    v = run(run_frontdoor(sc, 7))
    assert v.passed, v.failures
    assert v.schedule == s1
    assert v.counters.get("interrupt_points_fired", 0) >= 1
    assert v.counters.get("mds_crash_points_fired", 0) >= 1


@pytest.mark.chaos
@pytest.mark.slow
def test_frontdoor_smoke_replays_bit_identical():
    from ceph_tpu.chaos.frontdoor import frontdoor_scenarios, run_frontdoor

    sc = frontdoor_scenarios(1.0)["frontdoor-smoke"]
    v1 = run(run_frontdoor(sc, 7))
    v2 = run(run_frontdoor(sc, 7))
    assert v1.passed, v1.failures
    assert v2.passed, v2.failures
    assert v1.replay_key() == v2.replay_key()


@pytest.mark.chaos
@pytest.mark.slow
def test_rbd_snap_midwrite_scenario(tmp_path):
    from ceph_tpu.chaos.frontdoor import frontdoor_scenarios, run_frontdoor

    sc = frontdoor_scenarios(1.0)["rbd-snap-midwrite"]
    v = run(run_frontdoor(sc, 11, tmpdir=str(tmp_path)))
    assert v.passed, v.failures
    assert v.counters.get("interrupt_points_fired", 0) >= 1


@pytest.mark.chaos
@pytest.mark.slow
def test_rgw_multipart_crash_scenario(tmp_path):
    from ceph_tpu.chaos.frontdoor import frontdoor_scenarios, run_frontdoor

    sc = frontdoor_scenarios(1.0)["rgw-multipart-crash"]
    v = run(run_frontdoor(sc, 11, tmpdir=str(tmp_path)))
    assert v.passed, v.failures
    assert v.counters.get("interrupt_points_fired", 0) >= 1


@pytest.mark.chaos
@pytest.mark.slow
def test_mds_journal_replay_scenario(tmp_path):
    from ceph_tpu.chaos.frontdoor import frontdoor_scenarios, run_frontdoor

    sc = frontdoor_scenarios(1.0)["mds-journal-replay"]
    v = run(run_frontdoor(sc, 11, tmpdir=str(tmp_path)))
    assert v.passed, v.failures
    assert v.counters.get("mds_crash_points_fired", 0) >= 2
