"""Round-16 verified batched reads: bit-exactness + corruption matrix.

The read-side twin of tests/test_batch_dataplane.py's write gate: the
coalesced decode must be invisible in the bytes — N concurrent reads
through the read coalescer return byte-identical data to the same reads
issued serially through the per-op anchor path (mixed-profile ticks,
the 1-op tick, degraded fast-k reads, and the recovery reencode
included).  Unit level, the multi decode/reencode must match their
per-op equivalents exactly, and the corruption matrix proves every
shard position's rot is detected by crc and rebuilt bit-identically
from the survivors.
"""

import asyncio
import itertools

import numpy as np
import pytest

from tests._flaky import contention_retry

from ceph_tpu.cluster.vstart import _fast_config, start_cluster
from ceph_tpu.ec import factory
from ceph_tpu.ec.stripe import (
    StripeInfo,
    decode_stripes,
    decode_stripes_multi,
    encode_stripes,
    reencode_stripes,
    reencode_stripes_multi,
)
from ceph_tpu.ops import crc32c as crcmod


def run(coro):
    return asyncio.run(coro)


def _codec(k, m):
    return factory({"plugin": "jerasure", "technique": "reed_sol_van",
                    "k": str(k), "m": str(m)})


# ------------------------------------------------------------- unit level


def test_decode_stripes_multi_bit_exact():
    """One coalesced tick == N per-op decodes, byte for byte — across
    mixed object sizes AND mixed erasure patterns in the same tick."""
    codec = _codec(2, 1)
    sinfo = StripeInfo(2, 4096)
    rng = np.random.default_rng(5)
    reqs = []
    for size in (8192, 40960, 1, 12345, 0):
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        full = encode_stripes(codec, sinfo, data)
        for keep in ((0, 1), (1, 2), (0, 2)):
            reqs.append(({s: full[s] for s in keep}, size, data))
    outs = decode_stripes_multi(codec, sinfo,
                                [(sh, ls) for sh, ls, _d in reqs])
    for (shards, ls, data), got in zip(reqs, outs):
        assert got == decode_stripes(codec, sinfo, shards, ls)
        assert got == data


def test_decode_stripes_multi_single_op_degenerate():
    codec = _codec(2, 1)
    sinfo = StripeInfo(2, 4096)
    data = bytes(range(256)) * 50
    full = encode_stripes(codec, sinfo, data)
    [got] = decode_stripes_multi(codec, sinfo,
                                 [({1: full[1], 2: full[2]}, len(data))])
    assert got == data


def test_reencode_stripes_multi_bit_exact():
    """The recovery rebuild's multi twin: per-op reencode equality for
    every availability pattern of a k3m2 object, all in one call."""
    codec = _codec(3, 2)
    sinfo = StripeInfo(3, 4096)
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, 49152, dtype=np.uint8).tobytes()
    full = encode_stripes(codec, sinfo, data)
    reqs = [({s: full[s] for s in keep}, len(data))
            for keep in itertools.combinations(range(5), 3)]
    outs = reencode_stripes_multi(codec, sinfo, reqs)
    for (shards, ls), got in zip(reqs, outs):
        assert np.array_equal(got, reencode_stripes(codec, sinfo,
                                                    shards, ls))
        assert np.array_equal(got, full)


def test_corruption_matrix_every_shard_position():
    """Synthetic corruption matrix: flip a bit in EACH shard position
    (data and parity), assert (a) the crc catches exactly the flipped
    shard, and (b) the rebuild from the survivors — corrupt shard
    excluded as a decode source — is bit-identical to the original.
    Then every erasure pattern up to m=k-1=2 erasures rebuilds exactly
    (single vs k-1 erasures, data vs parity mixes)."""
    codec = _codec(3, 2)
    sinfo = StripeInfo(3, 4096)
    rng = np.random.default_rng(13)
    data = rng.integers(0, 256, 36864, dtype=np.uint8).tobytes()
    full = encode_stripes(codec, sinfo, data)
    n = full.shape[0]
    crcs = [crcmod.crc32c(0xFFFFFFFF, full[s].tobytes())
            for s in range(n)]
    for bad in range(n):
        rotted = full.copy()
        rotted[bad, 777] ^= 0x40
        # detection: exactly the flipped shard fails its stored crc
        got = crcmod.crc32c_rows(rotted)
        fails = [s for s in range(n) if got[s] != crcs[s]]
        assert fails == [bad]
        # repair: rebuild with the corrupt shard EXCLUDED as a source
        survivors = {s: rotted[s] for s in range(n) if s != bad}
        [rebuilt] = reencode_stripes_multi(
            codec, sinfo, [(survivors, len(data))])
        assert np.array_equal(rebuilt, full), f"shard {bad}"
    # erasure sweep: every 1- and 2-erasure pattern decodes AND
    # rebuilds to the originals
    for nlost in (1, 2):
        for lost in itertools.combinations(range(n), nlost):
            survivors = {s: full[s] for s in range(n) if s not in lost}
            [got] = decode_stripes_multi(
                codec, sinfo, [(survivors, len(data))])
            assert got == data, lost
            [rebuilt] = reencode_stripes_multi(
                codec, sinfo, [(survivors, len(data))])
            assert np.array_equal(rebuilt, full), lost


def test_choose_decode_group_mixed_generation():
    """The pure gather chooser: a member holding an OLDER committed
    generation is flagged stale (read-repair candidate), un-acked
    newer generations never outvote committed ones, and an acked
    generation short of k shards refuses the stale read."""
    from ceph_tpu.cluster.backend_ec import choose_decode_group

    committed = lambda v: v <= 5  # noqa: E731
    # g5 committed on shards 0,1; shard 2 stuck at g3 (missed a write)
    got = {0: (b"a5", 5, 100), 1: (b"b5", 5, 100), 2: (b"c3", 3, 60)}
    shards, size, version, stale = choose_decode_group(got, 2, committed)
    assert version == 5 and size == 100 and set(shards) == {0, 1}
    assert stale == {2}
    # an un-acked g7 on one shard must NOT be chosen over committed g5
    got = {0: (b"a7", 7, 140), 1: (b"b5", 5, 100), 2: (b"c5", 5, 100)}
    shards, size, version, stale = choose_decode_group(got, 2, committed)
    assert version == 5 and set(shards) == {1, 2}
    assert stale == set()      # g7 is in flight, NOT stale
    # acked newest lacking k shards: refuse the stale read
    got = {0: (b"a5", 5, 100), 1: (b"b3", 3, 60), 2: (b"c3", 3, 60)}
    with pytest.raises(IOError):
        choose_decode_group(got, 2, committed)
    # brand-new object: only un-acked state exists — serve it
    got = {0: (b"a9", 9, 20), 1: (b"b9", 9, 20)}
    shards, size, version, stale = choose_decode_group(got, 2, committed)
    assert version == 9 and set(shards) == {0, 1} and not stale


def test_read_batcher_verify_and_fault_isolation():
    """ReadBatcher unit: the verify tick answers per-row pass/fail from
    one crc batch, and a poisoned decode request (too few shards) fails
    ALONE — its tick-mates still decode (per-item fault isolation)."""
    from ceph_tpu.cluster.batcher import ReadBatcher
    from ceph_tpu.utils import Config, PerfCounters

    codec = _codec(2, 1)
    sinfo = StripeInfo(2, 4096)
    data = b"\xa5" * 8192
    full = encode_stripes(codec, sinfo, data)

    class _FakeOSD:
        config = Config(osd_batch_tick_ops=16)
        perf = PerfCounters("t")
        _stopped = False

        class clock:
            @staticmethod
            def monotonic():
                import time

                return time.monotonic()

        async def _compute(self, fn, *args):
            return fn(*args)

        def _track(self, task):
            return task

    async def scenario():
        rb = ReadBatcher(_FakeOSD())
        row = full[0].tobytes()
        good_crc = crcmod.crc32c(0xFFFFFFFF, row)
        oks = await rb.verify([row, row], [good_crc, good_crc ^ 1])
        assert oks == [True, False]
        # one under-k request + two good ones, same tick
        results = await asyncio.gather(
            rb.decode(codec, sinfo, {0: full[0], 1: full[1]}, len(data)),
            rb.decode(codec, sinfo, {0: full[0]}, len(data)),
            rb.decode(codec, sinfo, {1: full[1], 2: full[2]}, len(data)),
            return_exceptions=True)
        assert results[0] == data
        assert isinstance(results[1], ValueError)
        assert results[2] == data

    run(scenario())


# ---------------------------------------------------------- cluster level


async def _read_workload(cluster, concurrent: bool):
    """Write a fixed workload (two EC profiles + RMW + a solo object),
    then read every object — concurrently (coalesced ticks) or serially
    (the per-op anchor).  Returns {(pool_name, oid): bytes} plus the
    expected payloads."""
    client = await cluster.client()
    pool_a = await client.pool_create(
        "vra", "erasure", pg_num=4,
        ec_profile={"plugin": "jerasure", "technique": "reed_sol_van",
                    "k": "2", "m": "1"})
    pool_b = await client.pool_create(
        "vrb", "erasure", pg_num=4,
        ec_profile={"plugin": "jerasure", "technique": "reed_sol_van",
                    "k": "3", "m": "2"})
    io_a, io_b = client.ioctx(pool_a), client.ioctx(pool_b)
    rng = np.random.default_rng(77)
    expect = {}
    for i in range(4):
        payload = rng.integers(0, 256, 32768 + i * 4096,
                               dtype=np.uint8).tobytes()
        await io_a.write_full(f"ra{i}", payload, timeout=120)
        expect[("a", f"ra{i}")] = payload
    for i in range(3):
        payload = rng.integers(0, 256, 24576, dtype=np.uint8).tobytes()
        await io_b.write_full(f"rb{i}", payload, timeout=120)
        expect[("b", f"rb{i}")] = payload
    # RMW overlay crossing a stripe boundary
    patch = rng.integers(0, 256, 9000, dtype=np.uint8).tobytes()
    await io_a.write("ra0", patch, offset=5000, timeout=120)
    base = bytearray(expect[("a", "ra0")])
    base[5000:5000 + len(patch)] = patch
    expect[("a", "ra0")] = bytes(base)

    ios = {"a": io_a, "b": io_b}
    jobs = [(pool_name, oid) for pool_name, oid in expect]
    if concurrent:
        datas = await asyncio.gather(
            *(ios[p].read(oid, timeout=120) for p, oid in jobs))
        got = dict(zip(jobs, datas))
        # sub-range reads coalesce too
        parts = await asyncio.gather(
            *(ios[p].read(oid, offset=100, length=1000, timeout=120)
              for p, oid in jobs))
        got_parts = dict(zip(jobs, parts))
    else:
        got = {}
        got_parts = {}
        for p, oid in jobs:
            got[(p, oid)] = await ios[p].read(oid, timeout=120)
            got_parts[(p, oid)] = await ios[p].read(
                oid, offset=100, length=1000, timeout=120)
    return client, expect, got, got_parts, (pool_a, io_a)


@contention_retry()
def test_batched_reads_bit_exact_vs_per_op_path():
    """THE round-16 read gate: concurrent reads through the read
    coalescer (verify-on-read enabled) return byte-identical data to
    the same reads issued serially through the per-op anchor — full
    and sub-range reads, mixed profiles, plus a degraded fast-k read
    with a shard holder stopped."""
    async def run_path(coalesced: bool):
        cfg = _fast_config()
        if not coalesced:
            cfg.osd_op_shards = 0
            cfg.osd_batch_tick_ops = 0
            cfg.osd_pipeline_writes = 0
        cluster = await start_cluster(5, config=cfg)
        try:
            client, expect, got, got_parts, (pool_a, io_a) = \
                await _read_workload(cluster, concurrent=coalesced)
            for key, payload in expect.items():
                assert got[key] == payload, key
                assert got_parts[key] == payload[100:1100], key
            # degraded fast-k: stop a NON-primary holder of ra1 and
            # read again — correctness never rests on the fast path
            pgid = client.objecter.object_pgid(pool_a, "ra1")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            # the victim must hold a DATA shard (k=2: shards 0/1), so
            # the degraded read really exercises a reconstructing
            # decode, not a parity-free assembly
            victim = next(acting[s] for s in range(2)
                          if acting[s] >= 0 and acting[s] != primary)
            await cluster.kill_osd(victim)
            degraded = await io_a.read("ra1", timeout=120)
            assert degraded == expect[("a", "ra1")]
            if coalesced:
                # healthy reads short-circuit (pure host interleave +
                # inline hw crc); the DEGRADED decode above is what
                # must ride a coalesced tick
                ticks = sum(o.perf.get("osd_read_batch_ticks")
                            for o in cluster.osds.values())
                assert ticks > 0
            return {k: (got[k], got_parts[k]) for k in expect}, degraded
        finally:
            await cluster.stop()

    batched = run(run_path(True))
    serial = run(run_path(False))
    assert batched == serial


@contention_retry()
def test_recovery_reencode_through_seam_heals_blanked_shard():
    """Recovery rebuild rides the coalescer seam: blank one member's
    shard entirely, let scrub's generation/crc detection rebuild it,
    and assert the healed shard is byte-identical to its pre-damage
    state (the reencode path's end-to-end exactness witness)."""
    async def scenario():
        cluster = await start_cluster(4)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "vrc", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            payload = bytes(range(256)) * 128
            await io.write_full("heal", payload, timeout=120)
            pgid = client.objecter.object_pgid(pool, "heal")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            victim = next(o for o in acting if o >= 0 and o != primary)
            before = bytes(cluster.osds[victim].store.read(coll, "heal"))
            # rot the victim's shard in place (crc now mismatches)
            cluster.osds[victim].store.debug_bitrot(coll, "heal", 999)
            rep = await cluster.osds[primary].scrub_pg(
                cluster.osds[primary].pgs[pgid])
            assert "heal" in rep["repaired"], rep
            # the repair push is fire-and-forget: converge-poll the
            # victim's store to a wall deadline instead of racing it
            deadline = asyncio.get_event_loop().time() + 20.0
            after = None
            while asyncio.get_event_loop().time() < deadline:
                after = bytes(
                    cluster.osds[victim].store.read(coll, "heal"))
                if after == before:
                    break
                await asyncio.sleep(0.05)
            assert after == before
            assert crcmod.crc32c(0xFFFFFFFF, after) == int(
                cluster.osds[victim].store.getattr(coll, "heal",
                                                   "hinfo_crc"))
        finally:
            await cluster.stop()

    run(scenario())
