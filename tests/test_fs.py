"""FileSystem (CephFS analog) over a live cluster: namespace + striped
file I/O with metadata in omap directory objects.

Reference shape: src/mds/ dirfrag omap storage + src/client/ file I/O
through the Striper.
"""

import asyncio

import pytest

from ceph_tpu.cluster.fs import FileSystem
from ceph_tpu.cluster.striper import FileLayout
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


async def _mount(cluster):
    client = await cluster.client()
    meta = await client.pool_create("fs_meta", "replicated",
                                    pg_num=8, size=2)
    data = await client.pool_create("fs_data", "replicated",
                                    pg_num=8, size=2)
    fs = FileSystem(client.ioctx(meta), client.ioctx(data),
                    layout=FileLayout(stripe_unit=4096, stripe_count=2,
                                      object_size=16384))
    await fs.mkfs()
    return fs


def test_fs_namespace_and_io():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            fs = await _mount(cluster)
            # namespace
            await fs.mkdir("/home")
            await fs.mkdir("/home/user")
            await fs.create("/home/user/hello.txt")
            assert await fs.listdir("/") == ["home"]
            assert await fs.listdir("/home/user") == ["hello.txt"]
            with pytest.raises(FileExistsError):
                await fs.mkdir("/home")
            with pytest.raises(FileNotFoundError):
                await fs.stat("/home/user/nope")

            # striped file I/O across object boundaries
            payload = bytes(range(256)) * 300  # ~75 KiB, several objects
            await fs.write("/home/user/hello.txt", 0, payload)
            assert await fs.read("/home/user/hello.txt") == payload
            st = await fs.stat("/home/user/hello.txt")
            assert st.mode == "file" and st.size == len(payload)
            # offset overwrite + sparse extension
            await fs.write("/home/user/hello.txt", 100, b"X" * 50)
            got = await fs.read("/home/user/hello.txt", 90, 80)
            assert got == payload[90:100] + b"X" * 50 + payload[150:170]
            await fs.write("/home/user/hello.txt", 200000, b"tail")
            st = await fs.stat("/home/user/hello.txt")
            assert st.size == 200004
            assert await fs.read("/home/user/hello.txt",
                                 199990, 20) == b"\0" * 10 + b"tail"

            # rename + unlink
            await fs.rename("/home/user/hello.txt", "/home/moved.txt")
            assert await fs.listdir("/home") == ["moved.txt", "user"]
            assert (await fs.read("/home/moved.txt", 0, 10)) == payload[:10]
            with pytest.raises(OSError):
                await fs.unlink("/home")   # non-empty directory
            await fs.unlink("/home/moved.txt")
            await fs.unlink("/home/user")
            await fs.unlink("/home")
            assert await fs.listdir("/") == []
        finally:
            await cluster.stop()

    run(scenario())


def test_fs_data_on_ec_pool():
    """File data striped onto an EC pool; metadata replicated — the
    standard CephFS deployment split."""
    async def scenario():
        cluster = await start_cluster(4)
        try:
            client = await cluster.client()
            meta = await client.pool_create("fsm", "replicated",
                                            pg_num=4, size=2)
            data = await client.pool_create(
                "fsd", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            fs = FileSystem(client.ioctx(meta), client.ioctx(data))
            await fs.mkfs()
            await fs.create("/big.bin")
            blob = b"ec-file-data" * 2000
            await fs.write("/big.bin", 0, blob)
            assert await fs.read("/big.bin") == blob
        finally:
            await cluster.stop()

    run(scenario())
