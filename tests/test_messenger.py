"""Messenger reliability: sessions, reconnect, ordered replay.

Reference semantics: AsyncConnection out_seq/out_q replay after a session
reset (src/msg/async/AsyncConnection.cc) — ordered at-least-once delivery
toward idempotent handlers.
"""

import asyncio

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster.messenger import (
    Connection,
    Dispatcher,
    EntityName,
    Message,
    Messenger,
)
from dataclasses import dataclass, field
from typing import List


@dataclass
class Num(Message):
    n: int = 0


class Collector(Dispatcher):
    def __init__(self):
        self.got: List[int] = []

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, Num):
            self.got.append(msg.n)
            return True
        return False


def run(coro):
    return asyncio.run(coro)


def test_reconnect_replays_unacked_in_order():
    """Kill the TCP connection mid-stream: every message still arrives,
    in order (duplicates allowed — at-least-once), nothing lost."""
    async def scenario():
        rx = Messenger(EntityName("osd", 1))
        coll = Collector()
        rx.add_dispatcher(coll)
        addr = await rx.bind()
        tx = Messenger(EntityName("osd", 2))
        try:
            total = 60
            for i in range(total):
                if i in (20, 40):
                    # hard-drop the transport under the sender's feet
                    conn = tx._out.get(tuple(addr))
                    if conn:
                        conn.writer.close()
                await tx.send_message(Num(n=i), addr)
            # converge-poll: reconnect + replay land asynchronously
            deadline = asyncio.get_event_loop().time() + 10.0
            while set(coll.got) < set(range(total)) and \
                    asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
            # completeness: every n delivered at least once
            assert set(coll.got) == set(range(total)), \
                sorted(set(range(total)) - set(coll.got))
            # order: the dedup'ed sequence is exactly 0..N-1
            dedup = []
            for n in coll.got:
                if not dedup or n > dedup[-1]:
                    dedup.append(n)
            assert dedup == list(range(total))
        finally:
            await tx.shutdown()
            await rx.shutdown()

    run(scenario())


def test_reconnect_survives_receiver_restart():
    """The receiving endpoint dies completely and comes back on the same
    port: the unacked tail replays to the new incarnation."""
    async def scenario():
        rx = Messenger(EntityName("osd", 1))
        coll = Collector()
        rx.add_dispatcher(coll)
        addr = await rx.bind()
        tx = Messenger(EntityName("osd", 2))
        try:
            for i in range(10):
                await tx.send_message(Num(n=i), addr)
            # converge-poll: let the first batch drain before the kill
            deadline = asyncio.get_event_loop().time() + 10.0
            while set(coll.got) < set(range(10)) and \
                    asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
            await rx.shutdown()

            rx2 = Messenger(EntityName("osd", 1))
            coll2 = Collector()
            rx2.add_dispatcher(coll2)
            await rx2.bind(host=addr[0], port=addr[1])
            try:
                for i in range(10, 20):
                    await tx.send_message(Num(n=i), addr)
                # converge-poll: the tail replays to the new incarnation
                deadline = asyncio.get_event_loop().time() + 10.0
                while not set(range(10, 20)) <= set(coll2.got) and \
                        asyncio.get_event_loop().time() < deadline:
                    await asyncio.sleep(0.02)
                got = set(coll2.got)
                # the new incarnation received at least the new tail; any
                # unacked old frames replayed too (at-least-once)
                assert set(range(10, 20)) <= got, sorted(got)
            finally:
                await rx2.shutdown()
        finally:
            await tx.shutdown()

    run(scenario())


def test_unreachable_peer_raises_after_retries():
    async def scenario():
        tx = Messenger(EntityName("client", 9))
        try:
            with pytest.raises((ConnectionError, OSError)):
                await tx.send_message(Num(n=1), ("127.0.0.1", 1))
        finally:
            await tx.shutdown()

    run(scenario())


@contention_retry()
def test_ec_write_survives_connection_drops():
    """Cluster-level: EC writes while the primary's osd-osd connections
    are repeatedly hard-dropped — no silent shard divergence: every
    object remains readable and every acting shard holder converges."""
    async def scenario():
        from ceph_tpu.cluster.vstart import start_cluster

        cluster = await start_cluster(4)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "ecdrop", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            payloads = {}
            for i in range(12):
                oid = f"obj{i}"
                payloads[oid] = f"drop-{i}-".encode() * 120
                if i % 3 == 1:
                    # sever every osd-to-osd connection in the cluster
                    for osd in cluster.osds.values():
                        for conn in list(osd.messenger._out.values()):
                            conn.writer.close()
                await io.write_full(oid, payloads[oid], timeout=60)
            for oid, data in payloads.items():
                assert await io.read(oid, timeout=60) == data, oid

            # shard-level convergence: every acting member holds its
            # shard (replays after the drops land asynchronously —
            # converge-poll, then assert)
            def _all_shards_present() -> bool:
                for oid in payloads:
                    pgid = client.objecter.object_pgid(pool, oid)
                    _, _, acting, _ = \
                        client.objecter.osdmap.pg_to_up_acting_osds(pgid)
                    for o in acting:
                        if o >= 0 and o in cluster.osds and \
                                cluster.osds[o].store.stat(
                                    f"pg_{pgid.pool}_{pgid.seed}",
                                    oid) is None:
                            return False
                return True

            deadline = asyncio.get_event_loop().time() + 15.0
            while not _all_shards_present() and \
                    asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.05)
            for oid in payloads:
                pgid = client.objecter.object_pgid(pool, oid)
                _, _, acting, _ = \
                    client.objecter.osdmap.pg_to_up_acting_osds(pgid)
                for o in acting:
                    if o >= 0 and o in cluster.osds:
                        assert cluster.osds[o].store.stat(
                            f"pg_{pgid.pool}_{pgid.seed}", oid) is not None, \
                            (oid, o)
        finally:
            await cluster.stop()

    run(scenario())


def test_signed_cluster_end_to_end_and_rejects_unsigned():
    """cephx-lite: a secret-keyed cluster serves I/O normally; unsigned
    or tampered frames never reach a dispatcher."""
    async def scenario():
        from ceph_tpu.cluster.vstart import _fast_config, start_cluster

        cfg = _fast_config()
        cfg.auth_shared_secret = "sekrit"
        cluster = await start_cluster(3, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("authp", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"signed-payload" * 50)
            assert await io.read("obj") == b"signed-payload" * 50

            # an UNSIGNED client cannot talk to the signed cluster
            from ceph_tpu.cluster.objecter import RadosClient
            from ceph_tpu.utils import Config

            rogue = RadosClient(cluster.mon_addr, name="rogue",
                                config=Config())
            with pytest.raises((asyncio.TimeoutError, ConnectionError,
                                OSError, TimeoutError)):
                await asyncio.wait_for(rogue.connect(), timeout=3)
            await rogue.shutdown()
        finally:
            await cluster.stop()

    run(scenario())


def test_tampered_frame_rejected():
    async def scenario():
        rx = Messenger(EntityName("osd", 1), secret=b"k")
        coll = Collector()
        rx.add_dispatcher(coll)
        addr = await rx.bind()
        tx = Messenger(EntityName("osd", 2), secret=b"k")
        try:
            await tx.send_message(Num(n=1), addr)
            # converge-poll: the signed frame lands first
            deadline = asyncio.get_event_loop().time() + 10.0
            while coll.got != [1] and \
                    asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
            # flip a byte inside the next frame by writing raw garbage on
            # a fresh socket (wrong signature)
            import pickle as p
            import struct

            reader, writer = await asyncio.open_connection(*addr)
            m = Num(n=666)
            m.src = EntityName("osd", 3)
            payload = p.dumps(m) + b"\x00" * 16
            writer.write(struct.pack("<I", len(payload)) + payload)
            await writer.drain()
            # negative-condition window: give the rx loop the chance to
            # (wrongly) dispatch the forged frame — there is no positive
            # state to converge on when asserting an absence
            await asyncio.sleep(0.2)  # graftlint: ignore[fixed-sleep-in-tests]
            writer.close()
            assert coll.got == [1]      # forged 666 never dispatched
        finally:
            await tx.shutdown()
            await rx.shutdown()

    run(scenario())


from dataclasses import dataclass as _dataclass

from ceph_tpu.cluster.messenger import Message as _Message


@_dataclass
class _Blob(_Message):
    data: bytes = b""


def test_byte_throttle_backpressure():
    """VERDICT r4 weak #6: per-peer-type byte-budget backpressure — a
    slow dispatcher makes fast senders WAIT (socket drain stops) instead
    of growing an unbounded queue (reference osd_client_message_size_cap
    throttle, ceph_osd.cc:511-525)."""
    import asyncio

    from ceph_tpu.cluster.messenger import (
        EntityName, Messenger, Dispatcher, Policy, Throttle)

    async def scenario():
        gate = asyncio.Event()
        in_dispatch = []

        class Slow(Dispatcher):
            async def ms_dispatch(self, conn, msg):
                if isinstance(msg, _Blob):
                    in_dispatch.append(len(msg.data))
                    await gate.wait()
                    return True
                return False

        server = Messenger(EntityName("osd", 0))
        server.add_dispatcher(Slow())
        # budget admits ONE 64 KiB frame at a time
        server.set_policy("client", Policy(
            lossy=True, throttle=Throttle(100_000)))
        addr = await server.bind()
        senders = [Messenger(EntityName("client", i)) for i in (1, 2, 3)]
        try:
            for s in senders:
                await s.send_message(_Blob(data=b"x" * 65536), addr)
            loop = asyncio.get_event_loop()
            deadline = loop.time() + 10.0
            while len(in_dispatch) < 1 and loop.time() < deadline:
                await asyncio.sleep(0.02)
            # negative-condition window: the OTHER two frames must NOT
            # enter dispatch while the byte budget is held — an absence
            # has no positive state to converge on
            await asyncio.sleep(0.3)  # graftlint: ignore[fixed-sleep-in-tests]
            # only one frame admitted into dispatch; the rest backpressure
            assert len(in_dispatch) == 1, in_dispatch
            gate.set()
            deadline = loop.time() + 10.0
            while len(in_dispatch) < 3 and loop.time() < deadline:
                await asyncio.sleep(0.02)
            assert len(in_dispatch) == 3, in_dispatch
        finally:
            gate.set()
            for s in senders:
                await s.shutdown()
            await server.shutdown()

    asyncio.run(scenario())
