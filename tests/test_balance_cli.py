"""scripts/balance.py CLI: exit codes 0/1/2 (graft-balance satellite).

Usage errors (2) and one real ``status`` boot run as subprocesses, like
the trace/chaos CLI tests.  The operation-outcome codes (0 vs 1) are
driven in-band against a fake cluster so a stuck reshape or a commit
error doesn't need a real cluster wedged on purpose — the real grow /
drain / optimize flows are exercised end-to-end by the elastic chaos
scenarios (test_balance_elastic, scripts/chaos.py expand-drain-smoke).
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "balance.py")


def _load_cli():
    spec = importlib.util.spec_from_file_location("balance_cli", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ fakes


class _FakeIO:
    async def write_full(self, oid, data):
        pass


class _FakeClient:
    async def pool_create(self, name, kind, pg_num=8, size=3):
        return 1

    def ioctx(self, pool):
        return _FakeIO()


class _FakeMon:
    def _health_data(self):
        return {"status": "HEALTH_OK"}


class _FakeCluster:
    """Scripted mgr: each ``balance status`` poll pops the next canned
    reshape-op snapshot (the last one repeats, like a stuck op)."""

    def __init__(self, statuses, command_results=None):
        self.statuses = list(statuses)
        self.command_results = dict(command_results or {})
        self.commands = []
        self.booted = []
        self.stopped = False
        self.mon = _FakeMon()
        self.osds = {}

    async def daemon_command(self, name, cmd, timeout=30.0):
        prefix = cmd if isinstance(cmd, str) else cmd["prefix"]
        self.commands.append(cmd)
        if prefix == "balance status":
            ops = (self.statuses.pop(0) if len(self.statuses) > 1
                   else self.statuses[0])
            return {"reshape_ops": ops}
        return self.command_results[prefix]

    async def boot_osds(self, osd_ids, timeout=15.0):
        self.booted = list(osd_ids)

    async def stop(self):
        self.stopped = True


def _wire(mod, cluster):
    async def fake_boot(n_osds, osds_per_host=1):
        return cluster, _FakeClient()

    mod._boot = fake_boot
    mod.RESHAPE_DEADLINE = 2.0


def _run_main(mod, argv):
    old = sys.argv
    sys.argv = ["balance.py"] + argv
    try:
        return mod.main()
    finally:
        sys.argv = old


# --------------------------------------------------- exit 0 / 1 in-band


def test_grow_exit0_boots_minted_osds_and_waits_done(capsys):
    mod = _load_cli()
    cluster = _FakeCluster(
        statuses=[[{"id": 7, "kind": "grow", "osds": [3, 4],
                    "phase": "waiting-up", "detail": ""}],
                  [{"id": 7, "kind": "grow", "osds": [3, 4],
                    "phase": "done", "detail": "all new osds up"}]],
        command_results={"balance grow": {"id": 7, "kind": "grow",
                                          "osds": [3, 4],
                                          "phase": "waiting-up"}})
    _wire(mod, cluster)
    assert _run_main(mod, ["grow", "--count", "2"]) == 0
    # the CLI played the operator: booted exactly the minted ids
    assert cluster.booted == [3, 4]
    assert cluster.stopped
    assert "OK grew" in capsys.readouterr().out


def test_grow_exit1_when_reshape_op_stuck(capsys):
    mod = _load_cli()
    cluster = _FakeCluster(
        statuses=[[{"id": 7, "kind": "grow", "osds": [3],
                    "phase": "waiting-up", "detail": "1 of 1 not up"}]],
        command_results={"balance grow": {"id": 7, "kind": "grow",
                                          "osds": [3],
                                          "phase": "waiting-up"}})
    _wire(mod, cluster)
    assert _run_main(mod, ["grow", "--count", "1"]) == 1
    assert cluster.stopped
    assert "stuck in phase" in capsys.readouterr().err


def test_drain_exit0_stops_daemons_at_wait_down():
    mod = _load_cli()

    class _FakeOSD:
        def __init__(self):
            self.stopped = False

        async def stop(self):
            self.stopped = True

    osd = _FakeOSD()
    cluster = _FakeCluster(
        statuses=[[{"id": 2, "kind": "drain", "osds": [4],
                    "phase": "wait-clean", "detail": ""}],
                  [{"id": 2, "kind": "drain", "osds": [4],
                    "phase": "wait-down", "detail": "stop daemons"}],
                  [{"id": 2, "kind": "drain", "osds": [4],
                    "phase": "done", "detail": "purged 1 osds"}]],
        command_results={"balance drain": {"id": 2, "kind": "drain",
                                           "osds": [4],
                                           "phase": "wait-clean"}})
    cluster.osds[4] = osd
    _wire(mod, cluster)
    assert _run_main(mod, ["drain", "--osds", "4"]) == 0
    # the operator's half of the handshake happened: the retiring
    # daemon was stopped once the op said wait-down
    assert osd.stopped
    assert 4 not in cluster.osds


def test_drain_exit1_when_stuck_in_wait_clean():
    mod = _load_cli()
    cluster = _FakeCluster(
        statuses=[[{"id": 2, "kind": "drain", "osds": [4],
                    "phase": "wait-clean",
                    "detail": "3 pg slots still mapped"}]],
        command_results={"balance drain": {"id": 2, "kind": "drain",
                                           "osds": [4],
                                           "phase": "wait-clean"}})
    _wire(mod, cluster)
    assert _run_main(mod, ["drain", "--osds", "4"]) == 1


def test_optimize_exit_codes_commit_error_vs_clean(capsys):
    mod = _load_cli()
    cluster = _FakeCluster(
        statuses=[[]],
        command_results={"balance optimize": {
            "epoch": 9, "moves": 3, "dry_run": False,
            "commit_error": "TimeoutError('mon')"}})
    _wire(mod, cluster)
    assert _run_main(mod, ["optimize"]) == 1
    assert "FAIL commit" in capsys.readouterr().err

    cluster = _FakeCluster(
        statuses=[[]],
        command_results={"balance optimize": {
            "epoch": 9, "moves": 3, "dry_run": True}})
    _wire(mod, cluster)
    assert _run_main(mod, ["optimize", "--dry-run"]) == 0
    assert "OK planned 3 moves" in capsys.readouterr().out


def test_autoscale_exit0(capsys):
    mod = _load_cli()
    cluster = _FakeCluster(
        statuses=[[]],
        command_results={"balance autoscale": {
            "epoch": 5, "dry_run": False, "actions": [],
            "pools": {}}})
    _wire(mod, cluster)
    assert _run_main(mod, ["autoscale"]) == 0
    assert "OK autoscale" in capsys.readouterr().out


# ------------------------------------------------------- exit 2 (usage)


def test_usage_errors_exit2():
    """Bad arguments never boot a cluster and exit 2 — subprocess, so
    argparse's own exit path is covered too."""
    cases = [
        ["grow", "--count", "0"],            # non-positive grow
        ["grow", "--count", "-3"],
        ["drain", "--osds", "abc"],          # unparsable id list
        ["drain", "--osds", ""],             # empty id list
        ["drain", "--osds", "9"],            # outside the cluster
        ["drain", "--osds", "0,1,2,3,4"],    # would drain everything
        ["bogus"],                           # unknown subcommand
        ["grow"],                            # missing required --count
    ]
    for argv in cases:
        proc = subprocess.run(
            [sys.executable, SCRIPT] + argv,
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert proc.returncode == 2, (argv, proc.stdout, proc.stderr)
        assert "Traceback" not in proc.stderr, argv


# ------------------------------------------------------------ e2e smoke


def test_status_subprocess_real_cluster():
    """One real boot through the CLI: ``status --json`` against a live
    3-OSD cluster reports the subsystem disabled (loops off is the CLI
    contract) with the seeded pool visible to the autoscaler."""
    proc = subprocess.run(
        [sys.executable, SCRIPT, "status", "--osds", "3",
         "--pg-num", "8", "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["enabled"] is False and doc["autoscale_enabled"] is False
    assert doc["reshape_ops"] == []
    pools = doc["pools"]
    assert any(p.get("pool") == "balance" for p in pools.values())
