"""CRUSH text-map compiler/decompiler (round-4, VERDICT r3 missing #8).

Reference: src/crush/CrushCompiler.cc — the `crushtool -c`/`-d`
operator map language.  Round-trip fidelity is the gate: decompile ->
compile must reproduce identical PLACEMENTS (the semantics operators
care about), and a hand-written text map must compile and place.
"""

import subprocess
import sys

import pytest

from ceph_tpu.crush.compiler import compile_text, decompile
from ceph_tpu.crush.scalar import ScalarMapper
from ceph_tpu.crush.types import build_hierarchy

TEXT_MAP = """
# begin crush map
tunable choose_total_tries 50
tunable chooseleaf_descend_once 1
tunable chooseleaf_vary_r 1
tunable chooseleaf_stable 1

# devices
device 0 osd.0
device 1 osd.1 class ssd
device 2 osd.2
device 3 osd.3 class ssd

# types
type 0 osd
type 1 host
type 3 root

# buckets
host host0 {
    id -1
    alg straw2
    hash 0
    item osd.0 weight 1.000
    item osd.1 weight 1.000
}
host host1 {
    id -2
    alg straw2
    hash 0
    item osd.2 weight 1.000
    item osd.3 weight 2.000
}
root default {
    id -3
    alg straw2
    hash 0
    item host0 weight 2.000
    item host1 weight 3.000
}

# rules
rule replicated_rule {
    ruleset 0
    type replicated
    min_size 1
    max_size 10
    step take default
    step chooseleaf firstn 0 type host
    step emit
}
# end crush map
"""


def _placements(cmap, ruleno=0, n=200, numrep=2):
    sm = ScalarMapper(cmap)
    w = [0x10000] * cmap.max_devices
    return [sm.do_rule(ruleno, x, numrep, w) for x in range(n)]


def test_compile_hand_written_text_map():
    cmap = compile_text(TEXT_MAP)
    assert cmap.max_devices == 4
    assert cmap.device_class == {1: "ssd", 3: "ssd"}
    assert cmap.tunables.choose_total_tries == 50
    assert set(cmap.item_names.values()) == {"host0", "host1", "default"}
    assert cmap.buckets[-2].weights == [0x10000, 0x20000]
    maps = _placements(cmap)
    for m in maps:
        assert len(m) == 2
        # chooseleaf host: replicas land on distinct hosts
        assert ({m[0]} <= {0, 1}) != ({m[1]} <= {0, 1})


def test_round_trip_preserves_placements():
    cmap, ruleno = build_hierarchy(n_hosts=6, osds_per_host=3, numrep=3)
    text = decompile(cmap)
    cmap2 = compile_text(text)
    sm1 = _placements(cmap, ruleno, 300, 3)
    sm2 = _placements(cmap2, ruleno, 300, 3)
    assert sm1 == sm2, "round-tripped map changed placements"
    # and the text itself is stable across a second round trip
    assert decompile(cmap2) == text


def test_compile_rejects_bad_maps():
    with pytest.raises(ValueError):
        compile_text("tunable bogus_knob 1\n")
    with pytest.raises(ValueError):
        compile_text(TEXT_MAP.replace("step take default",
                                      "step take nonexistent"))
    with pytest.raises(ValueError):
        compile_text(TEXT_MAP.replace("alg straw2", "alg quantum"))


def test_crushtool_text_cli(tmp_path):
    src = tmp_path / "map.txt"
    src.write_text(TEXT_MAP)
    binfn = tmp_path / "map.bin"
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.tools.crushtool",
         "-i", str(src), "-o", str(binfn), "--compile"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.tools.crushtool",
         "-i", str(binfn), "--decompile"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    assert "step chooseleaf firstn 0 type host" in out.stdout
    assert "device 1 osd.1 class ssd" in out.stdout
    # the decompiled text recompiles to the same placements
    cmap2 = compile_text(out.stdout)
    assert _placements(cmap2) == _placements(compile_text(TEXT_MAP))
