"""Liberation-family codecs (liberation / blaum_roth / liber8tion) and the
wide-field (w in {16, 32}) matrix codes.

Mirrors the reference's typed jerasure tests over all techniques
(src/test/erasure-code/TestErasureCodeJerasure.cc:57-280): encode/decode
round-trips, exhaustive 2-erasure MDS sweeps, geometry rules, and
batch-vs-single consistency for the packet-interleaved layout.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec import factory
from ceph_tpu.ec.interface import ECError
from ceph_tpu.ec.liberation import (
    blaum_roth_coding_bitmatrix,
    liber8tion_coding_bitmatrix,
    liberation_coding_bitmatrix,
)
from ceph_tpu.ops.gfw import gf2_invert_matrix


def _mds_2erasure_sweep(codec):
    n = codec.get_chunk_count()
    data = bytes(range(256)) * 40
    chunks = codec.encode(range(n), data)
    for er in itertools.combinations(range(n), 2):
        avail = {i: v for i, v in chunks.items() if i not in er}
        dec = codec.decode(set(er), avail)
        for e in er:
            assert np.array_equal(dec[e], chunks[e]), er


@pytest.mark.parametrize("k,w", [(2, 3), (4, 7), (7, 7), (5, 11)])
def test_liberation_mds(k, w):
    codec = factory({"plugin": "jerasure", "technique": "liberation",
                     "k": str(k), "w": str(w), "packetsize": "4"})
    _mds_2erasure_sweep(codec)


@pytest.mark.parametrize("k,w", [(2, 4), (4, 4), (5, 6), (7, 10)])
def test_blaum_roth_mds(k, w):
    """MDS holds when w+1 is prime."""
    codec = factory({"plugin": "jerasure", "technique": "blaum_roth",
                     "k": str(k), "w": str(w), "packetsize": "4"})
    _mds_2erasure_sweep(codec)


@pytest.mark.parametrize("k", [2, 5, 8])
def test_liber8tion_mds(k):
    codec = factory({"plugin": "jerasure", "technique": "liber8tion",
                     "k": str(k), "packetsize": "4"})
    assert codec.w == 8 and codec.m == 2
    _mds_2erasure_sweep(codec)


def test_liberation_matrix_structure():
    w, k = 7, 4
    bm = liberation_coding_bitmatrix(k, w)
    assert bm.shape == (2 * w, k * w)
    # parity row 0 is [I I ... I]
    assert np.array_equal(bm[:w], np.tile(np.eye(w, dtype=np.uint8), (1, k)))
    # minimal density: block (1, 0) has w ones, blocks (1, j>0) have w+1
    for j in range(k):
        ones = int(bm[w:, j * w:(j + 1) * w].sum())
        assert ones == (w if j == 0 else w + 1), j


def test_blaum_roth_blocks_are_ring_powers():
    w, k = 4, 3
    bm = blaum_roth_coding_bitmatrix(k, w)
    b1 = bm[w:, w:2 * w]          # multiply-by-x
    b2 = bm[w:, 2 * w:3 * w]      # multiply-by-x^2
    assert np.array_equal((b1.astype(int) @ b1.astype(int)) % 2, b2)


def test_liberation_family_blocks_invertible():
    """The RAID-6 MDS conditions on the X blocks directly."""
    for bm, w, k in [
        (liberation_coding_bitmatrix(5, 7), 7, 5),
        (blaum_roth_coding_bitmatrix(5, 6), 6, 5),
        (liber8tion_coding_bitmatrix(6), 8, 6),
    ]:
        blocks = [bm[w:, j * w:(j + 1) * w] for j in range(k)]
        for x in blocks:
            gf2_invert_matrix(x)  # raises if singular
        for a, b in itertools.combinations(blocks, 2):
            gf2_invert_matrix(a ^ b)


def test_liberation_rejects_bad_profiles():
    with pytest.raises(ECError):   # w not prime
        factory({"plugin": "jerasure", "technique": "liberation",
                 "k": "4", "w": "8", "packetsize": "4"})
    with pytest.raises(ECError):   # k > w
        factory({"plugin": "jerasure", "technique": "liberation",
                 "k": "8", "w": "7", "packetsize": "4"})
    with pytest.raises(ECError):   # bad packetsize
        factory({"plugin": "jerasure", "technique": "liberation",
                 "k": "4", "w": "7", "packetsize": "3"})


def test_liberation_chunk_geometry():
    codec = factory({"plugin": "jerasure", "technique": "liberation",
                     "k": "4", "w": "7", "packetsize": "4"})
    # alignment = k*w*packetsize*sizeof(int) (reference get_alignment)
    assert codec.get_alignment() == 4 * 7 * 4 * 4
    cs = codec.get_chunk_size(1)
    assert cs % (7 * 4) == 0


def test_liberation_batch_matches_single():
    codec = factory({"plugin": "jerasure", "technique": "liberation",
                     "k": "4", "w": "7", "packetsize": "4"})
    n, k = codec.get_chunk_count(), 4
    S = 7 * 4 * 2
    rng = np.random.default_rng(31)
    batch = rng.integers(0, 256, (3, k, S), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(batch))
    for b in range(3):
        ch = {i: batch[b, i].copy() for i in range(k)}
        for i in range(k, n):
            ch[i] = np.zeros(S, dtype=np.uint8)
        codec.encode_chunks(ch)
        for i in range(n - k):
            assert np.array_equal(parity[b, i], ch[k + i])
    full = np.concatenate([batch, parity], axis=1)
    out = np.asarray(codec.decode_batch((0, k), full))
    assert np.array_equal(out[:, 0], batch[:, 0])
    assert np.array_equal(out[:, 1], parity[:, 0])


@pytest.mark.parametrize("w", [16, 32])
def test_wide_field_roundtrip(w):
    codec = factory({"plugin": "jerasure", "technique": "reed_sol_van",
                     "k": "4", "m": "2", "w": str(w)})
    data = bytes(range(256)) * 64
    n = codec.get_chunk_count()
    chunks = codec.encode(range(n), data)
    for er in itertools.combinations(range(n), 2):
        avail = {i: v for i, v in chunks.items() if i not in er}
        dec = codec.decode(set(er), avail)
        for e in er:
            assert np.array_equal(dec[e], chunks[e]), er


@pytest.mark.parametrize("w", [16, 32])
def test_wide_field_batch(w):
    codec = factory({"plugin": "jerasure", "technique": "reed_sol_van",
                     "k": "4", "m": "2", "w": str(w)})
    rng = np.random.default_rng(33)
    batch = rng.integers(0, 256, (4, 4, 64), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(batch))
    # batch bytes agree with the single-stripe path
    for b in range(4):
        ch = {i: batch[b, i].copy() for i in range(4)}
        for i in range(4, 6):
            ch[i] = np.zeros(64, dtype=np.uint8)
        codec.encode_chunks(ch)
        for i in range(2):
            assert np.array_equal(parity[b, i], ch[4 + i])
    full = np.concatenate([batch, parity], axis=1)
    out = np.asarray(codec.decode_batch((1, 5), full))
    assert np.array_equal(out[:, 0], batch[:, 1])
    assert np.array_equal(out[:, 1], parity[:, 1])


def test_wide_field_r6():
    codec = factory({"plugin": "jerasure", "technique": "reed_sol_r6_op",
                     "k": "4", "w": "16"})
    data = bytes(range(256)) * 16
    chunks = codec.encode(range(6), data)
    avail = {i: v for i, v in chunks.items() if i not in (2, 5)}
    dec = codec.decode({2, 5}, avail)
    assert np.array_equal(dec[2], chunks[2])
    assert np.array_equal(dec[5], chunks[5])


def test_blaum_roth_w7_encodes_but_is_not_mds():
    """Reference parity: w=7 (w+1 = 8, not prime) is tolerated for
    backward compatibility (ErasureCodeJerasure.cc:446-459) but the ring
    GF(2)[x]/M_8(x) = GF(2)[x]/(x-1)^7 makes x^i + x^j non-invertible, so
    double-DATA-erasure recovery must fail."""
    codec = factory({"plugin": "jerasure", "technique": "blaum_roth",
                     "k": "4", "w": "7", "packetsize": "4"})
    data = bytes(range(256)) * 40
    n = codec.get_chunk_count()
    chunks = codec.encode(range(n), data)   # encoding works
    avail = {i: v for i, v in chunks.items() if i not in (0, 1)}
    with pytest.raises(Exception):
        codec.decode({0, 1}, avail)
    # single erasures still recover (XOR row)
    avail = {i: v for i, v in chunks.items() if i != 2}
    dec = codec.decode({2}, avail)
    assert np.array_equal(dec[2], chunks[2])
