"""CephX-lite: tickets, session keys, caps (round-4 item 6).

Reference: src/auth/cephx/CephxProtocol.h:412 (tickets/authorizers),
CephxServiceHandler.h:23 (mon-side issuance), MonCap/OSDCap enforcement.
"""

import asyncio

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster import auth
from ceph_tpu.cluster.vstart import _fast_config, start_cluster


def run(coro):
    return asyncio.run(coro)


def _cephx_config():
    cfg = _fast_config()
    cfg.auth_shared_secret = "round4-cluster-master-key"
    cfg.auth_supported = "cephx"
    return cfg


@contention_retry()
def test_cluster_end_to_end_with_cephx():
    """The whole data path — pool create, replicated + EC I/O, snaps —
    runs over per-session keys issued through mon tickets."""
    async def scenario():
        cluster = await start_cluster(3, config=_cephx_config())
        try:
            client = await cluster.client()
            # the client really bootstrapped a ticket (no master key)
            mctx = client.objecter.messenger.auth
            assert mctx is not None and mctx.master is None
            assert mctx.ticket_blob is not None
            pool = await client.pool_create("authrepl", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            await io.write_full("obj", b"signed-per-session" * 10)
            assert await io.read("obj") == b"signed-per-session" * 10
            ecpool = await client.pool_create(
                "authec", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            eio = client.ioctx(ecpool)
            await eio.write_full("eobj", b"ec-under-cephx" * 100)
            assert await eio.read("eobj") == b"ec-under-cephx" * 100
            sid = await io.snap_create("s1")
            await io.write_full("obj", b"after")
            assert await io.read("obj", snapid=sid) == \
                b"signed-per-session" * 10
        finally:
            await cluster.stop()

    run(scenario())


def test_revoked_entity_refused():
    async def scenario():
        cluster = await start_cluster(2, config=_cephx_config())
        try:
            admin = await cluster.client()
            await admin.objecter.mon_command(
                {"prefix": "auth revoke", "entity": "client.mallory"})
            with pytest.raises((PermissionError, TimeoutError)):
                await cluster.client("mallory")
        finally:
            await cluster.stop()

    run(scenario())


def test_wrong_entity_key_refused():
    async def scenario():
        cfg = _cephx_config()
        cluster = await start_cluster(2, config=cfg)
        try:
            bad = _cephx_config()
            bad.auth_shared_secret = ""          # no master to derive from
            bad.auth_entity_key = "ab" * 32      # wrong key
            with pytest.raises((PermissionError, TimeoutError)):
                from ceph_tpu.cluster.objecter import RadosClient

                c = RadosClient(cluster.mon_addr, name="admin", config=bad)
                await c.connect()
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_expired_ticket_refused_then_renewal_works():
    async def scenario():
        cfg = _cephx_config()
        cluster = await start_cluster(2, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("exp", "replicated",
                                            pg_num=4, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"before-expiry")
            # forge expiry: replace the client's ticket with one already
            # past its TTL (sealed with the real service key, so only
            # the expiry check can reject it)
            master = cfg.auth_secret()
            mctx = client.objecter.messenger.auth
            blob, sealed, skey = auth.issue_ticket(
                master, "client.admin",
                auth.default_caps_for("client.admin"), ttl=-5.0)
            mctx.ticket_blob, mctx.session_key = blob, skey
            mctx.valid_until = 1e18    # lie so the client USES it
            # new connections present the expired ticket -> refused
            for m in list(client.objecter.messenger._out.values()):
                await m.close()
            client.objecter.messenger._out.clear()
            with pytest.raises((IOError, TimeoutError, ConnectionError)):
                await io.read("obj", timeout=4)
            # renewal: bootstrap a fresh ticket, traffic flows again
            mctx.ticket_blob = None
            mctx.valid_until = 0.0
            await client.objecter.messenger.cephx_bootstrap(
                cluster.mon_addr)
            for m in list(client.objecter.messenger._out.values()):
                await m.close()
            client.objecter.messenger._out.clear()
            assert await io.read("obj", timeout=30) == b"before-expiry"
        finally:
            await cluster.stop()

    run(scenario())


def test_caps_enforced_non_admin_cannot_mutate_mon():
    """A plain client entity gets mon 'r' caps: reads/subscriptions work
    but pool creation is EPERM (MonCap analog)."""
    async def scenario():
        cluster = await start_cluster(2, config=_cephx_config())
        try:
            admin = await cluster.client()
            pool = await admin.pool_create("capspool", "replicated",
                                           pg_num=4, size=2)
            plain = await cluster.client("plainuser")
            # osd rw allowed for plain clients
            pio = plain.ioctx(pool)
            await pio.write_full("obj", b"plain-write-ok")
            assert await pio.read("obj") == b"plain-write-ok"
            # mon mutation refused
            with pytest.raises(Exception) as ei:
                await plain.pool_create("forbidden", "replicated",
                                        pg_num=4, size=2)
            assert "EPERM" in str(ei.value) or "-1" in str(ei.value)
        finally:
            await cluster.stop()

    run(scenario())


def test_preauth_bytes_never_reach_deserializer():
    """ADVICE r4 (high): in cephx mode an unauthenticated peer must not
    be able to drive pickle.loads.  A raw socket sends (a) a pickled
    data frame with no handshake and (b) garbage handshake frames; the
    daemon must reset the connection without deserializing either, and
    stay healthy for real clients afterwards."""
    import pickle
    import struct

    async def scenario():
        cluster = await start_cluster(2, config=_cephx_config())
        try:
            client = await cluster.client()
            pool = await client.pool_create("sec", "replicated",
                                            pg_num=4, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"payload")

            executed = []

            class Evil:
                def __reduce__(self):
                    # the callable runs at pickle.LOADS time only
                    return (executed.append, ("deserialized",))

            some_osd = next(iter(cluster.osds.values()))
            addr = some_osd.messenger.my_addr
            # (a) pickled data frame, no handshake
            reader, writer = await asyncio.open_connection(addr[0], addr[1])
            evil = b"\x00" + pickle.dumps(Evil())
            writer.write(struct.pack("<I", len(evil)) + evil)
            await writer.drain()
            assert await reader.read(64) == b""  # peer reset, no reply
            writer.close()
            # (b) malformed handshake frames (types 1-3, junk bodies)
            for t in (1, 2, 3, 77):
                reader, writer = await asyncio.open_connection(
                    addr[0], addr[1])
                junk = bytes([t]) + b"\xff" * 11
                writer.write(struct.pack("<I", len(junk)) + junk)
                await writer.drain()
                assert await reader.read(64) == b""
                writer.close()
            # daemon still healthy for authenticated traffic
            assert await io.read("obj", timeout=30) == b"payload"
            assert not executed, "unauthenticated pickle was deserialized"
        finally:
            await cluster.stop()

    run(scenario())
