"""Round-18 client-edge batching: per-(session, OSD) op-frame
coalescing with batched replies.

Unit level: the objecter's OpBatcher coalesces a tick's ops to one OSD
into ONE MOSDOpBatch frame (a lone op ships the plain legacy MOSDOp),
and the reply-batch scatter resolves each item's future individually —
per-item ``throttled`` flags preserved, a reqid ABSENT from the reply
tick left pending (the SubWriteBatcher un-ack rule at the client edge).

Cluster level: a mid-batch THROTTLED item shrinks only its own op's
window accounting while its tick-mates ack through, and a mid-batch
expired-deadline item is shed OSD-side with zero acked-past-deadline.
"""

import asyncio

from tests._flaky import contention_retry

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.objecter import Objecter
from ceph_tpu.cluster.vstart import _fast_config, start_cluster
from ceph_tpu.utils import Config


def run(coro):
    return asyncio.run(coro)


def _mk_objecter(**cfg) -> Objecter:
    """An objecter with a live event loop but no cluster: the unit
    seams (OpBatcher, reply scatter) never touch the wire."""
    return Objecter("cbt", ("127.0.0.1", 1), config=Config(**cfg))


# ------------------------------------------------------------- unit level


def test_reply_batch_scatters_per_item_preserving_throttled_and_absence():
    """One MOSDOpReplyBatch resolves each item's future with ITS reply
    (throttled flag intact); an inflight reqid absent from the tick
    stays PENDING — its op's own timeout/resend covers it."""

    async def scenario():
        obj = _mk_objecter()
        loop = asyncio.get_event_loop()
        futs = {i: loop.create_future() for i in range(4)}
        for i, fut in futs.items():
            obj._inflight[("c", i)] = fut
        await obj.ms_dispatch(None, M.MOSDOpReplyBatch(items=[
            M.MOSDOpReply(reqid=("c", 0), result=0, data=b"a"),
            M.MOSDOpReply(reqid=("c", 1), result=M.THROTTLED,
                          throttled=True),
            M.MOSDOpReply(reqid=("c", 2), result=-2),
            # reqid 3 deliberately absent: shed on the OSD
        ]))
        assert futs[0].result().result == 0
        assert futs[0].result().data == b"a"
        assert futs[1].result().throttled is True
        assert futs[1].result().result == M.THROTTLED
        assert futs[2].result().result == -2
        assert not futs[3].done(), "absent item must stay un-acked"
        assert ("c", 3) in obj._inflight
        fc = obj.flow_counters()
        assert fc["client_batch_reply_frames"] == 1
        assert fc["client_batch_reply_items"] == 3

    run(scenario())


def test_op_batcher_coalesces_per_osd_and_lone_op_ships_plain_frame():
    """Concurrent sends to one OSD pack into MOSDOpBatch frames (with
    the amortized client_batch_wait/send trace stamps); a lone op to
    another OSD ships the plain legacy MOSDOp, unstamped."""

    async def scenario():
        obj = _mk_objecter(objecter_batch_tick_ops=8)
        sent = []

        async def fake_send(msg, addr):
            sent.append((addr, msg))

        obj.messenger.send_message = fake_send
        addr_a, addr_b = ("10.0.0.1", 1), ("10.0.0.2", 2)

        def op(tid):
            m = M.MOSDOp(reqid=("c", tid), pgid=None, oid=f"o{tid}",
                         ops=[("write_full", {"data": b"x"})], epoch=7)
            m.trace = {"id": f"t{tid}", "events": []}
            return m

        await asyncio.gather(*[obj._send_op(op(i), addr_a)
                               for i in range(5)],
                             obj._send_op(op(99), addr_b))
        a_frames = [m for a, m in sent if a == addr_a]
        b_frames = [m for a, m in sent if a == addr_b]
        # OSD b saw a lone op: the plain legacy frame, no batch stamps
        assert len(b_frames) == 1 and isinstance(b_frames[0], M.MOSDOp)
        assert all(name not in ("objecter:batch_tick",
                                "objecter:batch_sent")
                   for name, _ in b_frames[0].trace["events"])
        # OSD a saw >= 1 frame covering all 5 ops; the multi-item ones
        # are MOSDOpBatch with per-item amortized stamps
        items = []
        for m in a_frames:
            if isinstance(m, M.MOSDOpBatch):
                assert m.epoch == 7
                for it in m.items:
                    names = [n for n, _ in it.trace["events"]]
                    assert "objecter:batch_tick" in names
                    assert "objecter:batch_sent" in names
                items.extend(m.items)
            else:
                items.append(m)
        assert {it.reqid[1] for it in items} == set(range(5))
        fc = obj.flow_counters()
        assert fc["client_batch_ticks"] >= 1
        assert fc["client_batch_ops"] >= 2
        await obj.stop()

    run(scenario())


def test_op_batcher_zero_gate_keeps_legacy_per_op_frames():
    """objecter_batch_tick_ops=0 (the anchor): every op ships its own
    MOSDOp frame and the batcher is never armed."""

    async def scenario():
        obj = _mk_objecter()  # zero-default gate
        sent = []

        async def fake_send(msg, addr):
            sent.append(msg)

        obj.messenger.send_message = fake_send
        await asyncio.gather(*[
            obj._send_op(M.MOSDOp(reqid=("c", i), pgid=None, oid="o",
                                  ops=[("read", {})], epoch=1),
                         ("10.0.0.1", 1))
            for i in range(4)])
        assert len(sent) == 4
        assert all(isinstance(m, M.MOSDOp) for m in sent)
        assert not obj._op_batcher._workers
        assert obj.flow_counters()["client_batch_ticks"] == 0

    run(scenario())


def test_op_batcher_send_failure_fails_only_that_tick():
    """A frame-send failure surfaces on every op OF THAT TICK (their
    resend machinery owns recovery); later ticks send normally."""

    async def scenario():
        obj = _mk_objecter(objecter_batch_tick_ops=8)
        calls = []

        async def flaky_send(msg, addr):
            calls.append(msg)
            if len(calls) == 1:
                raise ConnectionError("wire down")

        obj.messenger.send_message = flaky_send

        def op(tid):
            return M.MOSDOp(reqid=("c", tid), pgid=None, oid="o",
                            ops=[("read", {})], epoch=1)

        results = await asyncio.gather(
            *[obj._send_op(op(i), ("10.0.0.1", 1)) for i in range(3)],
            return_exceptions=True)
        assert any(isinstance(r, ConnectionError) for r in results)
        # the batcher recovered: a fresh op rides a fresh tick
        await obj._send_op(op(9), ("10.0.0.1", 1))
        assert len(calls) >= 2
        await obj.stop()

    run(scenario())


def test_client_batch_attribution_stage_math():
    """The client-edge amortized marks: client_batch_wait +
    client_batch_send partition the send->tick window exactly like
    batch_wait/batch_encode, and stage sums equal the traced total."""
    from ceph_tpu.trace.attribution import attribute_events

    # op sent to the coalescer at t=1.0; its tick built 2.0 -> 2.6
    # packing 3 ops: the op books (2.6-2.0)/3 as its send share
    share = (2.6 - 2.0) / 3
    evs = [(0.0, "objecter:submit"), (1.0, "objecter:send"),
           (2.6 - share, "objecter:batch_tick"),
           (2.6, "objecter:batch_sent"),
           (2.7, "msgr:osd.0:recv"), (2.9, "done")]
    stages, total = attribute_events(evs)
    assert abs(sum(stages.values()) - total) < 1e-9
    assert abs(stages["client_batch_send"] - share) < 1e-9
    assert abs(stages["client_batch_wait"] - (1.6 - share)) < 1e-9
    assert stages["wire"] > 0


def test_fast_config_enables_client_batching_and_plain_config_does_not():
    """vstart clusters run the client-edge coalescer; plain Config()
    keeps the per-op frame anchor (the bisection rule every batching
    layer follows)."""
    cfg = _fast_config()
    assert cfg.objecter_batch_tick_ops > 0
    assert Config().objecter_batch_tick_ops == 0


# ---------------------------------------------------------- cluster level


@contention_retry()
def test_mid_batch_throttled_item_does_not_collapse_tick_mates():
    """Tight OSD admission under client batching: THROTTLED pushback
    arrives per ITEM inside the batched reply, so tick-mates ack
    normally — every write eventually succeeds, pushbacks are counted,
    and the window is pushback-per-item (far fewer pushbacks than if
    each throttled reply frame marked its whole tick)."""

    async def scenario():
        cfg = _fast_config()
        cfg.osd_op_throttle_ops = 2
        cluster = await start_cluster(3, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("cbt", pg_num=8, size=3)
            io = client.ioctx(pool)
            await asyncio.gather(*[
                io.write_full(f"o{i}", bytes([i]) * 2048, timeout=60)
                for i in range(16)])
            # all acked: nothing was lost to a frame-wide pushback
            datas = await asyncio.gather(*[io.read(f"o{i}")
                                           for i in range(16)])
            assert all(datas[i] == bytes([i]) * 2048
                       for i in range(16))
            fc = client.objecter.flow_counters()
            return fc
        finally:
            await cluster.stop()

    fc = run(scenario())
    assert fc["client_batch_ticks"] > 0, "ops never coalesced"
    assert fc["client_cwnd_pushbacks"] > 0, \
        "throttle budget never pushed back (test lost its pressure)"
    # per-item accounting: acks >= the 32 data ops + their retries'
    # successes; window recovered (additive increase after the acks)
    assert fc["client_ops_acked"] >= 32
    assert fc["client_cwnd"] >= 1


@contention_retry()
def test_mid_batch_expired_item_unacks_only_itself():
    """Six coalesced writes to one hot object through a 2 op/s mclock
    limit: the queue tail expires mid-batch, the OSD sheds those at
    dequeue so they are ABSENT from the reply tick (only their clients
    time out), and zero ops ack past their deadline — the round-18
    per-item un-ack rule under real pacing."""

    async def scenario():
        config = _fast_config()
        config.osd_op_queue = "mclock"
        cluster = await start_cluster(3, config=config)
        try:
            client = await cluster.client()
            pool = await client.pool_create("cbx", pg_num=4, size=3)
            io = client.ioctx(pool)
            await io.write_full("hot", b"warm")
            entity = client.objecter.client_name.split("#", 1)[0]
            for osd in cluster.osds.values():
                osd.set_qos(entity, reservation=0.0, weight=1.0,
                            limit=2.0)
            loop = asyncio.get_event_loop()
            deadline_s = 1.2
            late_acks = []

            async def put(i):
                t0 = loop.time()
                try:
                    await io.write_full("hot", bytes([i]) * 512,
                                        timeout=deadline_s)
                except (IOError, OSError, TimeoutError):
                    return 0
                if loop.time() - t0 > deadline_s + 0.25:
                    late_acks.append(i)
                return 1

            acked = sum(await asyncio.gather(
                *[put(i) for i in range(6)]))
            deadline = loop.time() + 10.0
            shed = 0
            while loop.time() < deadline:
                shed = sum(o.perf.get("osd_ops_shed_expired")
                           for o in cluster.osds.values())
                if shed > 0:
                    break
                await asyncio.sleep(0.05)
            fc = client.objecter.flow_counters()
            return acked, shed, late_acks, fc
        finally:
            await cluster.stop()

    acked, shed, late_acks, fc = run(scenario())
    assert fc["client_batch_ticks"] > 0, "ops never coalesced"
    assert late_acks == [], f"ops acked past deadline: {late_acks}"
    assert shed > 0, "expired queued ops executed instead of shed"
    assert acked >= 1  # the head of the queue still made it
