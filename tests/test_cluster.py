"""Cluster-layer tests: mon + OSDs on loopback, replicated + EC pools,
failure/recovery.

The tier-3 analog of the reference's qa/standalone cluster bash tests
(qa/standalone/erasure-code/test-erasure-code.sh:21-53): real daemons, real
sockets, one host.  Exercises every message family in
ceph_tpu/cluster/messages.py: boot/subscribe/map (MOSDBoot, MMonSubscribe,
MOSDMapMsg), commands (MMonCommand/Reply), client ops (MOSDOp/Reply),
replication (MOSDRepOp/Reply), EC shard I/O (MOSDECSubOpWrite/Read + Reply),
failure detection (MPing, MOSDFailure), and recovery (MOSDPGPush/Reply).
"""

import asyncio

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster.osd import OSDDaemon
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


EC_PROFILE = {"plugin": "jerasure", "technique": "reed_sol_van",
              "k": "2", "m": "1"}


def test_replicated_put_get_delete():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("repl", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            payload = b"replicated-payload" * 100
            await io.write_full("obj1", payload)
            assert await io.read("obj1") == payload
            assert await io.stat("obj1") == len(payload)
            # overwrite
            await io.write_full("obj1", b"short")
            assert await io.read("obj1") == b"short"
            await io.remove("obj1")
            with pytest.raises(FileNotFoundError):
                await io.read("obj1")
            # data must exist on every acting replica, not just the
            # primary (converge-poll to a wall deadline: ack precedes
            # the last store applies only by scheduler noise, but a
            # fixed beat flaked under host load)
            pgid = client.objecter.object_pgid(pool, "obj2")
            await io.write_full("obj2", b"fanout")
            _, _, acting, _ = client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            coll = f"pg_{pgid.pool}_{pgid.seed}"

            def _holders():
                return [o for o in acting
                        if cluster.osds[o].store.stat(coll, "obj2")
                        is not None]

            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline and \
                    _holders() != list(acting):
                await asyncio.sleep(0.05)
            assert _holders() == list(acting), \
                f"replicas missing: {_holders()} vs acting {acting}"
        finally:
            await cluster.stop()

    run(scenario())


def test_ec_put_get():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("ecpool", "erasure", pg_num=8,
                                            ec_profile=EC_PROFILE)
            io = client.ioctx(pool)
            payload = bytes(range(256)) * 64
            await io.write_full("ecobj", payload)
            assert await io.read("ecobj") == payload
            assert await io.stat("ecobj") == len(payload)
            # each acting OSD holds exactly one shard, not the full object
            pgid = client.objecter.object_pgid(pool, "ecobj")
            _, _, acting, _ = client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            from ceph_tpu.crush.types import CRUSH_ITEM_NONE
            for shard, osd in enumerate(acting):
                if osd == CRUSH_ITEM_NONE:
                    continue
                size = cluster.osds[osd].store.stat(coll, "ecobj")
                assert size is not None and size < len(payload)
                attr = cluster.osds[osd].store.getattr(coll, "ecobj", "shard")
                assert int(attr) == shard
        finally:
            await cluster.stop()

    run(scenario())


def test_ec_read_with_dead_shard():
    """Kill an OSD; reads must reconstruct the lost shard from survivors
    (the SURVEY §7.5 acceptance scenario: decode path under failure)."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("ecpool", "erasure", pg_num=8,
                                            ec_profile=EC_PROFILE)
            io = client.ioctx(pool)
            objects = {f"obj{i}": bytes([i]) * (1000 + i) for i in range(8)}
            for oid, data in objects.items():
                await io.write_full(oid, data)
            victim = 2
            await cluster.kill_osd(victim)
            await cluster.wait_down(victim)
            # misdirected ops resend against the refreshed map; reads on PGs
            # that lost a shard decode from the k survivors
            for oid, data in objects.items():
                assert await io.read(oid) == data, oid
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_failure_detection_marks_down():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            victim = 1
            assert cluster.mon.osdmap.osd_up[victim]
            await cluster.kill_osd(victim)
            # peers' heartbeats stop acking -> MOSDFailure -> mon marks down
            await cluster.wait_down(victim)
            assert not cluster.mon.osdmap.osd_up[victim]
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_down_out_rebalance_and_recovery():
    """Down OSD is auto-outed by the mon tick; replicated PGs remap and the
    new acting set is backfilled by primary-driven recovery."""
    async def scenario():
        cluster = await start_cluster(4, osds_per_host=1)
        try:
            client = await cluster.client()
            pool = await client.pool_create("repl", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            objects = {f"o{i}": bytes([i]) * 500 for i in range(12)}
            for oid, data in objects.items():
                await io.write_full(oid, data)
            victim = 0
            await cluster.kill_osd(victim)
            await cluster.wait_down(victim)
            # wait for auto-out (mon_osd_down_out_interval=2s) + remap
            deadline = asyncio.get_event_loop().time() + 15
            while asyncio.get_event_loop().time() < deadline:
                if cluster.mon.osdmap.osd_weight[victim] == 0:
                    break
                await asyncio.sleep(0.1)
            assert cluster.mon.osdmap.osd_weight[victim] == 0, "never auto-outed"
            # converge-poll instead of a fixed recovery-window sleep
            # (load-deflake round 11: the invariant stays strict, only
            # the wall clock is relaxed): wait until the client's map
            # has remapped every PG off the victim
            from ceph_tpu.osdmap.osdmap import PGid

            def _remapped():
                m = client.objecter.osdmap
                return all(
                    victim not in m.pg_to_up_acting_osds(
                        PGid(pool, seed))[2]
                    for seed in range(8))

            deadline = asyncio.get_event_loop().time() + 20
            while asyncio.get_event_loop().time() < deadline \
                    and not _remapped():
                await asyncio.sleep(0.1)
            assert _remapped(), "PGs never remapped off the out OSD"
            # every object still readable; every PG's acting set avoids victim
            for oid, data in objects.items():
                assert await io.read(oid) == data, oid
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_ec_recovery_rebuilds_lost_shards():
    """Kill an OSD holding shards, revive it empty: primary-driven EC
    recovery re-encodes and pushes the missing shard back
    (ECBackend::run_recovery_op analog)."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("ecpool", "erasure", pg_num=4,
                                            ec_profile=EC_PROFILE)
            io = client.ioctx(pool)
            objects = {f"e{i}": bytes([i + 1]) * 900 for i in range(6)}
            for oid, data in objects.items():
                await io.write_full(oid, data)
            victim = 1
            await cluster.kill_osd(victim)
            await cluster.wait_down(victim)
            # revive with an EMPTY store: boot -> map -> recovery repushes
            await cluster.revive_osd(victim)
            deadline = asyncio.get_event_loop().time() + 15
            revived = cluster.osds[victim]

            def victim_shard_count():
                n = 0
                for seed in range(4):
                    coll = f"pg_{pool}_{seed}"
                    n += len(revived.store.list_objects(coll))
                return n

            # count how many shards the victim *should* hold
            while asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.2)
                if victim_shard_count() >= 1:
                    break
            assert victim_shard_count() >= 1, "no shards recovered to revived OSD"
            for oid, data in objects.items():
                assert await io.read(oid) == data, oid
        finally:
            await cluster.stop()

    run(scenario())


def test_mon_status_and_perf_dump():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            status = await client.status()
            assert status["num_osds"] == 3
            assert status["num_up"] == 3
            perf = await client.objecter.mon_command({"prefix": "perf dump"})
            assert perf["mon"]["mon_osd_boot"] >= 3
            with pytest.raises(RuntimeError):
                await client.objecter.mon_command({"prefix": "bogus"})
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_client_misdirect_resend():
    """Write through a client whose map predates a pool's remap: the OSD
    replies -EAGAIN-style misdirect and the client refreshes + resends."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("repl", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            await io.write_full("mis", b"first")
            # stale-map simulation: client keeps targeting with an old map
            # while the cluster loses an OSD
            victim = 0
            await cluster.kill_osd(victim)
            await cluster.wait_down(victim)
            # converge-poll (round 18 deflake): wait until every
            # SURVIVING OSD's map marks the victim down — the remapped
            # primary must know it owns the PG before the stale client
            # retargets, and on a loaded host that propagation can
            # outlive any fixed sleep
            loop = asyncio.get_event_loop()
            deadline = loop.time() + 10.0
            while loop.time() < deadline and any(
                    o.osdmap is None or o.osdmap.is_up(victim)
                    for oid, o in cluster.osds.items() if oid != victim):
                await asyncio.sleep(0.05)
            # ops keep succeeding despite the stale cached map (resend loop)
            await io.write_full("mis", b"second")
            assert await io.read("mis") == b"second"
        finally:
            await cluster.stop()

    run(scenario())


def test_ec_partial_write_rmw():
    """Overwrite a sub-range of an EC object: read-modify-write over stripe
    bounds (reference ECBackend::start_rmw, ECBackend.cc:1785)."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            profile = dict(EC_PROFILE, stripe_unit="64")
            pool = await client.pool_create("ecpool", "erasure", pg_num=4,
                                            ec_profile=profile)
            io = client.ioctx(pool)
            base = bytes(range(256)) * 4  # 1024 bytes = 8 stripes of 128
            await io.write_full("rmw", base)
            # unaligned overwrite inside one stripe
            patch = b"X" * 50
            await io.write("rmw", patch, offset=200)
            expect = bytearray(base)
            expect[200:250] = patch
            assert await io.read("rmw") == bytes(expect)
            # overwrite spanning stripe boundaries
            patch2 = b"Y" * 300
            await io.write("rmw", patch2, offset=100)
            expect[100:400] = patch2
            assert await io.read("rmw") == bytes(expect)
            # appending extension past the old end
            tail = b"Z" * 77
            await io.write("rmw", tail, offset=len(expect) + 31)
            expect_full = bytes(expect) + b"\0" * 31 + tail
            assert await io.read("rmw") == expect_full
            assert await io.stat("rmw") == len(expect_full)
            # range reads
            assert await io.read("rmw", offset=150, length=100) == \
                expect_full[150:250]
            assert await io.read("rmw", offset=1000) == expect_full[1000:]
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_ec_rmw_survives_shard_loss():
    """RMW then kill an OSD: the modified object decodes correctly from the
    survivors (stripe-consistent shards)."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            profile = dict(EC_PROFILE, stripe_unit="64")
            pool = await client.pool_create("ecpool", "erasure", pg_num=4,
                                            ec_profile=profile)
            io = client.ioctx(pool)
            base = b"A" * 640
            await io.write_full("obj", base)
            await io.write("obj", b"B" * 128, offset=256)
            expect = b"A" * 256 + b"B" * 128 + b"A" * 256
            victim = 0
            await cluster.kill_osd(victim)
            await cluster.wait_down(victim)
            assert await io.read("obj") == expect
        finally:
            await cluster.stop()

    run(scenario())


def test_replicated_partial_write():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("repl", "replicated",
                                            pg_num=4, size=2)
            io = client.ioctx(pool)
            await io.write_full("p", b"0123456789")
            await io.write("p", b"AB", offset=3)
            assert await io.read("p") == b"012AB56789"
            assert await io.read("p", offset=2, length=4) == b"2AB5"
        finally:
            await cluster.stop()

    run(scenario())


def test_map_distribution_is_incremental():
    """After the initial full map, epoch churn ships deltas: the number of
    full maps sent stays bounded by subscriber joins, not by epochs."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            for i in range(4):
                await client.pool_create(f"p{i}", "replicated", pg_num=4,
                                         size=2)
            perf = cluster.mon.perf.dump()["mon"]
            # 3 OSD subscribes + 1 client subscribe = at most a handful of
            # full maps; the pool-create broadcasts must all be incremental
            assert perf.get("mon_inc_maps_sent", 0) >= 8, perf
            assert perf.get("mon_full_maps_sent", 0) <= 6, perf
            # clients converge on the same epoch as the mon
            await client.objecter._refresh_map()
            assert client.objecter.osdmap.epoch == cluster.mon.osdmap.epoch
        finally:
            await cluster.stop()

    run(scenario())


def test_delta_recovery_counts():
    async def scenario():
        from ceph_tpu.cluster.vstart import _fast_config

        cfg = _fast_config()
        cfg.mon_osd_down_out_interval = 60.0
        cluster = await start_cluster(4, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("repl", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            total = 24
            for i in range(total):
                await io.write_full(f"obj{i}", f"payload-{i}".encode() * 50)

            target = 1
            # stop the daemon but KEEP its store for the restart
            stopped = cluster.osds.pop(target)
            store = stopped.store
            await stopped.stop()
            await cluster.wait_down(target)

            delta = {f"new{i}": f"delta-{i}".encode() * 80 for i in range(3)}
            for oid, data in delta.items():
                await io.write_full(oid, data)
            await io.write_full("obj0", b"obj0-rewritten" * 40)

            before = sum(o.perf.get("osd_pushes_sent") or 0
                         for o in cluster.osds.values())
            osd = OSDDaemon(target, cluster.mon_addr, config=cfg, store=store)
            await osd.start()
            cluster.osds[target] = osd
            # wait for the mon to mark it up + peers to recover it
            deadline = asyncio.get_event_loop().time() + 15
            while asyncio.get_event_loop().time() < deadline:
                if cluster.mon.osdmap.osd_up[target]:
                    break
                await asyncio.sleep(0.05)

            # converge-poll instead of a fixed recovery-window sleep
            # (load-deflake round 11): wait until the rejoined member
            # actually holds every delta byte it is acting for — the
            # strict invariant — with a generous wall deadline
            def _member_oids():
                out = []
                for oid, data in delta.items():
                    pgid = client.objecter.object_pgid(pool, oid)
                    _, _, acting, _ = \
                        client.objecter.osdmap.pg_to_up_acting_osds(pgid)
                    if target in acting:
                        out.append((f"pg_{pgid.pool}_{pgid.seed}",
                                    oid, data))
                return out

            def _caught_up():
                try:
                    return all(osd.store.read(coll, oid) == data
                               for coll, oid, data in _member_oids())
                except FileNotFoundError:
                    return False  # push not applied yet

            def _pushes():
                after = sum(o.perf.get("osd_pushes_sent") or 0
                            for o in cluster.osds.values()
                            if o is not osd)
                return after - before

            # recovery must have actually pushed something AND the
            # member must hold the delta bytes (pushes>0 guards the
            # vacuous case where no delta object maps to the member)
            deadline = asyncio.get_event_loop().time() + 20
            while asyncio.get_event_loop().time() < deadline and \
                    not (_caught_up() and _pushes() > 0):
                await asyncio.sleep(0.1)
            assert _caught_up(), "rejoined member never caught up"

            pushes = _pushes()
            changed = len(delta) + 1  # new0..2 + obj0 rewrite
            # delta resync: push count tracks the CHANGED objects, far
            # below the total object count.  Upper bound allows seeded
            # recovery-round retries under host load (each retry may
            # re-push); the strict discriminator is pushes < total
            assert 0 < pushes <= changed * 6, (pushes, changed)
            assert pushes < total, (pushes, total)
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_concurrent_writes_during_restart_converge():
    """Concurrent writers + a member bounce: every acting replica ends
    byte-identical (per-PG ordering + log-delta resync)."""
    async def scenario():
        from ceph_tpu.cluster.vstart import _fast_config

        cfg = _fast_config()
        cfg.mon_osd_down_out_interval = 60.0
        cluster = await start_cluster(4, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("repl", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            stop_evt = asyncio.Event()

            done = [0]      # completed write rounds across both writers

            async def writer(tag):
                i = 0
                while not stop_evt.is_set():
                    for oid in ("shared-a", "shared-b"):
                        try:
                            await io.write_full(
                                oid, f"{tag}-{i}-".encode() * 100)
                            done[0] += 1
                        except Exception:
                            pass
                    i += 1
                    await asyncio.sleep(0.01)

            async def _writes_past(mark, n, timeout=15.0):
                # converge on OBSERVED write progress instead of fixed
                # beats: the scenario needs writes to really land in
                # each phase (down / recovering), and a timed window
                # under host load sometimes contained none
                deadline = asyncio.get_event_loop().time() + timeout
                while asyncio.get_event_loop().time() < deadline and \
                        done[0] < mark + n:
                    await asyncio.sleep(0.05)
                return done[0]

            writers = [asyncio.get_event_loop().create_task(writer(t))
                       for t in ("w1", "w2")]
            await _writes_past(0, 4)
            target = 2
            stopped = cluster.osds.pop(target)
            store = stopped.store
            await stopped.stop()
            await cluster.wait_down(target)
            mark = done[0]
            await _writes_past(mark, 4)   # writes flow while down
            osd = OSDDaemon(target, cluster.mon_addr, config=cfg, store=store)
            await osd.start()
            cluster.osds[target] = osd
            deadline = asyncio.get_event_loop().time() + 15
            while asyncio.get_event_loop().time() < deadline:
                if cluster.mon.osdmap.osd_up[target]:
                    break
                await asyncio.sleep(0.05)
            mark = done[0]
            await _writes_past(mark, 4)   # writes overlap the resync
            stop_evt.set()
            await asyncio.gather(*writers)

            # converge-poll instead of a fixed recovery-window sleep
            # (load-deflake round 11): replicas must END byte-identical
            # — strict — but recovery gets a generous wall deadline
            def _replica_sets():
                out = {}
                for oid in ("shared-a", "shared-b"):
                    pgid = client.objecter.object_pgid(pool, oid)
                    coll = f"pg_{pgid.pool}_{pgid.seed}"
                    _, _, acting, _ = \
                        client.objecter.osdmap.pg_to_up_acting_osds(pgid)
                    out[oid] = {o: bytes(
                        cluster.osds[o].store.read(coll, oid))
                        for o in acting}
                return out

            deadline = asyncio.get_event_loop().time() + 20
            while asyncio.get_event_loop().time() < deadline:
                if all(len(set(blobs.values())) == 1
                       for blobs in _replica_sets().values()):
                    break
                await asyncio.sleep(0.2)
            for oid, blobs in _replica_sets().items():
                assert len(set(blobs.values())) == 1, \
                    (oid, {k: v[:20] for k, v in blobs.items()})
        finally:
            await cluster.stop()

    run(scenario())
