"""Round-16 integrity & self-healing: read-repair, scheduled scrub,
inconsistent->clean health flow, cluster-full graceful degradation, and
the seeded integrity scenarios (bitrot-under-load / disk-fill-drain).
"""

import asyncio

import pytest

from tests._flaky import contention_retry

from ceph_tpu.chaos.disk import DiskInjector
from ceph_tpu.chaos.rng import stream
from ceph_tpu.cluster.store import MemStore, Transaction
from ceph_tpu.cluster.vstart import _fast_config, start_cluster
from ceph_tpu.ops import crc32c as crcmod


def run(coro):
    return asyncio.run(coro)


EC21 = {"plugin": "jerasure", "technique": "reed_sol_van",
        "k": "2", "m": "1"}


async def _converge_poll(fn, timeout=20.0, interval=0.05):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        v = fn()
        if v:
            return v
        await asyncio.sleep(interval)
    return fn()


# ------------------------------------------------------- memstore capacity


def test_memstore_capacity_enforced_and_accounted():
    """The used counter tracks write/truncate/clone/remove exactly, a
    growing txn past capacity refuses WHOLE with ENOSPC (atomicity),
    and shrink/delete txns always admit (the dig-yourself-out rule)."""
    st = MemStore(device_bytes=10000)
    st.queue_transaction(Transaction().write("c", "a", 0, b"x" * 4000))
    st.queue_transaction(Transaction().write("c", "b", 0, b"y" * 4000))
    assert st.statfs() == (10000, 8000)
    # growth past capacity: refused whole, nothing applied
    with pytest.raises(OSError) as ei:
        st.queue_transaction(
            Transaction().write("c", "big", 0, b"z" * 4000))
    assert ei.value.errno == 28
    assert st.stat("c", "big") is None and st.statfs()[1] == 8000
    # overwrite in place (no growth) admits at the brim
    st.queue_transaction(Transaction().write("c", "a", 0, b"w" * 4000))
    # delete + rewrite inside ONE txn: net growth fits -> admitted
    st.queue_transaction(Transaction()
                         .remove("c", "a")
                         .write("c", "a2", 0, b"v" * 3000))
    assert st.statfs()[1] == 7000
    # truncate up counts, truncate down credits
    st.queue_transaction(Transaction().truncate("c", "a2", 1000))
    assert st.statfs()[1] == 5000
    # clone counts the copy
    st.queue_transaction(Transaction().clone("c", "b", "b2"))
    assert st.statfs()[1] == 9000
    with pytest.raises(OSError):
        st.queue_transaction(Transaction().clone("c", "b", "b3"))
    # remove_collection returns everything
    st.queue_transaction(Transaction().remove_collection("c"))
    assert st.statfs()[1] == 0
    # recount matches the incremental counter after arbitrary churn
    st.queue_transaction(Transaction().write("d", "o", 100, b"q" * 50))
    used = st.statfs()[1]
    st._recount_used()
    assert st.statfs()[1] == used == 150


# --------------------------------------------------------- read repair


@contention_retry()
def test_read_repair_heals_bitrot_off_client_path():
    """A flipped bit on one shard: the read still returns the acked
    payload (decode around the corruption — zero wrong bytes), the
    corrupt shard is rebuilt in place asynchronously, counters fire,
    and the PG's inconsistent set drains (clean health flow)."""
    async def scenario():
        cluster = await start_cluster(4)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rr", "erasure", pg_num=4,
                                            ec_profile=EC21)
            io = client.ioctx(pool)
            payload = b"verified-read-payload-" * 800
            await io.write_full("obj0", payload, timeout=120)
            pgid = client.objecter.object_pgid(pool, "obj0")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            victim = [o for o in acting if o >= 0][0]
            DiskInjector(stream(7, "t")).flip_bit(
                cluster.osds[victim].store, coll, "obj0", bit=12345)
            got = await io.read("obj0", timeout=60)
            assert got == payload          # zero wrong-bytes acks
            assert await _converge_poll(lambda: sum(
                o.perf.get("osd_read_repairs")
                for o in cluster.osds.values()))
            assert sum(o.perf.get("osd_read_shard_crc_errors")
                       for o in cluster.osds.values()) >= 1

            def _healed():
                full = cluster.osds[victim].store.read(coll, "obj0")
                stored = int(cluster.osds[victim].store.getattr(
                    coll, "obj0", "hinfo_crc"))
                return crcmod.crc32c(0xFFFFFFFF, full) == stored

            assert await _converge_poll(_healed)
            st = cluster.osds[primary].pgs[pgid]
            assert await _converge_poll(lambda: not st.inconsistent)
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_scheduled_scrub_repairs_without_a_read():
    """The jittered scrub scheduler finds and heals silent rot that NO
    client read ever touches, and the list-inconsistent / repair admin
    commands serve their contract."""
    async def scenario():
        cfg = _fast_config()
        cfg.osd_scrub_interval = 0.4
        cluster = await start_cluster(4, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("ss", "erasure", pg_num=4,
                                            ec_profile=EC21)
            io = client.ioctx(pool)
            await io.write_full("cold", b"never-read-again-" * 600,
                                timeout=120)
            pgid = client.objecter.object_pgid(pool, "cold")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            victim = [o for o in acting if o >= 0][-1]
            DiskInjector(stream(9, "s")).flip_bit(
                cluster.osds[victim].store, coll, "cold", bit=777)

            def _healed():
                full = cluster.osds[victim].store.read(coll, "cold")
                stored = int(cluster.osds[victim].store.getattr(
                    coll, "cold", "hinfo_crc"))
                return crcmod.crc32c(0xFFFFFFFF, full) == stored

            assert await _converge_poll(_healed, timeout=30.0)
            assert sum(o.perf.get("osd_scrubs_scheduled")
                       for o in cluster.osds.values()) > 0
            assert sum(o.perf.get("osd_scrub_errors_repaired")
                       for o in cluster.osds.values()) >= 1
            # admin surface: nothing left inconsistent, repair runs
            li = await cluster.daemon_command(f"osd.{primary}",
                                              "list-inconsistent")
            assert li == {}
            rep = await cluster.daemon_command(f"osd.{primary}",
                                               "repair")
            assert all(not r["inconsistent"]
                       for r in rep.values()), rep
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_inconsistent_health_raises_and_clears():
    """PG_INCONSISTENT / OSD_SCRUB_ERRORS ride the beacon stream: an
    unrepaired object raises both (and list-inconsistent names it);
    healing clears them on the next beacon, like SLOW_OPS."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("hi", "replicated",
                                            pg_num=4, size=3)
            io = client.ioctx(pool)
            await io.write_full("h0", b"payload", timeout=60)
            pgid = client.objecter.object_pgid(pool, "h0")
            _, _, _, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            st = cluster.osds[primary].pgs[pgid]
            st.inconsistent.add("h0")

            def _raised():
                checks = cluster.mon._health_data()["checks"]
                return "PG_INCONSISTENT" in checks and \
                    "OSD_SCRUB_ERRORS" in checks

            assert await _converge_poll(_raised)
            li = await cluster.daemon_command(f"osd.{primary}",
                                              "list-inconsistent")
            assert li == {str(pgid): ["h0"]}
            st.inconsistent.discard("h0")
            assert await _converge_poll(
                lambda: "PG_INCONSISTENT" not in
                cluster.mon._health_data()["checks"])
        finally:
            await cluster.stop()

    run(scenario())


# --------------------------------------------------------- cluster full


@contention_retry()
def test_full_flag_cycle_enospc_drain_resume():
    """Fill to the enforced capacity: explicit ENOSPC (errno 28, never
    a timeout), the map's full flag + OSD_FULL/HEALTH_ERR raise,
    deletes stay admitted, the flag clears as space frees, writes
    resume, and every surviving acked object reads back intact."""
    async def scenario():
        cfg = _fast_config()
        cfg.memstore_device_bytes = 1 << 19       # 512 KiB stores
        cluster = await start_cluster(3, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("ff", "replicated",
                                            pg_num=4, size=3)
            io = client.ioctx(pool)
            payload = b"f" * 24576
            acked, enospc = [], 0
            for i in range(40):
                try:
                    await io.write_full(f"o{i}", payload, timeout=20)
                    acked.append(f"o{i}")
                except OSError as e:
                    assert getattr(e, "errno", None) == 28, e
                    enospc += 1
                    if enospc >= 3:
                        break
                    await asyncio.sleep(0.15)
            assert enospc >= 3 and acked
            assert await _converge_poll(
                lambda: "full" in cluster.mon.osdmap.flags)
            h = cluster.mon._health_data()
            assert "OSD_FULL" in h["checks"]
            assert h["status"] == "HEALTH_ERR"
            # deletes admitted WHILE full
            doomed = acked[: max(1, len(acked) * 3 // 4)]
            for oid in doomed:
                await io.remove(oid, timeout=20)
            survivors = [o for o in acked if o not in doomed]
            assert await _converge_poll(
                lambda: "full" not in cluster.mon.osdmap.flags,
                timeout=30.0)
            await cluster.wait_for_epoch(cluster.mon.osdmap.epoch,
                                         timeout=10)
            await io.write_full("post", payload, timeout=30)
            assert await io.read("post", timeout=30) == payload
            for oid in survivors:      # zero acked-then-lost
                assert await io.read(oid, timeout=30) == payload, oid
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_backfillfull_gates_backfill_data_movement():
    """With the backfillfull flag on the primary's map, a peering
    round defers FULL-INVENTORY backfill (counter + incomplete round)
    while log-DELTA recovery still proceeds; clearing the flag lets
    the armed retry backfill the member."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            # ONE PG so the log-trim below provably strands the victim
            # behind the tail (a true backfill, not a delta resync)
            pool = await client.pool_create("bf", "replicated",
                                            pg_num=1, size=3)
            io = client.ioctx(pool)
            payload = b"b" * 8192
            for i in range(4):
                await io.write_full(f"g{i}", payload, timeout=60)
            # the victim must be a NON-primary member: the gate lives
            # on the pushing primary (a dead primary would come back
            # and PULL itself current instead — the ungated path)
            pgid = client.objecter.object_pgid(pool, "g0")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            victim = next(o for o in acting if o >= 0 and o != primary)
            await cluster.kill_osd(victim)
            await cluster.wait_down(victim)
            # shrink the survivors' log window and write past it: the
            # dead member falls behind the TAIL — backfill territory
            for osd in cluster.osds.values():
                for st in osd.pgs.values():
                    st.log.max_entries = 2
            for i in range(4, 12):
                await io.write_full(f"g{i}", payload, timeout=60)
            # arm the gate on every survivor's map copy, then revive
            # the (empty) member: backfill must defer
            for osd in cluster.osds.values():
                osd.osdmap.flags.add("backfillfull")
            await cluster.revive_osd(victim)
            assert await _converge_poll(lambda: sum(
                o.perf.get("osd_backfill_blocked_full")
                for o in cluster.osds.values()), timeout=30.0)
            # clear the gate; the capped-backoff retry completes the
            # backfill and the member converges
            for osd in cluster.osds.values():
                osd.osdmap.flags.discard("backfillfull")

            def _member_current():
                osd = cluster.osds.get(victim)
                if osd is None:
                    return False
                return all(osd.store.stat(
                    f"pg_{p.pool}_{p.seed}", f"g{i}") is not None
                    for i in range(12)
                    for p in [client.objecter.object_pgid(
                        pool, f"g{i}")])

            assert await _converge_poll(_member_current, timeout=40.0)
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_read_repair_heals_generation_stale_shard():
    """A primary shard surgically regressed to an older committed
    generation (bytes/attrs/version self-consistent, crc clean — an
    interrupted recovery's leftover): the read serves the committed
    group's bytes AND the stale detection queues a read-repair that
    brings the shard back to the current generation, no scrub needed
    (the detect-only anchor lives in test_rewind)."""
    from ceph_tpu.cluster.store import Transaction

    async def scenario():
        cluster = await start_cluster(4)
        try:
            client = await cluster.client()
            pool = await client.pool_create("sr", "erasure", pg_num=4,
                                            ec_profile=EC21)
            io = client.ioctx(pool)
            g1 = b"g1-" * 340
            g2 = b"g2-xyz" * 180
            await io.write_full("obj", g1, timeout=120)
            pgid = client.objecter.object_pgid(pool, "obj")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            _, _, _, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            posd = cluster.osds[primary]
            old_bytes = bytes(posd.store.read(coll, "obj"))
            old_attrs = {k: posd.store.getattr(coll, "obj", k)
                         for k in ("shard", "size", "hinfo_crc")}
            old_ver = posd.store.get_version(coll, "obj")
            await io.write_full("obj", g2, timeout=120)
            txn = (Transaction()
                   .write(coll, "obj", 0, old_bytes)
                   .truncate(coll, "obj", len(old_bytes)))
            for k, v in old_attrs.items():
                txn.setattr(coll, "obj", k, v)
            txn.set_version(coll, "obj", old_ver)
            posd.store.queue_transaction(txn)
            assert await io.read("obj", timeout=60) == g2

            def _healed():
                sa = posd.store.getattr(coll, "obj", "size")
                return sa == str(len(g2)).encode() and \
                    posd.store.get_version(coll, "obj") != old_ver

            assert await _converge_poll(_healed)
            assert sum(o.perf.get("osd_read_repairs")
                       for o in cluster.osds.values()) >= 1
        finally:
            await cluster.stop()

    run(scenario())


# ------------------------------------------------------------- scenarios


def test_integrity_plans_are_seed_deterministic():
    """Replay contract, plan level: schedules/plans are pure functions
    of (scenario, seed) for both integrity scenarios."""
    from ceph_tpu.chaos.integrity import (FillScenario, build_fill_plan,
                                          integrity_scenarios)
    from ceph_tpu.chaos.scenario import build_schedule

    lib = integrity_scenarios(0.06)
    bl = lib["bitrot-under-load"]
    assert build_schedule(bl, 23) == build_schedule(bl, 23)
    fd = lib["disk-fill-drain"]
    assert isinstance(fd, FillScenario)
    assert build_fill_plan(fd, 23) == build_fill_plan(fd, 23)
    assert build_fill_plan(fd, 23) != build_fill_plan(fd, 24)


@pytest.mark.chaos
@contention_retry()
def test_bitrot_under_load_smoke():
    """Tier-1 smoke of the bitrot-under-load acceptance scenario at
    small scale: seeded PASS, flips actually injected, repairs fired."""
    from ceph_tpu.chaos.integrity import integrity_scenarios
    from ceph_tpu.chaos.scenario import run_scenario

    sc = integrity_scenarios(0.06)["bitrot-under-load"]
    verdict = run(run_scenario(sc, 11))
    assert verdict.passed, verdict.failures
    assert verdict.counters.get("disk_bitrot_flips", 0) >= 1


@pytest.mark.chaos
@contention_retry()
def test_disk_fill_drain_smoke():
    """Tier-1 smoke of the disk-fill-drain acceptance scenario: seeded
    PASS through the full fill -> flag -> drain -> clear -> resume
    cycle with zero acked-then-lost writes."""
    from ceph_tpu.chaos.integrity import integrity_scenarios, \
        run_fill_drain

    sc = integrity_scenarios(0.06)["disk-fill-drain"]
    verdict = run(run_fill_drain(sc, 7))
    assert verdict.passed, verdict.failures
    assert verdict.counters.get("fill_enospc", 0) >= 1
    assert verdict.counters.get("full_rejects", 0) >= 1
    assert verdict.counters.get("drained", 0) >= 1


@pytest.mark.chaos
@pytest.mark.slow
def test_bitrot_under_load_full_replays_bit_identically():
    """Acceptance: the FULL bitrot-under-load scenario passes seeded
    and two runs of one seed produce identical replay keys."""
    from ceph_tpu.chaos.integrity import integrity_scenarios
    from ceph_tpu.chaos.scenario import run_scenario

    sc = integrity_scenarios(1.0)["bitrot-under-load"]
    v1 = run(run_scenario(sc, 11))
    v2 = run(run_scenario(sc, 11))
    assert v1.passed, v1.failures
    assert v1.replay_key() == v2.replay_key()


@pytest.mark.chaos
@pytest.mark.slow
def test_disk_fill_drain_full_replays_bit_identically():
    from ceph_tpu.chaos.integrity import integrity_scenarios, \
        run_fill_drain

    sc = integrity_scenarios(1.0)["disk-fill-drain"]
    v1 = run(run_fill_drain(sc, 7))
    v2 = run(run_fill_drain(sc, 7))
    assert v1.passed, v1.failures
    assert v1.replay_key() == v2.replay_key()
