"""Planar at-rest shards (round 19): the tier-1 bit-exactness gate.

The contract under test: with ``osd_ec_planar_at_rest=1`` EC shards
LIVE as packed bit-plane matrices — in the store, on the wire, and
entering the kernels — with ZERO layout conversions on the
steady-state write/read/RMW/recovery/deep-scrub paths (the
``ec_planar_unseamed_conversions`` counter is pinned to 0), while
every client-visible byte, shard crc, and scrub verdict stays
bit-identical to the ``osd_ec_planar_at_rest=0`` byte anchor.
"""

import asyncio
import os

import numpy as np
import pytest

from ceph_tpu.cluster.pg import _coll
from ceph_tpu.cluster.store import MemStore, Transaction
from ceph_tpu.ec import planar_store
from ceph_tpu.ec import stripe as stripemod
from ceph_tpu.ec.registry import factory
from ceph_tpu.ops import crc32c as crcmod
from ceph_tpu.ops.profiling import KERNELS
from tests._flaky import contention_retry

pytestmark = pytest.mark.skipif(
    os.environ.get("JAX_PLATFORMS", "") == "",
    reason="run under JAX_PLATFORMS=cpu like the tier-1 lane")


def run(coro):
    return asyncio.run(coro)


def _rng(seed=7):
    return np.random.default_rng(seed)


def _profile(k, m):
    return {"plugin": "jerasure", "technique": "reed_sol_van",
            "k": str(k), "m": str(m)}


def _unseamed():
    return KERNELS.get("ec_planar_unseamed_conversions")


# ------------------------------------------------------- layer 0: helpers


def test_planar_blob_roundtrip_and_crc_identity():
    """shard bytes <-> plane matrix <-> serialized blob round-trips,
    and the plane-major crc equals the byte crc for BOTH seeds the
    data plane uses (cumulative hinfo ~0 and append-delta 0)."""
    r = _rng()
    for nbytes in (8, 64, 4096, 8 * 1237):
        shard = r.integers(0, 256, nbytes, dtype=np.uint8).tobytes()
        planes = planar_store.shard_to_planes(shard)
        assert planes.shape == (8, nbytes // 8)
        assert planar_store.planes_to_shard(planes) == shard
        blob = planar_store.planes_to_blob(planes)
        assert len(blob) == nbytes  # layout is accounting-free
        assert np.array_equal(planar_store.blob_to_planes(blob), planes)
        for seed in (0xFFFFFFFF, 0):
            assert crcmod.crc32c_planar_rows(planes, seed=seed)[0] == \
                crcmod.crc32c(seed, shard)


def test_splice_columns_matches_byte_rmw():
    """The store's plane-window splice == the byte path's
    write-at-offset + truncate, for overwrite, append, and extend."""
    r = _rng(11)
    old = r.integers(0, 256, 2048, dtype=np.uint8).tobytes()
    for (off, wlen, total) in ((1024, 512, 2048),   # mid overwrite
                               (2048, 1024, 3072),  # append-extend
                               (0, 2048, 1024)):    # rewrite + shrink
        win = r.integers(0, 256, wlen, dtype=np.uint8).tobytes()
        ref = bytearray(old)
        if len(ref) < total:
            ref.extend(b"\0" * (total - len(ref)))
        ref[off:off + wlen] = win
        ref = bytes(ref[:total])
        merged = planar_store.splice_columns(
            planar_store.shard_to_planes(old), off // 8,
            planar_store.shard_to_planes(win), total // 8)
        assert planar_store.planes_to_shard(merged) == ref


# ------------------------------------- layer 1: stripe-level bit-exactness


@pytest.mark.parametrize("k,m", [(2, 1), (3, 2)])
def test_stripe_planar_vs_byte_anchor_bit_exact(k, m):
    """encode/decode/reencode in the plane domain produce the same
    shard bytes, shard crcs, and logical bytes as the byte anchors."""
    codec = factory(_profile(k, m))
    sinfo = stripemod.StripeInfo(k, 64)
    assert stripemod.planar_at_rest_ok(codec, sinfo.chunk_size)
    r = _rng(13)
    datas = [r.integers(0, 256, n, dtype=np.uint8).tobytes()
             for n in (k * 64, 5 * k * 64, 3 * k * 64 - 17)]
    byte_out = stripemod.encode_stripes_multi(
        codec, sinfo, datas, want_crcs=[True] * len(datas))
    plane_out = stripemod.encode_planes_multi(
        codec, sinfo, datas, want_crcs=[True] * len(datas))
    for (bs, bc), (ps, pc), data in zip(byte_out, plane_out, datas):
        assert pc == bc  # plane-major crcs == byte-anchor crcs
        shards = {}
        for i in range(k + m):
            assert planar_store.planes_to_blob(
                planar_store.shard_to_planes(bs[i].tobytes())) == \
                ps[i].tobytes()
            shards[i] = ps[i]
        # decode with an erasure, planes in -> logical bytes out
        alive = {i: s for i, s in shards.items() if i != 1}
        [logical] = stripemod.decode_planes_multi(
            codec, sinfo, [(alive, len(data))])
        assert logical == data
        # recovery rebuild: full plane matrices back, byte-identical
        [rebuilt] = stripemod.reencode_planes_multi(
            codec, sinfo, [(alive, len(data))])
        for i in range(k + m):
            assert rebuilt[i].tobytes() == ps[i].tobytes()


# ------------------------------------------- layer 2: the store substrate


def test_memstore_planar_accounting_and_enospc_parity():
    """Planar objects count their TRUE plane bytes (== logical bytes:
    the layout is accounting-free) against _used/statfs, and a planar
    store fills to capacity with the same ENOSPC + full-flag behavior
    as the byte anchor."""
    cap = 1 << 14
    outcomes = []
    for planar in (False, True):
        s = MemStore(device_bytes=cap)
        s.queue_transaction(Transaction().create_collection("c"))
        blob = bytes(range(256)) * 16  # 4096 B
        for i in range(4):
            txn = Transaction()
            if planar:
                txn.write_planar(
                    "c", f"o{i}", 0,
                    planar_store.planes_to_blob(
                        planar_store.shard_to_planes(blob)),
                    len(blob) // 8)
            else:
                txn.write("c", f"o{i}", 0, blob)
            s.queue_transaction(txn)
        used, total = s.statfs()
        assert (used, total) == (cap, cap)
        txn = Transaction()
        if planar:
            txn.write_planar("c", "overflow", 0, blob, len(blob) // 8)
        else:
            txn.write("c", "overflow", 0, blob)
        with pytest.raises(OSError) as ei:
            s.queue_transaction(txn)
        outcomes.append((used, ei.value.errno, str(ei.value)))
        if planar:
            assert all(s.object_layout("c", f"o{i}")
                       == planar_store.LAYOUT_PLANAR for i in range(4))
    assert outcomes[0] == outcomes[1]  # byte anchor == planar, exactly


def test_filestore_checkpoint_and_journal_bounce_planar(tmp_path):
    """Planar objects survive a FileStore crash-bounce bit-identical:
    once via checkpoint, once via journal replay alone."""
    from ceph_tpu.cluster.filestore import FileStore

    blob = planar_store.planes_to_blob(
        planar_store.shard_to_planes(bytes(range(256)) * 8))
    for checkpoint_every, tag in ((1, "ckpt"), (2048, "journal")):
        path = str(tmp_path / tag)
        s = FileStore(path, checkpoint_every=checkpoint_every)
        s.mount()
        s.queue_transaction(
            Transaction().create_collection("c")
            .write_planar("c", "obj", 0, blob, len(blob) // 8)
            .setattr("c", "obj", "hinfo_crc", b"123"))
        # crash: NO umount — the rebouncing store must replay
        s2 = FileStore(path)
        s2.mount()
        assert s2.object_layout("c", "obj") == planar_store.LAYOUT_PLANAR
        assert s2.read_planar("c", "obj") == blob
        assert s2.getattr("c", "obj", "hinfo_crc") == b"123"
        s2.umount()


def test_bluestore_wal_bounce_and_bitrot_planar(tmp_path):
    """Planar objects survive a BlueStore WAL crash-bounce
    bit-identical, and the per-block csum still detects bitrot under
    the planar blob."""
    from ceph_tpu.cluster.bluestore import BlueStore

    blob = planar_store.planes_to_blob(
        planar_store.shard_to_planes(bytes(range(256)) * 32))
    path = str(tmp_path / "bs")
    s = BlueStore(path, size=8 << 20, checkpoint_every=10_000)
    s.mount()
    s.queue_transaction(
        Transaction().create_collection("c")
        .write_planar("c", "obj", 0, blob, len(blob) // 8))
    # crash: no umount — WAL replay must rebuild the planar onode
    s2 = BlueStore(path, size=8 << 20)
    s2.mount()
    assert s2.object_layout("c", "obj") == planar_store.LAYOUT_PLANAR
    assert s2.read_planar("c", "obj") == blob
    s2.debug_bitrot("c", "obj", bit=41)
    with pytest.raises(IOError):
        s2.read_planar("c", "obj")
    s2.umount()


# ------------------------------------------ layer 3: the cluster-level A/B

PROFILE = _profile(2, 1)


async def _cluster_workload(planar: int):
    """One full shard life-cycle (write_full, append, RMW, ranged +
    full reads, deep scrub) on a 3-OSD cluster; returns every
    client-visible byte, per-member shard crc, scrub verdict, and the
    planar counter deltas."""
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    cfg = _fast_config()
    cfg.osd_ec_planar_at_rest = planar
    cluster = await start_cluster(3, config=cfg)
    out = {}
    try:
        client = await cluster.client()
        pool = await client.pool_create("p", "erasure", pg_num=4,
                                        ec_profile=PROFILE)
        io = client.ioctx(pool)
        base = _unseamed()
        await io.write_full("a", bytes(range(256)) * 40, timeout=60)
        await io.append("a", b"tail-" * 100)
        await io.write("a", b"X" * 777, 1000)          # mid-object RMW
        await io.write_full("b", b"hello world" * 9)
        await io.truncate("b", 37)
        out["reads"] = (await io.read("a"), await io.read("b"),
                        await io.read("a", 500, 2000))
        # per-member shard state: crc + layout, keyed by (oid, shard)
        state = {}
        layouts = set()
        for osd in cluster.osds.values():
            for coll in list(osd.store._colls):
                for oid in ("a", "b"):
                    if oid in osd.store._colls[coll]:
                        sh = osd.store.getattr(coll, oid, "shard")
                        state[(oid, sh)] = osd.store.getattr(
                            coll, oid, "hinfo_crc")
                        layouts.add(osd.store.object_layout(coll, oid))
        out["shard_crcs"] = state
        out["layouts"] = layouts
        # deep scrub the PG holding "a": verdict must be clean
        pgid = client.objecter.object_pgid(pool, "a")
        _, _, _, primary = \
            client.objecter.osdmap.pg_to_up_acting_osds(pgid)
        st = cluster.osds[primary].pgs[pgid]
        report = await cluster.osds[primary].scrub_pg(st)
        out["scrub"] = (sorted(report["inconsistent"]),
                        sorted(report["repaired"]))
        out["unseamed_delta"] = _unseamed() - base
        out["ingest"] = KERNELS.get("ec_planar_ingest_conversions")
        out["egress"] = KERNELS.get("ec_planar_egress_conversions")
    finally:
        await cluster.stop()
    return out


@contention_retry()
def test_cluster_planar_vs_byte_anchor_bit_exact():
    """THE round-19 gate: the same workload under planar=1 and the
    byte anchor yields byte-identical client reads, identical shard
    crcs, and identical (clean) scrub verdicts — while the planar run
    stores every EC object as planes and books ZERO unseamed
    conversions (write, append, RMW, ranged read, deep scrub all
    steady-state conversion-free)."""
    async def scenario():
        p = await _cluster_workload(1)
        b = await _cluster_workload(0)
        assert p["reads"] == b["reads"]
        assert p["shard_crcs"] == b["shard_crcs"]
        assert p["scrub"] == b["scrub"] == ([], [])
        assert p["layouts"] == {planar_store.LAYOUT_PLANAR}
        assert b["layouts"] == {None}
        assert p["unseamed_delta"] == 0, \
            f"unseamed conversions on the steady-state path: " \
            f"{p['unseamed_delta']}"
        assert p["ingest"] > 0 and p["egress"] > 0

    run(scenario())


@contention_retry()
def test_cluster_planar_scrub_repair_and_recovery():
    """Corrupt one member's planar shard: deep scrub detects it over
    plane-major rows, the recovery rebuild re-encodes IN the plane
    domain, the repaired shard lands planar bit-identical — and the
    whole detect/rebuild/land cycle books zero unseamed
    conversions."""
    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3)   # vstart default: planar on
        try:
            client = await cluster.client()
            pool = await client.pool_create("sp", "erasure", pg_num=4,
                                            ec_profile=PROFILE)
            io = client.ioctx(pool)
            payload = b"planar-scrub" * 300
            await io.write_full("obj", payload, timeout=60)
            base = _unseamed()
            pgid = client.objecter.object_pgid(pool, "obj")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            victim = next(o for o in acting
                          if o >= 0 and o != primary
                          and o in cluster.osds)
            vstore = cluster.osds[victim].store
            assert vstore.object_layout(_coll(pgid), "obj") \
                == planar_store.LAYOUT_PLANAR
            before = bytes(vstore.read_planar(_coll(pgid), "obj"))
            vstore._colls[_coll(pgid)]["obj"].data[3] ^= 0xFF
            st = cluster.osds[primary].pgs[pgid]
            report = await cluster.osds[primary].scrub_pg(st)
            assert report["inconsistent"] == ["obj"]
            assert report["repaired"] == ["obj"]
            # repair lands asynchronously on the victim: converge-poll
            # against a wall deadline instead of a fixed sleep
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                if bytes(vstore.read_planar(_coll(pgid), "obj")) \
                        == before:
                    break
                await asyncio.sleep(0.05)
            assert bytes(vstore.read_planar(_coll(pgid), "obj")) \
                == before
            assert vstore.object_layout(_coll(pgid), "obj") \
                == planar_store.LAYOUT_PLANAR
            assert await io.read("obj", timeout=60) == payload
            assert _unseamed() - base == 0
        finally:
            await cluster.stop()

    run(scenario())


# ------------------------------------------------- layer 4: observability


def test_planar_counters_ride_prometheus_scrape():
    """The round-19 KERNELS counters surface through the same
    perfcoll.dump() -> render_prometheus path the mgr's scrape and
    exporter serve (Mgr registers KERNELS at construction)."""
    from ceph_tpu.cluster.mgr import render_prometheus
    from ceph_tpu.utils import PerfCountersCollection

    # ensure the counters exist process-wide (any prior planar test
    # already booked them; book explicitly so this test stands alone)
    from ceph_tpu.ops.profiling import record_planar_at_rest

    record_planar_at_rest("ingest", 4096)
    record_planar_at_rest("egress", 4096)
    coll = PerfCountersCollection()
    coll.register(KERNELS)
    text = render_prometheus(
        {n: c["counters"] if "counters" in c else c
         for n, c in coll.dump().items()})
    for name in ("ec_planar_ingest_conversions",
                 "ec_planar_ingest_bytes",
                 "ec_planar_egress_conversions"):
        assert name in text, text[:2000]


def test_attribution_books_planar_convert_stage():
    from ceph_tpu.trace.attribution import stage_for

    assert stage_for("planar_ingest") == "planar_convert"
    assert stage_for("planar_egress") == "planar_convert"
