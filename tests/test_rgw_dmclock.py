"""RGW-lite gateway + dmClock QoS scheduling.

Reference: src/rgw/ (bucket index over omap, S3 listing semantics) and
src/dmclock/ + mClock queues (reservation/weight/limit tags).
"""

import asyncio

import pytest

from ceph_tpu.cluster.dmclock import DmClockQueue, QoSSpec
from ceph_tpu.cluster.rgw import RGW
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


def test_rgw_bucket_object_lifecycle():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rgwp", "replicated",
                                            pg_num=8, size=2)
            rgw = RGW(client.ioctx(pool))
            await rgw.create_bucket("photos")
            with pytest.raises(FileExistsError):
                await rgw.create_bucket("photos")
            assert await rgw.list_buckets() == ["photos"]

            etag = await rgw.put_object("photos", "a/1.jpg",
                                        b"jpegbytes" * 100,
                                        content_type="image/jpeg",
                                        user_meta={"owner": "alice"})
            await rgw.put_object("photos", "a/2.jpg", b"x" * 10)
            await rgw.put_object("photos", "b/3.jpg", b"y" * 20)

            meta, data = await rgw.get_object("photos", "a/1.jpg")
            assert data == b"jpegbytes" * 100
            assert meta.etag == etag and meta.content_type == "image/jpeg"
            assert meta.user_meta == {"owner": "alice"}

            # S3 listing: prefix + marker + truncation
            res = await rgw.list_objects("photos", prefix="a/")
            assert [m.key for m in res.keys] == ["a/1.jpg", "a/2.jpg"]
            res = await rgw.list_objects("photos", max_keys=2)
            assert res.is_truncated and res.next_marker == "a/2.jpg"
            res2 = await rgw.list_objects("photos",
                                          marker=res.next_marker)
            assert [m.key for m in res2.keys] == ["b/3.jpg"]

            with pytest.raises(OSError):
                await rgw.delete_bucket("photos")   # not empty
            for k in ("a/1.jpg", "a/2.jpg", "b/3.jpg"):
                await rgw.delete_object("photos", k)
            with pytest.raises(FileNotFoundError):
                await rgw.get_object("photos", "a/1.jpg")
            await rgw.delete_bucket("photos")
            assert await rgw.list_buckets() == []
        finally:
            await cluster.stop()

    run(scenario())


def test_dmclock_reservation_and_weights():
    t = [0.0]
    q = DmClockQueue(now=lambda: t[0])
    # gold: guaranteed 10 ops/s; silver: best-effort weight 1
    q.set_client("gold", QoSSpec(reservation=10.0, weight=1.0))
    q.set_client("silver", QoSSpec(weight=1.0))
    for i in range(5):
        q.enqueue("gold", f"g{i}")
        q.enqueue("silver", f"s{i}")
    # at t=0 the first gold reservation tag is eligible immediately
    first = q.dequeue()
    assert first == "g0"
    t[0] = 10.0  # plenty of time: everything eligible
    rest = q.drain_eligible()
    assert set(rest) == {f"g{i}" for i in range(1, 5)} | \
        {f"s{i}" for i in range(5)}
    assert len(q) == 0


def test_dmclock_limit_caps_service():
    t = [0.0]
    q = DmClockQueue(now=lambda: t[0])
    q.set_client("capped", QoSSpec(weight=1.0, limit=1.0))  # 1 op/s cap
    for i in range(3):
        q.enqueue("capped", i)
    assert q.dequeue() == 0
    # the next item's L-tag is ~1s out: not eligible yet
    assert q.dequeue() is None
    t[0] = 1.05
    assert q.dequeue() == 1
    assert q.dequeue() is None
    t[0] = 2.1
    assert q.dequeue() == 2


def test_dmclock_weight_proportionality():
    t = [0.0]
    q = DmClockQueue(now=lambda: t[0])
    q.set_client("heavy", QoSSpec(weight=3.0))
    q.set_client("light", QoSSpec(weight=1.0))
    for i in range(40):
        q.enqueue("heavy", ("h", i))
        q.enqueue("light", ("l", i))
    # serve 20 decisions while time stands still past the first tags:
    # P-tags advance 3x slower for heavy, so it gets ~3x the service
    t[0] = 0.001
    served = []
    for _ in range(20):
        item = q.dequeue()
        if item is None:
            t[0] += 0.3
            continue
        served.append(item)
    heavy = sum(1 for s in served if s[0] == "h")
    light = sum(1 for s in served if s[0] == "l")
    assert heavy > light * 1.8, (heavy, light)
