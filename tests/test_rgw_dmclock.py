"""RGW-lite gateway + dmClock QoS scheduling.

Reference: src/rgw/ (bucket index over omap, S3 listing semantics) and
src/dmclock/ + mClock queues (reservation/weight/limit tags).
"""

import asyncio

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster.dmclock import DmClockQueue, QoSSpec
from ceph_tpu.cluster.rgw import RGW
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


def test_rgw_bucket_object_lifecycle():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rgwp", "replicated",
                                            pg_num=8, size=2)
            rgw = RGW(client.ioctx(pool))
            await rgw.create_bucket("photos")
            with pytest.raises(FileExistsError):
                await rgw.create_bucket("photos")
            assert await rgw.list_buckets() == ["photos"]

            etag = await rgw.put_object("photos", "a/1.jpg",
                                        b"jpegbytes" * 100,
                                        content_type="image/jpeg",
                                        user_meta={"owner": "alice"})
            await rgw.put_object("photos", "a/2.jpg", b"x" * 10)
            await rgw.put_object("photos", "b/3.jpg", b"y" * 20)

            meta, data = await rgw.get_object("photos", "a/1.jpg")
            assert data == b"jpegbytes" * 100
            assert meta.etag == etag and meta.content_type == "image/jpeg"
            assert meta.user_meta == {"owner": "alice"}

            # S3 listing: prefix + marker + truncation
            res = await rgw.list_objects("photos", prefix="a/")
            assert [m.key for m in res.keys] == ["a/1.jpg", "a/2.jpg"]
            res = await rgw.list_objects("photos", max_keys=2)
            assert res.is_truncated and res.next_marker == "a/2.jpg"
            res2 = await rgw.list_objects("photos",
                                          marker=res.next_marker)
            assert [m.key for m in res2.keys] == ["b/3.jpg"]

            with pytest.raises(OSError):
                await rgw.delete_bucket("photos")   # not empty
            for k in ("a/1.jpg", "a/2.jpg", "b/3.jpg"):
                await rgw.delete_object("photos", k)
            with pytest.raises(FileNotFoundError):
                await rgw.get_object("photos", "a/1.jpg")
            await rgw.delete_bucket("photos")
            assert await rgw.list_buckets() == []
        finally:
            await cluster.stop()

    run(scenario())


def test_dmclock_reservation_and_weights():
    t = [0.0]
    q = DmClockQueue(now=lambda: t[0])
    # gold: guaranteed 10 ops/s; silver: best-effort weight 1
    q.set_client("gold", QoSSpec(reservation=10.0, weight=1.0))
    q.set_client("silver", QoSSpec(weight=1.0))
    for i in range(5):
        q.enqueue("gold", f"g{i}")
        q.enqueue("silver", f"s{i}")
    # at t=0 the first gold reservation tag is eligible immediately
    first = q.dequeue()
    assert first == "g0"
    t[0] = 10.0  # plenty of time: everything eligible
    rest = q.drain_eligible()
    assert set(rest) == {f"g{i}" for i in range(1, 5)} | \
        {f"s{i}" for i in range(5)}
    assert len(q) == 0


def test_dmclock_limit_caps_service():
    t = [0.0]
    q = DmClockQueue(now=lambda: t[0])
    q.set_client("capped", QoSSpec(weight=1.0, limit=1.0))  # 1 op/s cap
    for i in range(3):
        q.enqueue("capped", i)
    assert q.dequeue() == 0
    # the next item's L-tag is ~1s out: not eligible yet
    assert q.dequeue() is None
    t[0] = 1.05
    assert q.dequeue() == 1
    assert q.dequeue() is None
    t[0] = 2.1
    assert q.dequeue() == 2


def test_dmclock_weight_proportionality():
    t = [0.0]
    q = DmClockQueue(now=lambda: t[0])
    q.set_client("heavy", QoSSpec(weight=3.0))
    q.set_client("light", QoSSpec(weight=1.0))
    for i in range(40):
        q.enqueue("heavy", ("h", i))
        q.enqueue("light", ("l", i))
    # serve 20 decisions while time stands still past the first tags:
    # P-tags advance 3x slower for heavy, so it gets ~3x the service
    t[0] = 0.001
    served = []
    for _ in range(20):
        item = q.dequeue()
        if item is None:
            t[0] += 0.3
            continue
        served.append(item)
    heavy = sum(1 for s in served if s[0] == "h")
    light = sum(1 for s in served if s[0] == "l")
    assert heavy > light * 1.8, (heavy, light)


@contention_retry()
def test_mclock_op_queue_in_osd():
    """osd_op_queue=mclock: client ops flow through the dmClock queue;
    a limited client is throttled while an unlimited one proceeds."""
    async def scenario():
        from ceph_tpu.cluster.vstart import _fast_config, start_cluster

        cfg = _fast_config()
        cfg.osd_op_queue = "mclock"
        cluster = await start_cluster(3, config=cfg)
        try:
            fast = await cluster.client("fast")
            slow = await cluster.client("slow")
            pool = await fast.pool_create("qosp", "replicated",
                                          pg_num=1, size=2)
            fio = fast.ioctx(pool)
            sio = slow.ioctx(pool)
            # warm the path (and identify the single PG's primary)
            await fio.write_full("warm", b"w")
            pgid = fast.objecter.object_pgid(pool, "warm")
            _, _, _, primary = \
                fast.objecter.osdmap.pg_to_up_acting_osds(pgid)
            # throttle the slow client to 5 ops/s on the primary
            cluster.osds[primary].set_qos("slow", limit=5.0)

            async def hammer(io, n):
                done = 0
                for i in range(n):
                    await io.write_full(f"{id(io)}-{i}", b"x")
                    done += 1
                return done

            t0 = asyncio.get_event_loop().time()
            fast_done, slow_done = await asyncio.gather(
                hammer(fio, 40), hammer(sio, 40))
            dt = asyncio.get_event_loop().time() - t0
            assert fast_done == 40 and slow_done == 40
            # the slow client's 40 ops at 5/s force dt >= ~7s while the
            # fast client alone would finish far sooner
            assert dt >= 5.0, dt
            q = cluster.osds[primary].perf.get("osd_ops_queued_mclock")
            assert q >= 80
        finally:
            await cluster.stop()

    run(scenario())
