"""Capped exponential backoff with seeded jitter (utils/backoff.py).

The monclient-hunting / messenger-reconnect satellite: the schedule
from a fixed seed is asserted exactly, so retry timing is replayable in
chaos scenarios and regression-pinned here.
"""

import asyncio
import random

from ceph_tpu.utils.backoff import ExpBackoff


def test_backoff_schedule_deterministic_from_seed():
    a = ExpBackoff(base=0.05, cap=1.0, rng=random.Random(7))
    b = ExpBackoff(base=0.05, cap=1.0, rng=random.Random(7))
    sched_a = [a.next() for _ in range(8)]
    sched_b = [b.next() for _ in range(8)]
    assert sched_a == sched_b
    # full jitter stays inside the capped exponential envelope
    for n, d in enumerate(sched_a):
        assert 0.0 <= d <= min(1.0, 0.05 * 2 ** n)
    # the envelope actually grows: later draws can exceed the first cap
    assert max(sched_a[4:]) > 0.05


def test_backoff_reset_restarts_envelope():
    b = ExpBackoff(base=0.1, cap=10.0, factor=2.0,
                   rng=random.Random(3))
    for _ in range(6):
        b.next()
    b.reset()
    assert b.next() <= 0.1  # attempt-0 ceiling again


def test_backoff_schedule_preview_does_not_consume():
    b = ExpBackoff(base=0.05, cap=1.0, rng=random.Random(11))
    preview = b.schedule(5)
    live = [b.next() for _ in range(5)]
    assert preview == live


def test_montargeter_hunts_with_backoff():
    """A dead monmap is hunted with growing jittered delays (not
    hammered), and the schedule replays from the same seed."""
    from ceph_tpu.cluster.monclient import MonTargeter

    class DeadMessenger:
        my_addr = ("127.0.0.1", 1)

        async def send_message(self, msg, addr):
            raise ConnectionError("down")

    async def hunt_delays(seed):
        mt = MonTargeter(DeadMessenger(),
                         [("127.0.0.1", 2), ("127.0.0.1", 3)],
                         rng=random.Random(seed))
        slept = []
        orig_sleep = asyncio.sleep

        async def spy_sleep(d):
            slept.append(d)
            await orig_sleep(0)

        asyncio.sleep = spy_sleep
        try:
            ok = await mt.send(object())
        finally:
            asyncio.sleep = orig_sleep
        assert not ok
        return slept

    s1 = asyncio.run(hunt_delays(5))
    s2 = asyncio.run(hunt_delays(5))
    assert s1 == s2
    # one backoff BETWEEN targets; the last failure returns immediately
    # (sleeping after the final target would delay the failure verdict
    # with no further attempt to protect)
    assert len(s1) == 1
    assert all(d >= 0 for d in s1)


def test_aimd_window_shape():
    """AIMD congestion window: starts at the ceiling (no-op until real
    pushback), halves multiplicatively on pushback, recovers additively
    at ~1/w per ack, and never leaves [1, ceiling]."""
    from ceph_tpu.utils.backoff import AIMDWindow

    w = AIMDWindow(64)
    assert w.limit == 64 and w.window == 64.0
    w.on_ack()
    assert w.window == 64.0  # capped at the ceiling
    w.on_pushback()
    assert w.window == 32.0 and w.pushbacks == 1
    for _ in range(10):
        w.on_pushback()
    assert w.window == 1.0  # floor
    before = w.window
    w.on_ack()
    assert before < w.window <= before + 1.0  # additive recovery
    # a full window's worth of acks gains ~one slot
    w2 = AIMDWindow(64)
    w2.on_pushback()  # 32
    for _ in range(32):
        w2.on_ack()
    assert 32.5 < w2.window < 34.0
