"""graft-race dynamic half: the seeded schedule-perturbation loop
(ceph_tpu/utils/schedfuzz.py), the cross-task write-after-read tracker
(ceph_tpu/analysis/racecheck.py), the `graftlint --race` CLI, and the
tier-1 race smoke.

The two regression anchors at the bottom pin the real bugs this
sanitizer convicted on its first outing (batch-smoke seed 2 at smoke
scale): a drained-but-short commit frontier that nothing ever re-armed,
and a planar-at-rest rewind that restored the rolled-back PLANES while
leaving the divergent write's size/hinfo_crc/version attrs stamped —
old data under a new crc, failing verify-on-read forever.
"""

import asyncio
import importlib.util
import os
import sys

import pytest

from ceph_tpu.analysis import racecheck
from ceph_tpu.analysis.racecheck import (NULL_RACE, RaceTracker, _NullRace,
                                         race_run)
from ceph_tpu.utils.lockdep import DepLock
from ceph_tpu.utils.schedfuzz import SchedFuzzLoop, run_fuzzed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- schedfuzz


def _workload(n: int = 6, rounds: int = 4):
    """IO-free N-worker interleaving probe: the recorded (worker, round)
    order IS the interleaving, so digests and results are comparable
    bit for bit (no sockets -> no OS-timing nondeterminism)."""
    order = []

    async def worker(i):
        for r in range(rounds):
            await asyncio.sleep(0)
            order.append((i, r))

    async def main():
        await asyncio.gather(*(worker(i) for i in range(n)))
        return tuple(order)

    return main


def test_schedfuzz_same_seed_replays_bit_identically():
    r1, d1 = run_fuzzed(_workload(), seed=7)
    r2, d2 = run_fuzzed(_workload(), seed=7)
    assert r1 == r2
    assert d1 == d2


def test_schedfuzz_seeds_explore_distinct_interleavings():
    results = {}
    digests = set()
    for seed in range(8):
        r, d = run_fuzzed(_workload(), seed=seed)
        results[seed] = r
        digests.add(d)
    # not every pair need differ, but a seeded explorer that always
    # lands on one schedule explores nothing
    assert len(set(results.values())) > 1
    assert len(digests) > 1


def test_schedfuzz_perturbs_the_fifo_order():
    fifo = asyncio.run(_workload()())
    perturbed = {run_fuzzed(_workload(), seed=s)[0] for s in range(6)}
    assert any(p != fifo for p in perturbed), \
        "six seeds all reproduced FIFO: the shim is not perturbing"


def test_schedfuzz_trace_is_a_valid_decision_record():
    loop = SchedFuzzLoop(seed=11)
    try:
        asyncio.set_event_loop(loop)
        loop.run_until_complete(_workload()())
    finally:
        asyncio.set_event_loop(None)
        loop.close()
    trace = loop.fuzz_trace()
    assert trace, "a 6-worker gather produced zero perturbable ticks"
    last_tick = 0
    for tick, n, perm, deferred in trace:
        assert tick > last_tick
        last_tick = tick
        assert sorted(perm) == list(range(n))   # true permutation
        assert 0 <= deferred <= n
    # the digest is a pure function of the trace
    assert loop.trace_digest() == loop.trace_digest()


# ----------------------------------------------------- NULL_RACE contract


def test_null_race_noop_contract():
    """Default-off is a provable no-op: falsy, slotless (retains
    nothing), constant report, and it IS the module default."""
    assert racecheck.TRACKER is NULL_RACE
    assert not NULL_RACE
    assert NULL_RACE.enabled is False
    assert _NullRace.__slots__ == ()
    with pytest.raises(AttributeError):
        NULL_RACE.anything = 1
    NULL_RACE.note_read(("pg", 0, "1.0"), "self_info")
    NULL_RACE.note_write(("pg", 0, "1.0"), "self_info")
    NULL_RACE.advance_tick()
    assert NULL_RACE.findings() == []
    assert NULL_RACE.report() == {"enabled": False, "seed": 0,
                                  "ticks": 0, "reads": 0, "writes": 0,
                                  "findings": []}


def test_from_config_gates_on_race_check_enabled():
    from ceph_tpu.utils import Config

    cfg = Config()
    assert cfg.race_check_enabled == 0
    assert racecheck.from_config(cfg) is NULL_RACE
    cfg.race_check_enabled = 1
    cfg.race_check_seed = 5
    t = racecheck.from_config(cfg)
    assert isinstance(t, RaceTracker)
    assert t.seed == 5


# ------------------------------------------------------------ the tracker


def test_tracker_convicts_cross_task_write_after_read():
    t = RaceTracker(seed=3)

    async def main():
        wrote = asyncio.Event()

        async def reader():
            t.note_read(("pg", 0, "1.0"), "self_info")
            await wrote.wait()      # finishes WITHOUT re-reading

        async def writer():
            await asyncio.sleep(0)
            t.note_write(("pg", 0, "1.0"), "self_info")
            wrote.set()

        rt = asyncio.get_event_loop().create_task(reader(),
                                                  name="recovery-round")
        wt = asyncio.get_event_loop().create_task(writer(),
                                                  name="commit-entry")
        await asyncio.gather(rt, wt)
        return t.findings()

    found = asyncio.run(main())
    assert len(found) == 1
    f = found[0]
    assert f["rule"] == "write-after-read"
    assert "recovery-round" in f["message"]
    assert "commit-entry" in f["message"]
    # both probes attributed: task, site, stack
    assert f["read"]["task"] == "recovery-round" and f["read"]["stack"]
    assert f["write"]["task"] == "commit-entry" and f["write"]["stack"]


def test_tracker_reread_revalidates():
    """A re-read AFTER the write is exactly what a fix looks like (the
    PR-11 refresh, the PR-9 identity recheck): no conviction."""
    t = RaceTracker()

    async def main():
        wrote = asyncio.Event()

        async def reader():
            t.note_read(("pg", 0, "1.0"), "self_info")
            await wrote.wait()
            t.note_read(("pg", 0, "1.0"), "self_info")   # the refresh

        async def writer():
            await asyncio.sleep(0)
            t.note_write(("pg", 0, "1.0"), "self_info")
            wrote.set()

        await asyncio.gather(asyncio.ensure_future(reader()),
                             asyncio.ensure_future(writer()))
        return t.findings()

    assert asyncio.run(main()) == []


def test_tracker_common_lock_suppresses():
    """Reader and writer holding a shared DepLock at their probes were
    serialized by it — no interleaving to convict."""
    t = RaceTracker()

    async def main():
        wrote = asyncio.Event()

        async def reader():
            DepLock._held[id(asyncio.current_task())] = ["pg:1.0"]
            t.note_read(("pgs", 0, "1.0"), "registry")
            await wrote.wait()

        async def writer():
            await asyncio.sleep(0)
            DepLock._held[id(asyncio.current_task())] = ["pg:1.0"]
            t.note_write(("pgs", 0, "1.0"), "registry")
            wrote.set()

        await asyncio.gather(asyncio.ensure_future(reader()),
                             asyncio.ensure_future(writer()))
        return t.findings()

    assert asyncio.run(main()) == []


def test_tracker_cancelled_reader_never_convicts():
    """Chaos kills cancel in-flight commit tasks; a cancelled reader
    unwound without acting on its snapshot."""
    t = RaceTracker()

    async def main():
        async def reader():
            t.note_read(("pgs", 0, "1.0"), "registry")
            # not a timing guess: park forever so cancel() is the only
            # way out — the cancelled-reader shape under test
            await asyncio.sleep(3600)  # graftlint: ignore[fixed-sleep-in-tests]

        rt = asyncio.get_event_loop().create_task(reader())
        await asyncio.sleep(0)
        t.note_write(("pgs", 0, "1.0"), "registry")
        rt.cancel()
        try:
            await rt
        except asyncio.CancelledError:
            pass
        return t.findings()

    assert asyncio.run(main()) == []


def test_tracker_own_write_neither_convicts_nor_revalidates():
    """A task's own write doesn't convict it (no interleaving), but its
    local snapshot is STILL stale — the record must stand so a later
    cross-task write convicts (the single-task half of the PR-11 bug)."""
    t = RaceTracker()

    async def main():
        wrote = asyncio.Event()

        async def reader():
            t.note_read(("pg", 0, "1.0"), "self_info")
            t.note_write(("pg", 0, "1.0"), "self_info")   # own write
            await wrote.wait()

        async def writer():
            await asyncio.sleep(0)
            t.note_write(("pg", 0, "1.0"), "self_info")
            wrote.set()

        await asyncio.gather(asyncio.ensure_future(reader()),
                             asyncio.ensure_future(writer()))
        return t.findings()

    found = asyncio.run(main())
    assert len(found) == 1, "record was dropped by the task's own write"


# ------------------------- the two lint-corpus bug classes, at runtime


def _recovery_shape(refresh: bool):
    """The PR-11 shape as the probes see it: a recovery round snapshots
    self-info, awaits peer queries, and (fixed) re-reads after the
    await; a concurrent commit advances the log head meanwhile."""
    t = RaceTracker()

    async def main():
        advanced = asyncio.Event()

        async def recovery_round():
            t.note_read(("pg", 0, "1.0"), "self_info")    # round start
            await advanced.wait()                          # peer query
            if refresh:
                t.note_read(("pg", 0, "1.0"), "self_info")  # the fix
            # ... elects an authority from infos and returns

        async def commit():
            await asyncio.sleep(0)
            t.note_write(("pg", 0, "1.0"), "self_info")   # log head +1
            advanced.set()

        await asyncio.gather(asyncio.ensure_future(recovery_round()),
                             asyncio.ensure_future(commit()))
        return t.findings()

    return asyncio.run(main())


def test_pr11_stale_selfinfo_shape_convicts():
    assert len(_recovery_shape(refresh=False)) == 1


def test_pr11_refreshed_selfinfo_shape_is_quiet():
    assert _recovery_shape(refresh=True) == []


def _commit_shape(recheck: bool):
    """The PR-9 shape: a commit opens against the PGState it pulled
    from the registry, awaits acks, and (fixed) re-checks registry
    identity at resolve time; peering replaces the entry meanwhile."""
    t = RaceTracker()

    async def main():
        replaced = asyncio.Event()

        async def commit():
            t.note_read(("pgs", 0, "1.0"), "registry")    # frontier open
            await replaced.wait()                          # ack wait
            if recheck:
                t.note_read(("pgs", 0, "1.0"), "registry")  # _frontier_done
            # ... advances the watermark on the snapshot it held

        async def map_apply():
            await asyncio.sleep(0)
            t.note_write(("pgs", 0, "1.0"), "registry")   # entry replaced
            replaced.set()

        await asyncio.gather(asyncio.ensure_future(commit()),
                             asyncio.ensure_future(map_apply()))
        return t.findings()

    return asyncio.run(main())


def test_pr9_superseded_pgstate_shape_convicts():
    assert len(_commit_shape(recheck=False)) == 1


def test_pr9_identity_recheck_shape_is_quiet():
    assert _commit_shape(recheck=True) == []


# ------------------------------------------------------- race_run + CLI


def test_race_run_unknown_scenario_raises():
    with pytest.raises(KeyError):
        race_run("no-such-scenario", 1)


def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "_graftlint_cli", os.path.join(REPO, "scripts", "graftlint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_graftlint_race_cli_exit_codes(monkeypatch, capsys):
    """--race contract: 0 clean, 1 convictions or scenario failures,
    2 usage errors — CI tells 'found a race' from 'asked wrong'."""
    cli = _load_cli()
    assert cli.main(["--race", "batch-smoke", "--seeds", "bogus"]) == 2
    assert cli.main(["--race", "batch-smoke", "--seeds", ""]) == 2
    assert cli.main(["--race", "definitely-not-a-scenario"]) == 2

    class _Pass:
        passed = True
        failures = []

    class _Fail:
        passed = False
        failures = ["durability: obj3 unreadable"]

    clean = {"enabled": True, "seed": 1, "ticks": 3, "reads": 1,
             "writes": 1, "findings": []}
    dirty = dict(clean, findings=[{"message": "task A raced task B",
                                   "rule": "write-after-read"}])
    monkeypatch.setattr(racecheck, "race_run",
                        lambda *a, **k: (_Pass, clean, "digest"))
    assert cli.main(["--race", "batch-smoke", "--seeds", "1,2"]) == 0
    monkeypatch.setattr(racecheck, "race_run",
                        lambda *a, **k: (_Pass, dirty, "digest"))
    assert cli.main(["--race", "batch-smoke", "--seeds", "1"]) == 1
    monkeypatch.setattr(racecheck, "race_run",
                        lambda *a, **k: (_Fail, clean, "digest"))
    assert cli.main(["--race", "batch-smoke", "--seeds", "1"]) == 1
    capsys.readouterr()


def test_admin_race_report_command():
    """`race report` serves the tracker's report, and the disabled
    payload (never an error) when no tracker is installed — the
    blackbox-dump contract."""
    from ceph_tpu.utils.admin_socket import AdminSocket
    from ceph_tpu.utils.perf import PerfCounters

    sock = AdminSocket()
    sock.register_common(PerfCounters("t"))
    res, data = asyncio.run(sock.dispatch({"prefix": "race report"}))
    assert res == 0 and data["enabled"] is False
    prev = racecheck.install(RaceTracker(seed=9))
    try:
        res, data = asyncio.run(sock.dispatch({"prefix": "race report"}))
        assert res == 0 and data["enabled"] is True and data["seed"] == 9
    finally:
        racecheck.install(prev)


def test_boot_arms_tracker_from_config():
    """`race_check_enabled=1` arms the process-global tracker at
    vstart boot (seeded from `race_check_seed`), live I/O moves the
    probe counters, and `race report` serves them; a default boot
    leaves NULL_RACE installed."""
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    async def scenario():
        cfg = _fast_config()
        cfg.set("race_check_enabled", 1)
        cfg.set("race_check_seed", 7)
        cluster = await start_cluster(3, config=cfg)
        try:
            assert racecheck.TRACKER.enabled
            client = await cluster.client()
            pool = await client.pool_create("p", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            await io.write_full("obj", b"x" * 512)
            assert await io.read("obj") == b"x" * 512
            return await cluster.daemon_command("osd.0", "race report")
        finally:
            await cluster.stop()
            racecheck.uninstall()

    assert racecheck.TRACKER is racecheck.NULL_RACE
    try:
        rep = asyncio.run(scenario())
    finally:
        racecheck.uninstall()
    assert rep["enabled"] is True and rep["seed"] == 7
    assert rep["reads"] > 0 and rep["writes"] > 0
    assert rep["findings"] == [], rep["findings"]
    assert racecheck.TRACKER is racecheck.NULL_RACE


# ------------------------------------------- regression: frontier re-arm


def test_frontier_rearm_when_drained_short():
    """batch-smoke seed 2, wedge #1: every open frontier entry resolved
    (some ok=False — their acks died with a crashed peer) leaves the
    pipeline DRAINED with the watermark short of the log head, and no
    later ack or map change is coming — without a re-arm the primary is
    incomplete forever on an idle pool.  _frontier_done must arm the
    recovery retry exactly then."""
    from ceph_tpu.cluster.pg import PGLogMixin, PGState
    from ceph_tpu.osdmap.osdmap import PGid
    from ceph_tpu.utils import PerfCounters

    class _Store:
        def omap_get(self, coll, oid):
            return {}

        def queue_transaction(self, txn):
            pass

    class _Host(PGLogMixin):
        osd_id = 0

        def __init__(self):
            self.store = _Store()
            self.perf = PerfCounters("t")
            self.retries = []

        def _queue_recovery_retry(self, st):
            self.retries.append(st)

    h = _Host()
    st = PGState(PGid(1, 0))
    st.primary = 0
    for v in ((1, 1), (1, 2)):
        h._frontier_open(st, v)
    st.last_update = (1, 2)
    h._frontier_done(st, (1, 1), ok=True)
    assert h.retries == []          # (1,2) still open: not drained
    h._frontier_done(st, (1, 2), ok=False)   # acks lost: resolves dirty
    assert not st.pipeline_pending
    assert st.last_complete == (1, 1) and st.last_update == (1, 2)
    assert h.retries == [st], "drained-short frontier did not re-arm"

    # watermark AT the head after a clean drain: no spurious re-arm
    h2 = _Host()
    st2 = PGState(PGid(1, 1))
    st2.primary = 0
    h2._frontier_open(st2, (1, 1))
    st2.last_update = (1, 1)
    h2._frontier_done(st2, (1, 1), ok=True)
    assert h2.retries == []

    # a REPLICA never self-arms (peering is primary-driven)
    h3 = _Host()
    st3 = PGState(PGid(1, 2))
    st3.primary = 7
    h3._frontier_open(st3, (1, 1))
    st3.last_update = (1, 1)
    h3._frontier_done(st3, (1, 1), ok=False)
    assert h3.retries == []


# --------------------------------- regression: planar rewind attr restore


def test_planar_rewind_restores_attrs_and_version():
    """batch-smoke seed 2, wedge #2: rewinding a divergent planar-at-rest
    write restored the old PLANES but left the divergent write's
    size/hinfo_crc/version attrs stamped — old data under a new crc, so
    the member failed verify-on-read on every later gather (and with two
    of k+m=3 members rewound, the object was unreadable AND unrepairable).
    Attrs and version must roll back with the bytes."""
    from ceph_tpu.cluster.backend_ec import ECBackendMixin
    from ceph_tpu.cluster.pg import PGLogMixin, PGState
    from ceph_tpu.cluster.pglog import LogEntry, PGLog
    from ceph_tpu.cluster.store import MemStore, Transaction
    from ceph_tpu.ec import planar_store
    from ceph_tpu.osdmap.osdmap import PGid
    from ceph_tpu.utils import PerfCounters

    class _Host(ECBackendMixin, PGLogMixin):
        osd_id = 0

        def __init__(self):
            self.store = MemStore()
            self.perf = PerfCounters("t")

    h = _Host()
    pgid = PGid(1, 0)
    coll = f"pg_{pgid.pool}_{pgid.seed}"
    h.store.queue_transaction(Transaction().create_collection(coll))

    def planar_blob(byte: bytes, n: int) -> bytes:
        return planar_store.planes_to_blob(
            planar_store.shard_to_planes(byte * n, seam=None))

    # v1: the committed generation (64-byte shard, logical size 120)
    h._apply_shard(pgid, "obj", 0, planar_blob(b"A", 64), 0, 64,
                   {"size": 120, "version": 1},
                   layout=planar_store.LAYOUT_PLANAR)
    v1_planes = h.store.read_planar(coll, "obj")
    v1_attrs = {k: h.store.getattr(coll, "obj", k)
                for k in ("shard", "size", "hinfo_crc")}
    assert v1_attrs["hinfo_crc"] is not None

    # v2: the divergent write (different bytes AND size)
    h._apply_shard(pgid, "obj", 0, planar_blob(b"B", 72), 0, 72,
                   {"size": 130, "version": 2},
                   layout=planar_store.LAYOUT_PLANAR)
    assert h.store.getattr(coll, "obj", "size") == b"130"
    assert h.store.getattr(coll, "obj", "hinfo_crc") != \
        v1_attrs["hinfo_crc"]

    st = PGState(pgid)
    st.log = PGLog(entries=[
        LogEntry(op="modify", oid="obj", version=(1, 1)),
        LogEntry(op="modify", oid="obj", version=(1, 2))])
    st.last_update = (1, 2)
    h.rewind_divergent_log(st, (1, 1))

    assert h.store.read_planar(coll, "obj") == v1_planes
    assert h.store.object_layout(coll, "obj") == \
        planar_store.LAYOUT_PLANAR
    for name, want in v1_attrs.items():
        assert h.store.getattr(coll, "obj", name) == want, \
            f"attr {name!r} not rolled back with the planes"
    assert h.store.get_version(coll, "obj") == 1


# ------------------------------------------------------- the race smokes


@pytest.mark.chaos
def test_race_smoke_batch_seeds():
    """Tier-1 dynamic gate: shrunk batch-smoke under the perturbed loop
    with the tracker armed, three seeds.  Seed 2 is the one that
    convicted both regression anchors above — green here means the
    fixes hold UNDER the hostile interleavings, not just on FIFO."""
    keys = {}
    for seed in (1, 2, 3):
        verdict, report, digest = race_run("batch-smoke", seed,
                                           shrink=True)
        assert verdict.passed, (seed, verdict.failures)
        assert report["findings"] == [], (seed, report["findings"])
        # the probes flowed: a silently unprobed run would pass forever
        assert report["reads"] > 0 and report["writes"] > 0
        assert racecheck.TRACKER is NULL_RACE    # restored after the run
        keys[seed] = verdict.replay_key()
    # seeded replay: same seed -> same resolved schedule and outcome
    # (trace digests are NOT asserted for cluster runs — select()
    # readiness order is the OS's; the IO-free tests above pin digests)
    v2, _, _ = race_run("batch-smoke", 1, shrink=True)
    assert v2.replay_key() == keys[1]


@pytest.mark.race
@pytest.mark.chaos
@pytest.mark.parametrize("scenario", ["batch-smoke", "overload-smoke",
                                      "smoke"])
def test_race_full_scenarios(scenario):
    """Full-scale sanitizer pass (slow-implied via the race marker):
    whole scenarios under the shim, two seeds each."""
    for seed in (1, 2):
        verdict, report, _ = race_run(scenario, seed)
        assert verdict.passed, (scenario, seed, verdict.failures)
        assert report["findings"] == [], (scenario, seed,
                                          report["findings"])
