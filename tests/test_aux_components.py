"""Auxiliary components: Compressor, SloppyCRCMap, KeyValueDB, lockdep.

Reference: src/compressor/, src/common/SloppyCRCMap.cc, src/kv/,
src/common/lockdep.cc.
"""

import asyncio

import pytest

from ceph_tpu.cluster.kv import KVTransaction, MemDB, StoreDB
from ceph_tpu.ops.sloppy_crc import SloppyCRCMap
from ceph_tpu.utils import compressor
from ceph_tpu.utils.lockdep import DepLock, LockCycleError, LockDep


@pytest.mark.parametrize("name", ["zlib", "lzma", "bz2", "snappy"])
def test_compressor_roundtrip(name):
    c = compressor.create(name)
    data = b"compress me " * 1000
    blob = c.compress(data)
    assert len(blob) < len(data)
    assert c.decompress(blob) == data


def test_compressor_registry():
    assert set(compressor.get_available()) >= {"zlib", "lzma", "bz2"}
    with pytest.raises(ValueError):
        compressor.create("nope")


def test_maybe_compress_required_ratio():
    ok, blob = compressor.maybe_compress("zlib", b"a" * 10000)
    assert ok and len(blob) < 10000
    import os

    ok, blob = compressor.maybe_compress("zlib", os.urandom(4096))
    assert not ok and len(blob) == 4096  # incompressible: left alone


def test_sloppy_crc_detects_rot():
    m = SloppyCRCMap(block_size=64)
    data = bytes(range(256))
    m.write(0, data)
    assert m.read(0, data) == []
    rotted = bytearray(data)
    rotted[70] ^= 0xFF
    bad = m.read(0, bytes(rotted))
    assert len(bad) == 1 and bad[0][0] == 1  # block 1 flagged
    # partial overwrite invalidates that block's crc, so no false alarm
    m.write(65, b"zz")
    assert all(b != 1 for b, _, _ in m.read(0, bytes(rotted)))


def test_sloppy_crc_truncate():
    m = SloppyCRCMap(block_size=64)
    m.write(0, bytes(256))
    m.truncate(100)
    assert sorted(m.crc) == [0]


@pytest.mark.parametrize("mk", ["mem", "store"])
def test_kv_db(mk, tmp_path):
    if mk == "mem":
        db = MemDB()
    else:
        from ceph_tpu.cluster.filestore import FileStore

        store = FileStore(str(tmp_path / "kv"))
        store.mount()
        db = StoreDB(store)
    db.submit_transaction(
        KVTransaction().set("osdmap", "epoch_1", b"m1")
        .set("osdmap", "epoch_2", b"m2").set("paxos", "v", b"p"))
    assert db.get("osdmap", "epoch_1") == b"m1"
    assert list(db.iterate("osdmap")) == [
        ("epoch_1", b"m1"), ("epoch_2", b"m2")]
    db.submit_transaction(KVTransaction().rmkey("osdmap", "epoch_1"))
    assert db.get("osdmap", "epoch_1") is None
    db.submit_transaction(KVTransaction().rmkeys_by_prefix("paxos"))
    assert db.get("paxos", "v") is None
    if mk == "store":
        # durability through the journaled store
        store.umount()
        from ceph_tpu.cluster.filestore import FileStore

        store2 = FileStore(str(tmp_path / "kv"))
        store2.mount()
        db2 = StoreDB(store2)
        assert db2.get("osdmap", "epoch_2") == b"m2"
        store2.umount()


def test_lockdep_detects_cycle():
    LockDep.instance().reset()
    a, b = DepLock("A"), DepLock("B")

    async def ab():
        async with a:
            async with b:
                pass

    async def ba():
        async with b:
            async with a:
                pass

    asyncio.run(ab())             # establishes A -> B
    with pytest.raises(LockCycleError):
        asyncio.run(ba())         # B -> A closes the cycle
    LockDep.instance().reset()


def test_lockdep_allows_consistent_order():
    LockDep.instance().reset()
    a, b, c = DepLock("A2"), DepLock("B2"), DepLock("C2")

    async def chain():
        async with a:
            async with b:
                async with c:
                    pass

    asyncio.run(chain())
    asyncio.run(chain())  # same order again: fine
    LockDep.instance().reset()
