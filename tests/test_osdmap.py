"""OSDMap placement pipeline: scalar vs batched, overrides, rebalance."""

import copy

import numpy as np
import pytest

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.osdmap import OSDMap, PGPool, PGid
from ceph_tpu.osdmap.osdmap import (
    POOL_TYPE_ERASURE,
    POOL_TYPE_REPLICATED,
    build_simple_osdmap,
    ceph_stable_mod,
)


def test_stable_mod():
    # reference ceph_stable_mod semantics
    assert ceph_stable_mod(9, 8, 15) == 1
    assert ceph_stable_mod(13, 12, 15) == 5
    for x in range(64):
        v = ceph_stable_mod(x, 12, 15)
        assert 0 <= v < 12


@pytest.mark.parametrize("ptype", [POOL_TYPE_REPLICATED, POOL_TYPE_ERASURE],
                         ids=["replicated", "erasure"])
def test_batched_matches_scalar(ptype):
    m = build_simple_osdmap(n_osds=24, osds_per_host=4, pg_num=64,
                            pool_type=ptype, size=3)
    m.mark_down(5)
    m.mark_out(9)
    m.set_primary_affinity(2, 0x8000)
    pg = PGid(1, 3)
    m.pg_upmap_items[pg] = [(m.pg_to_up_acting_osds(pg)[0][0], 11)]
    up, upp = m.pool_mapping(1)
    for s in range(64):
        want_up, want_p, _, _ = m.pg_to_up_acting_osds(PGid(1, s))
        got = [int(v) for v in up[s] if v != CRUSH_ITEM_NONE] \
            if ptype == POOL_TYPE_REPLICATED else [int(v) for v in up[s]]
        if ptype == POOL_TYPE_REPLICATED:
            assert got == want_up, s
        else:
            assert got[: len(want_up)] == want_up, s
        assert int(upp[s]) == want_p, s


def test_down_osd_leaves_up_set():
    m = build_simple_osdmap(n_osds=16, pg_num=32)
    pg = PGid(1, 0)
    up0, p0, _, _ = m.pg_to_up_acting_osds(pg)
    assert len(up0) == 3 and p0 == up0[0]
    m.mark_down(up0[0])
    up1, p1, _, _ = m.pg_to_up_acting_osds(pg)
    assert up0[0] not in up1
    assert p1 != up0[0]


def test_erasure_keeps_positions():
    m = build_simple_osdmap(n_osds=16, pg_num=32, pool_type=POOL_TYPE_ERASURE,
                            size=4)
    pg = PGid(1, 7)
    up0, _, _, _ = m.pg_to_up_acting_osds(pg)
    assert len(up0) == 4
    m.mark_down(up0[1])
    up1, _, _, _ = m.pg_to_up_acting_osds(pg)
    # indep placement is positionally stable: slot 1 becomes NONE
    assert up1[1] == CRUSH_ITEM_NONE
    assert up1[0] == up0[0] and up1[2] == up0[2] and up1[3] == up0[3]


def test_pg_temp():
    m = build_simple_osdmap(n_osds=16, pg_num=32)
    pg = PGid(1, 4)
    up, upp, acting, actp = m.pg_to_up_acting_osds(pg)
    assert acting == up
    others = [o for o in range(12) if o not in up][:3]
    m.pg_temp[pg] = others
    up2, _, acting2, actp2 = m.pg_to_up_acting_osds(pg)
    assert up2 == up  # up unchanged
    assert acting2 == others
    assert actp2 == others[0]


def test_upmap_full_override():
    m = build_simple_osdmap(n_osds=16, pg_num=32)
    pg = PGid(1, 9)
    target = [1, 5, 9]
    m.pg_upmap[pg] = target
    up, p, _, _ = m.pg_to_up_acting_osds(pg)
    assert up == target
    # upmap to an out osd is ignored
    m.mark_out(5)
    up2, _, _, _ = m.pg_to_up_acting_osds(pg)
    assert up2 != target


def test_rebalance_diff():
    m = build_simple_osdmap(n_osds=32, osds_per_host=4, pg_num=128)
    m2 = copy.deepcopy(m)
    m2.mark_out(3)
    m2._tensor = None  # rebuild mapper after weight change
    moved, frac = m.rebalance_diff(1, m2)
    assert 0 < len(moved) < 128
    # only PGs that mapped to osd 3 (or cascade) should move; most stay
    assert frac < 0.5


def test_pps_batch_matches_scalar():
    pool = PGPool(pool_id=7, pg_num=64, pgp_num=48)
    seeds = np.arange(64, dtype=np.uint32)
    batch = pool.raw_pg_to_pps_batch(seeds)
    for s in range(64):
        assert int(batch[s]) == pool.raw_pg_to_pps(s)


def test_apply_incremental_matches_direct_mutation():
    from ceph_tpu.osdmap.osdmap import Incremental

    m = build_simple_osdmap(n_osds=16, pg_num=32)
    direct = copy.deepcopy(m)
    direct.mark_down(3)
    direct.mark_out(3)
    direct.mark_down(7)

    inc = Incremental(epoch=m.epoch + 1)
    inc.new_down.extend([3, 7])
    inc.new_weights[3] = 0
    m.apply_incremental(inc)

    assert not m.osd_up[3] and not m.osd_up[7]
    assert m.osd_weight[3] == 0
    for seed in range(32):
        assert m.pg_to_up_acting_osds(PGid(1, seed)) == \
            direct.pg_to_up_acting_osds(PGid(1, seed))

    # a gap is rejected
    bad = Incremental(epoch=m.epoch + 5)
    with pytest.raises(ValueError):
        m.apply_incremental(bad)


def test_apply_incremental_new_pool_and_rule():
    from ceph_tpu.crush.types import (
        RULE_CHOOSELEAF_FIRSTN, RULE_EMIT, RULE_TAKE, Rule)
    from ceph_tpu.osdmap.osdmap import Incremental

    m = build_simple_osdmap(n_osds=16, pg_num=32)
    root = [bid for bid, b in m.crush.buckets.items() if b.type == 3][0]
    ruleno = len(m.crush.rules)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_rules.append(Rule(steps=[
        (RULE_TAKE, root, 0), (RULE_CHOOSELEAF_FIRSTN, 2, 1),
        (RULE_EMIT, 0, 0)]))
    inc.new_pools[9] = PGPool(pool_id=9, size=2, min_size=1, pg_num=16,
                              pgp_num=16, crush_rule=ruleno, name="p9")
    m.apply_incremental(inc)
    up, upp, acting, actp = m.pg_to_up_acting_osds(PGid(9, 0))
    assert len(up) == 2 and upp == up[0]


def test_incremental_pg_temp_set_and_clear():
    from ceph_tpu.osdmap.osdmap import Incremental

    m = build_simple_osdmap(n_osds=16, pg_num=32)
    pg = PGid(1, 5)
    up, upp, _, _ = m.pg_to_up_acting_osds(pg)
    temp = [o for o in range(16) if o not in up][:3]
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pg_temp[pg] = temp
    m.apply_incremental(inc)
    _, _, acting, actp = m.pg_to_up_acting_osds(pg)
    assert acting == temp and actp == temp[0]
    inc2 = Incremental(epoch=m.epoch + 1)
    inc2.new_pg_temp[pg] = []
    m.apply_incremental(inc2)
    _, _, acting, _ = m.pg_to_up_acting_osds(pg)
    assert acting == up


def test_pool_mapping_scalar_fallback_uniform_bucket():
    """A map the TensorMapper rejects (uniform bucket) must still batch-map
    via the scalar fallback, matching the per-PG chain."""
    from ceph_tpu.crush.types import (
        Bucket, CrushMap, RULE_CHOOSELEAF_FIRSTN, RULE_EMIT, RULE_TAKE, Rule)

    cmap = CrushMap()
    host_ids = []
    dev = 0
    for h in range(4):
        items = [dev, dev + 1]
        dev += 2
        hid = cmap.add_bucket(
            Bucket(id=0, type=1, alg="uniform", items=items,
                   weights=[0x10000, 0x10000]), name=f"host{h}")
        host_ids.append(hid)
    root = cmap.add_bucket(
        Bucket(id=0, type=3, alg="straw2", items=host_ids,
               weights=[0x20000] * 4), name="default")
    ruleno = cmap.add_rule(Rule(steps=[
        (RULE_TAKE, root, 0), (RULE_CHOOSELEAF_FIRSTN, 3, 1),
        (RULE_EMIT, 0, 0)]))
    m = OSDMap(cmap, max_osd=8)
    m.add_pool(PGPool(pool_id=1, size=3, min_size=2, pg_num=32, pgp_num=32,
                      crush_rule=ruleno, name="u"))
    with pytest.raises(NotImplementedError):
        _ = m.tensor_mapper
    up, upp = m.pool_mapping(1)  # must not raise: scalar fallback
    for seed in range(32):
        su, supp, _, _ = m.pg_to_up_acting_osds(PGid(1, seed))
        row = [int(o) for o in up[seed] if o != CRUSH_ITEM_NONE]
        assert row == su, seed
        assert int(upp[seed]) == supp
    # the fallback must be SURFACED, not silent (r3 verdict weakness #5):
    # counted on the map and reported by the mon 'status' command
    assert getattr(m, "scalar_fallbacks", 0) >= 1
