"""OSDMap placement pipeline: scalar vs batched, overrides, rebalance."""

import copy

import numpy as np
import pytest

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.osdmap import OSDMap, PGPool, PGid
from ceph_tpu.osdmap.osdmap import (
    POOL_TYPE_ERASURE,
    POOL_TYPE_REPLICATED,
    build_simple_osdmap,
    ceph_stable_mod,
)


def test_stable_mod():
    # reference ceph_stable_mod semantics
    assert ceph_stable_mod(9, 8, 15) == 1
    assert ceph_stable_mod(13, 12, 15) == 5
    for x in range(64):
        v = ceph_stable_mod(x, 12, 15)
        assert 0 <= v < 12


@pytest.mark.parametrize("ptype", [POOL_TYPE_REPLICATED, POOL_TYPE_ERASURE],
                         ids=["replicated", "erasure"])
def test_batched_matches_scalar(ptype):
    m = build_simple_osdmap(n_osds=24, osds_per_host=4, pg_num=64,
                            pool_type=ptype, size=3)
    m.mark_down(5)
    m.mark_out(9)
    m.set_primary_affinity(2, 0x8000)
    pg = PGid(1, 3)
    m.pg_upmap_items[pg] = [(m.pg_to_up_acting_osds(pg)[0][0], 11)]
    up, upp = m.pool_mapping(1)
    for s in range(64):
        want_up, want_p, _, _ = m.pg_to_up_acting_osds(PGid(1, s))
        got = [int(v) for v in up[s] if v != CRUSH_ITEM_NONE] \
            if ptype == POOL_TYPE_REPLICATED else [int(v) for v in up[s]]
        if ptype == POOL_TYPE_REPLICATED:
            assert got == want_up, s
        else:
            assert got[: len(want_up)] == want_up, s
        assert int(upp[s]) == want_p, s


def test_down_osd_leaves_up_set():
    m = build_simple_osdmap(n_osds=16, pg_num=32)
    pg = PGid(1, 0)
    up0, p0, _, _ = m.pg_to_up_acting_osds(pg)
    assert len(up0) == 3 and p0 == up0[0]
    m.mark_down(up0[0])
    up1, p1, _, _ = m.pg_to_up_acting_osds(pg)
    assert up0[0] not in up1
    assert p1 != up0[0]


def test_erasure_keeps_positions():
    m = build_simple_osdmap(n_osds=16, pg_num=32, pool_type=POOL_TYPE_ERASURE,
                            size=4)
    pg = PGid(1, 7)
    up0, _, _, _ = m.pg_to_up_acting_osds(pg)
    assert len(up0) == 4
    m.mark_down(up0[1])
    up1, _, _, _ = m.pg_to_up_acting_osds(pg)
    # indep placement is positionally stable: slot 1 becomes NONE
    assert up1[1] == CRUSH_ITEM_NONE
    assert up1[0] == up0[0] and up1[2] == up0[2] and up1[3] == up0[3]


def test_pg_temp():
    m = build_simple_osdmap(n_osds=16, pg_num=32)
    pg = PGid(1, 4)
    up, upp, acting, actp = m.pg_to_up_acting_osds(pg)
    assert acting == up
    others = [o for o in range(12) if o not in up][:3]
    m.pg_temp[pg] = others
    up2, _, acting2, actp2 = m.pg_to_up_acting_osds(pg)
    assert up2 == up  # up unchanged
    assert acting2 == others
    assert actp2 == others[0]


def test_upmap_full_override():
    m = build_simple_osdmap(n_osds=16, pg_num=32)
    pg = PGid(1, 9)
    target = [1, 5, 9]
    m.pg_upmap[pg] = target
    up, p, _, _ = m.pg_to_up_acting_osds(pg)
    assert up == target
    # upmap to an out osd is ignored
    m.mark_out(5)
    up2, _, _, _ = m.pg_to_up_acting_osds(pg)
    assert up2 != target


def test_rebalance_diff():
    m = build_simple_osdmap(n_osds=32, osds_per_host=4, pg_num=128)
    m2 = copy.deepcopy(m)
    m2.mark_out(3)
    m2._tensor = None  # rebuild mapper after weight change
    moved, frac = m.rebalance_diff(1, m2)
    assert 0 < len(moved) < 128
    # only PGs that mapped to osd 3 (or cascade) should move; most stay
    assert frac < 0.5


def test_pps_batch_matches_scalar():
    pool = PGPool(pool_id=7, pg_num=64, pgp_num=48)
    seeds = np.arange(64, dtype=np.uint32)
    batch = pool.raw_pg_to_pps_batch(seeds)
    for s in range(64):
        assert int(batch[s]) == pool.raw_pg_to_pps(s)
