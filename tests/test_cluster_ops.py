"""Client/OSD op breadth: xattr, omap, object classes (exec), and
watch/notify against a live cluster.

Mirrors the reference op-interpreter surface (PrimaryLogPG::do_osd_ops,
src/osd/PrimaryLogPG.cc:4917: xattr/omap/CALL/notify cases) and the
Objecter linger machinery (src/osdc/Objecter.cc:778).
"""

import asyncio
import pickle

import pytest

from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


def test_xattr_roundtrip_and_replication():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("xp", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            await io.write_full("obj", b"payload")
            await io.setxattr("obj", "user.k1", b"v1")
            await io.setxattr("obj", "user.k2", b"v2")
            assert await io.getxattr("obj", "user.k1") == b"v1"
            assert await io.getxattrs("obj") == {
                "user.k1": b"v1", "user.k2": b"v2"}
            await io.rmxattr("obj", "user.k1")
            with pytest.raises(KeyError):
                await io.getxattr("obj", "user.k1")
            # replicated to every acting member's store (with the "_"
            # user-attr prefix)
            pgid = client.objecter.object_pgid(pool, "obj")
            _, _, acting, _ = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)

            # converge-poll: replica applies land asynchronously after
            # the ack — wait for the state, not a guessed duration
            def _replicated() -> bool:
                for o in acting:
                    xs = cluster.osds[o].store.get_xattrs(
                        f"pg_{pgid.pool}_{pgid.seed}", "obj")
                    if xs.get("_user.k2") != b"v2" or "_user.k1" in xs:
                        return False
                return True

            deadline = asyncio.get_event_loop().time() + 10.0
            while not _replicated() and \
                    asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
            for o in acting:
                xs = cluster.osds[o].store.get_xattrs(
                    f"pg_{pgid.pool}_{pgid.seed}", "obj")
                assert xs.get("_user.k2") == b"v2", o
                assert "_user.k1" not in xs, o
            # missing object
            with pytest.raises(IOError):
                await io.getxattrs("nope")
        finally:
            await cluster.stop()

    run(scenario())


def test_omap_roundtrip():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("op", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"x")
            await io.omap_set("obj", {"a": b"1", "b": b"2", "c": b"3"})
            assert await io.omap_get("obj") == {
                "a": b"1", "b": b"2", "c": b"3"}
            await io.omap_rmkeys("obj", ["b"])
            assert await io.omap_get("obj") == {"a": b"1", "c": b"3"}
        finally:
            await cluster.stop()

    run(scenario())


def test_object_class_exec():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("cp", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"x")
            # cls_hello analog
            out = await io.execute("obj", "hello", "say_hello", b"ceph")
            assert out == b"Hello, ceph!"
            # cls_lock analog: exclusive lock semantics
            req = pickle.dumps({"name": "l1", "cookie": "c1"})
            await io.execute("obj", "lock", "lock", req)
            other = pickle.dumps({"name": "l1", "cookie": "c2"})
            with pytest.raises(IOError):
                await io.execute("obj", "lock", "lock", other)
            await io.execute("obj", "lock", "unlock", req)
            await io.execute("obj", "lock", "lock", other)  # now free
            # unknown class fails loudly
            with pytest.raises(IOError):
                await io.execute("obj", "nosuch", "m", b"")
        finally:
            await cluster.stop()

    run(scenario())


def test_watch_notify():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            watcher = await cluster.client("watcher")
            pool = await client.pool_create("wp", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            wio = watcher.ioctx(pool)
            await io.write_full("obj", b"x")

            got = []
            cookie = await wio.watch("obj", lambda payload:
                                     got.append(payload))
            ackers = await io.notify("obj", b"ping-1")
            assert got == [b"ping-1"]
            assert len(ackers) == 1

            # second notify, then unwatch stops delivery
            await io.notify("obj", b"ping-2")
            assert got == [b"ping-1", b"ping-2"]
            await wio.unwatch("obj", cookie)
            ackers = await io.notify("obj", b"ping-3")
            assert ackers == []
            assert got == [b"ping-1", b"ping-2"]
        finally:
            await cluster.stop()

    run(scenario())


from tests._flaky import contention_retry as _cr


@_cr()
def test_extended_osd_verbs_replicated_and_ec():
    """Round-4 widening of the do_osd_ops interpreter: append, truncate,
    zero, exclusive create, cmpxattr (reference PrimaryLogPG.cc:4917
    cases) on BOTH pool types."""
    import asyncio

    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pools = []
            pools.append(await client.pool_create(
                "verbs_r", "replicated", pg_num=8, size=2))
            pools.append(await client.pool_create(
                "verbs_e", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"}))
            for pool in pools:
                io = client.ioctx(pool)
                # append: atomic, returns the landing offset
                off0 = await io.append("log", b"one")
                off1 = await io.append("log", b"two")
                assert (off0, off1) == (0, 3)
                assert await io.read("log") == b"onetwo"
                # truncate shrink + grow (zero-extended)
                await io.write_full("t", b"0123456789" * 40)
                await io.truncate("t", 5)
                assert await io.read("t") == b"01234"
                await io.truncate("t", 8)
                assert await io.read("t") == b"01234\0\0\0"
                # zero a range
                await io.write_full("z", b"Z" * 64)
                await io.zero("z", 8, 16)
                got = await io.read("z")
                assert got[8:24] == b"\0" * 16 and got[:8] == b"Z" * 8
                # exclusive create
                await io.create("fresh")
                with __import__("pytest").raises(FileExistsError):
                    await io.create("fresh")
                # cmpxattr guard
                await io.setxattr("fresh", "tag", b"v1")
                assert await io.cmpxattr("fresh", "tag", b"v1")
                assert not await io.cmpxattr("fresh", "tag", b"v2")
        finally:
            await cluster.stop()

    asyncio.run(scenario())


def test_compound_op_vector_gates_on_first_error():
    """ADVICE r4: the op vector must stop at the FIRST failing op (the
    reference do_osd_ops `while (!bp.end() && !result)`) and return one
    terminal reply — a cmpxattr mismatch really gates the writes behind
    it."""
    async def scenario():
        cluster = await start_cluster(2)
        try:
            client = await cluster.client()
            pool = await client.pool_create("gate", "replicated",
                                            pg_num=4, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"original")
            await io.setxattr("obj", "user.state", b"ready")
            # matching gate: the write lands
            r = await client.objecter.op_submit(pool, "obj", [
                ("cmpxattr", {"name": "user.state", "value": b"ready"}),
                ("write_full", {"data": b"updated"})])
            assert r.result == 0
            assert await io.read("obj") == b"updated"
            # mismatching gate: -ECANCELED and the write must NOT land
            r = await client.objecter.op_submit(pool, "obj", [
                ("cmpxattr", {"name": "user.state", "value": b"WRONG"}),
                ("write_full", {"data": b"MUST-NOT-LAND"})])
            assert r.result == -125
            assert await io.read("obj") == b"updated"
        finally:
            await cluster.stop()

    run(scenario())


def test_mutation_never_lands_before_failing_guard():
    """Reference atomicity approximation: a mutation placed BEFORE a
    failing guard in the vector must not land (guards run first)."""
    async def scenario():
        cluster = await start_cluster(2)
        try:
            client = await cluster.client()
            pool = await client.pool_create("gate2", "replicated",
                                            pg_num=4, size=2)
            io = client.ioctx(pool)
            await io.write_full("obj", b"original")
            r = await client.objecter.op_submit(pool, "obj", [
                ("write_full", {"data": b"MUST-NOT-LAND"}),
                ("cmpxattr", {"name": "user.absent", "value": b"x"})])
            assert r.result == -125
            assert await io.read("obj") == b"original"
        finally:
            await cluster.stop()

    run(scenario())


from tests._flaky import contention_retry


@contention_retry()
def test_copy_from_cross_pool_and_rollback():
    """VERDICT r4 missing #7 verbs: server-side copy_from (replicated ->
    EC and back, with xattrs/omap) and head rollback-to-snap with the
    snapshot state intact (reference PrimaryLogPG.cc:3113 COPY_FROM and
    _rollback_to)."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            rp = await client.pool_create("cp_rep", "replicated",
                                          pg_num=4, size=2)
            ep = await client.pool_create(
                "cp_ec", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            rio, eio = client.ioctx(rp), client.ioctx(ep)
            # warm the EC codec compile before timed internal ops
            await eio.write_full("warm", b"w" * 4096)
            payload = bytes(range(256)) * 40
            await rio.write_full("src", payload)
            await rio.setxattr("src", "user.tag", b"orig")
            await rio.omap_set("src", {"k1": b"v1"})
            # replicated -> EC, different object name
            n = await eio.copy_from("dst", "src", src_pool=rp)
            assert n == len(payload)
            assert await eio.read("dst") == payload
            assert await eio.getxattr("dst", "user.tag") == b"orig"
            assert (await eio.omap_get("dst"))["k1"] == b"v1"
            # EC -> replicated round trip
            await rio.copy_from("back", "dst", src_pool=ep)
            assert await rio.read("back") == payload

            # copy onto an EXISTING dst replaces wholesale: stale dst
            # metadata absent from the source must vanish
            await eio.setxattr("dst", "user.stale", b"gone")
            await eio.omap_set("dst", {"stale_k": b"gone"})
            await eio.copy_from("dst", "src", src_pool=rp)
            with pytest.raises(KeyError):
                await eio.getxattr("dst", "user.stale")
            assert "stale_k" not in await eio.omap_get("dst")

            # rollback: snapshot, overwrite, roll back
            await rio.snap_create("keep")
            sid = next(s for s, nme in
                       client.objecter.osdmap.pools[rp].snaps.items()
                       if nme == "keep")
            await rio.write_full("src", b"overwritten")
            await rio.setxattr("src", "user.tag", b"new")
            await rio.setxattr("src", "user.post", b"added-after-snap")
            await rio.omap_set("src", {"k_post": b"after"})
            assert await rio.read("src") == b"overwritten"
            await rio.rollback("src", sid)
            assert await rio.read("src") == payload
            assert await rio.getxattr("src", "user.tag") == b"orig"
            # keys created AFTER the snapshot are gone (wholesale restore)
            with pytest.raises(KeyError):
                await rio.getxattr("src", "user.post")
            assert "k_post" not in await rio.omap_get("src")
            # the snapshot itself still reads the original
            assert await rio.read("src", snapid=sid) == payload
            # copy_from a snapshot source
            await eio.copy_from("from_snap", "src", src_pool=rp,
                                src_snapid=sid)
            assert await eio.read("from_snap") == payload
        finally:
            await cluster.stop()

    run(scenario())
