"""FileStore durability + OSD restart resume.

Tier-2 store-contract tests (the reference's store_test.cc fixtures run the
same ObjectStore contract against memstore/filestore/bluestore) plus the
tier-3 full-cluster restart: write, stop EVERY osd, restart from disk,
read back with ZERO recovery pushes (reference OSD::init read_superblock/
load_pgs resume, src/osd/OSD.cc:2556,2572).
"""

import asyncio

import pytest

from ceph_tpu.cluster.filestore import FileStore
from ceph_tpu.cluster.store import Transaction


def run(coro):
    return asyncio.run(coro)


def test_filestore_roundtrip(tmp_path):
    s = FileStore(str(tmp_path / "osd0"))
    s.mount()
    s.queue_transaction(
        Transaction()
        .create_collection("c")
        .write("c", "obj", 0, b"hello world")
        .setattr("c", "obj", "k", b"v")
        .omap_set("c", "obj", {"ok": b"ov"})
        .set_version("c", "obj", 7))
    s.umount()

    s2 = FileStore(str(tmp_path / "osd0"))
    s2.mount()
    assert s2.read("c", "obj") == b"hello world"
    assert s2.getattr("c", "obj", "k") == b"v"
    assert s2.omap_get("c", "obj") == {"ok": b"ov"}
    assert s2.get_version("c", "obj") == 7
    s2.umount()


def test_filestore_journal_replay_without_checkpoint(tmp_path):
    """Crash before any checkpoint: journal alone restores state."""
    s = FileStore(str(tmp_path / "osd1"))
    s.mount()
    s.queue_transaction(
        Transaction().create_collection("c").write("c", "o", 0, b"abc"))
    # simulate crash: no umount/checkpoint, just drop the handle
    s._journal.flush()
    s._journal.close()

    s2 = FileStore(str(tmp_path / "osd1"))
    s2.mount()
    assert s2.read("c", "o") == b"abc"
    s2.umount()


def test_filestore_torn_tail_discarded(tmp_path):
    s = FileStore(str(tmp_path / "osd2"))
    s.mount()
    s.queue_transaction(
        Transaction().create_collection("c").write("c", "o", 0, b"good"))
    s._journal.flush()
    s._journal.close()
    # append a torn frame (header promises more bytes than present)
    with open(s._journal_path, "ab") as f:
        f.write(b"\xff\x00\x00\x00partial")

    s2 = FileStore(str(tmp_path / "osd2"))
    s2.mount()  # must not raise; torn tail discarded
    assert s2.read("c", "o") == b"good"
    s2.umount()


def test_filestore_checkpoint_truncates_journal(tmp_path):
    s = FileStore(str(tmp_path / "osd3"), checkpoint_every=4)
    s.mount()
    for i in range(10):
        s.queue_transaction(
            Transaction().create_collection("c").write("c", f"o{i}", 0,
                                                       b"x" * 100))
    import os

    assert os.path.getsize(s._journal_path) < 4 * 300
    s.umount()
    s2 = FileStore(str(tmp_path / "osd3"))
    s2.mount()
    assert len([o for o in s2.list_objects("c")]) == 10
    s2.umount()


def test_cluster_full_restart_zero_pushes(tmp_path):
    """Write to a durable cluster, stop EVERY osd, restart from disk:
    reads succeed and recovery pushes nothing (logs all agree)."""
    async def scenario():
        from ceph_tpu.cluster.osd import OSDDaemon
        from ceph_tpu.cluster.vstart import _fast_config, start_cluster

        cfg = _fast_config()
        cfg.mon_osd_down_out_interval = 120.0

        def factory(osd_id):
            return FileStore(str(tmp_path / f"osd{osd_id}"))

        cluster = await start_cluster(3, config=cfg, store_factory=factory)
        try:
            client = await cluster.client()
            rpool = await client.pool_create("repl", "replicated",
                                             pg_num=8, size=3)
            epool = await client.pool_create(
                "ecp", "erasure", pg_num=8,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            rio = client.ioctx(rpool)
            eio = client.ioctx(epool)
            payloads = {f"r{i}": f"repl-{i}".encode() * 100 for i in range(6)}
            epayloads = {f"e{i}": f"ec-{i}".encode() * 200 for i in range(4)}
            for oid, data in payloads.items():
                await rio.write_full(oid, data)
            for oid, data in epayloads.items():
                await eio.write_full(oid, data)

            # full stop of every OSD (mon stays; its durable store is the
            # paxos-mon milestone)
            ids = list(cluster.osds)
            for o in ids:
                osd = cluster.osds.pop(o)
                await osd.stop()
            for o in ids:
                await cluster.wait_down(o)

            for o in ids:
                osd = OSDDaemon(o, cluster.mon_addr, config=cfg,
                                store=factory(o))
                await osd.start()
                cluster.osds[o] = osd
            deadline = asyncio.get_event_loop().time() + 15
            while asyncio.get_event_loop().time() < deadline:
                if all(cluster.mon.osdmap.osd_up[o] for o in ids):
                    break
                await asyncio.sleep(0.05)
            # peering window: converge-poll the first read against a
            # wall deadline instead of a fixed sleep
            deadline = asyncio.get_event_loop().time() + 15
            first = next(iter(payloads))
            while asyncio.get_event_loop().time() < deadline:
                try:
                    if await rio.read(first, timeout=5) \
                            == payloads[first]:
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.05)

            for oid, data in payloads.items():
                assert await rio.read(oid) == data, oid
            for oid, data in epayloads.items():
                assert await eio.read(oid) == data, oid
            pushes = sum(o.perf.get("osd_pushes_sent")
                         for o in cluster.osds.values())
            assert pushes == 0, f"restart resume must not push ({pushes})"
        finally:
            await cluster.stop()

    run(scenario())


def test_whole_cluster_restart_including_mon(tmp_path):
    """THE full durability story: stop mon AND every osd, restart all
    from disk — pools, maps, and data all resume (MonitorDBStore +
    superblock + pg logs)."""
    async def phase1():
        from ceph_tpu.cluster.vstart import _fast_config, start_cluster

        cfg = _fast_config()

        def osd_store(o):
            return FileStore(str(tmp_path / f"osd{o}"))

        def mon_store(r):
            return FileStore(str(tmp_path / f"mon{r}"))

        cluster = await start_cluster(3, config=cfg,
                                      store_factory=osd_store,
                                      mon_store_factory=mon_store)
        try:
            client = await cluster.client()
            pool = await client.pool_create("persist", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            await io.write_full("survivor", b"across-restarts" * 50)
            return cluster.mon.osdmap.epoch, pool
        finally:
            await cluster.stop()

    epoch, pool = run(phase1())

    async def phase2():
        from ceph_tpu.cluster.mon import Monitor
        from ceph_tpu.cluster.objecter import RadosClient
        from ceph_tpu.cluster.osd import OSDDaemon
        from ceph_tpu.cluster.vstart import _fast_config
        from ceph_tpu.crush.types import build_hierarchy
        from ceph_tpu.osdmap.osdmap import OSDMap

        cfg = _fast_config()
        # the ctor map is a throwaway: start() resumes the persisted one
        cmap, _ = build_hierarchy(3, 1, numrep=3)
        mon = Monitor(OSDMap(cmap, max_osd=3), config=cfg,
                      store=FileStore(str(tmp_path / "mon0")))
        addr = await mon.start()
        assert mon.osdmap.epoch >= epoch          # resumed, not reset
        assert pool in mon.osdmap.pools           # pool survived
        osds = []
        try:
            for o in range(3):
                osd = OSDDaemon(o, addr, config=cfg,
                                store=FileStore(str(tmp_path / f"osd{o}")))
                await osd.start()
                osds.append(osd)
            deadline = asyncio.get_event_loop().time() + 15
            while asyncio.get_event_loop().time() < deadline:
                if all(mon.osdmap.osd_up[o] for o in range(3)):
                    break
                await asyncio.sleep(0.05)
            client = RadosClient(addr, config=cfg)
            await client.connect()
            try:
                io = client.ioctx(pool)
                assert await io.read("survivor") == b"across-restarts" * 50
            finally:
                await client.shutdown()
        finally:
            for osd in osds:
                await osd.stop()
            await mon.stop()

    run(phase2())
