"""Striper extent math + RBD image layer over a live cluster.

Reference: Striper::file_to_extents (src/osdc/Striper.h:31-54) and the
librbd striped data path.
"""

import asyncio

import pytest

from ceph_tpu.cluster.striper import (
    FileLayout,
    StripedReader,
    file_to_extents,
)
from ceph_tpu.cluster.rbd import RBD
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


def test_extents_single_object():
    lo = FileLayout(stripe_unit=1 << 20, stripe_count=1,
                    object_size=1 << 22)
    ex = file_to_extents("o.%016x", lo, 0, 100)
    assert len(ex) == 1
    assert ex[0].objectno == 0 and ex[0].offset == 0 and ex[0].length == 100


def test_extents_cross_object_boundary():
    lo = FileLayout(stripe_unit=4096, stripe_count=1, object_size=8192)
    ex = file_to_extents("o.%016x", lo, 6000, 4000)
    assert [(e.objectno, e.offset, e.length) for e in ex] == [
        (0, 6000, 2192), (1, 0, 1808)]


def test_extents_interleave_stripes():
    """stripe_count 2: units round-robin across the object pair."""
    lo = FileLayout(stripe_unit=1000, stripe_count=2, object_size=2000)
    ex = file_to_extents("o.%016x", lo, 0, 4000)
    by_obj = {e.objectno: e for e in ex}
    # period = 4000 bytes over objects {0, 1}; each gets 2 units
    assert by_obj[0].offset == 0 and by_obj[0].length == 2000
    assert by_obj[1].offset == 0 and by_obj[1].length == 2000
    # object 0 holds logical [0,1000)+[2000,3000); object 1 the others
    assert by_obj[0].buffer_extents == [(0, 1000), (2000, 1000)]
    assert by_obj[1].buffer_extents == [(1000, 1000), (3000, 1000)]


def test_scatter_assemble_roundtrip():
    lo = FileLayout(stripe_unit=512, stripe_count=3, object_size=2048)
    data = bytes(range(256)) * 40  # 10240 bytes, several periods
    ex = file_to_extents("o.%016x", lo, 300, len(data))
    per_obj = StripedReader.scatter(ex, data)
    # simulate object store
    objects = {}
    for oid, parts in per_obj.items():
        buf = bytearray(4096)
        for off, blob in parts:
            buf[off: off + len(blob)] = blob
        objects[oid] = bytes(buf)
    got = StripedReader.assemble(ex, objects, len(data))
    assert got == data


def test_rbd_image_end_to_end():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rbdpool", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            rbd = RBD(io)
            await rbd.create("img", size=1 << 20, stripe_unit=4096,
                             stripe_count=2, object_size=16384)
            assert await rbd.list() == ["img"]
            img = await rbd.open("img")
            assert img.size() == 1 << 20

            # striped write/read across object boundaries
            blob = bytes(range(256)) * 256  # 64 KiB
            await img.write(10000, blob)
            assert await img.read(10000, len(blob)) == blob
            # sparse read before anything written
            assert await img.read(1 << 19, 100) == b"\0" * 100
            # overwrite a slice
            await img.write(12000, b"X" * 5000)
            got = await img.read(10000, len(blob))
            expect = bytearray(blob)
            expect[2000:7000] = b"X" * 5000
            assert got == bytes(expect)

            # snapshots (metadata) + resize + stat
            sid = await img.snap_create("s1")
            assert img.snap_list() == {"s1": sid}
            await img.resize(1 << 21)
            st = await img.stat()
            assert st["size"] == 1 << 21 and st["snaps"] == {"s1": sid}

            # reopen sees persisted state
            img2 = await rbd.open("img")
            assert img2.size() == 1 << 21
            assert await img2.read(10000, 100) == blob[:100]

            await rbd.remove("img")
            assert await rbd.list() == []
        finally:
            await cluster.stop()

    run(scenario())


def test_rbd_image_on_ec_pool():
    """Images work unchanged on an erasure-coded pool (the data path is
    plain IoCtx ops; EC striping happens below)."""
    async def scenario():
        cluster = await start_cluster(4)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "rbdec", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            rbd = RBD(io)
            await rbd.create("ecimg", size=1 << 19, stripe_unit=8192,
                             stripe_count=1, object_size=32768)
            img = await rbd.open("ecimg")
            payload = b"ec-image-data" * 1000
            await img.write(5000, payload)
            assert await img.read(5000, len(payload)) == payload
        finally:
            await cluster.stop()

    run(scenario())


def test_rbd_shrink_then_grow_reads_zeros():
    """Shrinking must not let old bytes resurface after a later grow
    (dead object sets removed, partial tail zeroed)."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rz", "replicated",
                                            pg_num=8, size=2)
            rbd = RBD(client.ioctx(pool))
            await rbd.create("img", size=1 << 16, stripe_unit=4096,
                             stripe_count=2, object_size=16384)
            img = await rbd.open("img")
            await img.write(0, b"A" * (1 << 16))
            await img.resize(20000)
            await img.resize(1 << 16)
            # everything beyond the shrink point reads as zeros
            assert await img.read(20000, 4096) == b"\0" * 4096
            assert await img.read(40000, 100) == b"\0" * 100
            assert await img.read(0, 100) == b"A" * 100
        finally:
            await cluster.stop()

    run(scenario())
