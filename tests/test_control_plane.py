"""Control plane at scale (round 14): vectorized epoch deltas, bounded
delta chains, mon-side markdown coalescing, peering storm control, and
the storm scenarios that prove the cluster survives mass churn.

The acceptance gates live here:

- ``affected_pgs`` (whole-pool array diff) selects EXACTLY the PG set
  the per-PG scalar scan would re-peer, across mark down/out/in, weight
  change, pg_num growth, and upmap edits — in both snapshot modes;
- an OSD facing an over-long incremental chain skips to a full map
  instead of unpickling the chain on its dispatch loop;
- N simultaneous failure reports coalesce into few map epochs;
- the storm scenarios (rolling-restart-100 / mon-bounce-under-churn)
  pass seeded at tier-1 scale with deterministic schedules (full-size
  runs are slow-marked, with full bit-identical verdict replay).
"""

import asyncio
import copy
import dataclasses

import pytest

from ceph_tpu.osdmap.osdmap import (
    PGid,
    POOL_TYPE_ERASURE,
    POOL_TYPE_REPLICATED,
    affected_pgs,
    affected_pgs_scalar,
    build_simple_osdmap,
)


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------- vectorized delta oracle


def _mutations(m):
    """(name, mutated-map) cases: every class the issue names."""
    cases = []
    m2 = copy.deepcopy(m)
    m2.mark_down(5)
    cases.append(("mark_down", m2))
    m3 = copy.deepcopy(m)
    m3.mark_out(9)
    cases.append(("mark_out", m3))
    m4 = copy.deepcopy(m)
    m4.mark_in(9, 0x10000)
    cases.append(("mark_in", m4))
    m5 = copy.deepcopy(m)
    m5.mark_in(3, 0x8000)          # weight change (half weight)
    cases.append(("weight", m5))
    m6 = copy.deepcopy(m)
    m6.pools[1] = dataclasses.replace(m6.pools[1], pg_num=96)
    cases.append(("pg_num_growth", m6))
    m7 = copy.deepcopy(m)
    pg = PGid(1, 7)
    up = m7.pg_to_up_acting_osds(pg)[0]
    dst = next(o for o in range(16) if o not in up)
    m7.pg_upmap_items[pg] = [(up[0], dst)]
    cases.append(("upmap_items", m7))
    m8 = copy.deepcopy(m)
    m8.pg_upmap[PGid(1, 3)] = [1, 5, 9]
    cases.append(("upmap_full", m8))
    m9 = copy.deepcopy(m)
    m9.pg_temp[PGid(1, 11)] = [1, 2, 6]
    cases.append(("pg_temp", m9))
    return cases


@pytest.mark.parametrize("ptype", [POOL_TYPE_REPLICATED,
                                   POOL_TYPE_ERASURE],
                         ids=["replicated", "erasure"])
def test_affected_pgs_bit_identical_to_scalar_scan(ptype):
    """THE tier-1 acceptance gate: the vectorized whole-pool diff and
    the per-PG scalar scan select the identical affected-PG set for
    every mutation class, in the scalar-snapshot mode (small pools).
    The batched-array mode is covered separately to bound device time."""
    m = build_simple_osdmap(n_osds=16, osds_per_host=4, pg_num=48,
                            pool_type=ptype, size=3)
    for name, m2 in _mutations(m):
        want = affected_pgs_scalar(m, m2, 1)
        got = affected_pgs(m, m2, 1, batch_min=1000)  # scalar snapshots
        assert got == want, (name, sorted(got - want), sorted(want - got))
        # a mutation must actually affect something (or the case is
        # vacuous) — except mark_in back to the current weight
        if name not in ("mark_in",):
            assert want, name
        # identity diff: no epoch, no affected PGs
        assert affected_pgs(m, m, 1, batch_min=1000) == set()


def test_affected_pgs_batched_mode_matches_scalar_scan():
    """The batched-array diff path (pool_mapping snapshots + numpy row
    compare) agrees with the scalar scan too — one pool type suffices;
    the row semantics themselves are cross-checked pool-type-wide by
    test_osdmap.test_batched_matches_scalar."""
    m = build_simple_osdmap(n_osds=16, osds_per_host=4, pg_num=48,
                            pool_type=POOL_TYPE_REPLICATED, size=3)
    for name, m2 in _mutations(m):
        want = affected_pgs_scalar(m, m2, 1)
        got = affected_pgs(m, m2, 1, batch_min=1)     # batched arrays
        assert got == want, (name, sorted(got - want), sorted(want - got))


# ---------------------------------------------- osd/mon chain + coalesce


def test_inc_chain_cap_skips_to_full_and_failures_coalesce():
    """Two control-plane bounds on one cluster: (a) an OSD handed an
    incremental chain past osd_map_max_inc_chain requests a full map
    instead of applying it; (b) simultaneous failure reports coalesce
    into few epochs (mon_osd_failure_coalesce window); (c) a no-op
    epoch re-peers nothing (the vectorized delta's whole point)."""
    import pickle

    from ceph_tpu.cluster import messages as M
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster
    from ceph_tpu.osdmap.osdmap import Incremental

    async def scenario():
        cfg = _fast_config()
        cfg.mon_osd_failure_coalesce = 0.5
        cfg.osd_map_max_inc_chain = 2
        # the beacon-staleness tick must not win the markdown race:
        # this test proves the failure-REPORT aggregation path
        cfg.mon_osd_beacon_grace = 30.0
        cluster = await start_cluster(6, config=cfg)
        try:
            client = await cluster.client()
            await client.pool_create("cp", "replicated", pg_num=8,
                                     size=3)
            await cluster.wait_for_epoch(cluster.mon.osdmap.epoch,
                                         timeout=10)
            osd = cluster.osds[0]

            # (c) a placement-neutral epoch (clog-only inc) must not
            # re-peer anything on a vectorized-delta OSD
            repeered0 = osd.perf.get("osd_pgs_repeered")
            mon = cluster.mon
            async with mon._map_mutex:
                inc = mon._new_inc()
                inc.new_log_entries = (("test", 0.0, "INF", "noop"),)
                await mon._commit_inc(inc)
            await cluster.wait_for_epoch(mon.osdmap.epoch, timeout=10)
            assert osd.perf.get("osd_pgs_repeered") == repeered0

            # (a) synthetic over-long chain -> skip-to-full request
            base = osd.osdmap.epoch
            blobs = [pickle.dumps(Incremental(epoch=base + 1 + i))
                     for i in range(3)]
            skips0 = osd.perf.get("osd_map_skip_to_full")
            await osd._handle_inc_map(M.MOSDIncMapMsg(
                prev_epoch=base, epoch=base + 3, inc_blobs=blobs))
            assert osd.perf.get("osd_map_skip_to_full") == skips0 + 1
            # the chain was NOT applied; the mon's full-map reply (the
            # since=0 re-subscribe) re-syncs the daemon
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                if osd.osdmap.epoch >= mon.osdmap.epoch:
                    break
                await asyncio.sleep(0.05)
            assert osd.osdmap.epoch >= mon.osdmap.epoch

            # (b) three dead OSDs -> their markdowns share epochs
            epoch0 = mon.osdmap.epoch
            for victim in (3, 4, 5):
                await cluster.kill_osd(victim)
            deadline = asyncio.get_event_loop().time() + 20
            while asyncio.get_event_loop().time() < deadline:
                if all(not mon.osdmap.osd_up[v] for v in (3, 4, 5)):
                    break
                await asyncio.sleep(0.05)
            assert all(not mon.osdmap.osd_up[v] for v in (3, 4, 5))
            assert mon.perf.get("mon_failures_coalesced") >= 1
            # 3 markdowns + their clog flushes in well under 3+3 epochs
            assert mon.osdmap.epoch - epoch0 <= 4, \
                (epoch0, mon.osdmap.epoch)
        finally:
            await cluster.stop()

    run(scenario())


# ----------------------------------------------------- storm scenarios


def _scaled_storms():
    from ceph_tpu.chaos.scenario import storm_scenarios

    return storm_scenarios(0.06)


@pytest.mark.chaos
def test_storm_rolling_restart_scaled(tmp_path):
    """Tier-1 storm gate: the rolling-restart storm at --scale 0.06
    (the same code paths as the 100-bounce acceptance run: load-driver
    traffic, staggered+overlapping bounces, the HEALTH_OK and epochs/s
    gates, durability/frontier/acting invariants) passes seeded, and
    its fault schedule is seed-deterministic."""
    from ceph_tpu.chaos.scenario import build_schedule, run_scenario

    sc = _scaled_storms()["rolling-restart-100"]
    assert build_schedule(sc, 7) == build_schedule(sc, 7)
    v = run(run_scenario(sc, 7, tmpdir=str(tmp_path)))
    assert v.passed, v.failures
    assert v.counters.get("daemon_restarts", 0) >= 4
    assert v.counters.get("epochs_generated", 0) > 0


@pytest.mark.chaos
def test_storm_mon_bounce_scaled(tmp_path):
    """Tier-1 storm gate: the Paxos leader killed mid-epoch-burst at
    tier-1 scale — the quorum fails over, keeps committing markdowns/
    boots, the killed mon revives into the quorum, and every invariant
    plus the HEALTH_OK gate holds."""
    from ceph_tpu.chaos.scenario import run_scenario

    sc = _scaled_storms()["mon-bounce-under-churn"]
    v = run(run_scenario(sc, 11, tmpdir=str(tmp_path)))
    assert v.passed, v.failures
    assert v.counters.get("daemon_kills", 0) >= 1      # the leader
    assert v.counters.get("daemon_revives", 0) >= 0


@pytest.mark.chaos
@pytest.mark.slow
def test_storm_rolling_restart_full_replay(tmp_path):
    """The full acceptance shape: ~100 staggered+overlapping OSD
    bounces under sustained load-driver traffic, epochs/s floor and
    bounded time-to-HEALTH_OK enforced, replayed bit-identically."""
    from ceph_tpu.chaos.scenario import run_scenario, storm_scenarios

    sc = storm_scenarios(1.0)["rolling-restart-100"]
    v1 = run(run_scenario(sc, 42, tmpdir=str(tmp_path / "a")))
    assert v1.passed, v1.failures
    assert v1.counters.get("daemon_restarts", 0) >= 90
    v2 = run(run_scenario(sc, 42, tmpdir=str(tmp_path / "b")))
    assert v1.replay_key() == v2.replay_key()


@pytest.mark.chaos
@pytest.mark.slow
def test_storm_mon_bounce_full(tmp_path):
    """Full-size mon-bounce-under-churn: leader killed mid-burst with
    a dozen OSD bounces churning epochs through Paxos."""
    from ceph_tpu.chaos.scenario import run_scenario, storm_scenarios

    sc = storm_scenarios(1.0)["mon-bounce-under-churn"]
    v = run(run_scenario(sc, 42, tmpdir=str(tmp_path)))
    assert v.passed, v.failures
    assert v.counters.get("daemon_kills", 0) >= 1


# ------------------------------------------------- anchor-mode parity


def test_anchor_mode_converges_identically():
    """osd_map_vectorized_delta=0 (the per-PG-scan anchor) still
    converges a bounce to the same healthy end state — the bisection
    contract for the whole round-14 path."""
    from ceph_tpu.chaos.invariants import check_acting, check_health
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    async def scenario():
        cfg = _fast_config()
        cfg.osd_map_vectorized_delta = 0
        cluster = await start_cluster(4, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("anchor", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            for i in range(6):
                await io.write_full(f"a{i}", b"anchor" * 40)
            await cluster.restart_osd(1)
            fails = await check_acting(cluster, timeout=30)
            fails += await check_health(cluster, timeout=30)
            assert not fails, fails
            for i in range(6):
                assert await io.read(f"a{i}") == b"anchor" * 40
        finally:
            await cluster.stop()

    run(scenario())
