"""Sharded EC pipeline over the virtual 8-device mesh."""

import numpy as np
import pytest

import jax

from ceph_tpu.parallel import make_mesh, distributed_ec_step


def test_mesh_shapes():
    mesh = make_mesh(8)
    assert mesh.shape == {"data": 2, "shard": 4}
    mesh2 = make_mesh(2)
    assert mesh2.shape == {"data": 1, "shard": 2}


def test_distributed_step_reconstructs():
    mesh = make_mesh(8)
    fn, args = distributed_ec_step(mesh, k=8, m=4, batch=8, chunk=128)
    mismatches, chunks = fn(*args)
    assert int(mismatches) == 0
    assert chunks.shape == (8, 12, 128)
    # chunk layout is actually sharded over the mesh
    assert not chunks.sharding.is_fully_replicated


def test_graft_entry_single_chip():
    import __graft_entry__ as g

    fn, fargs = g.entry()
    out = fn(*fargs)
    jax.block_until_ready(out)
    assert out.shape == (256, 4, 512)
    # parity row 0 of the ISA vandermonde matrix is the XOR of data chunks
    data = np.asarray(fargs[0])
    want = data[:, 0, :].copy()
    for i in range(1, 8):
        want ^= data[:, i, :]
    assert np.array_equal(np.asarray(out)[:, 0, :], want)


def test_graft_dryrun():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
