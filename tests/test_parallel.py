"""Sharded EC pipeline over the virtual 8-device mesh."""

import numpy as np
from tests._flaky import contention_retry
import pytest

import jax

from ceph_tpu.parallel import make_mesh, distributed_ec_step


def test_mesh_shapes():
    mesh = make_mesh(8)
    assert mesh.shape == {"data": 2, "shard": 4}
    mesh2 = make_mesh(2)
    assert mesh2.shape == {"data": 1, "shard": 2}


def test_distributed_step_reconstructs():
    mesh = make_mesh(8)
    fn, args = distributed_ec_step(mesh, k=8, m=4, batch=8, chunk=128)
    mismatches, chunks = fn(*args)
    assert int(mismatches) == 0
    assert chunks.shape == (8, 12, 128)
    # chunk layout is actually sharded over the mesh
    assert not chunks.sharding.is_fully_replicated


def test_graft_entry_single_chip():
    import __graft_entry__ as g

    fn, fargs = g.entry()
    out = fn(*fargs)
    jax.block_until_ready(out)
    assert out.shape == (256, 4, 512)
    # parity row 0 of the ISA vandermonde matrix is the XOR of data chunks
    data = np.asarray(fargs[0])
    want = data[:, 0, :].copy()
    for i in range(1, 8):
        want ^= data[:, i, :]
    assert np.array_equal(np.asarray(out)[:, 0, :], want)


def test_graft_dryrun():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


# ---- round 4: the generalized mesh data plane (MeshECEngine) ----

def _engine(k=8, m=4):
    from ceph_tpu.ec import matrices
    from ceph_tpu.parallel import MeshECEngine, make_mesh

    mesh = make_mesh(8)
    return MeshECEngine(mesh, k, m, matrices.isa_rs_matrix(k, m)), mesh


def test_mesh_engine_encode_matches_single_device():
    from ceph_tpu.ec import factory

    eng, _ = _engine()
    codec = factory({"plugin": "isa", "k": "8", "m": "4"})
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (8, 8, 256), dtype=np.uint8)
    mesh_par = np.asarray(eng.encode_batch(data))
    single = np.asarray(codec.encode_batch(data))
    assert np.array_equal(mesh_par, single)


@pytest.mark.parametrize("erasures", [
    (0,), (5,), (8,), (11,),              # single: data / parity
    (0, 11), (2, 3), (9, 10),             # double
    (0, 4, 8), (1, 2, 3, 9),              # up to m erasures
])
def test_mesh_engine_decode_patterns(erasures):
    """Arbitrary erasure patterns reconstruct byte-exactly on the mesh
    (the round-3 demo hardcoded shard 0)."""
    eng, _ = _engine()
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, (8, 8, 128), dtype=np.uint8)
    parity = np.asarray(eng.encode_batch(data))
    chunks = np.concatenate([data, parity], axis=1)
    got = np.asarray(eng.decode_batch(erasures, chunks))
    want = chunks[:, list(erasures), :]
    assert np.array_equal(got, want), erasures


def test_mesh_engine_rmw_delta_parity():
    """Partial-stripe RMW: delta parity update equals full re-encode."""
    eng, _ = _engine()
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (8, 8, 128), dtype=np.uint8)
    parity = np.asarray(eng.encode_batch(data))
    chunks = np.concatenate([data, parity], axis=1)
    update = rng.integers(0, 256, (8, 8, 32), dtype=np.uint8)
    new_chunks = np.asarray(eng.rmw_batch(chunks, update, col_start=48))
    # reference: patch the data and re-encode from scratch
    patched = data.copy()
    patched[:, :, 48:80] = update
    want_parity = np.asarray(eng.encode_batch(patched))
    assert np.array_equal(new_chunks[:, :8, :], patched)
    assert np.array_equal(new_chunks[:, 8:, :], want_parity)


def test_mesh_engine_rmw_then_decode():
    """RMW output survives shard loss — the combined path the cluster's
    EC pool runs (write, partial overwrite, degraded read)."""
    eng, _ = _engine()
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, (8, 8, 128), dtype=np.uint8)
    parity = np.asarray(eng.encode_batch(data))
    chunks = np.concatenate([data, parity], axis=1)
    update = rng.integers(0, 256, (8, 8, 64), dtype=np.uint8)
    chunks = np.asarray(eng.rmw_batch(chunks, update, col_start=0))
    got = np.asarray(eng.decode_batch((1, 6), chunks))
    assert np.array_equal(got[:, 0, :], chunks[:, 1, :])
    assert np.array_equal(got[:, 1, :], chunks[:, 6, :])


def test_crush_batch_sharded_matches_single():
    """Mesh-sharded placement must equal the single-device mapper."""
    from ceph_tpu.crush.mapper import TensorMapper
    from ceph_tpu.crush.types import build_hierarchy
    from ceph_tpu.parallel import crush_batch_sharded, make_mesh

    cmap, rule = build_hierarchy(n_hosts=8, osds_per_host=4, numrep=3)
    mapper = TensorMapper(cmap)
    weights = np.full(cmap.max_devices, 0x10000, dtype=np.uint32)
    xs = np.arange(1000, dtype=np.uint32)
    single = np.asarray(
        mapper.do_rule_batch(rule, xs, result_max=3, weights=weights)[0])
    mesh = make_mesh(8)
    sharded, _ = crush_batch_sharded(mesh, mapper, rule, xs, 3, weights)
    assert np.array_equal(np.asarray(sharded), single)


@contention_retry()
def test_ec_cluster_pool_on_mesh_data_plane():
    """VERDICT r3 item 3 gate: a live EC pool whose batch encode/decode
    runs through the mesh engine on a 2-device mesh — write, partial
    RMW, read, degraded read with a stopped OSD."""
    import asyncio

    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    async def scenario():
        cfg = _fast_config()
        cfg.osd_ec_mesh = "on"
        cfg.osd_ec_mesh_devices = 2
        cluster = await start_cluster(3, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "mesh_ec", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            payload = bytes(range(256)) * 64          # 16 KiB
            await io.write_full("mobj", payload)
            # the pool's codec really is the mesh adapter
            from ceph_tpu.parallel.engine import MeshCodecAdapter

            some_osd = next(iter(cluster.osds.values()))
            pobj = some_osd.osdmap.pools[pool]
            assert isinstance(some_osd._codec(pobj), MeshCodecAdapter)
            assert await io.read("mobj") == payload
            # partial overwrite = the RMW path through the mesh engine
            await io.write("mobj", b"M" * 3000, offset=1000)
            got = await io.read("mobj")
            assert got[1000:4000] == b"M" * 3000
            assert got[:1000] == payload[:1000]
            # degraded read: stop a non-primary member
            pgid = client.objecter.object_pgid(pool, "mobj")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            victim = next(o for o in acting if o != primary)
            await cluster.osds[victim].stop()
            got = await io.read("mobj", timeout=60)
            assert got[1000:4000] == b"M" * 3000
        finally:
            await cluster.stop()

    asyncio.run(scenario())
