"""Bit-planar layout-contract tests (round 6).

The planar device layout (ceph_tpu/ec/planar.py) is only allowed to exist
because it is invisible at the host boundary: byte -> planar -> byte must
be the identity for every field width and codec geometry, and every
encode/decode routed through the planar path must be bit-identical to the
byte batch path — which the golden corpus pins to the independent C
oracle.  These tests enforce both halves of that contract, including
decode-after-erasure and the RMW/recovery stripe pipelines.
"""

import json
import pathlib

import numpy as np
import pytest

from ceph_tpu.ec import factory
from ceph_tpu.ec.planar import PlanarBatch
from ceph_tpu.ec.stripe import (
    StripeInfo,
    decode_stripes,
    encode_stripes,
    merge_range,
    reencode_stripes,
)
from ceph_tpu.ops import gf8, gfw

GOLDEN = pathlib.Path(__file__).parent / "golden" / "ec_golden.jsonl"


def _golden_cases():
    with open(GOLDEN) as f:
        return [json.loads(line) for line in f if line.strip()]


def _lcg_bytes(seed: int, n: int) -> bytes:
    x = seed & 0x7FFFFFFF
    out = bytearray(n)
    for i in range(n):
        x = (1103515245 * x + 12345) & 0x7FFFFFFF
        out[i] = (x >> 16) & 0xFF
    return bytes(out)


def _fnv1a64(data: bytes) -> str:
    h = 1469598103934665603
    for b in data:
        h ^= b
        h = (h * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return f"{h:016x}"


# ---------------------------------------------------------------------------
# layout round-trips: byte -> planar -> byte is the identity
# ---------------------------------------------------------------------------

# the (w, chunk_size) shapes the codec families actually use: jerasure
# rsvan w8/16/32, ISA (32-aligned), LRC/SHEC 4 KiB cluster units, plus
# minimal legal sizes
ROUNDTRIP_SHAPES = [
    (8, 32), (8, 512), (8, 1024), (8, 4096),
    (16, 64), (16, 1024), (16, 2048),
    (32, 128), (32, 2048), (32, 4096),
]


@pytest.mark.parametrize("w,s", ROUNDTRIP_SHAPES,
                         ids=[f"w{w}-s{s}" for w, s in ROUNDTRIP_SHAPES])
def test_planar_roundtrip_identity(w, s):
    rng = np.random.default_rng(w * 1000 + s)
    for c in (2, 6, 12):
        d = rng.integers(0, 256, (c, s), dtype=np.uint8)
        p = np.asarray(gfw.bytes_to_planar_w(d, w))
        assert p.shape == (c * w, s // w)
        back = np.asarray(gfw.planar_to_bytes_w(p, w))
        assert np.array_equal(back, d), (w, s, c)


def test_planar_w8_matches_gf8_specialization():
    rng = np.random.default_rng(1)
    d = rng.integers(0, 256, (7, 256), dtype=np.uint8)
    assert np.array_equal(np.asarray(gf8.bytes_to_planar(d)),
                          np.asarray(gfw.bytes_to_planar_w(d, 8)))
    p = np.asarray(gf8.bytes_to_planar(d))
    assert np.array_equal(np.asarray(gf8.planar_to_bytes(p)),
                          np.asarray(gfw.planar_to_bytes_w(p, 8)))


def test_planar_batch_roundtrip_both_layouts():
    rng = np.random.default_rng(2)
    batch = rng.integers(0, 256, (5, 4, 128), dtype=np.uint8)
    pb = PlanarBatch.from_batch(batch, w=8)
    assert np.array_equal(np.asarray(pb.to_batch()), batch)
    # packet flavor (w=2 packets of 16 to keep it small: s = w*p*ns)
    batch2 = rng.integers(0, 256, (3, 5, 2 * 16 * 4), dtype=np.uint8)
    pb2 = PlanarBatch.from_batch(batch2, w=2, layout="packet",
                                 packetsize=16)
    assert np.array_equal(np.asarray(pb2.to_batch()), batch2)


def test_planar_select_and_concat():
    rng = np.random.default_rng(3)
    batch = rng.integers(0, 256, (2, 6, 64), dtype=np.uint8)
    pb = PlanarBatch.from_batch(batch, w=8)
    sub = pb.select((4, 1))
    assert np.array_equal(np.asarray(sub.to_batch()), batch[:, [4, 1], :])
    joined = pb.select((0,)).concat(pb.select((5,)))
    assert np.array_equal(np.asarray(joined.to_batch()),
                          batch[:, [0, 5], :])


def test_planar_matmul_matches_reference_math():
    rng = np.random.default_rng(4)
    m = rng.integers(0, 256, (4, 8), dtype=np.uint8)
    d = rng.integers(0, 256, (8, 512), dtype=np.uint8)
    bm = gf8.expand_bitmatrix(m)
    got = np.asarray(gf8.planar_to_bytes(
        gf8.planar_matmul(bm, gf8.bytes_to_planar(d))))
    assert np.array_equal(got, gf8.gf_matmul_ref(m, d))


def test_planar_supported_geometry_guard():
    assert PlanarBatch.supported(512, 8)
    assert not PlanarBatch.supported(12, 8)
    assert not PlanarBatch.supported(0, 8)
    assert PlanarBatch.supported(2048, 16)
    assert not PlanarBatch.supported(2040, 16)
    assert PlanarBatch.supported(768, 8, "packet", 8)
    assert not PlanarBatch.supported(760, 8, "packet", 8)


# ---------------------------------------------------------------------------
# golden corpus through the planar path, chunk for chunk
# ---------------------------------------------------------------------------

def _case_id(case):
    return (f"{case['plugin']}-{case['technique']}-k{case['k']}m{case['m']}"
            + (f"-w{case['w']}" if case.get("w", 8) != 8 else "")
            + (f"-ps{case['packetsize']}" if case["packetsize"] else ""))


@pytest.mark.parametrize("case", _golden_cases(), ids=_case_id)
def test_golden_encode_through_planar_path(case):
    w = case.get("w", 8)
    profile = {"plugin": case["plugin"], "technique": case["technique"],
               "k": str(case["k"]), "m": str(case["m"]), "w": str(w)}
    if case["packetsize"]:
        profile["packetsize"] = str(case["packetsize"])
    if case.get("c"):
        profile["c"] = str(case["c"])
    codec = factory(profile)
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    s = case["chunk_size"]
    assert codec.planar_supported(s), (
        "golden geometry must ride the planar layout contract")
    data = _lcg_bytes(case["seed"], case["object_size"])
    prepared = codec.encode_prepare(data)
    batch = np.stack([prepared[codec.chunk_index(i)]
                      for i in range(k)])[None, :, :]        # (1, k, s)
    pb = codec.to_planar(batch)
    parity = np.asarray(codec.encode_planar(pb).to_batch())[0]
    chunks = {codec.chunk_index(i): np.asarray(prepared[codec.chunk_index(i)])
              for i in range(k)}
    for j in range(n - k):
        chunks[codec.chunk_index(k + j)] = parity[j]
    for i in range(n):
        blob = chunks[i].tobytes()
        expect = case["chunks"][i]
        assert blob[:16].hex() == expect["head"], f"chunk {i} head"
        assert _fnv1a64(blob) == expect["fnv1a64"], f"chunk {i} fingerprint"


@pytest.mark.parametrize("case", [c for c in _golden_cases()
                                  if c["m"] >= 2][:8], ids=_case_id)
def test_golden_decode_after_erasure_through_planar_path(case):
    """Erase chunks, reconstruct via decode_planar, compare against the
    golden chunk fingerprints — the full decode side of the contract."""
    w = case.get("w", 8)
    profile = {"plugin": case["plugin"], "technique": case["technique"],
               "k": str(case["k"]), "m": str(case["m"]), "w": str(w)}
    if case["packetsize"]:
        profile["packetsize"] = str(case["packetsize"])
    if case.get("c"):
        profile["c"] = str(case["c"])
    codec = factory(profile)
    n = codec.get_chunk_count()
    data = _lcg_bytes(case["seed"], case["object_size"])
    chunks = codec.encode(range(n), data)
    full = np.stack([np.asarray(chunks[i]) for i in range(n)])[None]
    erasures = (0, n - 1)
    zeroed = full.copy()
    for e in erasures:
        zeroed[:, e] = 0
    got = np.asarray(codec.decode_planar(
        erasures, codec.to_planar(zeroed)).to_batch())[0]
    for idx, e in enumerate(erasures):
        blob = got[idx].tobytes()
        expect = case["chunks"][e]
        assert _fnv1a64(blob) == expect["fnv1a64"], f"rebuilt chunk {e}"


# ---------------------------------------------------------------------------
# stripe pipeline: encode/decode/RMW/recovery through the planar contract
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def isa_codec():
    return factory({"plugin": "isa", "k": "4", "m": "2"})


def test_stripe_rmw_delta_through_planar(isa_codec):
    """The RMW sequence (decode old range, merge delta, re-encode) must be
    byte-identical to encoding the merged logical object directly."""
    sinfo = StripeInfo(4, 32)
    rng = np.random.default_rng(7)
    obj = rng.integers(0, 256, 4 * 32 * 4, dtype=np.uint8).tobytes()
    shards = encode_stripes(isa_codec, sinfo, obj)
    # read-modify-write: overlay 100 bytes at offset 77
    avail = {s: shards[s] for s in (1, 2, 3, 5)}   # lose shard 0 and 4 too
    old = decode_stripes(isa_codec, sinfo, avail, len(obj))
    assert old == obj
    delta = rng.integers(0, 256, 100, dtype=np.uint8).tobytes()
    merged = merge_range(old, len(obj), 77, delta)
    new_shards = encode_stripes(isa_codec, sinfo, merged)
    want = np.frombuffer(merged, dtype=np.uint8)
    back = decode_stripes(isa_codec, sinfo,
                          {s: new_shards[s] for s in range(6)}, len(merged))
    assert back == merged
    assert np.array_equal(np.frombuffer(back, dtype=np.uint8), want)


def test_reencode_stripes_matches_byte_pipeline(isa_codec):
    """Recovery fast path (planar decode+re-encode, one conversion each
    way) == decode_stripes + encode_stripes through logical bytes."""
    sinfo = StripeInfo(4, 32)
    rng = np.random.default_rng(8)
    obj = rng.integers(0, 256, 999, dtype=np.uint8).tobytes()
    shards = encode_stripes(isa_codec, sinfo, obj)
    avail = {s: shards[s] for s in (0, 2, 4, 5)}   # data 1,3 missing
    got = reencode_stripes(isa_codec, sinfo, avail, len(obj))
    data = decode_stripes(isa_codec, sinfo, avail, len(obj))
    want = encode_stripes(isa_codec, sinfo, data)
    assert np.array_equal(got, want)
    # parity-only loss: no decode needed, still one planar round trip
    avail2 = {s: shards[s] for s in (0, 1, 2, 3)}
    got2 = reencode_stripes(isa_codec, sinfo, avail2, len(obj))
    assert np.array_equal(got2, shards)
    with pytest.raises(ValueError):
        reencode_stripes(isa_codec, sinfo,
                         {s: shards[s] for s in (0, 1)}, len(obj))


def test_stripe_encode_planar_equals_non_planar_codec_path():
    """encode_stripes must produce identical shards whether or not the
    codec carries the planar contract (mesh-adapter fallback parity)."""
    codec = factory({"plugin": "isa", "k": "4", "m": "2"})
    sinfo = StripeInfo(4, 32)
    rng = np.random.default_rng(9)
    obj = rng.integers(0, 256, 700, dtype=np.uint8).tobytes()
    want = encode_stripes(codec, sinfo, obj)

    class NoPlanar:
        """Proxy hiding the planar entry points."""

        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            if name in ("planar_supported", "to_planar", "encode_planar",
                        "decode_planar"):
                raise AttributeError(name)
            return getattr(self._inner, name)

    got = encode_stripes(NoPlanar(codec), sinfo, obj)
    assert np.array_equal(got, want)


def test_lrc_single_erasure_decode_reads_only_local_group():
    """Satellite: the flattened LRC decode matrix must prune to the local
    repair group for a single local erasure (locality = the read-set win
    the reference's minimum_to_decode promises), staying bit-exact."""
    codec = factory({"plugin": "lrc", "k": "4", "m": "2", "l": "3"})
    n = codec.get_chunk_count()
    rng = np.random.default_rng(10)
    data = rng.integers(0, 256, (4, 4, 64), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(data))
    full = np.concatenate([data, parity], axis=1)
    zeroed = full.copy()
    zeroed[:, 1] = 0
    got = np.asarray(codec.decode_batch((1,), zeroed))
    assert np.array_equal(got[:, 0], full[:, 1])
    _, _, src_ids = codec._dec_jit[((1,), (1,))]
    assert len(src_ids) <= 3, (
        f"single local erasure should read the local group, got {src_ids}")
    # planar route agrees and shares the pruned plan
    gotp = np.asarray(codec.decode_planar(
        (1,), codec.to_planar(zeroed)).to_batch())
    assert np.array_equal(gotp[:, 0], full[:, 1])
