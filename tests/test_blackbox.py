"""graft-blackbox gates: the flight recorder's no-op contract, bounded
memory, the four postmortem trigger kinds, breach attribution coverage,
seeded-replay determinism, and the report CLI's exit codes.

The no-op pin mirrors the NULL_SPAN tracer pin: with
``blackbox_enabled=0`` (the default) every daemon's ``flight`` is the
shared ``NULL_FLIGHT`` singleton — one falsy test per feed site, zero
allocation, zero retention — so the disabled hot path is provably
unchanged.  The trigger matrix proves each trigger kind produces
EXACTLY one parseable ``POSTMORTEM_*.json`` bundle, and the replay test
proves a seeded rerun lands on the same bundle path with a
bit-identical ``replay_key``.
"""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from ceph_tpu.trace import postmortem as pm
from ceph_tpu.trace.flight import (
    NULL_FLIGHT,
    FlightRecorder,
    _NullFlight,
    merged_timeline,
)
from ceph_tpu.utils import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------- no-op contract


def test_disabled_recorder_is_the_null_singleton():
    """The NULL_SPAN-style pin: blackbox off (the DEFAULT) means every
    from_config call returns the one shared falsy null object — no ring,
    no per-daemon allocation, and feed sites cost one falsy test."""
    cfg = Config()
    assert getattr(cfg, "blackbox_enabled") == 0  # off by default
    for name in ("osd.0", "mon.0", "mgr", "client.x"):
        assert FlightRecorder.from_config(name, cfg) is NULL_FLIGHT
    assert not NULL_FLIGHT
    # every feed is a constant no-op: nothing recorded, nothing retained
    NULL_FLIGHT.record("op", desc="w", dur=1.0)
    NULL_FLIGHT.op_sample("w", 9.9, slow=True)
    assert NULL_FLIGHT.events() == []
    d = NULL_FLIGHT.dump()
    assert d["enabled"] is False and d["events"] == []
    # __slots__ of nothing: the null object CANNOT grow state
    assert _NullFlight.__slots__ == ()


def test_cluster_is_a_provable_noop_when_disabled():
    """Boot a default cluster: every daemon and client holds the
    NULL_FLIGHT singleton (identity, not equality), the admin surface
    serves a disabled payload, and triggers return without collecting."""
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    async def scenario():
        cluster = await start_cluster(3, config=_fast_config())
        try:
            client = await cluster.client()
            for osd in cluster.osds.values():
                assert osd.flight is NULL_FLIGHT
            for mon in cluster.mons:
                assert mon.flight is NULL_FLIGHT
            assert client.objecter.flight is NULL_FLIGHT
            d = await cluster.daemon_command("osd.0", "blackbox dump")
            assert d["flight"]["enabled"] is False
            # a trigger with the recorder off is one falsy test
            assert await cluster.blackbox_trigger(
                "slo_gate", "forced") is None
            assert cluster.postmortems == []
        finally:
            await cluster.stop()

    run(scenario())


# ------------------------------------------------------- bounded memory


def test_ring_bounded_under_flood():
    """100 events through a capacity-8 ring: the ring holds exactly the
    newest 8 and counts the 92 it forgot — memory stays bounded under
    any event flood."""
    fr = FlightRecorder("osd.9", capacity=8, sample_every=4)
    for i in range(100):
        fr.record("queue", depth=i)
    assert len(fr.events()) == 8
    assert fr.dropped == 92
    d = fr.dump()
    assert d["capacity"] == 8 and len(d["events"]) == 8
    assert [e["data"]["depth"] for e in d["events"]] == \
        list(range(92, 100))


def test_op_sampling_every_nth_and_slow_always():
    fr = FlightRecorder("osd.8", capacity=64, sample_every=4)
    for i in range(16):
        fr.op_sample(f"op{i}", 0.001)
    assert len(fr.events()) == 4  # every 4th op lands
    fr.op_sample("slowop", 9.9, slow=True)
    last = fr.events()[-1]
    assert last[2] == "op" and last[3]["slow"] is True


def test_merged_timeline_subtracts_recorded_skew():
    """A chaos-skewed daemon's stamps align onto the cluster timeline
    once its recorded offset is subtracted: osd.0 (+100s skew) stamped
    1100 happened AFTER osd.1's unskewed 999."""
    a = {"daemon": "osd.0", "skew": 100.0, "events": [
        {"seq": 1, "t": 1100.0, "kind": "map", "data": {"epoch": 2}}]}
    b = {"daemon": "osd.1", "skew": 0.0, "events": [
        {"seq": 1, "t": 999.0, "kind": "map", "data": {"epoch": 1}}]}
    tl = merged_timeline({"osd.0": a, "osd.1": b})
    assert [e["data"]["epoch"] for e in tl] == [1, 2]
    assert tl[1]["t"] == 1000.0


# ------------------------------------------------------- trigger matrix


def test_slo_gate_failure_produces_postmortem_bundle(tmp_path):
    """Trigger kind 1: a forced SLO-gate failure (unreachable goodput
    floor) auto-produces exactly one parseable bundle whose breach
    attribution explains >= 0.9 of the late ops' wall."""
    from dataclasses import replace

    from ceph_tpu.load.driver import builtin_specs, run_load

    spec = replace(
        builtin_specs()["smoke-micro"], name="bb-slo",
        gates=(("goodput_min_frac", 1e9),),
        config=(("blackbox_enabled", 1),
                ("blackbox_dir", str(tmp_path))))
    _result, report = run(run_load(spec, 7))
    assert not report.passed
    assert any(g["gate"] == "goodput" for g in report.failing_gates())
    assert report.postmortem and os.path.exists(report.postmortem)
    bundle = pm.load_bundle(report.postmortem)
    assert bundle["trigger"]["kind"] == "slo_gate"
    # observed-vs-threshold rows for the failing gates ride the trigger
    det = {g["gate"]: g for g in bundle["trigger"]["detail"]["gates"]}
    assert det["goodput"]["threshold"] >= 1e9
    # breach attribution coverage: the acceptance bar
    breach = bundle["breach"]
    assert breach["breach_ops"] >= 1
    assert breach["attribution"]["wall_coverage"] >= 0.9
    assert breach["suspects"], "top-suspects table must not be empty"
    # client rings rode along (clients have no admin socket)
    assert any(k.startswith("client.") for k in bundle["daemons"])
    # exactly ONE bundle for one failed judgment
    assert len(list(tmp_path.glob("POSTMORTEM_*.json"))) == 1


@pytest.mark.chaos
def test_chaos_conviction_bundle_replays_bit_identical(tmp_path):
    """Trigger kind 2: a forced chaos conviction (unreachable epochs
    floor) produces a bundle, the Verdict records the failing gate's
    observed-vs-threshold row + the bundle path, and a seeded rerun
    lands on the SAME bundle path with a bit-identical replay key."""
    from ceph_tpu.chaos.scenario import Scenario, run_scenario

    sc = Scenario(
        name="bb-conv", osds=3, pool_size=2, pg_num=4, rounds=1,
        objects_per_round=2, payload_repeat=10,
        invariants=("durability",), epochs_floor=1e9,
        config=(("blackbox_enabled", 1),
                ("blackbox_dir", str(tmp_path))),
        converge_timeout=45.0)
    v1 = run(run_scenario(sc, 13))
    assert not v1.passed
    rows = {g["gate"]: g for g in v1.gates}
    assert rows["epochs"]["passed"] is False
    assert rows["epochs"]["threshold"] == 1e9
    assert v1.postmortem and os.path.exists(v1.postmortem)
    b1 = pm.load_bundle(v1.postmortem)
    assert b1["trigger"]["kind"] == "chaos_conviction"
    assert b1["trigger"]["detail"]["gates"]
    # breach attribution coverage holds on the convicted run too
    assert b1["breach"]["attribution"].get("wall_coverage", 0) >= 0.9
    k1 = pm.replay_key(b1)
    # seeded replay: the bundle filename is a pure function of the
    # trigger, so run 2 overwrites run 1's bundle on the same path
    v2 = run(run_scenario(sc, 13))
    assert v2.postmortem == v1.postmortem
    assert pm.replay_key(pm.load_bundle(v2.postmortem)) == k1


def test_crash_point_trigger_produces_one_bundle(tmp_path):
    """Trigger kind 3: an armed chaos crash point power-cuts its daemon
    AND fires a postmortem — the bundle is taken with the victim
    already down (its absence from the daemon set IS evidence)."""
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    async def scenario():
        cfg = _fast_config()
        cfg.set("blackbox_enabled", 1)
        cfg.set("blackbox_dir", str(tmp_path))
        cluster = await start_cluster(4, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("bb", "replicated",
                                            pg_num=4, size=3)
            io = client.ioctx(pool)
            await io.write_full("o0", b"x" * 4096)
            pgid = client.objecter.object_pgid(pool, "o0")
            _, _, _, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            cluster.osds[primary].config.injectargs(
                {"chaos_crash_point": "commit_pre_fanout"})
            # the write that trips the crash retries and lands whole
            await io.write_full("o0", b"y" * 4096, timeout=60)
            await cluster.drain_chaos()
            await cluster.drain_blackbox()
            recs = [r for r in cluster.postmortems
                    if r["kind"] == "crash_point"]
            assert len(recs) == 1, cluster.postmortems
            assert f"osd.{primary}" in recs[0]["reason"]
            assert recs[0]["path"] and os.path.exists(recs[0]["path"])
            bundle = pm.load_bundle(recs[0]["path"])
            assert f"osd.{primary}" not in bundle["daemons"]
            # the survivors' rings carry events (heartbeat queue samples
            # at minimum)
            assert any(d.get("events")
                       for d in bundle["daemons"].values()
                       if isinstance(d, dict))
        finally:
            await cluster.stop()

    run(scenario())


def test_health_err_transition_triggers_one_bundle(tmp_path):
    """Trigger kind 4: the mon's edge INTO HEALTH_ERR (every OSD down)
    fires exactly one bundle, and the mon's bounded health-history ring
    (the satellite) records the raise + status transition and serves
    them over the admin socket."""
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    async def scenario():
        cfg = _fast_config()
        cfg.set("blackbox_enabled", 1)
        cfg.set("blackbox_dir", str(tmp_path))
        cfg.set("mon_health_history", 8)
        cluster = await start_cluster(2, config=cfg)
        try:
            await cluster.client()  # collection rides a live session
            for osd_id in sorted(cluster.osds):
                await cluster.kill_osd(osd_id)
            loop = asyncio.get_event_loop()
            deadline = loop.time() + 30
            while loop.time() < deadline and not cluster.postmortems:
                await asyncio.sleep(0.1)
            await cluster.drain_blackbox()
            recs = [r for r in cluster.postmortems
                    if r["kind"] == "health_err"]
            assert len(recs) == 1, cluster.postmortems
            bundle = pm.load_bundle(recs[0]["path"])
            assert bundle["trigger"]["detail"]["checks"].get("OSD_DOWN")
            hist = bundle["health_history"]
            assert any(r["check"] == "OSD_DOWN" and r["op"] == "raise"
                       for r in hist)
            # satellite: the mon serves the ring, bounded by config
            served = await cluster.daemon_command("mon.0",
                                                  "health history")
            assert len(served) <= 8
            assert any(r["check"] == "STATUS"
                       and r["severity"] == "HEALTH_ERR"
                       for r in served)
        finally:
            await cluster.stop()

    run(scenario())


# ----------------------------------------------------------- report CLI


def _cli(args, cwd):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "blackbox.py"),
         *args],
        cwd=cwd, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def _fake_bundle():
    return {
        "kind": pm.BUNDLE_KIND,
        "trigger": {"kind": "slo_gate", "reason": "forced",
                    "detail": {"gates": [{"gate": "goodput", "value": 1,
                                          "threshold": 2}],
                               "seed": 7, "spec": "bb"}},
        "daemons": {"osd.0": {"daemon": "osd.0", "skew": 0.0,
                              "dropped": 0, "capacity": 8, "events": [
                                  {"seq": 1, "t": 10.0, "kind": "queue",
                                   "data": {"depth": 3}}]}},
        "historic_ops": {"osd.0": {"ops": {"ops": [
            {"description": "write_full o0 pg=1.2s0",
             "duration": 0.02,
             "type_data": {"events": [
                 {"time": 0.0, "event": "initiated"},
                 {"time": 0.02, "event": "done"}]}}]},
            "slow": {"ops": []}}},
        "health": {"status": "HEALTH_OK", "checks": {}},
        "health_history": [],
        "mgr_scrape": {"error": "no mgr"},
    }


def test_cli_exit_codes(tmp_path):
    """Exit-code contract: 0 success, 1 bundle found but malformed for
    the request, 2 usage / no bundle / not a bundle."""
    # 2: nothing that looks like a bundle anywhere
    empty = tmp_path / "empty"
    empty.mkdir()
    assert _cli(["report"], empty).returncode == 2
    # 2: a JSON file that is not a postmortem bundle
    bad = tmp_path / "POSTMORTEM_x_nota.json"
    bad.write_text(json.dumps({"kind": "something-else"}))
    assert _cli(["key", str(bad)], tmp_path).returncode == 2
    # 0: a well-formed bundle reports, keys, and exports
    good = tmp_path / "POSTMORTEM_slo_gate_abc.json"
    good.write_text(json.dumps(_fake_bundle()))
    r = _cli(["report", str(good)], tmp_path)
    assert r.returncode == 0, r.stderr
    assert "breach set" in r.stdout and "goodput" in r.stdout
    r = _cli(["key", str(good)], tmp_path)
    assert r.returncode == 0 and len(r.stdout.strip()) == 64
    out = tmp_path / "t.trace.json"
    r = _cli(["perfetto", str(good), "--out", str(out)], tmp_path)
    assert r.returncode == 0, r.stderr
    assert json.loads(out.read_text())["traceEvents"]
    # 1: right kind, rotten content (non-numeric event stamps)
    rot = _fake_bundle()
    rot["daemons"]["osd.0"]["events"][0]["t"] = "not-a-stamp"
    rot_p = tmp_path / "POSTMORTEM_slo_gate_rot.json"
    rot_p.write_text(json.dumps(rot))
    assert _cli(["report", str(rot_p)], tmp_path).returncode == 1


def test_replay_key_ignores_wall_stamps():
    """The determinism witness hashes the trigger's deterministic
    projection ONLY: two bundles that differ in every wall stamp,
    duration, and counter still produce one key; changing the trigger
    identity changes it."""
    b1, b2 = _fake_bundle(), _fake_bundle()
    b2["daemons"]["osd.0"]["events"][0]["t"] = 99999.0
    b2["historic_ops"]["osd.0"]["ops"]["ops"][0]["duration"] = 5.0
    assert pm.replay_key(b1) == pm.replay_key(b2)
    b3 = _fake_bundle()
    b3["trigger"]["reason"] = "a different conviction"
    assert pm.replay_key(b3) != pm.replay_key(b1)
