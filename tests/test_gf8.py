"""GF(2^8) substrate tests: field axioms, known vectors, device == host."""

import numpy as np
import pytest

from ceph_tpu.ops import gf8


def test_known_products():
    # 2 * 0x80 = 0x100 -> reduced by 0x11d -> 0x1d
    assert gf8.gf_mul(2, 0x80) == 0x1D
    assert gf8.gf_mul(0, 0xAB) == 0
    assert gf8.gf_mul(1, 0xAB) == 0xAB
    # exp/log consistency: 2 is primitive
    assert gf8.GF_EXP[0] == 1
    assert gf8.GF_EXP[1] == 2
    assert len(set(gf8.GF_EXP[:255].tolist())) == 255


def test_field_axioms():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 256, 64, dtype=np.uint8)
    b = rng.integers(0, 256, 64, dtype=np.uint8)
    c = rng.integers(0, 256, 64, dtype=np.uint8)
    assert np.array_equal(gf8.gf_mul(a, b), gf8.gf_mul(b, a))
    # distributive over XOR (field addition)
    left = gf8.gf_mul(a, b ^ c)
    right = gf8.gf_mul(a, b) ^ gf8.gf_mul(a, c)
    assert np.array_equal(left, right)
    # associativity
    assert np.array_equal(
        gf8.gf_mul(gf8.gf_mul(a, b), c), gf8.gf_mul(a, gf8.gf_mul(b, c))
    )


def test_inverse():
    a = np.arange(1, 256, dtype=np.uint8)
    assert np.all(gf8.gf_mul(a, gf8.gf_inv(a)) == 1)
    with pytest.raises(ZeroDivisionError):
        gf8.gf_inv(0)


def test_gf_pow():
    assert gf8.gf_pow(2, 0) == 1
    assert gf8.gf_pow(2, 8) == 0x1D
    assert gf8.gf_pow(0, 5) == 0
    for n in range(1, 10):
        assert gf8.gf_pow(3, n) == gf8.gf_mul(gf8.gf_pow(3, n - 1), 3)


def test_bitmat_table():
    # multiply-by-a as a bit matrix reproduces gf_mul for every a, x
    rng = np.random.default_rng(1)
    for a in [0, 1, 2, 3, 0x1D, 0x80, 0xFF] + list(rng.integers(0, 256, 8)):
        m = gf8.GF_BITMAT[a]
        for x in rng.integers(0, 256, 16):
            xbits = (int(x) >> np.arange(8)) & 1
            ybits = (m @ xbits) % 2
            y = int((ybits << np.arange(8)).sum())
            assert y == int(gf8.gf_mul(a, x)), (a, x)


def test_device_matmul_matches_host():
    rng = np.random.default_rng(2)
    for r, k, n in [(4, 8, 256), (2, 4, 100), (6, 6, 1)]:
        m = rng.integers(0, 256, (r, k), dtype=np.uint8)
        d = rng.integers(0, 256, (k, n), dtype=np.uint8)
        want = gf8.gf_matmul_ref(m, d)
        got = np.asarray(gf8.gf_matmul(m, d))
        assert np.array_equal(want, got)


def test_matrix_inversion():
    rng = np.random.default_rng(3)
    eye = np.eye(5, dtype=np.uint8)
    for _ in range(10):
        a = rng.integers(0, 256, (5, 5), dtype=np.uint8)
        try:
            inv = gf8.gf_invert_matrix(a)
        except gf8.SingularMatrixError:
            continue
        assert np.array_equal(gf8.gf_matmul_ref(a, inv), eye)
        assert np.array_equal(gf8.gf_matmul_ref(inv, a), eye)


def test_singular_matrix_raises():
    a = np.zeros((3, 3), dtype=np.uint8)
    with pytest.raises(gf8.SingularMatrixError):
        gf8.gf_invert_matrix(a)


def test_pallas_kernel_matches_xla_when_available():
    """The Pallas alternative path must stay bit-exact with the XLA hot
    path (it only runs on a real TPU backend; CPU meshes skip)."""
    import numpy as np
    import pytest

    from ceph_tpu.ops import gf8, gf8_pallas

    if not gf8_pallas.available():
        pytest.skip("no TPU backend for Pallas")
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    mat = rng.integers(0, 256, (4, 8), dtype=np.uint8)
    bm = jnp.asarray(gf8.expand_bitmatrix(mat))
    data = jnp.asarray(rng.integers(0, 256, (8, 6144), dtype=np.uint8))
    assert np.array_equal(np.asarray(gf8.bitmatrix_matmul(bm, data)),
                          np.asarray(gf8_pallas.bitmatrix_matmul(bm, data)))
