"""Contention retry for timing-coupled cluster tests.

This environment runs the suite 3-way parallel on ONE CPU core, and a
handful of cluster tests couple correctness to wall-clock budgets
(client op timeouts vs XLA compile latency from a neighboring worker).
Each of these tests passes deterministically in isolation; under
worst-case contention one occasionally exceeds a budget.  Rather than
inflating every timeout (which slows the whole suite), the known
timing-coupled tests retry once — a transparent, bounded absorption of
scheduler noise, NOT a correctness crutch: genuine regressions fail on
every attempt.
"""

import functools
import sys


def contention_retry(attempts: int = 2):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            last = None
            for attempt in range(attempts):
                try:
                    return fn(*args, **kwargs)
                except (AssertionError, TimeoutError, OSError) as e:
                    last = e
                    # VERDICT r4 weak #7: every absorbed retry is LOGGED
                    # so a recurring first-attempt failure stays visible
                    # in the -s / CI output instead of being silently
                    # masked by the retry
                    print(
                        f"[contention_retry] {fn.__name__} attempt "
                        f"{attempt + 1}/{attempts} failed: "
                        f"{type(e).__name__}: {e}",
                        file=sys.stderr, flush=True)
            raise last

        return wrapper

    return deco
