"""uint32-pair 64-bit arithmetic vs Python big ints."""

import numpy as np

from ceph_tpu.ops import u64pair as u


def _pairs(vals):
    v = np.asarray(vals, dtype=np.uint64)
    return (v >> np.uint64(32)).astype(np.uint32), (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def _ints(p):
    return (p[0].astype(np.uint64) << np.uint64(32)) | p[1].astype(np.uint64)


RNG = np.random.default_rng(0)
A = RNG.integers(0, 2**64, 4096, dtype=np.uint64)
B = RNG.integers(0, 2**64, 4096, dtype=np.uint64)


def test_add_sub():
    a, b = _pairs(A), _pairs(B)
    assert np.array_equal(_ints(u.add(a, b)), A + B)  # uint64 wraps
    assert np.array_equal(_ints(u.sub(a, b)), A - B)


def test_shr_cmp():
    a, b = _pairs(A), _pairs(B)
    for n in (1, 4, 16, 31):
        assert np.array_equal(_ints(u.shr(a, n)), A >> np.uint64(n))
    assert np.array_equal(u.lt(a, b), A < B)
    assert np.array_equal(u.ge(a, b), A >= B)


def test_mul32():
    x = (A & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    y = (B & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    got = _ints(u.mul32(x, y))
    want = x.astype(np.uint64) * y.astype(np.uint64)
    assert np.array_equal(got, want)


def test_mulhi64():
    a, b = _pairs(A), _pairs(B)
    got = _ints(u.mulhi64(a, b))
    want = np.array([(int(x) * int(y)) >> 64 for x, y in zip(A, B)],
                    dtype=np.uint64)
    assert np.array_equal(got, want)


def test_div_by_recip():
    # n in the straw2 range [0, 2^48], w arbitrary u32 >= 1
    n_vals = np.concatenate([
        RNG.integers(0, 2**48 + 1, 2000, dtype=np.uint64),
        np.array([0, 1, 2**48, 2**48 - 1, 0xFFFF], dtype=np.uint64),
    ])
    w_vals = np.concatenate([
        RNG.integers(1, 2**32, 2000, dtype=np.uint64),
        np.array([1, 1, 1, 0x10000, 0xFFFFFFFF], dtype=np.uint64),
    ])
    n = _pairs(n_vals)
    w = w_vals.astype(np.uint32)
    recips = np.array([2**64 - 1 if int(x) == 1 else (2**64) // int(x)
                       for x in w_vals], dtype=np.uint64)
    r = _pairs(recips)
    got = _ints(u.div_by_recip(n, w, r[0], r[1]))
    want = np.array([int(a) // int(b) for a, b in zip(n_vals, w_vals)],
                    dtype=np.uint64)
    assert np.array_equal(got, want)
