"""Scenario-runner gates: the tier-1 seeded smoke scenario with the
seed-replay contract, and the full (slow-marked) scenario library.

The replay test IS the acceptance criterion: the same ``--seed`` must
produce an identical fault schedule and an identical verdict across two
independent runs.
"""

import asyncio

import pytest

from ceph_tpu.chaos.scenario import (
    build_schedule,
    builtin_scenarios,
    run_scenario,
)


def run(coro):
    return asyncio.run(coro)


@pytest.mark.chaos
def test_smoke_scenario_replays_bit_identical():
    """Tier-1 smoke: kill-one-OSD + 10% drop over a small object count,
    run TWICE from the same seed — identical schedule, identical (PASS)
    verdict, and the durability invariants hold both times."""
    sc = builtin_scenarios()["smoke"]
    assert build_schedule(sc, 42) == build_schedule(sc, 42)
    v1 = run(run_scenario(sc, 42))
    v2 = run(run_scenario(sc, 42))
    assert v1.passed, v1.failures
    assert v2.passed, v2.failures
    assert v1.replay_key() == v2.replay_key()
    assert v1.schedule == v2.schedule
    # faults actually fired (this is a chaos run, not a quiet one)
    assert v1.counters.get("daemon_kills") == 1
    assert v1.counters.get("net_drops", 0) > 0


@pytest.mark.chaos
def test_schedules_differ_across_seeds():
    sc = builtin_scenarios()["thrash-replicated"]
    sched = {seed: build_schedule(sc, seed) for seed in range(20)}
    # victims vary with the seed (the schedule is seed-driven, not
    # hardcoded): at least two distinct plans across 20 seeds
    assert len({str(s) for s in sched.values()}) > 1


@pytest.mark.chaos
@pytest.mark.slow
def test_partition_kill_torn_scenario(tmp_path):
    """The acceptance gate: asymmetric-healing partition + power-cut
    kill + torn journal tail on FileStore — durability suite passes."""
    v = run(run_scenario(builtin_scenarios()["partition-kill-torn"], 7,
                         tmpdir=str(tmp_path)))
    assert v.passed, v.failures
    assert v.counters.get("disk_torn_journals") == 1
    assert v.counters.get("net_partition_blocks", 0) > 0


@pytest.mark.chaos
@pytest.mark.slow
def test_clock_skew_scenario():
    v = run(run_scenario(builtin_scenarios()["clock-skew"], 3))
    assert v.passed, v.failures
    assert v.counters.get("clock_skews", 0) >= 1


@pytest.mark.chaos
@pytest.mark.slow
def test_bitrot_scrub_scenario():
    v = run(run_scenario(builtin_scenarios()["bitrot-scrub"], 11))
    assert v.passed, v.failures
    assert v.counters.get("disk_bitrot_flips") == 1
