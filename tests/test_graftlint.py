"""graftlint: the static-analysis tier-1 gate + rule self-tests.

Three layers: (1) the whole repo lints clean against the shipped
baseline — THE gate every future PR runs for free; (2) each rule
family fires on its bad-corpus fixture and stays quiet on its good
twin; (3) the runtime wiring — merged static+runtime lock-graph
acyclicity, DepLock held-stack bookkeeping, and the lockdep dump /
graftlint report admin commands.
"""

import ast
import asyncio
import os
import subprocess
import sys

import pytest

from ceph_tpu.analysis import baseline as baseline_mod
from ceph_tpu.analysis import (
    asyncio_rules, engine, jax_hygiene, lockgraph, planar_hygiene,
    symmetry, taskspawn,
)
from ceph_tpu.utils.lockdep import DepLock, LockCycleError, LockDep

REPO = engine.repo_root()
CORPUS = os.path.join(REPO, "tests", "lint_corpus")


def corpus(name):
    return os.path.join(CORPUS, name)


def lint_files(rule_mod, *names, relpath_as=None, runtime_edges=None):
    """Run one rule family over corpus files; relpath_as relabels the
    single module (the asyncio Lock rule is cluster/-scoped)."""
    modules, errors = engine.load_modules([corpus(n) for n in names])
    assert not errors, errors
    if relpath_as is not None:
        for m in modules:
            m.relpath = relpath_as
    ctx = engine.LintContext(runtime_edges=runtime_edges)
    return rule_mod.check(modules, ctx), ctx


# ---------------------------------------------------------------- the gate


def test_repo_lints_clean_with_shipped_baseline():
    """Tier-1 gate: zero unsuppressed findings over the whole repo, and
    the merged lock graph is acyclic."""
    baseline = baseline_mod.load_baseline(
        baseline_mod.default_baseline_path())
    report = engine.run_lint(baseline=baseline)
    assert report.parse_errors == []
    assert report.findings == [], "\n" + report.render_text()
    assert report.lock_graph["acyclic"], report.lock_graph
    # the static pass actually extracted the cluster's lock nestings
    # (daemon locks order before messenger locks)
    edges = "\n".join(report.lock_graph["static_edges"])
    assert "pg.lock -> messenger.session" in edges
    assert "messenger.session -> messenger.conn_send" in edges


def test_balance_subsystem_in_scope_with_no_baseline_debt():
    """Scope pin (graft-balance): every file of ceph_tpu/balance/ is in
    the default lint file set — a package move or walker regression
    can't silently drop the subsystem from the gate — and the shipped
    baseline carries ZERO entries for it (the subsystem lints clean,
    not suppressed)."""
    paths = {os.path.relpath(p, REPO).replace(os.sep, "/")
             for p in engine.default_paths()}
    bal_dir = os.path.join(REPO, "ceph_tpu", "balance")
    expected = {f"ceph_tpu/balance/{fn}" for fn in os.listdir(bal_dir)
                if fn.endswith(".py")}
    assert expected, "ceph_tpu/balance/ vanished"
    assert expected <= paths, expected - paths
    # the CLI entry point and the elastic scenario module ride along
    assert "scripts/balance.py" in paths
    assert "ceph_tpu/chaos/balance.py" in paths
    baseline = baseline_mod.load_baseline(
        baseline_mod.default_baseline_path())
    debt = [k for k in baseline if "balance" in k]
    assert debt == [], debt


def test_cli_exits_zero_on_repo():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftlint.py")],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_cli_json_and_dot(tmp_path):
    import json

    dot = tmp_path / "locks.dot"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
         "--json", "--dot", str(dot)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True
    assert doc["lock_graph"]["acyclic"] is True
    text = dot.read_text()
    assert "digraph lock_order" in text
    assert '"pg.lock" -> "messenger.session"' in text


# ------------------------------------------------------- rule: lock-order


def test_lock_order_good_clean():
    findings, _ = lint_files(lockgraph, "lock_order_good.py")
    assert findings == []


def test_lock_order_bad_cycle_detected():
    findings, ctx = lint_files(lockgraph, "lock_order_bad.py")
    assert len(findings) == 1
    assert findings[0].rule == "lock-order"
    assert "corpus.A" in findings[0].message
    assert "corpus.B" in findings[0].message
    assert ctx.lock_graph["acyclic"] is False


def test_lock_order_call_through_cycle():
    """Neither function nests directly; the inversion only exists
    through the awaited call — the interprocedural pass finds it."""
    findings, _ = lint_files(lockgraph, "lock_order_call_through_bad.py")
    assert len(findings) == 1
    assert "corpus.CT_A" in findings[0].message


def test_static_detection_fires_before_any_runtime_acquisition():
    """Cycle injection: the bad corpus never RUNS — no lock is ever
    acquired (the runtime lockdep graph stays empty), yet the static
    pass already reports the deadlock runtime lockdep would only catch
    after both paths execute."""
    LockDep.instance().reset()
    assert LockDep.instance().edges == {}
    findings, _ = lint_files(lockgraph, "lock_order_bad.py")
    assert findings, "static analysis must fire with zero runtime edges"
    assert LockDep.instance().edges == {}  # still nothing ever ran


def test_merged_static_plus_runtime_cycle():
    """A runtime-observed edge closing a static edge into a cycle fails
    the merged graph: the corpus's GOOD file (A->B only) plus a live
    B->A edge from the runtime lockdep dump."""
    findings, ctx = lint_files(lockgraph, "lock_order_good.py",
                               runtime_edges={"corpus.B": ["corpus.A"]})
    assert len(findings) == 1
    assert ctx.lock_graph["acyclic"] is False
    # and the real LockDep dump shape feeds straight in
    async def scenario():
        a, b = DepLock("mg.A"), DepLock("mg.B")
        async with a:
            async with b:
                pass

    asyncio.run(scenario())
    dump = LockDep.instance().dump()
    assert dump["edges"] == {"mg.A": ["mg.B"]}
    succ = lockgraph.merged_graph({}, dump["edges"])
    assert lockgraph.find_cycle(succ) is None
    succ = lockgraph.merged_graph({("mg.B", "mg.A"): ("t", 1)},
                                  dump["edges"])
    assert lockgraph.find_cycle(succ) is not None


# ------------------------------------------------------- rule: jax-hygiene


def test_jax_hygiene_good_clean():
    findings, _ = lint_files(jax_hygiene, "jax_hygiene_good.py")
    assert findings == [], [f.render() for f in findings]


def test_jax_hygiene_bad_all_families_fire():
    findings, _ = lint_files(jax_hygiene, "jax_hygiene_bad.py")
    msgs = "\n".join(f.message for f in findings)
    syms = {f.symbol for f in findings}
    assert "host materialization" in msgs and "bad_asarray" in syms
    assert "float" in msgs and "bad_float" in syms
    assert "wall-clock" in msgs and "bad_clock" in syms
    assert "branches on traced value" in msgs and "bad_branch" in syms
    assert "block_until_ready" in msgs  # scan-body host sync
    assert "module-scope jnp.arange" in msgs  # import-time device work


# ----------------------------------------------------- rule: encode-decode


def test_symmetry_good_clean():
    findings, _ = lint_files(symmetry, "symmetry_good.py")
    assert findings == [], [f.render() for f in findings]


def test_symmetry_bad_all_families_fire():
    findings, _ = lint_files(symmetry, "symmetry_bad.py")
    msgs = "\n".join(f.message for f in findings)
    assert "'stamp' is encoded but never restored" in msgs
    assert "exceeds declared struct_v=2" in msgs
    assert "not monotonic" in msgs
    assert "'blob' is encoded but not decoded" in msgs
    assert "MOrphan is encoded but _decode_frame never constructs" in msgs
    assert "wire message field 'blob' has no default" in msgs


# -------------------------------------------------- rule: asyncio-blocking


def test_asyncio_good_clean():
    findings, _ = lint_files(
        asyncio_rules, "asyncio_good.py",
        relpath_as="ceph_tpu/cluster/asyncio_good.py")
    assert findings == [], [f.render() for f in findings]


def test_asyncio_bad_fires():
    findings, _ = lint_files(
        asyncio_rules, "asyncio_bad.py",
        relpath_as="ceph_tpu/cluster/asyncio_bad.py")
    msgs = "\n".join(f.message for f in findings)
    assert "time.sleep" in msgs
    assert "open()" in msgs
    assert "subprocess.run" in msgs
    assert "bare asyncio.Lock() escapes lockdep" in msgs


# ----------------------------------------------------- rule: task-spawn


def test_task_spawn_good_clean():
    findings, _ = lint_files(
        taskspawn, "task_spawn_good.py",
        relpath_as="ceph_tpu/cluster/task_spawn_good.py")
    assert findings == [], [f.render() for f in findings]


def test_task_spawn_bad_all_shapes_fire():
    findings, _ = lint_files(
        taskspawn, "task_spawn_bad.py",
        relpath_as="ceph_tpu/cluster/task_spawn_bad.py")
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 5, [f.render() for f in findings]
    assert "task handle discarded" in msgs
    assert "_tasks.append()" in msgs        # grow-only list
    assert "_running.add()" in msgs         # grow-only set
    assert "'orphan' but never tracked" in msgs
    assert all(f.rule == "task-spawn" for f in findings)


def test_task_spawn_scoped_to_cluster():
    """The rule is cluster/-scoped like the bare-Lock rule: the same
    source outside ceph_tpu/cluster/ stays quiet."""
    findings, _ = lint_files(taskspawn, "task_spawn_bad.py")
    assert findings == []


# ------------------------------------- rule: swallowed-async-error


def test_swallowed_async_error_good_clean():
    from ceph_tpu.analysis import async_errors

    findings, _ = lint_files(
        async_errors, "swallowed_async_good.py",
        relpath_as="ceph_tpu/cluster/swallowed_async_good.py")
    assert findings == [], [f.render() for f in findings]


def test_swallowed_async_error_bad_all_shapes_fire():
    from ceph_tpu.analysis import async_errors

    findings, _ = lint_files(
        async_errors, "swallowed_async_bad.py",
        relpath_as="ceph_tpu/cluster/swallowed_async_bad.py")
    assert len(findings) == 4, [f.render() for f in findings]
    msgs = "\n".join(f.message for f in findings)
    assert "bare 'except:'" in msgs
    assert "'except Exception:'" in msgs
    assert "result discarded" in msgs
    assert "bound to 'results' but never read" in msgs
    assert all(f.rule == "swallowed-async-error" for f in findings)


def test_swallowed_async_error_scoped_to_cluster():
    from ceph_tpu.analysis import async_errors

    findings, _ = lint_files(async_errors, "swallowed_async_bad.py")
    assert findings == []


def test_rpc_timeout_good_clean():
    from ceph_tpu.analysis import rpc_timeout

    findings, _ = lint_files(
        rpc_timeout, "rpc_timeout_good.py",
        relpath_as="ceph_tpu/cluster/rpc_timeout_good.py")
    assert findings == [], [f.render() for f in findings]


def test_rpc_timeout_bad_fires():
    from ceph_tpu.analysis import rpc_timeout

    findings, _ = lint_files(
        rpc_timeout, "rpc_timeout_bad.py",
        relpath_as="ceph_tpu/cluster/rpc_timeout_bad.py")
    # plain, annotated, and chained bindings all fire
    assert len(findings) == 4, [f.render() for f in findings]
    assert all(f.rule == "rpc-timeout" for f in findings)
    msgs = "\n".join(f.message for f in findings)
    assert "can hang forever" in msgs
    assert "wait_for" in msgs


def test_rpc_timeout_scoped_to_cluster():
    from ceph_tpu.analysis import rpc_timeout

    findings, _ = lint_files(rpc_timeout, "rpc_timeout_bad.py")
    assert findings == []


def test_scopes_cover_blackbox_modules():
    """Scope pin (round 17): the task-spawn / swallowed-async-error /
    rpc-timeout rules must keep the graft-blackbox modules in range —
    the flight recorder feeds daemon hot paths and the postmortem
    collector awaits admin commands across a possibly-dying cluster,
    exactly the bug classes these rules exist for.  A scope refactor
    that drops them would silently stop linting them."""
    from ceph_tpu.analysis import async_errors, rpc_timeout, taskspawn

    blackbox_files = [
        "ceph_tpu/trace/flight.py",
        "ceph_tpu/trace/postmortem.py",
        # the trigger/bundle seams live in already-scoped packages —
        # pinned too so the bundle path can't drift out of range
        "ceph_tpu/cluster/vstart.py",
        "ceph_tpu/load/driver.py",
        "ceph_tpu/chaos/scenario.py",
    ]
    for mod in (taskspawn, async_errors, rpc_timeout):
        for path in blackbox_files:
            assert path.startswith(mod.SCOPE), (mod.RULE, path)


def test_scopes_cover_client_batcher_modules():
    """Scope pin (round 18): the client-edge coalescer lives in the
    objecter + cluster/batcher.py — the task-spawn /
    swallowed-async-error / rpc-timeout rules must keep both in range
    (the OpBatcher spawns per-(session, OSD) drain tasks and parks ops
    on futures, exactly these rules' bug classes), and
    per-op-device-dispatch must keep covering the modules feeding the
    batch seam.  Zero new baseline entries is the round-18 contract:
    the only sanctioned quiet zone stays cluster/batcher.py itself."""
    from ceph_tpu.analysis import (async_errors, device_dispatch,
                                   rpc_timeout, taskspawn)

    client_batch_files = [
        "ceph_tpu/cluster/objecter.py",
        "ceph_tpu/cluster/batcher.py",
        "ceph_tpu/cluster/client_ops.py",
    ]
    for mod in (taskspawn, async_errors, rpc_timeout):
        for path in client_batch_files:
            assert path.startswith(mod.SCOPE), (mod.RULE, path)
    # per-op-device-dispatch scopes to cluster/ with batcher.py as the
    # one sanctioned coalescer seam — pin both halves of that contract
    for path in client_batch_files:
        assert path.startswith("ceph_tpu/cluster/"), path
    assert device_dispatch.COALESCER == "ceph_tpu/cluster/batcher.py"


def test_device_dispatch_good_clean():
    from ceph_tpu.analysis import device_dispatch

    findings, _ = lint_files(
        device_dispatch, "device_dispatch_good.py",
        relpath_as="ceph_tpu/cluster/device_dispatch_good.py")
    assert findings == [], [f.render() for f in findings]


def test_device_dispatch_bad_fires():
    from ceph_tpu.analysis import device_dispatch

    findings, _ = lint_files(
        device_dispatch, "device_dispatch_bad.py",
        relpath_as="ceph_tpu/cluster/device_dispatch_bad.py")
    # direct planar calls (2), the executor-hop callable, and the
    # per-op batched crc all fire
    assert len(findings) == 4, [f.render() for f in findings]
    assert all(f.rule == "per-op-device-dispatch" for f in findings)
    msgs = "\n".join(f.message for f in findings)
    assert "to_planar()" in msgs and "encode_planar()" in msgs
    assert "encode_stripes handed to self._compute()" in msgs
    assert "crc32c_batch()" in msgs
    assert "batch coalescer" in msgs


def test_device_dispatch_scoped_and_coalescer_exempt():
    from ceph_tpu.analysis import device_dispatch

    # outside ceph_tpu/cluster/: quiet
    findings, _ = lint_files(device_dispatch, "device_dispatch_bad.py")
    assert findings == []
    # the coalescer module itself is the sanctioned seam: quiet
    findings, _ = lint_files(
        device_dispatch, "device_dispatch_bad.py",
        relpath_as="ceph_tpu/cluster/batcher.py")
    assert findings == []


def test_device_dispatch_baseline_is_empty():
    """Round 16 acceptance: the three accepted per-op-device-dispatch
    remnants (legacy encode branch, read decode, recovery reencode) are
    GONE — every device dispatch of the cluster data plane routes
    through cluster/batcher.py, and the shipped baseline carries ZERO
    suppressions for the rule (a regression would need a visible
    baseline diff to land)."""
    keys = baseline_mod.load_baseline(
        baseline_mod.default_baseline_path())
    assert not [k for k in keys
                if k.startswith("per-op-device-dispatch::")], keys


# ------------------------------------------------------- runtime wiring


def test_deplock_aexit_pops_most_recent():
    """The held-list fix: same-named locks nesting must unwind LIFO.
    list.remove dropped the FIRST occurrence, so the survivor entry was
    the inner one — harmless per-element but corrupting once order
    matters to anything walking the stack.  Cycle DETECTION is disabled
    for the scenario (same-name re-acquisition through a second
    instance is itself a lockdep edge cycle); only the held-stack
    bookkeeping is under test here."""

    async def scenario():
        outer, mid, inner = DepLock("dl.A"), DepLock("dl.B"), DepLock("dl.A")
        async with outer:
            async with mid:
                key = id(asyncio.current_task())
                async with inner:
                    assert DepLock._held[key] == ["dl.A", "dl.B", "dl.A"]
                # the INNER dl.A must be the one popped
                assert DepLock._held[key] == ["dl.A", "dl.B"]
            assert DepLock._held[key] == ["dl.A"]
        assert key not in DepLock._held

    LockDep.instance().enabled = False
    try:
        asyncio.run(scenario())
    finally:
        LockDep.instance().enabled = True


def test_lockdep_fixture_isolate_between_tests_a():
    """With the autouse reset fixture, an A->B order learned here must
    not leak into the next test (which takes B->A legitimately)."""

    async def scenario():
        async with DepLock("iso.A"):
            async with DepLock("iso.B"):
                pass

    asyncio.run(scenario())
    assert "iso.A" in LockDep.instance().edges


def test_lockdep_fixture_isolate_between_tests_b():
    assert "iso.A" not in LockDep.instance().edges  # fixture wiped it

    async def scenario():
        async with DepLock("iso.B"):
            async with DepLock("iso.A"):  # would cycle without the reset
                pass

    asyncio.run(scenario())


def test_admin_socket_lockdep_dump_and_graftlint_report():
    """`ceph daemon <name> lockdep dump` / `graftlint report` (router
    from PR 1): the observed lock graph and the last lint summary are
    servable from every daemon's AdminSocket."""
    from ceph_tpu.utils.admin_socket import AdminSocket
    from ceph_tpu.utils.perf import PerfCounters

    async def scenario():
        asok = AdminSocket()
        asok.register_common(PerfCounters("t"))
        async with DepLock("asok.A"):
            async with DepLock("asok.B"):
                pass
        rc, dump = await asok.dispatch({"prefix": "lockdep dump"})
        assert rc == 0
        assert dump["edges"] == {"asok.A": ["asok.B"]}
        rc, rep = await asok.dispatch({"prefix": "graftlint report"})
        assert rc == 0
        assert rep["ok"] is True
        assert rep["files_checked"] > 100
        assert rep["lock_graph"]["acyclic"] is True

    asyncio.run(scenario())


def test_runtime_lockdep_still_catches_dynamic_cycles():
    """The static pass complements — not replaces — runtime lockdep."""

    async def scenario():
        a, b = DepLock("rt.A"), DepLock("rt.B")
        async with a:
            async with b:
                pass
        with pytest.raises(LockCycleError):
            async with b:
                async with a:
                    pass

    asyncio.run(scenario())


def test_baseline_roundtrip(tmp_path):
    f = engine.Finding(rule="r", path="p.py", line=3, symbol="s",
                       message="m")
    path = tmp_path / "b.json"
    n = baseline_mod.write_baseline(str(path), [f])
    assert n == 1
    keys = baseline_mod.load_baseline(str(path))
    assert f.baseline_key in keys
    # line drift does not invalidate the suppression
    f2 = engine.Finding(rule="r", path="p.py", line=99, symbol="s",
                        message="m")
    assert f2.baseline_key in keys


def test_pragma_suppression(tmp_path):
    src = (
        "import asyncio\n"
        "import time\n"
        "async def tick():\n"
        "    # graftlint: ignore[asyncio-blocking]\n"
        "    time.sleep(1)\n")
    p = tmp_path / "prag.py"
    p.write_text(src)
    report = engine.run_lint(paths=[str(p)],
                             rules=[asyncio_rules], root=str(tmp_path))
    assert report.findings == []
    p.write_text(src.replace("    # graftlint: ignore"
                             "[asyncio-blocking]\n", ""))
    report = engine.run_lint(paths=[str(p)],
                             rules=[asyncio_rules], root=str(tmp_path))
    assert len(report.findings) == 1


def test_static_argnames_params_are_static(tmp_path):
    """`static_argnames` (the string idiom) must exempt those params
    exactly like `static_argnums` — correct JAX code must not fail the
    gate."""
    src = (
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, static_argnames=('w',))\n"
        "def f(x, w):\n"
        "    if w == 8:\n"
        "        return x\n"
        "    return x + w\n")
    p = tmp_path / "argnames.py"
    p.write_text(src)
    report = engine.run_lint(paths=[str(p)], rules=[jax_hygiene],
                             root=str(tmp_path))
    assert report.findings == [], [f.render() for f in report.findings]


def test_subset_lint_does_not_poison_report_cache(tmp_path):
    """last_report (the `graftlint report` admin payload) must never
    serve a subset lint as the repo's state."""
    whole = engine.run_lint(baseline=baseline_mod.load_baseline(
        baseline_mod.default_baseline_path()))
    assert whole.ok
    bad = tmp_path / "bad.py"
    bad.write_text("import time\nasync def t():\n    time.sleep(1)\n")
    subset = engine.run_lint(paths=[str(bad)], rules=[asyncio_rules],
                             root=str(tmp_path))
    assert not subset.ok
    cached = engine.last_report(run_if_missing=False)
    assert cached is not None
    assert cached["ok"] is True  # still the whole-repo report
    assert cached["files_checked"] == whole.files_checked


def test_stale_baseline_reported(tmp_path):
    p = tmp_path / "clean.py"
    p.write_text("x = 1\n")
    report = engine.run_lint(paths=[str(p)], rules=[asyncio_rules],
                             baseline={"ghost::entry::s::m"},
                             root=str(tmp_path))
    assert report.ok
    assert report.stale_baseline == ["ghost::entry::s::m"]


# ------------------------------------------- planar-conversion-hygiene


def test_planar_hygiene_good_clean():
    """Seam-declared transitions and reshape-only blob views pass; the
    one deliberately-unseamed fixture line carries a pragma the engine
    (not the raw rule) drops — mirroring the store read() fallbacks."""
    findings, _ = lint_files(
        planar_hygiene, "planar_hygiene_good.py",
        relpath_as="ceph_tpu/cluster/store.py")
    # the raw rule still sees the pragma'd unseamed call …
    assert [f for f in findings if "unseamed" not in f.message] == [], \
        [f.render() for f in findings]
    # … and the engine's pragma pass is what suppresses it
    modules, _ = engine.load_modules([corpus("planar_hygiene_good.py")])
    (m,) = modules
    assert all(m.pragma_suppressed(f.rule, f.line) for f in findings)


def test_planar_hygiene_bad_all_shapes_fire():
    findings, _ = lint_files(
        planar_hygiene, "planar_hygiene_bad.py",
        relpath_as="ceph_tpu/cluster/store.py")
    msgs = "\n".join(f.message for f in findings)
    # raw transforms, undeclared seams (sync AND async), and the
    # declared-unseamed byte view all fire
    assert "raw layout transform to_planar()" in msgs
    assert "raw layout transform rows_to_planes()" in msgs
    assert "shard_to_planes() without an explicit seam=" in msgs
    assert "planes_to_shard() without an explicit seam=" in msgs
    assert 'seam="unseamed"' in msgs
    assert len(findings) == 6, [f.render() for f in findings]


def test_planar_hygiene_scoped_to_cluster():
    """Scope pin: the rule polices cluster/ modules only, and the tick
    coalescer — the sanctioned dispatch seam — is exempt by name."""
    for relpath in ("ceph_tpu/ec/planar_store.py",
                    "ceph_tpu/ops/gf8.py",
                    "tests/test_ec_planar.py",
                    "ceph_tpu/cluster/batcher.py"):
        findings, _ = lint_files(
            planar_hygiene, "planar_hygiene_bad.py",
            relpath_as=relpath)
        assert findings == [], (relpath, [f.render() for f in findings])


def test_planar_hygiene_zero_baseline_debt():
    """Round-19 contract: the at-rest refactor landed with ZERO
    planar-conversion-hygiene baseline entries — every conversion in
    cluster/ is seam-declared or pragma'd at a documented fallback."""
    baseline = baseline_mod.load_baseline(
        baseline_mod.default_baseline_path())
    assert not any(k.startswith("planar-conversion-hygiene::")
                   for k in baseline)
    report = engine.run_lint(rules=[planar_hygiene])
    assert report.findings == [], "\n" + report.render_text()


# --------------------------------------------------- rule: await-atomicity


def test_awaitrace_good_clean():
    from ceph_tpu.analysis import awaitrace

    findings, _ = lint_files(
        awaitrace, "awaitrace_good.py",
        relpath_as="ceph_tpu/cluster/awaitrace_good.py")
    assert findings == [], [f.render() for f in findings]


def test_awaitrace_bad_all_variants_fire():
    from ceph_tpu.analysis import awaitrace

    findings, _ = lint_files(
        awaitrace, "awaitrace_bad.py",
        relpath_as="ceph_tpu/cluster/awaitrace_bad.py")
    msgs = "\n".join(f"{f.symbol}: {f.message}" for f in findings)
    assert "stale_snapshot: stale-snapshot-across-await" in msgs
    assert "check_then_act: check-then-act-across-await" in msgs
    assert "lock_window_escape: lock-window-escape" in msgs
    assert len(findings) == 3, [f.render() for f in findings]


def test_awaitrace_scoped_to_cluster():
    """The bad corpus relabelled outside cluster/ stays quiet."""
    from ceph_tpu.analysis import awaitrace

    for relpath in ("ceph_tpu/chaos/scenario.py",
                    "tests/test_cluster_ops.py",
                    "ceph_tpu/trace/flight.py"):
        findings, _ = lint_files(
            awaitrace, "awaitrace_bad.py", relpath_as=relpath)
        assert findings == [], (relpath, [f.render() for f in findings])


def test_awaitrace_convicts_pr9_superseded_pgstate():
    """Historical-race pin: the PR-9 superseded-PGState ack-wait (the
    watermark persisted through a registry entry replaced during the
    await) is convicted in its pre-fix shape, and the shipped identity
    re-check shape stays quiet — the detector must catch the bugs we
    already paid for."""
    from ceph_tpu.analysis import awaitrace

    findings, _ = lint_files(
        awaitrace, "awaitrace_hist_pgstate.py",
        relpath_as="ceph_tpu/cluster/awaitrace_hist_pgstate.py")
    assert len(findings) == 1, [f.render() for f in findings]
    assert findings[0].symbol.endswith("buggy_pr9_shape")
    assert "stale-snapshot-across-await" in findings[0].message
    assert "'pgs'" in findings[0].message


def test_awaitrace_convicts_pr11_stale_selfinfo_floor():
    """Historical-race pin: PR 11's roll-forward floor resting on the
    round-start self head is convicted; the re-read-after-the-awaits
    fix shape stays quiet."""
    from ceph_tpu.analysis import awaitrace

    findings, _ = lint_files(
        awaitrace, "awaitrace_hist_selfinfo.py",
        relpath_as="ceph_tpu/cluster/awaitrace_hist_selfinfo.py")
    assert len(findings) == 1, [f.render() for f in findings]
    assert findings[0].symbol.endswith("buggy_pr11_shape")
    assert "stale-snapshot-across-await" in findings[0].message
    assert "'last_update'" in findings[0].message


def test_scopes_cover_awaitrace_cluster_modules():
    """Scope pin (round 20): await-atomicity must keep the async data
    plane in range — the PG state machine, the EC backend, recovery,
    scrub, and the op dispatch edge are exactly where the
    await-interleaving races this rule exists for have already
    happened (PRs 9/11/12).  A scope refactor that drops any of them
    would silently stop linting the hot path."""
    from ceph_tpu.analysis import awaitrace

    for path in ("ceph_tpu/cluster/pg.py",
                 "ceph_tpu/cluster/osd.py",
                 "ceph_tpu/cluster/backend_ec.py",
                 "ceph_tpu/cluster/recovery.py",
                 "ceph_tpu/cluster/scrub.py",
                 "ceph_tpu/cluster/client_ops.py",
                 "ceph_tpu/cluster/batcher.py"):
        assert path.startswith(awaitrace.SCOPE), (awaitrace.RULE, path)
    # the watch-list keeps the fields the historical races moved through
    for attr in ("pgs", "acting", "last_update", "last_complete",
                 "pipeline_pending"):
        assert attr in awaitrace.WATCHED_STATE, attr


def test_awaitrace_registered_in_default_rules():
    """A refactor of all_rules() can't silently drop the race rules."""
    from ceph_tpu.analysis import awaitrace, testsleep

    rules = engine.all_rules()
    assert awaitrace in rules
    assert testsleep in rules


# ----------------------------------------------- rule: fixed-sleep-in-tests


def test_fixed_sleep_good_clean():
    """Converge-polls, bounded retries, sleep(0) yields, variable
    durations, and pragma'd pacing all stay quiet (the pragma is
    applied the way run_lint applies it)."""
    from ceph_tpu.analysis import testsleep

    modules, errors = engine.load_modules(
        [corpus("fixed_sleep_good.py")])
    assert not errors, errors
    modules[0].relpath = "tests/fixed_sleep_good.py"
    findings = testsleep.check(modules, engine.LintContext())
    live = [f for f in findings
            if not modules[0].pragma_suppressed(f.rule, f.line)]
    assert live == [], [f.render() for f in live]


def test_fixed_sleep_bad_all_shapes_fire():
    from ceph_tpu.analysis import testsleep

    findings, _ = lint_files(
        testsleep, "fixed_sleep_bad.py",
        relpath_as="tests/fixed_sleep_bad.py")
    msgs = "\n".join(f.message for f in findings)
    assert "asyncio.sleep(0.1)" in msgs
    assert "asyncio.sleep(1)" in msgs
    assert "time.sleep(0.5)" in msgs
    assert len(findings) == 3, [f.render() for f in findings]


def test_fixed_sleep_scoped_to_tests():
    """Daemon code is the asyncio-blocking rule's turf: the bad corpus
    relabelled into ceph_tpu/ stays quiet under THIS rule."""
    from ceph_tpu.analysis import testsleep

    findings, _ = lint_files(
        testsleep, "fixed_sleep_bad.py",
        relpath_as="ceph_tpu/cluster/osd.py")
    assert findings == []


def test_fixed_sleep_zero_baseline_debt():
    """Round-20 contract: the deflake sweep landed with ZERO
    fixed-sleep-in-tests baseline entries — every remaining constant
    sleep in tests/ is a converge-poll interval or a pragma'd,
    reasoned, time-semantic pacing sleep."""
    from ceph_tpu.analysis import testsleep

    baseline = baseline_mod.load_baseline(
        baseline_mod.default_baseline_path())
    assert not any(k.startswith("fixed-sleep-in-tests::")
                   for k in baseline)
    report = engine.run_lint(rules=[testsleep])
    assert report.findings == [], "\n" + report.render_text()
