"""Operator tools: crushtool / osdmaptool / rados / objectstore-tool.

Reference: src/tools/ — validated end-to-end against real maps, stores,
and a live cluster.
"""

import asyncio
import json
import pickle

import pytest

from ceph_tpu.crush.types import build_hierarchy
from ceph_tpu.tools import crushtool, objectstore_tool, osdmaptool, rados


def test_crushtool_compile_decompile_test(tmp_path, capsys):
    cmap, rule = build_hierarchy(4, 2, numrep=3)
    spec = crushtool.map_to_json(cmap)
    jf = tmp_path / "map.json"
    jf.write_text(json.dumps(spec))
    # compile json -> binary
    bf = tmp_path / "map.bin"
    assert crushtool.main(["-i", str(jf), "--compile",
                           "-o", str(bf)]) == 0
    # decompile back (json form; the default is the operator text
    # language, covered by tests/test_crush_compiler.py) and compare
    assert crushtool.main(["-i", str(bf), "--decompile", "--json"]) == 0
    out = capsys.readouterr().out
    spec2 = json.loads(out)
    assert {b["id"] for b in spec2["buckets"]} == \
        {b["id"] for b in spec["buckets"]}
    # batch placement test with utilization
    rc = crushtool.main(["-i", str(bf), "--test", "--rule", str(rule),
                         "--num-rep", "2", "--max-x", "511",
                         "--show-utilization"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "tested 512 inputs" in out and "0 bad mappings" in out


def test_osdmaptool_print_and_histogram(tmp_path, capsys):
    from ceph_tpu.osdmap.osdmap import OSDMap, PGPool

    cmap, rule = build_hierarchy(4, 2, numrep=3)
    m = OSDMap(cmap, max_osd=8)
    from ceph_tpu.osdmap.osdmap import POOL_TYPE_REPLICATED

    m.pools[1] = PGPool(pool_id=1, type=POOL_TYPE_REPLICATED, size=3,
                        min_size=2, pg_num=32, pgp_num=32,
                        crush_rule=rule, name="data")
    mf = tmp_path / "osdmap.bin"
    mf.write_bytes(pickle.dumps(m))
    assert osdmaptool.main([str(mf), "--print", "--test-map-pgs"]) == 0
    out = capsys.readouterr().out
    assert "max_osd 8" in out
    assert "pool 1 'data' replicated size 3" in out
    assert "pg_num 32" in out
    assert "osd.0" in out


def test_objectstore_tool(tmp_path, capsys):
    from ceph_tpu.cluster.filestore import FileStore
    from ceph_tpu.cluster.store import Transaction

    s = FileStore(str(tmp_path / "osd0"))
    s.mount()
    s.queue_transaction(
        Transaction().create_collection("pg_1_0")
        .write("pg_1_0", "obj", 0, b"tool-bytes")
        .setattr("pg_1_0", "obj", "_k", b"v")
        .set_version("pg_1_0", "obj", 4))
    s.umount()

    assert objectstore_tool.main(
        ["--data-path", str(tmp_path / "osd0"), "--op", "list"]) == 0
    assert "pg_1_0/obj" in capsys.readouterr().out
    assert objectstore_tool.main(
        ["--data-path", str(tmp_path / "osd0"), "--op", "info",
         "--collection", "pg_1_0", "--object", "obj"]) == 0
    out = capsys.readouterr().out
    assert "size 10" in out and "version 4" in out
    assert objectstore_tool.main(
        ["--data-path", str(tmp_path / "osd0"), "--op", "dump",
         "--collection", "pg_1_0", "--object", "obj"]) == 0
    assert "tool-bytes" in capsys.readouterr().out


def test_rados_cli_against_live_cluster(tmp_path, capsys):
    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            await client.pool_create("cli", "replicated", pg_num=8, size=2)
            mon = f"{cluster.mon_addrs[0][0]}:{cluster.mon_addrs[0][1]}"
            return cluster, mon
        except Exception:
            await cluster.stop()
            raise

    loop = asyncio.new_event_loop()
    try:
        cluster, mon = loop.run_until_complete(scenario())
    finally:
        pass
    try:
        infile = tmp_path / "payload"
        infile.write_bytes(b"cli-payload" * 100)

        # drive the CLI coroutine inside the cluster's event loop
        def cli(argv):
            return loop.run_until_complete(
                rados._run(rados.parse_args(argv)))

        assert cli(["--mon", mon, "lspools"]) == 0
        assert "cli" in capsys.readouterr().out
        assert cli(["--mon", mon, "-p", "cli", "put", "obj1",
                    str(infile)]) == 0
        outfile = tmp_path / "out"
        assert cli(["--mon", mon, "-p", "cli", "get", "obj1",
                    str(outfile)]) == 0
        assert outfile.read_bytes() == b"cli-payload" * 100
        assert cli(["--mon", mon, "-p", "cli", "ls"]) == 0
        assert "obj1" in capsys.readouterr().out
        assert cli(["--mon", mon, "-p", "cli", "rm", "obj1"]) == 0
    finally:
        loop.run_until_complete(cluster.stop())
        loop.close()


def test_rados_bench_modes_on_ec_pool(capsys):
    """VERDICT r4 missing #8: `rados bench <secs> write|seq|rand` on an
    EC pool reports MB/s + latency percentiles (reference
    src/tools/rados/rados.cc:103 obj_bencher)."""
    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            await client.pool_create(
                "benchec", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            mon = f"{cluster.mon_addrs[0][0]}:{cluster.mon_addrs[0][1]}"
            return cluster, mon
        except Exception:
            await cluster.stop()
            raise

    loop = asyncio.new_event_loop()
    cluster, mon = loop.run_until_complete(scenario())
    try:
        def cli(argv):
            return loop.run_until_complete(
                rados._run(rados.parse_args(argv)))

        assert cli(["--mon", mon, "-p", "benchec", "bench", "1.0",
                    "write", "-t", "4", "--block-size", "32768",
                    "--no-cleanup"]) == 0
        out = capsys.readouterr().out
        assert "bandwidth" in out and "latency ms" in out and "p95" in out
        assert cli(["--mon", mon, "-p", "benchec", "bench", "0.5",
                    "seq", "-t", "4", "--block-size", "32768"]) == 0
        assert "seq:" in capsys.readouterr().out
        assert cli(["--mon", mon, "-p", "benchec", "bench", "0.5",
                    "rand", "-t", "4", "--block-size", "32768"]) == 0
        assert "rand:" in capsys.readouterr().out
    finally:
        loop.run_until_complete(cluster.stop())
        loop.close()
