"""Cache tiering: EC base + replicated cache overlay (VERDICT r4
missing #1).

Reference seams: PrimaryLogPG maybe_handle_cache / promote_object /
do_proxy_read (src/osd/PrimaryLogPG.h:904,919-923), TierAgentState
flush/evict, OSDMonitor 'osd tier *' commands, and the Objecter overlay
redirect (read_tier/write_tier, osd_types.h:1323-28).
"""

import asyncio

import pytest

from tests._flaky import contention_retry

from ceph_tpu.cluster.vstart import _fast_config, start_cluster
from ceph_tpu.cluster.pg import _coll


def run(coro):
    return asyncio.run(coro)


async def _setup(cluster, base_kind="erasure"):
    client = await cluster.client()
    if base_kind == "erasure":
        base = await client.pool_create(
            "base", "erasure", pg_num=4,
            ec_profile={"plugin": "jerasure",
                        "technique": "reed_sol_van",
                        "k": "2", "m": "1"})
    else:
        base = await client.pool_create("base", "replicated",
                                        pg_num=4, size=2)
    cache = await client.pool_create("cache", "replicated",
                                     pg_num=4, size=2)
    await client.tier_add("base", "cache")
    await client.tier_cache_mode("cache", "writeback")
    await client.tier_set_overlay("base", "cache")
    return client, base, cache


def _pool_objects(cluster, pool_id):
    """Union of client-visible objects across every OSD's collections
    for a pool."""
    from ceph_tpu.cluster import snaps as snapmod

    out = set()
    for osd in cluster.osds.values():
        for coll in osd.store.list_collections():
            if not coll.startswith(f"pg_{pool_id}_"):
                continue
            for name in osd.store.list_objects(coll):
                if name.startswith("_") or snapmod.is_snap_key(name):
                    continue
                out.add(name)
    return out


@contention_retry()
def test_writeback_promote_flush_evict():
    async def scenario():
        cluster = await start_cluster(3, config=_fast_config())
        try:
            client, base, cache = await _setup(cluster)
            bio = client.ioctx(base)  # ops redirect through the overlay

            # 1. writes land in the CACHE pool (writeback)
            payload = b"tiered-payload " * 200
            await bio.write_full("hot", payload)
            assert await bio.read("hot") == payload
            assert "hot" in _pool_objects(cluster, cache)
            assert "hot" not in _pool_objects(cluster, base)

            # 2. the agent flushes the dirty object to the base
            for _ in range(300):
                if "hot" in _pool_objects(cluster, base):
                    break
                await asyncio.sleep(0.1)
            assert "hot" in _pool_objects(cluster, base), "never flushed"
            assert await bio.read("hot") == payload

            # 3. eviction: cap the cache and write enough cold objects
            await client.pool_set("cache", "target_max_objects", 4)
            for i in range(12):
                await bio.write_full(f"cold-{i}", b"c" * 512)
            for _ in range(400):
                if len(_pool_objects(cluster, cache)) <= 8:
                    break
                await asyncio.sleep(0.1)
            assert len(_pool_objects(cluster, cache)) <= 8, \
                _pool_objects(cluster, cache)
            # every object still reads back (from cache or via promote)
            for i in range(12):
                assert await bio.read(f"cold-{i}", timeout=60) \
                    == b"c" * 512

            # 4. promote-on-read: read an object that was evicted from
            # the cache — it must come back via promotion and land there
            evicted = sorted(
                _pool_objects(cluster, base) -
                _pool_objects(cluster, cache))
            if evicted:
                target = evicted[0]
                assert await bio.read(target, timeout=60) is not None
                assert target in _pool_objects(cluster, cache), \
                    "read miss did not promote"

            # 5. delete-through: removing via the overlay removes BOTH
            await bio.remove("hot")
            with pytest.raises((IOError, FileNotFoundError)):
                await bio.read("hot", timeout=15)
            # converge-poll: the write-through delete of the base copy
            # lands asynchronously behind the overlay ack
            deadline = asyncio.get_event_loop().time() + 15.0
            while asyncio.get_event_loop().time() < deadline:
                if "hot" not in _pool_objects(cluster, base) and \
                        "hot" not in _pool_objects(cluster, cache):
                    break
                await asyncio.sleep(0.05)
            assert "hot" not in _pool_objects(cluster, base)
            assert "hot" not in _pool_objects(cluster, cache)
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_readproxy_and_forward_modes():
    async def scenario():
        cluster = await start_cluster(3, config=_fast_config())
        try:
            client, base, cache = await _setup(cluster)
            bio = client.ioctx(base)
            await bio.write_full("obj", b"payload-1")
            # flush it to the base, then drop the cache copy via drain
            await client.tier_cache_mode("cache", "forward")
            for _ in range(300):
                if "obj" in _pool_objects(cluster, base) and \
                        "obj" not in _pool_objects(cluster, cache):
                    break
                await asyncio.sleep(0.1)
            assert "obj" in _pool_objects(cluster, base)
            assert "obj" not in _pool_objects(cluster, cache)
            # forward mode: reads work, nothing re-enters the cache
            assert await bio.read("obj") == b"payload-1"
            assert "obj" not in _pool_objects(cluster, cache)

            # readproxy: reads proxy to the base WITHOUT promoting;
            # writes still land in the cache
            await client.tier_cache_mode("cache", "readproxy")
            assert await bio.read("obj") == b"payload-1"
            assert "obj" not in _pool_objects(cluster, cache)
            await bio.write_full("obj2", b"payload-2")
            assert "obj2" in _pool_objects(cluster, cache)
            assert await bio.read("obj2") == b"payload-2"

            # remove-overlay: traffic goes straight to the base again
            await client.tier_remove_overlay("base")
            assert await bio.read("obj") == b"payload-1"
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_tiering_survives_cache_primary_kill():
    """Thrash: dirty objects in the cache survive a cache-primary kill —
    the replicated dirty flag lets the new primary flush them."""
    async def scenario():
        cluster = await start_cluster(3, config=_fast_config())
        try:
            client, base, cache = await _setup(cluster)
            bio = client.ioctx(base)
            payloads = {f"o{i}": (b"D%d" % i) * 300 for i in range(6)}
            for k, v in payloads.items():
                await bio.write_full(k, v)
            # kill one OSD serving the cache pool
            pgid = client.objecter.object_pgid(cache, "o0")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            await cluster.osds[primary].stop()
            # everything still reads back and eventually flushes
            for k, v in payloads.items():
                assert await bio.read(k, timeout=90) == v, k
            for _ in range(600):
                if all(k in _pool_objects(cluster, base)
                       for k in payloads):
                    break
                await asyncio.sleep(0.1)
            assert all(k in _pool_objects(cluster, base)
                       for k in payloads), "flush stalled after kill"
        finally:
            await cluster.stop()

    run(scenario())


def test_tier_command_validation():
    async def scenario():
        cluster = await start_cluster(2, config=_fast_config())
        try:
            client = await cluster.client()
            await client.pool_create("b1", "replicated", pg_num=4, size=2)
            await client.pool_create("c1", "replicated", pg_num=4, size=2)
            await client.pool_create("c2", "replicated", pg_num=4, size=2)
            await client.tier_add("b1", "c1")
            # a tier cannot itself get a tier; a pool can't tier twice
            with pytest.raises(RuntimeError):
                await client.tier_add("c1", "c2")
            with pytest.raises(RuntimeError):
                await client.tier_add("b1", "c1")
            # overlay must be a registered tier
            with pytest.raises(RuntimeError):
                await client.tier_set_overlay("b1", "c2")
            await client.tier_set_overlay("b1", "c1")
            # cannot remove an active overlay tier
            with pytest.raises(RuntimeError):
                await client.tier_remove("b1", "c1")
            await client.tier_remove_overlay("b1")
            await client.tier_remove("b1", "c1")
            p = client.objecter.osdmap.pools
            assert all(not po.is_tier() and not po.tiers
                       for po in p.values())
        finally:
            await cluster.stop()

    run(scenario())
