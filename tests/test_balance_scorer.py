"""graft-balance scorer gates (round-21 satellite).

Three contracts over ceph_tpu/balance/scorer.py:

1. **Bit-exact measurement twin** — ``deviation_stats`` reproduces the
   scalar anchor's (osdmap/balancer.py::calc_pg_upmaps) per-iteration
   arrays bit-for-bit on identical inputs: same dtypes, same values,
   same overfull/underfull orderings.
2. **No-worse skew** — the vectorized optimizer lands a final
   pg-per-osd stddev no worse than the anchor's on the same map, with
   every emitted mapping structurally valid (size kept, no dup OSDs,
   host failure domains distinct).
3. **Device batch width** — one optimizer call on a realistic skewed
   map pushes >= 1000 candidates through the batched scorer, counted
   by the KERNELS family the mgr counter scrape re-exports.
"""

import copy

import numpy as np

from ceph_tpu.balance.scorer import (
    calc_pg_upmaps_vectorized,
    deviation_stats,
    generate_candidates,
    score_candidates,
)
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.osdmap import balancer
from ceph_tpu.osdmap.balancer import _failure_domains, pg_per_osd_stddev
from ceph_tpu.osdmap.osdmap import PGid, build_simple_osdmap
from ceph_tpu.utils.perf import KERNELS


def _anchor_measurement(m, pools):
    """The anchor's per-iteration math, transcribed from
    calc_pg_upmaps — the oracle the twin must match bit-for-bit."""
    counts = np.zeros(m.max_osd, dtype=np.int64)
    total_slots = 0
    for pid in pools:
        up, _upp = m.pool_mapping(pid)
        valid = up[(up >= 0) & (up < m.max_osd)]
        counts += np.bincount(valid, minlength=m.max_osd)
        total_slots += int((up != CRUSH_ITEM_NONE).sum())
    weights = np.asarray(m.osd_weight[: m.max_osd], dtype=np.float64)
    weights = weights * np.asarray(m.osd_exists[: m.max_osd],
                                   dtype=np.float64)
    target = weights / weights.sum() * total_slots
    in_osds = weights > 0
    deviation = np.where(in_osds, counts - target, 0.0)
    ratio = np.where(target > 0, deviation / np.maximum(target, 1e-9), 0)
    overfull = [int(o) for o in np.argsort(-deviation)
                if deviation[o] >= 1.0 and ratio[o] > 0.05]
    underfull = [int(o) for o in np.argsort(deviation)
                 if deviation[o] <= -0.999 and in_osds[o]]
    return counts, target, deviation, ratio, overfull, underfull


def test_deviation_stats_bit_exact_vs_anchor():
    m = build_simple_osdmap(n_osds=24, osds_per_host=4, pg_num=128)
    pools = list(m.pools)
    counts, target, deviation, ratio, overfull, underfull = \
        _anchor_measurement(m, pools)
    st = deviation_stats(m, pools)
    assert st is not None
    # bit-exact: same dtype, same bytes — not allclose
    assert st.counts.dtype == counts.dtype
    assert np.array_equal(st.counts, counts)
    assert st.target.dtype == np.float64
    assert np.array_equal(st.target, target)
    assert np.array_equal(st.deviation, deviation)
    assert np.array_equal(st.ratio, ratio)
    # the anchor's candidate orderings fall out identically
    assert st.overfull(0.05) == overfull
    assert st.underfull() == underfull


def test_fill_score_is_exact_energy_delta():
    """The closed-form fill term equals the brute-force change to
    sum((counts - target)^2) when the move is actually applied."""
    m = build_simple_osdmap(n_osds=16, osds_per_host=4, pg_num=64)
    pools = list(m.pools)
    st = deviation_stats(m, pools)
    domains = {pid: _failure_domains(m, m.pools[pid].crush_rule)
               for pid in pools}
    cand = generate_candidates(m, st, domains)
    assert len(cand) > 0
    scores = score_candidates(st, cand, engine="numpy")
    energy0 = float(np.sum((st.counts - st.target) ** 2))
    for i in range(min(8, len(cand))):
        counts = st.counts.astype(np.float64).copy()
        counts[cand.src[i]] -= 1
        counts[cand.dst[i]] += 1
        delta = float(np.sum((counts - st.target) ** 2)) - energy0
        assert np.isclose(scores[i], delta), (i, scores[i], delta)


def test_vectorized_skew_no_worse_than_anchor_and_valid():
    m = build_simple_osdmap(n_osds=32, osds_per_host=4, pg_num=256)
    pid = list(m.pools)[0]
    m_scalar = copy.deepcopy(m)
    m_vec = copy.deepcopy(m)

    before = pg_per_osd_stddev(m, [pid])
    changes_s = balancer.calc_pg_upmaps(m_scalar, [pid])
    after_s = pg_per_osd_stddev(m_scalar, [pid])
    changes_v, scored = calc_pg_upmaps_vectorized(m_vec, [pid],
                                                  engine="numpy")
    after_v = pg_per_osd_stddev(m_vec, [pid])

    assert changes_s and changes_v
    assert after_v < before, (before, after_v)
    # the gate: batched never lands worse than the anchor (float-eps
    # slack only — both descend the same energy)
    assert after_v <= after_s + 1e-9, (after_s, after_v)

    # structural validity of every resulting mapping (try_pg_upmap
    # contract): no dup members, host failure domains distinct
    domains = _failure_domains(m_vec, m_vec.pools[pid].crush_rule)
    up, _ = m_vec.pool_mapping(pid)
    for s in range(m_vec.pools[pid].pg_num):
        members = [int(v) for v in up[s] if v >= 0]
        assert len(members) == len(set(members)), f"dup osd in pg {s}"
        doms = [domains.get(o) for o in members]
        assert len(doms) == len(set(doms)), \
            f"pg {s} violates host failure domain: {members}"


def test_batch_width_at_least_1000_candidates_counted():
    m = build_simple_osdmap(n_osds=32, osds_per_host=4, pg_num=256)
    pid = list(m.pools)[0]
    k0 = KERNELS.get("balance_candidates_scored")
    calls0 = KERNELS.get("balance_score_calls")
    changes, scored = calc_pg_upmaps_vectorized(m, [pid], engine="numpy")
    assert scored >= 1000, scored
    # the KERNELS family (re-exported by the mgr counter scrape) saw
    # exactly the batch the optimizer reports
    assert KERNELS.get("balance_candidates_scored") - k0 == scored
    assert KERNELS.get("balance_score_calls") > calls0
    assert changes


def test_device_engine_matches_numpy_scores():
    """Engine parity: the jitted scorer and the numpy scorer agree on
    the whole batch (CPU backend runs the same fused jit path the
    device takes, so this pins the math, not the hardware)."""
    m = build_simple_osdmap(n_osds=24, osds_per_host=4, pg_num=128)
    pools = list(m.pools)
    st = deviation_stats(m, pools)
    domains = {pid: _failure_domains(m, m.pools[pid].crush_rule)
               for pid in pools}
    cand = generate_candidates(m, st, domains)
    assert len(cand) > 0
    s_np = score_candidates(st, cand, engine="numpy")
    s_dev = score_candidates(st, cand, engine="device")
    assert np.allclose(s_np, s_dev, rtol=0, atol=1e-6)


def test_max_moves_budget_respected():
    m = build_simple_osdmap(n_osds=32, osds_per_host=4, pg_num=256)
    pid = list(m.pools)[0]
    changes, _ = calc_pg_upmaps_vectorized(m, [pid], max_moves=5,
                                           engine="numpy")
    n_moves = sum(len(v) for v in changes.values())
    assert 0 < n_moves <= 5, changes
    # the moves landed on the map, anchor-style mutation contract
    for pgid, pairs in changes.items():
        assert isinstance(pgid, PGid)
        assert m.pg_upmap_items[pgid] == pairs
