"""EC byte-golden gate.

Replays tests/golden/ec_golden.jsonl — generated once by the independent C
oracle in scripts/gen_ec_golden/gen.c (from-scratch GF(2^8) arithmetic, no
shared tables or code) — against the package codecs and demands
byte-identical chunks.  This is the corpus-pinning role of the reference's
ceph_erasure_code_non_regression (src/test/erasure-code/
ceph_erasure_code_non_regression.cc:226 + ceph-erasure-code-corpus).
"""

import json
import pathlib

import numpy as np
import pytest

from ceph_tpu.ec import factory

GOLDEN = pathlib.Path(__file__).parent / "golden" / "ec_golden.jsonl"


def _lcg_bytes(seed: int, n: int) -> bytes:
    """Must match gen.c: x = (1103515245 x + 12345) & 0x7fffffff,
    byte = (x >> 16) & 0xff."""
    x = seed & 0x7FFFFFFF
    out = bytearray(n)
    for i in range(n):
        x = (1103515245 * x + 12345) & 0x7FFFFFFF
        out[i] = (x >> 16) & 0xFF
    return bytes(out)


def _fnv1a64(data: bytes) -> str:
    h = 1469598103934665603
    for b in data:
        h ^= b
        h = (h * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return f"{h:016x}"


def _cases():
    with open(GOLDEN) as f:
        return [json.loads(line) for line in f if line.strip()]


def _case_id(case):
    return (f"{case['plugin']}-{case['technique']}-k{case['k']}m{case['m']}"
            + (f"-w{case['w']}" if case.get("w", 8) != 8 else "")
            + (f"-ps{case['packetsize']}" if case["packetsize"] else ""))


@pytest.mark.parametrize("case", _cases(), ids=_case_id)
def test_encode_bytes_match_independent_oracle(case):
    w = case.get("w", 8)
    profile = {
        "plugin": case["plugin"],
        "technique": case["technique"],
        "k": str(case["k"]),
        "m": str(case["m"]),
        "w": str(w),
    }
    if case["packetsize"]:
        profile["packetsize"] = str(case["packetsize"])
    if case.get("c"):
        profile["c"] = str(case["c"])
    codec = factory(profile)

    if "bitmatrix" in case:
        # native GF(2) bit-matrix code (liberation family)
        bm = np.asarray(case["bitmatrix"], dtype=np.uint8).reshape(
            case["m"] * w, case["k"] * w)
        assert np.array_equal(codec.bit_engine.coding_bits, bm), (
            "bit-matrix differs from oracle")
    else:
        # coding matrix must match element-for-element
        mat = np.asarray(case["matrix"], dtype=np.uint64).reshape(
            case["m"], case["k"])
        assert np.array_equal(
            codec.engine.coding.astype(np.uint64), mat), (
            f"coding matrix differs from oracle:\n{codec.engine.coding}"
            f"\nvs\n{mat}")

    # chunk geometry must agree (object sizes were chosen pre-aligned)
    assert codec.get_chunk_size(case["object_size"]) == case["chunk_size"]

    data = _lcg_bytes(case["seed"], case["object_size"])
    n = codec.get_chunk_count()
    chunks = codec.encode(range(n), data)
    for i in range(n):
        blob = chunks[i].tobytes()
        assert len(blob) == case["chunk_size"]
        expect = case["chunks"][i]
        assert blob[:16].hex() == expect["head"], f"chunk {i} head mismatch"
        assert _fnv1a64(blob) == expect["fnv1a64"], f"chunk {i} fingerprint"


def test_golden_file_covers_all_implemented_techniques():
    seen = {(c["plugin"], c["technique"]) for c in _cases()}
    assert ("jerasure", "reed_sol_van") in seen
    assert ("jerasure", "reed_sol_r6_op") in seen
    assert ("jerasure", "cauchy_orig") in seen
    assert ("jerasure", "cauchy_good") in seen
    assert ("jerasure", "liberation") in seen
    assert ("jerasure", "blaum_roth") in seen
    assert ("jerasure", "liber8tion") in seen
    assert ("isa", "reed_sol_van") in seen
    assert ("isa", "cauchy") in seen
    wides = {(c["plugin"], c["technique"], c.get("w", 8)) for c in _cases()}
    assert ("jerasure", "reed_sol_van", 16) in wides
    assert ("jerasure", "reed_sol_van", 32) in wides
    # round 5 (VERDICT r4 missing #6): shec across all field widths
    assert ("shec", "multiple", 8) in wides
    assert ("shec", "multiple", 16) in wides
    assert ("shec", "multiple", 32) in wides
    assert ("shec", "single", 16) in wides
