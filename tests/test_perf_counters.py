"""Perf-counter schemas, histograms, collection thread-safety, and the
Prometheus rendering (reference src/common/perf_counters.cc +
perf_histogram.h + the mgr prometheus module's text format)."""

import json
import threading

from ceph_tpu.utils import perf as perfmod
from ceph_tpu.utils.admin_socket import AdminSocket
from ceph_tpu.utils.perf import (
    PerfCounters,
    PerfCountersCollection,
    PerfHistogram,
)
from ceph_tpu.cluster.mgr import render_prometheus


def test_u64_and_time_counters():
    pc = PerfCounters("d")
    pc.add_u64("ops", unit=perfmod.UNIT_NONE,
               prio=perfmod.PRIO_CRITICAL, desc="ops served")
    pc.inc("ops", 3)
    pc.tinc("lat", 0.25)
    pc.tinc("lat", 0.75)
    d = pc.dump()["d"]
    assert d["ops"] == 3
    assert d["lat"]["avgcount"] == 2
    assert d["lat"]["sum"] == 1.0
    assert d["lat"]["last"] == 0.75
    assert d["lat"]["min"] == 0.25
    assert d["lat"]["max"] == 0.75
    schema = pc.dump_schema()["d"]
    assert schema["ops"]["priority"] == perfmod.PRIO_CRITICAL
    assert schema["ops"]["type"] == "u64"
    # undeclared counters still get an inferred schema entry
    assert schema["lat"]["type"] == "time_avg"
    assert schema["lat"]["unit"] == perfmod.UNIT_SECONDS


def test_histogram_buckets_power_of_two():
    h = PerfHistogram(buckets=8, scale=1.0)
    for v in (0, 1, 2, 3, 500, 10 ** 9):
        h.add(v)
    d = h.dump()
    assert d["count"] == 6
    assert d["buckets"][0] == 2          # 0 and 1
    assert d["buckets"][1] == 2          # 2 and 3
    assert d["buckets"][7] == 2          # 500 (2^8 cap) and 1e9 clamp
    assert d["lower_bounds"][:3] == [0, 2, 4]
    assert sum(d["buckets"]) == d["count"]


def test_histogram_scale_and_reset():
    pc = PerfCounters("d")
    pc.add_histogram("lat_hist", buckets=16, scale=1e6,
                     unit=perfmod.UNIT_SECONDS)
    pc.hinc("lat_hist", 0.000001)   # 1 us -> bucket 0
    pc.hinc("lat_hist", 0.001)      # 1000 us -> bucket 9
    d = pc.dump()["d"]["lat_hist"]
    assert d["buckets"][0] == 1
    assert d["buckets"][9] == 1
    assert pc.dump_histograms()["d"]["lat_hist"]["count"] == 2
    pc.reset()
    d = pc.dump()["d"]["lat_hist"]
    assert d["count"] == 0 and sum(d["buckets"]) == 0
    # hinc on an undeclared name auto-creates a default histogram
    pc.hinc("adhoc", 7)
    assert pc.dump()["d"]["adhoc"]["count"] == 1
    # everything dumped must be JSON-clean (the admin-socket contract)
    json.dumps(pc.dump())
    json.dumps(pc.dump_schema())


def test_collection_thread_safety_and_remove():
    coll = PerfCountersCollection()
    errors = []

    def churn(i):
        try:
            for j in range(200):
                pc = coll.create(f"d{i}_{j}")
                pc.inc("x")
                coll.dump()
                coll.remove(f"d{i}_{j}")
        except Exception as e:   # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=churn, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert coll.dump() == {}
    pc = PerfCounters("kept")
    coll.register(pc)
    pc.inc("y", 2)
    assert coll.dump()["kept"]["y"] == 2
    assert coll.get("kept") is pc
    coll.remove("kept")
    assert coll.get("kept") is None


def test_collection_reset_spares_shared_registries():
    """One daemon's 'perf reset' must not wipe the process-wide shared
    registry (KERNELS) that every other daemon dumps too."""
    coll = PerfCountersCollection()
    own = coll.create("osd.9")
    own.inc("ops", 5)
    shared = PerfCounters("device_kernels_test")
    shared.inc("calls", 7)
    coll.register(shared)          # shared=True default
    coll.reset()
    assert own.get("ops") == 0
    assert shared.get("calls") == 7
    # non-shared registration resets normally
    coll.register(shared, shared=False)
    coll.reset()
    assert shared.get("calls") == 0


def test_admin_socket_router():
    import asyncio

    from ceph_tpu.utils import Config

    pc = PerfCounters("d")
    pc.inc("ops", 4)
    asok = AdminSocket()
    asok.register_common(pc, Config())

    async def scenario():
        r, data = await asok.dispatch({"prefix": "perf dump"})
        assert r == 0 and data["d"]["ops"] == 4
        r, data = await asok.dispatch({"prefix": "perf schema"})
        assert r == 0 and "ops" in data["d"]
        r, data = await asok.dispatch({"prefix": "config show"})
        assert r == 0 and "osd_op_complaint_time" in data
        r, data = await asok.dispatch({"prefix": "perf reset"})
        assert r == 0
        r, data = await asok.dispatch({"prefix": "nope"})
        assert r == -22
        r, data = await asok.dispatch({"prefix": "help"})
        assert r == 0 and "perf dump" in data

        async def boom(cmd):
            raise ValueError("x")

        asok.register("boom", boom)
        r, data = await asok.dispatch({"prefix": "boom"})
        assert r == -22 and "ValueError" in data

    asyncio.run(scenario())
    assert pc.get("ops") == 0  # reset really zeroed


def test_prometheus_rendering():
    daemons = {
        "osd.0": {
            "ops": 5,
            "lat": {"avgcount": 2, "sum": 0.5, "last": 0.3,
                    "min": 0.2, "max": 0.3},
            "lat_hist": {"buckets": [1, 2, 0, 1],
                         "lower_bounds": [0, 2, 4, 8],
                         "scale": 1.0, "count": 4, "sum": 11.0},
        },
        "osd.1": {"ops": 7},
    }
    text = render_prometheus(daemons)
    assert 'ceph_ops{daemon="osd.0"} 5' in text
    assert 'ceph_ops{daemon="osd.1"} 7' in text
    assert 'ceph_lat_count{daemon="osd.0"} 2' in text
    assert 'ceph_lat_sum{daemon="osd.0"} 0.5' in text
    # histogram buckets are CUMULATIVE with le labels + +Inf terminal;
    # bucket 0 spans scaled [0, 2) so its bound is the next bucket's
    # lower bound, 2
    assert 'ceph_lat_hist_bucket{daemon="osd.0",le="2"} 1' in text
    assert 'ceph_lat_hist_bucket{daemon="osd.0",le="4"} 3' in text
    assert 'ceph_lat_hist_bucket{daemon="osd.0",le="+Inf"} 4' in text
    assert 'ceph_lat_hist_count{daemon="osd.0"} 4' in text
    # every metric family carries one TYPE header
    assert text.count("# TYPE ceph_ops untyped") == 1


def test_prometheus_le_bounds_unscale_to_sum_units():
    """A microsecond-bucketed latency histogram (scale=1e6) must emit
    le bounds in SECONDS — the same units as its _sum series — or
    histogram_quantile and rate(_sum)/rate(_count) disagree by 1e6."""
    text = render_prometheus({
        "osd.0": {"lat_hist": {
            "buckets": [3, 1], "lower_bounds": [0, 2],
            "scale": 1e6, "count": 4, "sum": 0.004}}})
    assert 'le="2e-06"' in text          # 2 us bucket bound in seconds
    assert 'le="4e-06"' in text
    assert 'ceph_lat_hist_sum{daemon="osd.0"} 0.004' in text


def test_kernel_counters_record_ec_dispatch():
    import numpy as np

    from ceph_tpu.ec import factory
    from ceph_tpu.utils.perf import KERNELS

    codec = factory({"plugin": "jerasure", "technique": "reed_sol_van",
                     "k": "2", "m": "1"})
    before = KERNELS.get("ec_matmul_calls")
    before_bytes = KERNELS.get("ec_matmul_bytes")
    data = np.zeros((4, 2, 256), dtype=np.uint8)
    codec.encode_batch(data)
    assert KERNELS.get("ec_matmul_calls") == before + 1
    assert KERNELS.get("ec_matmul_bytes") - before_bytes == data.size
    # the MXU pad-waste counter moved too (a (8, 16) bitmat is far off
    # the 128x128 tile)
    assert KERNELS.get("ec_matmul_mxu_pad_bytes") > 0
