"""graft-load gates: driver determinism, SLO judge math, the tier-1
load smoke (toy scale, every gate from scraped telemetry, bit-identical
replay), CLI exit codes, and the slow soak scenarios.

The replay test IS the acceptance criterion (round 13): the same seed
must produce an identical per-client op plan (``plan_key``) across two
independent runs, and the smoke window must pass every SLO gate.
"""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from ceph_tpu.load import slo
from ceph_tpu.load.dist import (
    arrival_offsets,
    client_stream,
    pick_weighted,
    zipf_pick,
)
from ceph_tpu.load.driver import (
    LoadResult,
    LoadSpec,
    build_plan,
    builtin_specs,
    plan_key,
    run_load,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "scripts", "load.py")


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------------ dist unit


def test_arrival_offsets_deterministic_and_bounded():
    for process in ("poisson", "fixed"):
        a = arrival_offsets(client_stream(7, 3), 5.0, 2.0, process)
        b = arrival_offsets(client_stream(7, 3), 5.0, 2.0, process)
        assert a == b
        assert a == sorted(a)
        assert all(0 <= t < 2.0 for t in a)
    # fixed-rate is evenly spaced at 1/rate after the seeded phase
    f = arrival_offsets(client_stream(7, 3), 5.0, 2.0, "fixed")
    gaps = {round(y - x, 9) for x, y in zip(f, f[1:])}
    assert gaps == {round(1 / 5.0, 9)}
    assert arrival_offsets(client_stream(1, 1), 0.0, 2.0) == []
    with pytest.raises(ValueError):
        arrival_offsets(client_stream(1, 1), 1.0, 1.0, "bogus")


def test_zipf_pick_single_draw_stream_contract():
    """One rng.random() call per pick — the chaos seed-replay contract
    the sampler carried when it lived in chaos/scenario.py."""
    import random

    a, b = random.Random(123), random.Random(123)
    picks = [zipf_pick(a, 64) for _ in range(50)]
    for _ in range(50):
        b.random()
    assert a.getstate() == b.getstate()
    # hot-set shape: rank 0 dominates a long tail
    many = [zipf_pick(random.Random(5), 64) for _ in range(1)]
    r = random.Random(5)
    many = [zipf_pick(r, 64) for _ in range(2000)]
    assert many.count(0) > many.count(10) > 0


def test_chaos_scenario_reuses_load_zipf():
    """Exactly one seeded zipfian implementation in the repo."""
    from ceph_tpu.chaos import scenario

    assert scenario._zipf_pick is zipf_pick


def test_pick_weighted_deterministic_and_skips_zero():
    rng = client_stream(9, 0)
    choices = (("a", 1.0), ("b", 0.0), ("c", 3.0))
    picks = [pick_weighted(rng, choices) for _ in range(200)]
    assert "b" not in picks
    assert picks.count("c") > picks.count("a") > 0


# ------------------------------------------------------- plan determinism


def test_plan_replays_bit_identical_and_varies_with_seed():
    spec = builtin_specs()["smoke"]
    p1, p2 = build_plan(spec, 42), build_plan(spec, 42)
    assert p1 == p2
    assert plan_key(p1) == plan_key(p2)
    keys = {plan_key(build_plan(spec, s)) for s in range(6)}
    assert len(keys) == 6
    # per-client streams: client k's ops are identical whether or not
    # other clients exist (adding clients never shifts earlier ones)
    import dataclasses

    fewer = dataclasses.replace(spec, clients=8)
    assert build_plan(fewer, 42) == build_plan(spec, 42)[:8]


# ---------------------------------------------------------- slo judge math


def test_parse_prometheus_and_counter_math():
    text = (
        "# TYPE ceph_osd_client_ops untyped\n"
        'ceph_osd_client_ops{daemon="osd.0"} 10\n'
        'ceph_osd_client_ops{daemon="osd.1"} 5\n'
        'ceph_client_cwnd{daemon="client.load0"} 256\n')
    prom = slo.parse_prometheus(text)
    snap = slo.TelemetrySnapshot(prom=prom, health={}, dmclock={})
    assert slo.counter_sum(snap, "ceph_osd_client_ops") == 15
    assert slo.counter_sum(snap, "ceph_client_cwnd",
                           daemon_prefix="client.") == 256


def _hist_snap(buckets):
    rows = []
    for daemon, per_le in buckets.items():
        for le, cum in per_le.items():
            rows.append(({"daemon": daemon, "le": le}, cum))
    return slo.TelemetrySnapshot(
        prom={"ceph_osd_op_lat_hist_bucket": rows}, health={},
        dmclock={})


def test_hist_quantile_from_cumulative_bucket_deltas():
    before = _hist_snap({"osd.0": {"0.002": 0, "0.004": 0, "+Inf": 0}})
    after = _hist_snap({"osd.0": {"0.002": 90, "0.004": 100,
                                  "+Inf": 100}})
    # p50 lands in the first bucket, p99 in the second
    assert slo.hist_quantile(before, after,
                             "ceph_osd_op_lat_hist", 0.5) == 0.002
    assert slo.hist_quantile(before, after,
                             "ceph_osd_op_lat_hist", 0.99) == 0.004
    # no samples in the window -> None (the gate fails honestly)
    assert slo.hist_quantile(after, after,
                             "ceph_osd_op_lat_hist", 0.99) is None
    # quantile in the +Inf bucket -> inf, NEVER clamped to the top
    # finite bound (an unbounded tail must fail a <= ceiling gate)
    spill = _hist_snap({"osd.0": {"0.002": 90, "0.004": 95,
                                  "+Inf": 100}})
    assert slo.hist_quantile(before, spill,
                             "ceph_osd_op_lat_hist",
                             0.99) == float("inf")
    from ceph_tpu.load.driver import builtin_specs

    rep = slo.judge(builtin_specs()["smoke"], _mk_result(offered=100),
                    before, spill)
    p99 = {r["gate"]: r for r in rep.rows}["p99"]
    assert not p99["passed"]
    assert p99["value"] == "+Inf"


def _mk_snap(ops=0, cwnd=256, pushbacks=0, hist=None, checks=None,
             mclock=False, res=0, evicted=0):
    prom = {
        "ceph_osd_client_ops": [({"daemon": "osd.0"}, ops)],
        "ceph_client_cwnd": [({"daemon": "client.load0"}, cwnd)],
        "ceph_client_cwnd_pushbacks": [({"daemon": "client.load0"},
                                        pushbacks)],
        "ceph_osd_qos_served_reservation": [({"daemon": "osd.0"}, res)],
        "ceph_osd_qos_evicted": [({"daemon": "osd.0"}, evicted)],
        # round-14 control-plane counters (the map_churn gate requires
        # presence on the scrape)
        "ceph_osd_map_epochs_applied": [({"daemon": "osd.0"}, 5)],
        "ceph_osd_pgs_repeered": [({"daemon": "osd.0"}, 2)],
        "ceph_osd_map_skip_to_full": [({"daemon": "osd.0"}, 0)],
        "ceph_osd_peering_lat_hist_bucket": [
            ({"daemon": "osd.0", "le": "+Inf"}, 2)],
        # round-16 integrity/full counters (the integrity gate requires
        # presence on the scrape)
        "ceph_osd_read_repairs": [({"daemon": "osd.0"}, 0)],
        "ceph_osd_read_shard_crc_errors": [({"daemon": "osd.0"}, 0)],
        "ceph_osd_scrub_errors_repaired": [({"daemon": "osd.0"}, 0)],
        "ceph_osd_full_rejects": [({"daemon": "osd.0"}, 0)],
        "ceph_osd_read_batch_ticks": [({"daemon": "osd.0"}, 1)],
        # round-21 mgr balance counters (the balance gate requires
        # presence on the scrape — declared at mgr init, zero when the
        # subsystem is disabled)
        "ceph_mgr_balancer_rounds": [({"daemon": "mgr.x"}, 0)],
        "ceph_mgr_balancer_candidates": [({"daemon": "mgr.x"}, 0)],
        "ceph_mgr_balancer_moves_committed": [({"daemon": "mgr.x"}, 0)],
        "ceph_mgr_balancer_throttled": [({"daemon": "mgr.x"}, 0)],
        "ceph_mgr_autoscale_rounds": [({"daemon": "mgr.x"}, 0)],
    }
    if hist:
        prom["ceph_osd_op_lat_hist_bucket"] = [
            ({"daemon": "osd.0", "le": le}, cum)
            for le, cum in hist.items()]
    return slo.TelemetrySnapshot(
        prom=prom, health={"status": "HEALTH_OK",
                           "checks": checks or {}},
        dmclock={"osd.0": {"enabled": mclock}})


def _mk_result(offered=100, late=0):
    r = LoadResult(spec_name="x", seed=1, plan_key="k", offered=offered)
    r.late_acks = ["late"] * late
    return r


def test_judge_all_gates_pass_and_fail_paths():
    spec = builtin_specs()["smoke"]
    before = _mk_snap(ops=0, hist={"0.002": 0, "+Inf": 0})
    good = _mk_snap(ops=100, hist={"0.002": 100, "+Inf": 100})
    rep = slo.judge(spec, _mk_result(), before, good)
    assert rep.passed, rep.failures()
    by = {r["gate"]: r for r in rep.rows}
    assert by["goodput"]["value"] == 100
    assert by["p99"]["value"] == 2.0       # 0.002s -> ms
    assert by["qos"]["passed"]             # counters exported

    # goodput below the floor fails
    rep = slo.judge(spec, _mk_result(offered=1000), before, good)
    assert not rep.passed
    assert not {r["gate"]: r for r in rep.rows}["goodput"]["passed"]

    # collapsed cwnd after pushbacks fails; wide-open passes
    collapsed = _mk_snap(ops=100, cwnd=1, pushbacks=40,
                         hist={"0.002": 100, "+Inf": 100})
    rep = slo.judge(spec, _mk_result(), before, collapsed)
    assert not {r["gate"]: r for r in rep.rows}["cwnd"]["passed"]

    # SLOW_OPS raised at window end fails the health gate
    slow = _mk_snap(ops=100, hist={"0.002": 100, "+Inf": 100},
                    checks={"SLOW_OPS": "3 slow ops"})
    rep = slo.judge(spec, _mk_result(), before, slow)
    assert not {r["gate"]: r for r in rep.rows}["health"]["passed"]

    # an ack past its deadline fails the client-observed gate
    rep = slo.judge(spec, _mk_result(late=1), before, good)
    assert not {r["gate"]: r for r in rep.rows}["deadline"]["passed"]

    # declared qos contention requires reservation-driven dequeues
    import dataclasses

    qspec = dataclasses.replace(
        spec, gates=spec.gates[:-1] + (("qos_reservation_min", 1.0),))
    idle = _mk_snap(ops=100, hist={"0.002": 100, "+Inf": 100},
                    mclock=True, res=0)
    rep = slo.judge(qspec, _mk_result(), before, idle)
    assert not {r["gate"]: r for r in rep.rows}["qos"]["passed"]


# ------------------------------------------------------- tier-1 load smoke


def test_load_smoke_all_gates_and_bit_identical_replay():
    """The round-13 tier-1 gate: ~64 simulated clients over a 4-session
    pool pass every SLO gate, judged from scraped telemetry, and the
    run replays bit-identically from its seed."""
    spec = builtin_specs()["smoke"]

    async def one():
        return await run_load(spec, 42)

    r1, rep1 = run(one())
    r2, rep2 = run(one())
    assert rep1.passed, rep1.failures()
    assert rep2.passed, rep2.failures()
    # bit-identical replay: same seed -> same plan, same offered count
    assert r1.plan_key == r2.plan_key
    assert r1.offered == r2.offered == 180
    gates = {r["gate"] for r in rep1.rows}
    assert gates == {"goodput", "p99", "cwnd", "qos", "health",
                     "map_churn", "integrity", "balance", "deadline"}
    # every scrape-side gate really had scrape data behind it
    by = {r["gate"]: r for r in rep1.rows}
    assert by["goodput"]["value"] >= r1.offered * 0.5
    assert by["p99"]["value"] is not None
    assert by["cwnd"]["value"] is not None    # client counters scraped
    # round-14 satellite: the control-plane counters (epochs applied,
    # PGs re-peered, peering histogram, skip-to-full) are ON the
    # scrape — the gate fails with "MISSING" when any drop off it.
    # The smoke drives no map churn, so the epochs-applied DELTA is
    # not asserted (whether a late pool-create epoch lands inside the
    # judged window is a race); the counter-moves property is gated
    # under real churn by test_control_plane's storm epochs floor.
    assert by["map_churn"]["passed"], by["map_churn"]
    assert by["map_churn"]["note"] == "", by["map_churn"]
    # round-16 satellite: the integrity/full counters (read repairs,
    # crc detections, scrub repairs, full rejects, read ticks) are ON
    # the scrape — presence-gated like map_churn; counter MOVEMENT is
    # gated by the bitrot-under-load scenario's repair invariant.
    assert by["integrity"]["passed"], by["integrity"]
    assert by["integrity"]["note"] == "", by["integrity"]
    # round-21 satellite: the mgr balance counter families (balancer
    # rounds/candidates/moves, autoscale rounds) are ON the scrape even
    # though the subsystem is disabled in the smoke — declared at mgr
    # init, all-zeros: the provable-no-op witness.  Moves MOVEMENT is
    # gated by the balance-convergence scenario's balance_moves_min.
    assert by["balance"]["passed"], by["balance"]
    assert by["balance"]["note"] == "", by["balance"]
    assert by["balance"]["value"] == 0  # disabled balancer commits nothing


def test_mgr_scrape_carries_client_and_qos_counters():
    """Satellite proof: the client AIMD window and the dmclock eviction
    stat are visible on the mgr Prometheus path (not only in per-daemon
    dumps)."""
    from ceph_tpu.load.driver import LoadContext, drive

    spec = builtin_specs()["smoke-micro"]

    async def scenario():
        ctx = await LoadContext.create(spec, 5)
        try:
            await drive(ctx, spec, 5)
            # converge-poll (round-13 deflake convention): wait until
            # the heartbeat-carried client report actually landed on
            # the mgr instead of sleeping a fixed beat
            loop = asyncio.get_event_loop()
            deadline = loop.time() + 10.0
            text = ""
            while loop.time() < deadline:
                text = await ctx.cluster.daemon_command(
                    "mgr", "prometheus metrics")
                if 'ceph_client_cwnd{daemon="client.load0"}' in text:
                    break
                await asyncio.sleep(0.05)
        finally:
            await ctx.close()
        return text

    text = run(scenario())
    assert 'ceph_client_cwnd{daemon="client.load0"}' in text
    assert "ceph_client_cwnd_pushbacks" in text
    assert "ceph_osd_qos_evicted" in text
    assert "ceph_osd_qos_served_reservation" in text


# --------------------------------------------------------------- CLI gates


def _cli(*args):
    return subprocess.run([sys.executable, CLI, *args],
                          capture_output=True, text=True, cwd=REPO)


def test_cli_plan_deterministic_and_unknown_spec():
    p1 = _cli("plan", "--spec", "smoke", "--seed", "42")
    p2 = _cli("plan", "--spec", "smoke", "--seed", "42")
    assert p1.returncode == 0, p1.stderr
    assert p1.stdout == p2.stdout
    doc = json.loads(p1.stdout)
    assert doc["offered_ops"] == 180
    assert len(doc["replay_key"]) == 64
    bad = _cli("plan", "--spec", "nope", "--seed", "1")
    assert bad.returncode == 2
    badsoak = _cli("soak", "--scenario", "nope")
    assert badsoak.returncode == 2


def test_cli_run_exit_codes_gates_pass_and_fail():
    """gates-pass=0, gate-fail!=0 — the chaos/trace CLI contract."""
    ok = _cli("run", "--spec", "smoke-micro", "--seed", "3")
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "ALL GATES PASS" in ok.stdout
    fail = _cli("run", "--spec", "smoke-micro", "--seed", "3",
                "--gate", "p99_ms=0.0001")
    assert fail.returncode == 1, fail.stdout + fail.stderr
    assert "FAIL p99" in fail.stdout
    typo = _cli("run", "--spec", "smoke-micro", "--seed", "3",
                "--gate", "goodput=1")
    assert typo.returncode == 2, typo.stdout + typo.stderr
    assert "unknown gate" in typo.stderr


def test_cli_report_reads_artifact(tmp_path):
    doc = {"kind": "graft-load ramp", "spec": "t", "seed": 1,
           "mode": "cluster_vstart", "vs_baseline": None,
           "session_only": True,
           "steps": [{"scale": 1, "offered_ops_s": 10.0,
                      "offered_ops": 10, "acked_ops_scraped": 10.0,
                      "p99_ms": 2.0, "passed": True, "gates": []}],
           "knee": {"scale": 1, "offered_ops_s": 10.0,
                    "acked_ops_scraped": 10.0, "p99_ms": 2.0}}
    path = tmp_path / "LOAD_r99.json"
    path.write_text(json.dumps(doc))
    out = _cli("report", str(path))
    assert out.returncode == 0, out.stderr
    assert "knee: 10.0 offered ops/s" in out.stdout
    missing = _cli("report", str(tmp_path / "nope.json"))
    assert missing.returncode == 2


# ------------------------------------------------------------ slow / soak


@pytest.mark.slow
def test_ramp_finds_knee_with_trust_stamps(tmp_path):
    """A short ramp emits an artifact whose every row carries the
    trust-model stamps (NULL vs_baseline, session-only)."""
    from ceph_tpu.load.ramp import format_table, ramp, write_artifact

    spec = builtin_specs()["smoke-micro"]
    doc = run(ramp(spec, 21, scales=(1, 2)))
    assert doc["vs_baseline"] is None
    assert doc["session_only"] and doc["load_sensitive_host"]
    assert doc["mode"] == "cluster_vstart"
    assert doc["knee"] is not None
    assert all("gates" in s for s in doc["steps"])
    path = write_artifact(doc, out=str(tmp_path / "LOAD_rt.json"))
    assert os.path.exists(path)
    assert "knee:" in format_table(doc)


@pytest.mark.soak
def test_soak_mixed_crash_invariants():
    """The round-13 acceptance soak: sustained mixed-verb EC traffic on
    FileStore racing tick/commit crash points; durability + frontier +
    deadline invariants hold after convergence.  soak-marked =>
    slow-implied (conftest), never on the tier-1/bench hot path."""
    import tempfile

    from ceph_tpu.load.soak import builtin_soaks, run_soak

    sk = builtin_soaks()["soak-mixed-crash"]
    with tempfile.TemporaryDirectory(prefix="graft_soak_") as tmpdir:
        v = run(run_soak(sk, 17, tmpdir=tmpdir))
    assert v.passed, v.failures
    assert v.counters.get("crash_points_fired", 0) >= 1
    assert v.acked_objects > 0
    # the fault schedule replays from the seed (same resolver as chaos)
    from ceph_tpu.chaos.scenario import build_schedule

    assert build_schedule(sk.schedule_shell(), 17) == v.schedule


@pytest.mark.soak
def test_soak_marker_implies_slow(request):
    """pytest.ini contract: soak tests are slow-implied, so the tier-1
    '-m not slow' gate can never pick one up."""
    marks = {m.name for m in request.node.iter_markers()}
    assert "slow" in marks
