"""graft-chaos unit + integration tests: seeded streams, injector
no-op proofs, disk faults, torn-journal crash-restart, clock skew,
admin-socket visibility, and the vstart config-preservation fix.
"""

import asyncio

import pytest

from ceph_tpu.chaos.clock import ChaosClock
from ceph_tpu.chaos.counters import CHAOS, chaos_total
from ceph_tpu.chaos.disk import DiskInjector
from ceph_tpu.chaos.net import NetInjector, parse_partitions
from ceph_tpu.chaos.rng import derive_seed, stream
from ceph_tpu.cluster.vstart import _fast_config, start_cluster
from ceph_tpu.utils import Config


def run(coro):
    return asyncio.run(coro)


def _counters():
    return dict(CHAOS.dump()["chaos"])


# ------------------------------------------------------------ rng streams


def test_streams_deterministic_and_independent():
    assert derive_seed(42, "net:osd.0") == derive_seed(42, "net:osd.0")
    assert derive_seed(42, "net:osd.0") != derive_seed(42, "net:osd.1")
    assert derive_seed(42, "net:osd.0") != derive_seed(43, "net:osd.0")
    a = [stream(42, "x").random() for _ in range(3)]
    b = [stream(42, "x").random() for _ in range(3)]
    assert a == b
    # one stream's draws never shift another's
    s_net, s_disk = stream(42, "net"), stream(42, "disk")
    first_disk = stream(42, "disk").random()
    for _ in range(100):
        s_net.random()
    assert s_disk.random() == first_disk


# ---------------------------------------------------------- no-op proofs


def test_injectors_none_at_default_config():
    cfg = Config()
    assert NetInjector.from_config(cfg, "osd.0") is None
    assert DiskInjector.from_config(cfg, "osd.0") is None
    cfg.chaos_net_drop = 0.5
    assert NetInjector.from_config(cfg, "osd.0") is not None
    cfg2 = Config(chaos_disk_read_err=0.5)
    assert DiskInjector.from_config(cfg2, "osd.0") is not None


def test_cluster_without_chaos_emits_zero_counters():
    """The acceptance no-op proof: a chaos-free cluster run — boot,
    pool, writes, reads, scrub — leaves messenger.chaos/store.chaos None
    and increments NO chaos counter."""
    async def scenario():
        before = chaos_total()
        cluster = await start_cluster(3)
        try:
            for osd in cluster.osds.values():
                assert osd.messenger.chaos is None
                assert osd.store.chaos is None
            for mon in cluster.mons:
                assert mon.messenger.chaos is None
            client = await cluster.client()
            pool = await client.pool_create("noop", "replicated",
                                            pg_num=4, size=3)
            io = client.ioctx(pool)
            for i in range(4):
                await io.write_full(f"o{i}", b"quiet" * 50)
            for i in range(4):
                assert await io.read(f"o{i}") == b"quiet" * 50
        finally:
            await cluster.stop()
        assert chaos_total() == before
    run(scenario())


# ------------------------------------------------------------------ net


def test_net_injector_rates_and_partitions():
    inj = NetInjector(stream(1, "t"), drop=1.0)
    fate = inj.on_frame(("h", 1))
    assert fate.drop and fate.retransmit > 0
    inj2 = NetInjector(stream(1, "t"), dup=1.0, reset=1.0)
    fate2 = inj2.on_frame(("h", 1))
    assert fate2.dup and fate2.reset and not fate2.drop
    assert parse_partitions("127.0.0.1:5,127.0.0.1:6") == {
        ("127.0.0.1", 5), ("127.0.0.1", 6)}
    inj2.partition(("127.0.0.1", 5))
    assert inj2.partitioned(("127.0.0.1", 5))
    with pytest.raises(ConnectionError):
        inj2.check_connect(("127.0.0.1", 5))
    inj2.heal()
    inj2.check_connect(("127.0.0.1", 5))  # healed: no raise


def test_messenger_injector_follows_injectargs():
    """The injectargs seam: chaos_net_* on a daemon's config rebuilds
    its messenger injector live; zeroing returns it to None."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            osd = cluster.osds[0]
            assert osd.messenger.chaos is None
            osd.config.injectargs({"chaos_net_drop": 0.25})
            assert osd.messenger.chaos is not None
            assert osd.messenger.chaos.drop == 0.25
            osd.config.injectargs({"chaos_net_drop": 0.0})
            assert osd.messenger.chaos is None
        finally:
            await cluster.stop()
    run(scenario())


# ----------------------------------------------------------------- disk


def test_disk_injector_eio_and_enospc():
    from ceph_tpu.cluster.store import MemStore, Transaction

    store = MemStore()
    store.queue_transaction(
        Transaction().create_collection("c").write("c", "o", 0, b"data"))
    store.chaos = DiskInjector(stream(1, "d"), read_err=1.0)
    with pytest.raises(IOError):
        store.read("c", "o")
    store.chaos = DiskInjector(stream(1, "d"), enospc=1.0)
    with pytest.raises(OSError) as ei:
        store.queue_transaction(Transaction().write("c", "o", 0, b"x"))
    assert ei.value.errno == 28
    # the refused txn left no bytes behind (atomicity)
    store.chaos = None
    assert store.read("c", "o") == b"data"


def test_flip_bit_memstore_silent():
    from ceph_tpu.cluster.store import MemStore, Transaction

    store = MemStore()
    store.queue_transaction(
        Transaction().create_collection("c").write("c", "o", 0,
                                                   b"A" * 64))
    inj = DiskInjector(stream(7, "rot"))
    before = _counters().get("disk_bitrot_flips", 0)
    bit = inj.flip_bit(store, "c", "o")
    assert _counters()["disk_bitrot_flips"] == before + 1
    data = store.read("c", "o")
    assert data != b"A" * 64
    # exactly one bit differs, version untouched (SILENT corruption)
    diff = [a ^ b for a, b in zip(data, b"A" * 64)]
    assert sum(bin(d).count("1") for d in diff) == 1
    assert store.get_version("c", "o") == 1
    # same seed -> same bit
    store2 = MemStore()
    store2.queue_transaction(
        Transaction().create_collection("c").write("c", "o", 0,
                                                   b"A" * 64))
    assert DiskInjector(stream(7, "rot")).flip_bit(store2, "c", "o") == bit


def test_flip_bit_bluestore_surfaces_as_eio(tmp_path):
    from ceph_tpu.cluster.bluestore import BlueStore
    from ceph_tpu.cluster.store import Transaction

    store = BlueStore(str(tmp_path / "bs"), size=8 << 20)
    store.mount()
    store.queue_transaction(
        Transaction().create_collection("c").write("c", "o", 0,
                                                   b"B" * 1000))
    DiskInjector(stream(3, "rot")).flip_bit(store, "c", "o", bit=40)
    # the onode csum was NOT updated: the read path catches the rot
    with pytest.raises(IOError):
        store.read("c", "o")
    store.umount()


# -------------------------------------------------- crash/torn journals


def test_filestore_crash_torn_tail_discards_last_txn(tmp_path):
    from ceph_tpu.cluster.filestore import FileStore
    from ceph_tpu.cluster.store import Transaction

    store = FileStore(str(tmp_path / "fs"))
    store.mount()
    store.queue_transaction(
        Transaction().create_collection("c").write("c", "a", 0,
                                                   b"first"))
    store.queue_transaction(Transaction().write("c", "b", 0, b"second"))
    store.crash(torn_tail=True)
    store.mount()
    # the torn tail frame was discarded atomically; earlier data intact
    assert store.read("c", "a") == b"first"
    assert store.stat("c", "b") is None
    store.umount()


def test_filestore_crash_lose_frames(tmp_path):
    from ceph_tpu.cluster.filestore import FileStore
    from ceph_tpu.cluster.store import Transaction

    store = FileStore(str(tmp_path / "fs2"))
    store.mount()
    store.queue_transaction(
        Transaction().create_collection("c").write("c", "a", 0, b"one"))
    store.queue_transaction(Transaction().write("c", "b", 0, b"two"))
    store.queue_transaction(Transaction().write("c", "z", 0, b"three"))
    store.crash(lose_frames=2)
    store.mount()
    assert store.read("c", "a") == b"one"
    assert store.stat("c", "b") is None
    assert store.stat("c", "z") is None
    store.umount()


def test_bluestore_crash_replays_wal(tmp_path):
    from ceph_tpu.cluster.bluestore import BlueStore
    from ceph_tpu.cluster.store import Transaction

    store = BlueStore(str(tmp_path / "bs2"), size=8 << 20)
    store.mount()
    store.queue_transaction(
        Transaction().create_collection("c").write("c", "a", 0,
                                                   b"W" * 100))
    store.queue_transaction(Transaction().write("c", "b", 0, b"X" * 100))
    store.crash(torn_tail=True)
    store.mount()
    assert store.read("c", "a") == b"W" * 100   # replayed from WAL
    assert store.stat("c", "b") is None         # torn frame discarded
    store.umount()


# ---------------------------------------------------------------- clock


def test_chaos_clock_skew_and_observer():
    import time as _time

    cfg = Config()
    clock = ChaosClock.from_config(cfg)
    assert abs(clock.monotonic() - _time.monotonic()) < 0.1
    before = _counters().get("clock_skews", 0)
    cfg.injectargs({"chaos_clock_skew": 5.0})
    assert clock.skew == 5.0
    assert clock.monotonic() - _time.monotonic() > 4.0
    assert _counters()["clock_skews"] == before + 1


def test_optracker_ages_follow_skewed_clock():
    from ceph_tpu.cluster.optracker import OpTracker

    clock = ChaosClock()
    tracker = OpTracker(slow_threshold=10.0, clock=clock)
    op = tracker.create("op")
    assert tracker.slow_in_flight() == (0, 0.0)
    clock.skew = 60.0            # the daemon's clock jumps forward
    n, oldest = tracker.slow_in_flight()
    assert n == 1 and oldest >= 10.0
    op.finish()


# -------------------------------------------------------- admin socket


def test_chaos_report_admin_command():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            data = await cluster.daemon_command("osd.1",
                                                "chaos report")
            assert data["active"] is False
            assert "net_drops" in data["counters"]
            cluster.osds[1].config.injectargs({"chaos_net_drop": 0.1})
            data = await cluster.daemon_command("osd.1",
                                                "chaos report")
            assert data["active"] is True
            assert data["options"]["chaos_net_drop"] == 0.1
            # the other daemon's view stays inactive (per-daemon config)
            data = await cluster.daemon_command("osd.0",
                                                "chaos report")
            assert data["active"] is False
        finally:
            await cluster.stop()
    run(scenario())


# ------------------------------------- vstart config preservation (fix)


def test_restart_osd_keeps_injected_config():
    """The satellite fix: kill/revive and restart must resume the
    daemon's per-daemon config copy, so injected fault options survive a
    bounce within a scenario."""
    async def scenario():
        cfg = _fast_config()
        cfg.mon_osd_down_out_interval = 60.0
        cluster = await start_cluster(3, config=cfg)
        try:
            cluster.osds[0].config.injectargs(
                {"chaos_net_drop": 0.05, "chaos_seed": 99})
            await cluster.restart_osd(0)
            assert cluster.osds[0].config.chaos_net_drop == 0.05
            assert cluster.osds[0].config.chaos_seed == 99
            assert cluster.osds[0].messenger.chaos is not None

            cluster.osds[1].config.injectargs({"chaos_clock_skew": 1.5})
            await cluster.kill_osd(1)
            await cluster.revive_osd(1)
            assert cluster.osds[1].config.chaos_clock_skew == 1.5
            assert cluster.osds[1].clock.skew == 1.5
            # an untouched daemon still boots from the cluster template
            await cluster.restart_osd(2)
            assert cluster.osds[2].config.chaos_net_drop == 0.0
        finally:
            await cluster.stop()
    run(scenario())


# ------------------------------ recovery retry without a map change (fix)


def test_incomplete_recovery_retries_without_map_change():
    """An incomplete recovery round (unreachable member, failed
    pull/push) must re-arm itself with capped backoff: peering is
    otherwise only triggered by map changes, and a pull that fails
    AFTER the last map change of an outage would leave the primary
    stale forever (graft-chaos: persistent torn EC reads)."""
    async def scenario():
        cluster = await start_cluster(3, config=_fast_config())
        try:
            client = await cluster.client()
            pool = await client.pool_create("retry", "replicated",
                                            pg_num=2, size=3)
            io = client.ioctx(pool)
            await io.write_full("o", b"x" * 64)
            pgid = client.objecter.object_pgid(pool, "o")
            _, _, _, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            osd = cluster.osds[primary]
            st = osd.pgs[pgid]

            import random as _random

            from ceph_tpu.utils.backoff import ExpBackoff

            # fast, seeded backoff so the test runs in milliseconds
            osd._recovery_backoffs[st.pgid] = ExpBackoff(
                base=0.02, cap=0.05, rng=_random.Random(7))
            calls = []
            orig = osd._recover_pg_locked

            async def flaky(st_arg):
                calls.append(len(calls))
                if len(calls) < 3:
                    return False          # incomplete: must re-arm
                return await orig(st_arg)

            osd._recover_pg_locked = flaky
            await osd._recover_pg(st)
            # converge-poll (round 12 deflake): wait for a COMPLETE
            # round to clear the backoff too — under suite load the
            # real rounds can keep coming up incomplete (2s peering
            # query timeouts) well past the old 5s window
            deadline = asyncio.get_event_loop().time() + 20.0
            while asyncio.get_event_loop().time() < deadline:
                if len(calls) >= 3 and \
                        st.pgid not in osd._recovery_retry_tasks and \
                        st.pgid not in osd._recovery_backoffs:
                    break
                await asyncio.sleep(0.05)
            assert len(calls) >= 3, "incomplete recovery never retried"
            # a COMPLETE round resets the backoff and leaves no retry
            assert st.pgid not in osd._recovery_backoffs
        finally:
            await cluster.stop()
    run(scenario())
