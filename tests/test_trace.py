"""graft-trace gates: span tracer semantics, stage attribution math,
the asyncio loop profiler, Perfetto export, the zero-overhead-when-
disabled contract, and the cross-daemon e2e smoke (one traced op
through vstart with the span tree + attribution asserted).
"""

import asyncio
import json
import os
import subprocess
import sys
import time

import pytest

from ceph_tpu.trace import (
    LoopProfiler,
    NULL_SPAN,
    Tracer,
    aggregate,
    assemble_tree,
    attribute_events,
    spans_from_events,
    stage_for,
)
from ceph_tpu.trace.perfetto import (
    chrome_trace_from_dumps,
    chrome_trace_from_spans,
)
from ceph_tpu.utils.perf import PerfCounters

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------------ span tracer


def test_disabled_tracer_is_provably_null():
    """The zero-overhead contract: disabled tracing allocates nothing,
    retains nothing, and never grows a message header."""
    t = Tracer("osd.0", enabled=False)
    s1 = t.start("a")
    s2 = t.start("b", trace_id="x", parent_id="y")
    assert s1 is NULL_SPAN and s2 is NULL_SPAN  # the shared singleton
    with s1:
        assert t.context() is None  # no header field, ever
        s1.annotate(k=1)
    s1.finish()
    assert t.dump_recent() == {}
    assert not s1  # falsy: `if span:` guards stay cheap


def test_span_tree_parenting_and_assembly():
    t = Tracer("client.x", enabled=True)
    u = Tracer("osd.1", enabled=True)
    with t.start("op_submit", trace_id="T") as root:
        ctx = t.context()
        assert ctx == {"id": "T", "span": root.span_id}
        # another daemon parents under the propagated span id
        with u.start("osd_op", trace_id=ctx["id"],
                     parent_id=ctx["span"]) as osd_span:
            with u.start("ec_sub_write"):  # nests via CURRENT_SPAN
                pass
    spans = t.dump_trace("T") + u.dump_trace("T")
    assert len(spans) == 3
    roots = assemble_tree(spans)
    assert len(roots) == 1 and roots[0]["name"] == "op_submit"
    assert roots[0]["children"][0]["name"] == "osd_op"
    assert roots[0]["children"][0]["children"][0]["name"] == "ec_sub_write"
    assert roots[0]["children"][0]["span_id"] == osd_span.span_id
    for s in spans:
        assert s["dur"] is not None and s["dur"] >= 0


def test_tracer_ring_bounded():
    t = Tracer("osd.0", enabled=True, keep=3)
    for i in range(10):
        t.start("op", trace_id=f"T{i}").finish()
    rec = t.dump_recent(99)
    assert len(rec) == 3
    assert set(rec) == {"T7", "T8", "T9"}  # newest kept


# ------------------------------------------------------------ attribution


def _synthetic_events():
    return [
        (-0.005, "objecter:submit"),
        (-0.004, "objecter:send"),
        (-0.003, "msgr:client.1:send"),
        (-0.001, "msgr:osd.0:recv"),
        (0.0, "initiated"),
        (0.0001, "dispatched"),
        (0.0002, "lock_wait:pg.lock"),
        (0.0012, "lock_acquired:pg.lock"),
        (0.002, "ec_encode"),
        (0.010, "ec_encoded"),
        (0.0105, "store:commit"),
        (0.011, "ec_sub_write_sent"),
        (0.015, "sub_write_acked"),
        (0.0151, "commit"),
        (0.0152, "done"),
    ]


def test_attribution_sums_exactly_and_maps_stages():
    stages, total = attribute_events(_synthetic_events())
    # every traced nanosecond lands in exactly one bucket
    assert abs(sum(stages.values()) - total) < 1e-12
    assert abs(total - 0.0202) < 1e-9
    assert abs(stages["lock:pg.lock"] - 0.001) < 1e-9
    assert abs(stages["device_encode"] - 0.008) < 1e-9
    assert abs(stages["sub_write_wait"] - 0.004) < 1e-9
    assert "wire" in stages and "dispatch_queue" in stages
    # aggregation with a measured wall computes the coverage metric
    agg = aggregate([_synthetic_events()], measured_wall_s=0.021)
    assert agg["ops"] == 1
    assert agg["wall_coverage"] == pytest.approx(0.0202 / 0.021, abs=1e-3)
    fracs = sum(row["frac"] for row in agg["stages"].values())
    assert fracs == pytest.approx(1.0, abs=0.01)


def test_merge_reports_sums_disjoint_daemon_slices():
    """Primaries spread across OSDs, so per-daemon reports are
    disjoint slices: the merged artifact must SUM them, not keep the
    biggest one."""
    from ceph_tpu.trace.attribution import merge_reports

    a = aggregate([_synthetic_events()])
    merged = merge_reports([a, a, {"ops": 0}], measured_wall_s=0.021)
    assert merged["ops"] == 2
    assert merged["traced_total_s"] == \
        pytest.approx(2 * a["traced_total_s"], abs=1e-6)
    assert merged["stages"]["device_encode"]["s"] == \
        pytest.approx(0.016, abs=1e-6)
    # per-op mean is unchanged by merging identical slices
    assert merged["wall_coverage"] == pytest.approx(0.0202 / 0.021,
                                                    abs=1e-3)
    empty = merge_reports([{"ops": 0}])
    assert empty == {"ops": 0, "traced_total_s": 0.0, "stages": {}}


def test_stage_mapping_rules():
    assert stage_for("msgr:osd.2:recv") == "wire"
    assert stage_for("msgr:osd.2:send") == "messenger_send"
    assert stage_for("msgr:flushed") == "messenger_send"
    assert stage_for("lock_acquired:messenger.session") == \
        "lock:messenger.session"
    assert stage_for("lock_wait:pg.lock") == "exec"
    assert stage_for("never_seen_before") == "other:never_seen_before"


def test_spans_from_events_rebased():
    spans = spans_from_events(_synthetic_events())
    assert spans[0]["start"] == 0.0
    assert all(sp["dur"] >= 0 for sp in spans)
    assert any(sp["stage"] == "device_encode" for sp in spans)


# --------------------------------------------------------------- perfetto


def test_chrome_trace_from_dumps_structure():
    op = {"trace_id": "T1", "description": "osd_op(...)",
          "duration": 0.02,
          "type_data": {"events": [
              {"time": t, "event": e} for t, e in _synthetic_events()]}}
    doc = chrome_trace_from_dumps({"osd.0": {"num_ops": 1, "ops": [op]}})
    evs = doc["traceEvents"]
    assert any(e["ph"] == "M" and e["args"]["name"] == "osd.0"
               for e in evs)
    slices = [e for e in evs if e["ph"] == "X"]
    assert slices and all(e["dur"] >= 0 and e["ts"] >= 0 for e in slices)
    json.dumps(doc)  # serializable


def test_chrome_trace_from_spans_structure():
    t = Tracer("osd.0", enabled=True)
    with t.start("osd_op", trace_id="T"):
        pass
    doc = chrome_trace_from_spans(t.dump_trace("T"))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 1 and xs[0]["name"] == "osd_op"


# ----------------------------------------------------------- lockdep hook


def test_lockdep_hook_marks_current_op():
    from ceph_tpu.cluster.optracker import CURRENT_OP, OpTracker
    from ceph_tpu.utils.lockdep import DepLock

    async def scenario():
        tr = OpTracker()
        op = tr.create("osd_op(test)")
        token = CURRENT_OP.set(op)
        try:
            async with DepLock("hook.test"):
                pass
        finally:
            CURRENT_OP.reset(token)
        op.finish()
        names = [e for _, e in op.events]
        assert "lock_wait:hook.test" in names
        assert "lock_acquired:hook.test" in names
        # and outside an op the hook is a no-op (nothing raised)
        async with DepLock("hook.idle"):
            pass

    run(scenario())


def test_event_ordering_inherited_stamps_never_drift_past_arrival():
    """The round-9 ordering fix: a wall-clock header stamp racing the
    op's monotonic start must still sort before 'initiated'."""
    from ceph_tpu.cluster.optracker import OpTracker

    tr = OpTracker()
    future_stamp = time.time() + 0.050  # wall/monotonic sampling skew
    op = tr.create("osd_op(x)", trace={
        "id": "T", "events": [("objecter:submit", time.time() - 0.01),
                              ("msgr:osd.0:recv", future_stamp)]})
    op.mark("dispatched")
    op.finish()
    d = op.dump()
    names = [e["event"] for e in d["type_data"]["events"]]
    assert names.index("msgr:osd.0:recv") < names.index("initiated") \
        < names.index("dispatched")
    times = [e["time"] for e in d["type_data"]["events"]]
    assert times == sorted(times)
    # completed ops expose the derived stage spans (satellite: optracker
    # and graft-trace agree on one op timeline)
    assert d["spans"] and all("stage" in sp for sp in d["spans"])


# ------------------------------------------------------------ loop profiler


def test_loop_profiler_catches_a_stall_and_wraps_tasks():
    perf = PerfCounters("t")
    mon = LoopProfiler(perf, interval=0.01, prefix="loop")

    async def scenario():
        loop = asyncio.get_event_loop()
        sampler = loop.create_task(mon.sample())
        try:
            # converge-poll (round-13 deflake convention): wait until
            # the sampler has provably taken a sample, so the stall
            # lands inside a measurement window
            deadline = loop.time() + 5.0
            while loop.time() < deadline and \
                    perf.dump()["t"]["loop_lag"]["avgcount"] < 1:
                await asyncio.sleep(0.005)

            async def stall():
                # deliberate loop stall — the exact bug class the
                # profiler exists to expose; the duration IS the test
                # stimulus, not a convergence wait
                # graftlint: ignore[asyncio-blocking] graftlint: ignore[fixed-sleep-in-tests]
                time.sleep(0.08)

            await mon.wrap(stall())
            # converge-poll until the sampler observed the stall (a
            # fixed post-stall sleep flakes on a loaded host)
            deadline = loop.time() + 5.0
            while loop.time() < deadline and mon.window_max < 0.05:
                await asyncio.sleep(0.005)
        finally:
            sampler.cancel()

    run(scenario())
    assert mon.window_max >= 0.05
    dump = perf.dump()["t"]
    assert dump["loop_lag"]["avgcount"] >= 1
    assert dump["loop_lag"]["max"] >= 0.05
    assert dump["loop_task_spawns"] == 1
    assert dump["loop_task_wall"]["avgcount"] == 1
    mon.reset_window()
    assert mon.window_max == 0.0
    assert mon.lag_report() is not None


def test_loop_profiler_disabled_is_identity():
    perf = PerfCounters("t")
    mon = LoopProfiler(perf, interval=0.0)
    assert not mon.enabled
    assert mon.lag_report() is None

    async def coro():
        return 7

    c = coro()
    assert mon.wrap(c) is c  # untouched coroutine
    assert run(_consume(c)) == 7
    assert perf.dump()["t"] == {}  # nothing declared


async def _consume(c):
    return await c


def test_loop_lag_flows_to_prometheus_and_daemonperf():
    """Satellite: the lag counters ride the existing exporter paths."""
    from ceph_tpu.cluster.mgr import render_prometheus
    from ceph_tpu.tools.ceph import _rate_rows

    perf = PerfCounters("osd.0")
    mon = LoopProfiler(perf, interval=0.01, prefix="osd_loop")
    assert mon.enabled
    perf.tinc("osd_loop_lag", 0.02)
    counters = perf.dump()["osd.0"]
    text = render_prometheus({"osd.0": counters})
    assert "ceph_osd_loop_lag_sum" in text
    assert "ceph_osd_loop_lag_count" in text
    prev = {"osd.0": {"osd_loop_lag": {"avgcount": 0, "sum": 0.0}}}
    rows = _rate_rows(prev, {"osd.0": counters}, 1.0)
    assert any("osd_loop_lag" in name for name, _ in rows)


# ------------------------------------------------------------ CLI (convert)


def test_trace_cli_exit_codes(tmp_path):
    """scripts/trace.py exit codes, tested like the chaos CLI: 0 on a
    good convert, 1 on bad input, 2 on usage errors."""
    script = os.path.join(REPO, "scripts", "trace.py")
    dump = {"num_ops": 1, "ops": [{
        "trace_id": "T1", "description": "osd_op",
        "type_data": {"events": [
            {"time": t, "event": e} for t, e in _synthetic_events()]}}]}
    df = tmp_path / "dump.json"
    df.write_text(json.dumps(dump))
    out = tmp_path / "trace.json"
    proc = subprocess.run(
        [sys.executable, script, "convert", str(df), "-o", str(out)],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["traceEvents"]
    # missing input -> 1
    proc = subprocess.run(
        [sys.executable, script, "convert", str(tmp_path / "nope.json")],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert proc.returncode == 1
    # a bare JSON array (not a dump payload) -> clean 1, no traceback
    dfa = tmp_path / "array.json"
    dfa.write_text(json.dumps([1, 2, 3]))
    proc = subprocess.run(
        [sys.executable, script, "convert", str(dfa)],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert proc.returncode == 1
    assert "Traceback" not in proc.stderr
    # empty dump -> 1
    df2 = tmp_path / "empty.json"
    df2.write_text(json.dumps({"num_ops": 0, "ops": []}))
    proc = subprocess.run(
        [sys.executable, script, "convert", str(df2)],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert proc.returncode == 1
    # usage error -> 2 (argparse)
    proc = subprocess.run(
        [sys.executable, script, "bogus"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert proc.returncode == 2


# ------------------------------------------------------------------- e2e


def _trace_config():
    from ceph_tpu.cluster.vstart import _fast_config

    config = _fast_config()
    config.trace_enabled = 1
    config.osd_op_history_size = 200
    return config


EC_PROFILE = {"plugin": "jerasure", "technique": "reed_sol_van",
              "k": "2", "m": "1"}


def test_traced_op_cross_daemon_smoke():
    """Tier-1 smoke (satellite 6): one traced EC write through vstart —
    span tree shape, unified optracker timeline, and attribution
    coverage against the client-measured wall."""
    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3, config=_trace_config())
        try:
            client = await cluster.client()
            pool = await client.pool_create("tr", "erasure", pg_num=4,
                                            ec_profile=EC_PROFILE)
            io = client.ioctx(pool)
            await io.write_full("warm", b"w" * 8192)  # compile warmup
            t0 = time.perf_counter()
            await io.write_full("traced", b"\xa5" * 65536)
            wall = time.perf_counter() - t0
            tracer = client.objecter.tracer
            tid = list(tracer._traces)[-1]
            # --- span tree across daemons (admin `trace dump`) ---
            spans = tracer.dump_trace(tid)
            for oid in cluster.osds:
                spans += await cluster.daemon_command(
                    f"osd.{oid}", {"prefix": "trace dump",
                                   "args": {"trace_id": tid}})
            roots = assemble_tree(spans)
            assert len(roots) == 1, [s["name"] for s in spans]
            root = roots[0]
            assert root["name"] == "op_submit"
            assert root["daemon"].startswith("client.")
            osd_ops = [c for c in root["children"]
                       if c["name"] == "osd_op"]
            assert len(osd_ops) == 1
            subs = [c for c in osd_ops[0]["children"]
                    if c["name"] == "ec_sub_write"]
            assert len(subs) == 2  # k2m1 on 3 osds: two peer shards
            assert {s["daemon"] for s in subs} & \
                {f"osd.{o}" for o in cluster.osds}
            # --- the optracker timeline carries the same trace id ---
            found = None
            for oid in cluster.osds:
                hist = await cluster.daemon_command(
                    f"osd.{oid}", "dump_historic_ops")
                for op in hist["ops"]:
                    if op.get("trace_id") == tid:
                        found = op
            assert found is not None
            names = [e["event"] for e in found["type_data"]["events"]]
            assert "objecter:submit" in names       # client-side stamps
            assert any(n.startswith("msgr:") and n.endswith(":recv")
                       for n in names)              # wire arrival
            # device-encode evidence: the coalesced tick marks (default
            # vstart config) or the per-op pair (osd_batch_tick_ops=0)
            assert (("batch_parked" in names and "batch_tick" in names
                     and "batch_encoded" in names)
                    or ("ec_encode" in names and "ec_encoded" in names))
            assert "store:commit" in names
            assert "ec_sub_write_sent" in names
            assert "sub_write_acked" in names
            assert "lock_acquired:pg.lock" in names  # lockdep hook
            times = [e["time"] for e in found["type_data"]["events"]]
            assert times == sorted(times)           # monotone timeline
            assert found["spans"]                   # unified spans view
            # --- attribution coverage vs the measured wall ---
            evs = [(e["time"], e["event"])
                   for e in found["type_data"]["events"]]
            stages, total = attribute_events(evs)
            assert abs(sum(stages.values()) - total) < 1e-9
            assert total >= 0.85 * wall, (total, wall, stages)
            # device work books as the amortized coalesced-tick stage
            # (default config) or the legacy per-op device_encode
            assert "batch_encode" in stages or "device_encode" in stages
            # the admin aggregation agrees
            primary = client.objecter._target_osd(
                client.objecter.object_pgid(pool, "traced"))
            rep = await cluster.daemon_command(
                f"osd.{primary}",
                {"prefix": "dump_op_attribution",
                 "args": {"match": "write_full",
                          "measured_wall_s": wall}})
            assert rep["ops"] >= 1
            assert rep["wall_coverage"] >= 0.85
        finally:
            await cluster.stop()

    run(scenario())


def test_trace_survives_reconnect_and_daemon_restart():
    """Satellite: trace propagation survives a chaos-dropped (and
    retransmitted) frame and a primary daemon restart — the header
    rides the replayed frame, so the op's timeline stays whole."""
    from ceph_tpu.cluster.vstart import start_cluster

    async def scenario():
        cluster = await start_cluster(3, config=_trace_config())
        try:
            client = await cluster.client()
            pool = await client.pool_create("tr2", "replicated",
                                            pg_num=4, size=2)
            io = client.ioctx(pool)
            await io.write_full("pre", b"x")
            # seeded drops on the CLIENT's outgoing frames: sends gate,
            # reconnect+replay carries the pickled trace header whole
            client.objecter.config.injectargs(
                {"chaos_seed": 7, "chaos_net_drop": 0.25})
            for i in range(6):
                await io.write_full(f"dropped_{i}", bytes([i]) * 512)
            client.objecter.config.injectargs({"chaos_net_drop": 0.0})

            async def traced_ids():
                out = set()
                for oid in cluster.osds:
                    hist = await cluster.daemon_command(
                        f"osd.{oid}", "dump_historic_ops")
                    for op in hist["ops"]:
                        if op.get("trace_id"):
                            names = [e["event"]
                                     for e in op["type_data"]["events"]]
                            assert "objecter:submit" in names
                            out.add(op["trace_id"])
                return out

            # every write that rode a dropped+retransmitted frame still
            # carries its full client trace (the header replays with
            # the pickled frame)
            assert len(await traced_ids()) >= 7
            # a restarted primary (fresh in-memory tracker) keeps
            # absorbing headers from the replayed client sessions
            pgid = client.objecter.object_pgid(pool, "after_restart")
            primary = client.objecter._target_osd(pgid)
            await cluster.restart_osd(primary)
            await io.write_full("after_restart", b"z" * 512)
            newest = list(client.objecter.tracer._traces)[-1]
            assert newest in await traced_ids()
        finally:
            await cluster.stop()

    run(scenario())


def test_tracing_disabled_bit_identical_ec_write():
    """Satellite: tracing enabled vs disabled produces bit-identical
    stored EC shards — the instrument can never perturb data."""
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    payloads = {f"obj_{i}": bytes([i * 17 % 251]) * (4096 * (i + 1))
                for i in range(3)}

    async def run_one(trace_on: bool):
        config = _fast_config()
        config.trace_enabled = 1 if trace_on else 0
        cluster = await start_cluster(3, config=config)
        try:
            client = await cluster.client()
            pool = await client.pool_create("bit", "erasure", pg_num=4,
                                            ec_profile=EC_PROFILE)
            io = client.ioctx(pool)
            for oid, data in payloads.items():
                await io.write_full(oid, data)
            state = {}
            for osd_id, osd in cluster.osds.items():
                for coll in osd.store.list_collections():
                    if not coll.startswith(f"pg_{pool}_"):
                        continue
                    for name in osd.store.list_objects(coll):
                        if name not in payloads:
                            continue
                        state[(osd_id, coll, name)] = (
                            bytes(osd.store.read(coll, name)),
                            osd.store.getattr(coll, name, "shard"),
                            osd.store.getattr(coll, name, "hinfo_crc"),
                        )
            return state
        finally:
            await cluster.stop()

    on = run(run_one(True))
    off = run(run_one(False))
    assert on and on == off


def test_loop_lag_health_warning_raises_and_clears():
    """Satellite: sustained loop lag raises LOOP_LAG beside SLOW_OPS
    (beacon-fed) and clears once the loop drains."""
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    async def scenario():
        config = _fast_config()
        config.loop_profile_interval = 0.02
        config.loop_lag_warn = 0.05
        cluster = await start_cluster(2, config=config)
        try:
            client = await cluster.client()
            # drive one op so the profiler-wrapped dispatch drainers run
            pool = await client.pool_create("ll", "replicated",
                                            pg_num=2, size=2)
            await client.ioctx(pool).write_full("o", b"x")
            spawns = walls = 0
            for oid in cluster.osds:
                d = await cluster.daemon_command(f"osd.{oid}",
                                                 "perf dump")
                spawns += d[f"osd.{oid}"]["osd_loop_task_spawns"]
                walls += d[f"osd.{oid}"]["osd_loop_task_wall"]["avgcount"]
            # per-task profiling is wired into the real dispatch path
            assert spawns >= 1 and walls >= 1

            async def stall():
                # block the shared loop long enough for a sample to
                # overshoot the warn threshold — the duration IS the
                # test stimulus, not a convergence wait
                # graftlint: ignore[asyncio-blocking] graftlint: ignore[fixed-sleep-in-tests]
                time.sleep(0.12)

            await stall()
            deadline = asyncio.get_event_loop().time() + 5.0
            seen = False
            while asyncio.get_event_loop().time() < deadline:
                health = await client.objecter.mon_command(
                    {"prefix": "health"})
                if "LOOP_LAG" in health["checks"]:
                    seen = True
                    break
                await asyncio.sleep(0.05)
            assert seen, health
            # drained: later beacons carry a clean window and it clears
            deadline = asyncio.get_event_loop().time() + 5.0
            while asyncio.get_event_loop().time() < deadline:
                health = await client.objecter.mon_command(
                    {"prefix": "health"})
                if "LOOP_LAG" not in health["checks"]:
                    return
                await asyncio.sleep(0.05)
            raise AssertionError(f"LOOP_LAG never cleared: {health}")
        finally:
            await cluster.stop()

    run(scenario())
