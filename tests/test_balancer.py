"""Upmap balancer: calc_pg_upmaps (round-4 item 8).

Reference: OSDMap::calc_pg_upmaps (src/osd/OSDMap.cc:3771) +
try_pg_upmap (:3727) — iterative deviation-driven pg_upmap_items
generation, validity-checked against the rule's failure domain.
"""

import pickle
import subprocess
import sys

import numpy as np
import pytest

from ceph_tpu.osdmap import balancer
from ceph_tpu.osdmap.osdmap import PGid, build_simple_osdmap


def _domain_of(m, osd):
    parent = {}
    for bid, b in m.crush.buckets.items():
        for item in b.items:
            parent[item] = bid
    node = osd
    while node in parent:
        node = parent[node]
        if m.crush.buckets[node].type == 1:  # host
            return node
    return osd


def test_balancer_reduces_stddev_and_stays_valid():
    m = build_simple_osdmap(n_osds=32, osds_per_host=4, pg_num=256)
    pid = list(m.pools)[0]
    before = balancer.pg_per_osd_stddev(m, [pid])
    changes = balancer.calc_pg_upmaps(m, [pid])
    after = balancer.pg_per_osd_stddev(m, [pid])
    assert changes, "no upmaps computed on a skewed map"
    assert after < before * 0.6, (before, after)
    # every mapping stays structurally valid: size maintained, no dup
    # OSDs, failure domains (hosts) distinct — the try_pg_upmap contract
    up, upp = m.pool_mapping(pid)
    pool = m.pools[pid]
    for s in range(pool.pg_num):
        members = [int(v) for v in up[s] if v >= 0]
        assert len(members) == len(set(members)), f"dup osd in pg {s}"
        doms = [_domain_of(m, o) for o in members]
        assert len(doms) == len(set(doms)), \
            f"pg {s} violates host failure domain: {members}"


def test_balancer_respects_upmap_application():
    """The computed items actually reroute placement: recomputing the
    mapping with them applied differs from the raw map."""
    m = build_simple_osdmap(n_osds=16, osds_per_host=4, pg_num=128)
    pid = list(m.pools)[0]
    raw_up, _ = m.pool_mapping(pid)
    changes = balancer.calc_pg_upmaps(m, [pid])
    new_up, _ = m.pool_mapping(pid)
    moved = {pgid.seed for pgid in changes}
    for s in moved:
        assert not np.array_equal(raw_up[s], new_up[s]), s
    # untouched PGs keep their placement (balancing is surgical)
    for s in set(range(128)) - moved:
        assert np.array_equal(raw_up[s], new_up[s]), s


def test_osdmaptool_upmap_cli(tmp_path):
    m = build_simple_osdmap(n_osds=32, osds_per_host=4, pg_num=256)
    src = tmp_path / "map.bin"
    dst = tmp_path / "balanced.bin"
    src.write_bytes(pickle.dumps(m))
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.tools.osdmaptool", str(src),
         "--upmap", str(dst)],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr
    assert "pgs-per-osd stddev" in out.stdout
    m2 = pickle.loads(dst.read_bytes())
    assert m2.pg_upmap_items, "balanced map carries no upmap items"
    assert balancer.pg_per_osd_stddev(m2) < \
        balancer.pg_per_osd_stddev(m)
