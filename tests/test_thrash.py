"""OSD thrashing: randomized kill/restart under continuous writes.

The tier-4 analog of qa/tasks/thrashosds.py + ceph_manager.py
(kill_osd :202 / revive_osd :380): a seeded sequence of daemon bounces
interleaved with client writes; afterwards the cluster must converge —
every object readable with its last-acknowledged contents.
"""

import asyncio
import random

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster.osd import OSDDaemon
from ceph_tpu.cluster.vstart import _fast_config, start_cluster


def run(coro):
    return asyncio.run(coro)


@contention_retry()
def test_thrash_osds_replicated():
    async def scenario():
        rng = random.Random(42)
        cfg = _fast_config()
        cfg.mon_osd_down_out_interval = 60.0   # bounce, don't rebalance
        cluster = await start_cluster(5, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("thrash", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            acked = {}

            async def put(i, gen):
                oid = f"obj{i}"
                data = f"gen{gen}-{i}-".encode() * 60
                try:
                    await io.write_full(oid, data, timeout=60)
                    acked[oid] = data   # only acknowledged writes count
                except (IOError, OSError, TimeoutError):
                    pass

            down = None
            for round_no in range(4):
                for i in range(6):
                    await put(i, round_no)
                victim = rng.choice([o for o in list(cluster.osds)
                                     if len(cluster.osds) > 3])
                # bounce: stop keeping the store, write more, restart
                stopped = cluster.osds.pop(victim)
                store = stopped.store
                await stopped.stop()
                down = victim
                for i in range(6, 10):
                    await put(i, round_no)
                osd = OSDDaemon(victim, cluster.mon_addr, config=cfg,
                                store=store)
                await osd.start()
                cluster.osds[victim] = osd
                deadline = asyncio.get_event_loop().time() + 20
                while asyncio.get_event_loop().time() < deadline:
                    if cluster.mon.osdmap.osd_up[victim]:
                        break
                    await asyncio.sleep(0.05)

            # convergence: every acknowledged write reads back intact
            for oid, data in sorted(acked.items()):
                got = await io.read(oid, timeout=60)
                assert got == data, oid

            def divergent():
                out = []
                for oid, data in sorted(acked.items()):
                    pgid = client.objecter.object_pgid(pool, oid)
                    coll = f"pg_{pgid.pool}_{pgid.seed}"
                    _, _, acting, _ = \
                        client.objecter.osdmap.pg_to_up_acting_osds(pgid)
                    blobs = set()
                    for o in acting:
                        if o >= 0 and o in cluster.osds:
                            try:
                                blobs.add(bytes(
                                    cluster.osds[o].store.read(coll, oid)))
                            except FileNotFoundError:
                                blobs.add(b"<missing>")
                    if blobs != {data}:
                        out.append((oid, [b[:16] for b in blobs]))
                return out

            # replicas must converge byte-for-byte within a bounded
            # window (recovery passes run per map change; queries against
            # recently-bounced peers can take seconds each)
            deadline = asyncio.get_event_loop().time() + 30
            bad = divergent()
            while bad and asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(1.0)
                bad = divergent()
            assert not bad, bad
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_thrash_osds_with_snapshots():
    """Thrash with pool snapshots in the mix (round-4 item 1 gate): after
    bounces + recovery, every snap reads back the contents recorded at
    snap time and heads read their last-acknowledged data."""
    async def scenario():
        rng = random.Random(7)
        cfg = _fast_config()
        cfg.mon_osd_down_out_interval = 60.0
        cluster = await start_cluster(5, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("sthrash", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            acked = {}
            snap_expect = {}   # (snapid) -> {oid: bytes at snap time}

            async def put(i, gen):
                oid = f"obj{i}"
                data = f"snapgen{gen}-{i}-".encode() * 50
                try:
                    await io.write_full(oid, data, timeout=60)
                    acked[oid] = data
                except (IOError, OSError, TimeoutError):
                    pass

            for round_no in range(3):
                for i in range(5):
                    await put(i, round_no)
                sid = await io.snap_create(f"s{round_no}")
                snap_expect[sid] = dict(acked)
                victim = rng.choice(list(cluster.osds))
                stopped = cluster.osds.pop(victim)
                store = stopped.store
                await stopped.stop()
                for i in range(5):
                    await put(i, round_no + 100)  # overwrite under snapc
                osd = OSDDaemon(victim, cluster.mon_addr, config=cfg,
                                store=store)
                await osd.start()
                cluster.osds[victim] = osd
                deadline = asyncio.get_event_loop().time() + 20
                while asyncio.get_event_loop().time() < deadline:
                    if cluster.mon.osdmap.osd_up[victim]:
                        break
                    await asyncio.sleep(0.05)

            for oid, data in sorted(acked.items()):
                assert await io.read(oid, timeout=60) == data, oid
            for sid, objs in snap_expect.items():
                for oid, data in sorted(objs.items()):
                    got = await io.read(oid, snapid=sid, timeout=60)
                    assert got == data, (oid, sid)
        finally:
            await cluster.stop()

    run(scenario())
