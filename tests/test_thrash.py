"""OSD thrashing as seeded chaos scenarios.

The tier-4 analog of qa/tasks/thrashosds.py, rebuilt on graft-chaos
(round-8 satellite): the old inline thrashers improvised faults with
ad-hoc sleeps and leaned on ``contention_retry`` to absorb their own
timing races — exactly why they were load-flaky.  Now the fault
schedule is resolved up-front from the scenario seed, the runner owns
convergence waits, and the durability invariants (every acked write
readable and checksum-clean, snapshots consistent, no stuck PG,
HEALTH_OK, lockdep-acyclic) do the judging.  A failure replays
bit-identically with ``scripts/chaos.py run --scenario ... --seed ...``.
"""

import asyncio

import pytest

from ceph_tpu.chaos.scenario import (
    Scenario,
    builtin_scenarios,
    ev,
    run_scenario,
)


def run(coro):
    return asyncio.run(coro)


@pytest.mark.chaos
@pytest.mark.slow
def test_thrash_osds_replicated():
    """Seeded restart-bounces under continuous writes with snapshots in
    the mix (the old test_thrash_osds_replicated +
    test_thrash_osds_with_snapshots, one deterministic schedule)."""
    v = run(run_scenario(builtin_scenarios()["thrash-replicated"], 42))
    assert v.passed, v.failures
    assert v.counters.get("daemon_restarts") == 4
    assert v.acked_objects == 8


@pytest.mark.chaos
@pytest.mark.slow
def test_thrash_osds_kill_revive():
    """Kill/revive variant: dead OSDs lose their (RAM) stores entirely,
    so recovery must re-protect every object from the survivors before
    the revived daemons rejoin."""
    sc = Scenario(
        name="thrash-kill", osds=5, pool_size=3, pg_num=8,
        rounds=3, objects_per_round=6,
        events=(
            ev(0, "kill_osd"),
            ev(1, "revive_osd"),
            ev(1, "kill_osd"),
            ev(2, "revive_osd"),
        ),
        invariants=("durability", "acting", "health", "scrub",
                    "lockdep"),
        converge_timeout=90.0)
    v = run(run_scenario(sc, 1337))
    assert v.passed, v.failures
    assert v.counters.get("daemon_kills") == 2
    assert v.counters.get("daemon_revives") == 2
