"""Native C++ seam: libec_jax plugin shim + TPU sidecar (round-4,
BASELINE north star).

Builds the shim with the exact dlopen symbols the reference registry
resolves (ErasureCodePlugin.cc:132-170), starts the coalescing sidecar
in-process, and runs the C++ driver through the full native path:
dlopen -> __erasure_code_init -> unix socket -> batched device codec.
"""

import asyncio
import os
import shutil
import subprocess
import sys

import pytest

SRC = os.path.join(os.path.dirname(__file__), "..", "native", "ec_sidecar")

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


def _build(tmp_path):
    so = tmp_path / "libec_jax.so"
    drv = tmp_path / "ec_jax_driver"
    subprocess.run(["g++", "-O2", "-fPIC", "-shared", "-o", str(so),
                    os.path.join(SRC, "libec_jax.cc")], check=True)
    subprocess.run(["g++", "-O2", "-o", str(drv),
                    os.path.join(SRC, "driver.cc"), "-ldl"], check=True)
    return so, drv


def test_native_plugin_roundtrip(tmp_path):
    so, drv = _build(tmp_path)
    sock = str(tmp_path / "ec_jax.sock")

    async def scenario():
        sys.path.insert(0, SRC)
        try:
            from tpu_sidecar import Sidecar
        finally:
            sys.path.pop(0)
        sidecar = Sidecar()
        server = await asyncio.start_unix_server(sidecar.handle, path=sock)
        env = dict(os.environ, EC_JAX_SIDECAR=sock)
        proc = await asyncio.create_subprocess_exec(
            str(drv), str(so), env=env,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE)
        out, err = await asyncio.wait_for(proc.communicate(), timeout=300)
        server.close()
        await server.wait_closed()
        assert proc.returncode == 0, (out, err)
        assert b'"native_seam": "ok"' in out, out
        assert sidecar.requests > 0
        return out

    out = asyncio.run(scenario())
    print(out.decode())


def test_sidecar_coalesces_concurrent_requests(tmp_path):
    """Concurrent stripes from multiple connections must merge into
    fewer device batches (the north-star batching claim, measured)."""
    sys.path.insert(0, SRC)
    try:
        from tpu_sidecar import Sidecar
    finally:
        sys.path.pop(0)

    import json
    import struct

    import numpy as np

    async def scenario():
        sidecar = Sidecar(coalesce_window=0.02)
        sock = str(tmp_path / "co.sock")
        server = await asyncio.start_unix_server(sidecar.handle, path=sock)
        profile = json.dumps({"plugin": "isa", "k": "8", "m": "4"})
        k, m, chunk = 8, 4, 512
        rng = np.random.default_rng(0)

        async def one(i):
            reader, writer = await asyncio.open_unix_connection(sock)
            data = rng.integers(0, 256, (k, chunk), dtype=np.uint8)
            body = (bytes([1]) + struct.pack("<H", len(profile))
                    + profile.encode() + bytes([k, m, 0])
                    + struct.pack("<I", chunk) + data.tobytes())
            writer.write(struct.pack("<I", len(body)) + body)
            await writer.drain()
            (n,) = struct.unpack("<I", await reader.readexactly(4))
            reply = await reader.readexactly(n)
            writer.close()
            assert reply[0] == 0
            parity = np.frombuffer(reply, dtype=np.uint8,
                                   offset=1).reshape(m, chunk)
            # row 0 of the ISA vandermonde parity is the XOR of data
            want = data[0].copy()
            for j in range(1, k):
                want ^= data[j]
            assert np.array_equal(parity[0], want)

        await asyncio.gather(*[one(i) for i in range(16)])
        server.close()
        await server.wait_closed()
        assert sidecar.requests == 16
        assert sidecar.batches < 16, \
            f"no coalescing: {sidecar.batches} batches for 16 requests"

    asyncio.run(scenario())
