"""The mesh data plane must NEVER commit host data to the default backend.

Round-4 regression (MULTICHIP_r04 RED): ``jnp.asarray(host_data)`` before
``jax.device_put`` commits the array to the *default* platform — under the
driver that is the real TPU, and a skewed libtpu made the touch fatal even
though the mesh was the virtual CPU one.  The only allowed placement path
is ``jax.device_put(numpy, mesh_sharding)`` (MeshECEngine._put).

Enforcement: rebind the ``jnp`` global of the parallel modules to a proxy
whose ``asarray``/``array`` raise on host (non-Array, non-Tracer) input,
then exercise the full engine surface.  Any reintroduced eager commit —
including a trace-time constant commit — trips the proxy.
"""

import numpy as np
import pytest

import jax
import jax.numpy as real_jnp

from ceph_tpu.ec import matrices
from ceph_tpu.parallel import (MeshECEngine, crush_batch_sharded,
                               distributed_ec_step, make_mesh)
from ceph_tpu.parallel import engine as engine_mod
from ceph_tpu.parallel import mesh as mesh_mod


class _NoHostCommitJnp:
    """jnp proxy: forbids asarray/array on host data."""

    def _guard(self, name, x):
        if not isinstance(x, (jax.Array, jax.core.Tracer)):
            raise AssertionError(
                f"jnp.{name} called on host data of type {type(x).__name__}"
                " — this commits to the DEFAULT backend; use"
                " jax.device_put(numpy, mesh_sharding) instead"
            )

    def asarray(self, x, *a, **kw):
        self._guard("asarray", x)
        return real_jnp.asarray(x, *a, **kw)

    def array(self, x, *a, **kw):
        self._guard("array", x)
        return real_jnp.array(x, *a, **kw)

    def __getattr__(self, name):
        return getattr(real_jnp, name)


@pytest.fixture
def forbid_host_commits(monkeypatch):
    proxy = _NoHostCommitJnp()
    monkeypatch.setattr(engine_mod, "jnp", proxy)
    monkeypatch.setattr(mesh_mod, "jnp", proxy)


def _assert_on_mesh(arr, mesh):
    mesh_devs = set(mesh.devices.flatten().tolist())
    assert set(arr.devices()) <= mesh_devs, (
        f"array landed on {arr.devices()} outside the mesh")


def test_engine_surface_never_touches_default_backend(forbid_host_commits):
    mesh = make_mesh(8)
    k, m = 8, 4
    eng = MeshECEngine(mesh, k, m, matrices.isa_rs_matrix(k, m))
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (8, k, 128), dtype=np.uint8)

    parity = eng.encode_batch(data)
    _assert_on_mesh(parity, mesh)
    chunks = np.concatenate([data, np.asarray(parity)], axis=1)

    update = rng.integers(0, 256, (8, k, 32), dtype=np.uint8)
    new_chunks = eng.rmw_batch(chunks, update, col_start=16)
    _assert_on_mesh(new_chunks, mesh)

    got = eng.decode_batch((0, 5, 9), np.asarray(new_chunks))
    _assert_on_mesh(got, mesh)
    assert np.array_equal(np.asarray(got),
                          np.asarray(new_chunks)[:, [0, 5, 9], :])


def test_distributed_step_never_touches_default_backend(forbid_host_commits):
    mesh = make_mesh(8)
    fn, args = distributed_ec_step(mesh, k=8, m=4, batch=8, chunk=128)
    _assert_on_mesh(args[0], mesh)
    mismatches, chunks = fn(*args)
    assert int(mismatches) == 0
    _assert_on_mesh(chunks, mesh)


def test_crush_batch_sharded_never_touches_default_backend(
        forbid_host_commits):
    from ceph_tpu.crush.mapper import TensorMapper
    from ceph_tpu.crush.types import build_hierarchy

    cmap, rule = build_hierarchy(n_hosts=4, osds_per_host=2, numrep=3)
    mapper = TensorMapper(cmap)
    weights = np.full(cmap.max_devices, 0x10000, dtype=np.uint32)
    xs = np.arange(64, dtype=np.uint32)
    mesh = make_mesh(8)
    res, lens = crush_batch_sharded(mesh, mapper, rule, xs, 3, weights)
    _assert_on_mesh(res, mesh)
    single = np.asarray(
        mapper.do_rule_batch(rule, xs, result_max=3, weights=weights)[0])
    assert np.array_equal(np.asarray(res), single)
