"""Scrub: background integrity verification + repair, no client read.

Reference: PG scrub comparing replica objects and EC shard CRCs
(doc/dev/osd_internals/erasure_coding/ecbackend.rst:86-99), repairs
through the recovery machinery.
"""

import asyncio

import pytest

from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


def _coll(pgid):
    return f"pg_{pgid.pool}_{pgid.seed}"


def _corrupt(store, coll, oid, at=3):
    """Flip a byte directly in the backing store: silent corruption the
    transaction/version layer never sees (qa EIO-injection analog)."""
    store._colls[coll][oid].data[at] ^= 0xFF


async def _converge(cond, timeout=10.0):
    """Wall-deadline converge-poll: replica/shard applies land
    asynchronously after the ack — wait for the state, not a guessed
    duration.  The caller asserts the condition afterwards."""
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        try:
            if cond():
                return
        except Exception:
            pass
        await asyncio.sleep(0.02)


def test_scrub_detects_and_repairs_replica_corruption():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("sp", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            payload = b"scrub-me" * 200
            await io.write_full("obj", payload)

            pgid = client.objecter.object_pgid(pool, "obj")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            await _converge(lambda: all(
                cluster.osds[o].store.read(_coll(pgid), "obj") ==
                bytes(payload) for o in acting))
            victim = next(o for o in acting if o != primary)
            _corrupt(cluster.osds[victim].store, _coll(pgid), "obj")
            assert cluster.osds[victim].store.read(
                _coll(pgid), "obj") != payload

            st = cluster.osds[primary].pgs[pgid]
            report = await cluster.osds[primary].scrub_pg(st)
            assert report["inconsistent"] == ["obj"]
            assert report["repaired"] == ["obj"]
            await _converge(lambda: cluster.osds[victim].store.read(
                _coll(pgid), "obj") == bytes(payload))
            # repaired WITHOUT any client read
            assert cluster.osds[victim].store.read(
                _coll(pgid), "obj") == bytes(payload)
            # clean scrub afterwards
            report = await cluster.osds[primary].scrub_pg(st)
            assert report["inconsistent"] == []
        finally:
            await cluster.stop()

    run(scenario())


def test_scrub_detects_and_repairs_primary_corruption():
    """The primary itself can be the divergent copy: majority wins."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("sp2", "replicated",
                                            pg_num=8, size=3)
            io = client.ioctx(pool)
            payload = b"primary-corrupt" * 100
            await io.write_full("obj", payload)

            pgid = client.objecter.object_pgid(pool, "obj")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            await _converge(lambda: all(
                cluster.osds[o].store.read(_coll(pgid), "obj") ==
                bytes(payload) for o in acting))
            _corrupt(cluster.osds[primary].store, _coll(pgid), "obj")

            st = cluster.osds[primary].pgs[pgid]
            report = await cluster.osds[primary].scrub_pg(st)
            assert report["inconsistent"] == ["obj"]
            await _converge(lambda: cluster.osds[primary].store.read(
                _coll(pgid), "obj") == bytes(payload))
            assert cluster.osds[primary].store.read(
                _coll(pgid), "obj") == bytes(payload)
        finally:
            await cluster.stop()

    run(scenario())


def test_scrub_repairs_corrupt_ec_shard():
    async def scenario():
        cluster = await start_cluster(4)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "esp", "erasure", pg_num=8,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            payload = b"ec-scrub" * 300
            await io.write_full("obj", payload, timeout=60)

            pgid = client.objecter.object_pgid(pool, "obj")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            await _converge(lambda: all(
                cluster.osds[o].store.read(_coll(pgid), "obj")
                for o in acting if o >= 0 and o in cluster.osds))
            victim = next(o for o in acting
                          if o >= 0 and o != primary
                          and o in cluster.osds)
            before = bytes(cluster.osds[victim].store.read(
                _coll(pgid), "obj"))
            _corrupt(cluster.osds[victim].store, _coll(pgid), "obj")

            st = cluster.osds[primary].pgs[pgid]
            report = await cluster.osds[primary].scrub_pg(st)
            assert report["inconsistent"] == ["obj"]
            assert report["repaired"] == ["obj"]
            # repair lands asynchronously on the victim: converge-poll
            # against a wall deadline instead of a fixed sleep
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                if bytes(cluster.osds[victim].store.read(
                        _coll(pgid), "obj")) == before:
                    break
                await asyncio.sleep(0.05)
            after = bytes(cluster.osds[victim].store.read(
                _coll(pgid), "obj"))
            assert after == before
            assert await io.read("obj", timeout=60) == payload
        finally:
            await cluster.stop()

    run(scenario())
