"""Vectorized TensorMapper vs scalar oracle (and thus vs reference C)."""

import json
import pathlib

import numpy as np
import pytest

from ceph_tpu.crush import CrushMap, Rule, ScalarMapper, Tunables, Bucket
from ceph_tpu.crush.mapper import TensorMapper
from ceph_tpu.crush.types import (
    CRUSH_ITEM_NONE,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_EMIT,
    RULE_TAKE,
    build_hierarchy,
)

GOLDEN = pathlib.Path(__file__).parent / "golden" / "crush_golden.jsonl"


def load_scenarios():
    out = []
    for line in GOLDEN.open():
        d = json.loads(line)
        if d["scenario"] == "hash":
            continue
        if d["tunables"]["fallback"]:
            continue  # legacy local-retry profile: scalar-only
        out.append(d)
    return out


def build_map(d) -> CrushMap:
    tn = d["tunables"]
    cmap = CrushMap(Tunables(
        choose_total_tries=tn["total"],
        choose_local_tries=tn["local"],
        choose_local_fallback_tries=tn["fallback"],
        chooseleaf_descend_once=tn["descend_once"],
        chooseleaf_vary_r=tn["vary_r"],
        chooseleaf_stable=tn["stable"],
    ))
    for b in d["buckets"]:
        cmap.add_bucket(Bucket(id=b["id"], type=b["type"],
                               alg=b.get("alg", "straw2"),
                               items=b["items"], weights=b["weights"]))
    cmap.add_rule(Rule(steps=[tuple(s) for s in d["steps"]]))
    return cmap


@pytest.mark.parametrize("scen", load_scenarios(), ids=lambda s: s["scenario"])
def test_vectorized_matches_golden(scen):
    if any(b.get("alg", "straw2") != "straw2" for b in scen["buckets"]):
        pytest.skip("TensorMapper is straw2-only; these run through the "
                    "scalar oracle (validated in test_crush_scalar)")
    cmap = build_map(scen)
    cargs = None
    if "choose_args" in scen:
        from ceph_tpu.crush.types import ChooseArg

        cargs = {int(bid): ChooseArg(ids=a.get("ids"),
                                     weight_set=a.get("weight_set"))
                 for bid, a in scen["choose_args"].items()}
    mapper = TensorMapper(cmap)
    n = len(scen["results"])
    res, rlen = mapper.do_rule_batch(
        0, np.arange(n, dtype=np.uint32), scen["result_max"],
        np.array(scen["weights"], dtype=np.uint32), choose_args=cargs)
    res = np.asarray(res)
    rlen = np.asarray(rlen)
    bad = []
    for x, want in enumerate(scen["results"]):
        got = [int(v) for v in res[x, : rlen[x]]]
        if got != want:
            bad.append((x, got, want))
    assert not bad, f"{len(bad)}/{n} mismatches, first: {bad[:5]}"


@pytest.mark.parametrize("firstn", [True, False], ids=["firstn", "indep"])
def test_vectorized_matches_scalar_random_map(firstn):
    # bigger randomized hierarchy incl. reweighed/out devices
    rng = np.random.default_rng(5)
    cmap = CrushMap()
    hosts = []
    dev = 0
    for h in range(12):
        n = int(rng.integers(2, 7))
        items = list(range(dev, dev + n))
        dev += n
        weights = [int(w) for w in rng.integers(1, 5, n) * 0x10000]
        if h == 3:
            weights[0] = 0
        hosts.append(cmap.make_straw2(1, items, weights))
    hw = [cmap.buckets[h].weight for h in hosts]
    root = cmap.make_straw2(3, hosts, hw)
    op = RULE_CHOOSELEAF_FIRSTN if firstn else RULE_CHOOSELEAF_INDEP
    ruleno = cmap.add_rule(Rule(steps=[
        (RULE_TAKE, root, 0), (op, 0, 1), (RULE_EMIT, 0, 0)]))
    weights = np.full(cmap.max_devices, 0x10000, dtype=np.uint32)
    weights[rng.integers(0, dev, 5)] = 0
    weights[rng.integers(0, dev, 5)] = 0x8000

    scalar = ScalarMapper(cmap)
    mapper = TensorMapper(cmap)
    n = 600
    result_max = 4
    res, rlen = mapper.do_rule_batch(
        ruleno, np.arange(n, dtype=np.uint32), result_max, weights)
    res = np.asarray(res)
    rlen = np.asarray(rlen)
    bad = []
    for x in range(n):
        want = scalar.do_rule(ruleno, x, result_max, list(weights))
        got = [int(v) for v in res[x, : rlen[x]]]
        if got != want:
            bad.append((x, got, want))
    assert not bad, f"{len(bad)}/{n} mismatches, first: {bad[:5]}"


def test_large_map_smoke():
    cmap, ruleno = build_hierarchy(n_hosts=40, osds_per_host=8, numrep=3)
    mapper = TensorMapper(cmap)
    weights = np.full(cmap.max_devices, 0x10000, dtype=np.uint32)
    res, rlen = mapper.do_rule_batch(
        ruleno, np.arange(4096, dtype=np.uint32), 3, weights)
    res = np.asarray(res)
    assert np.all(np.asarray(rlen) == 3)
    # all placements are distinct devices on distinct hosts
    assert np.all(res >= 0)
    assert np.all(res < cmap.max_devices)
    hosts = res // 8
    assert all(len(set(row)) == 3 for row in hosts)


@pytest.mark.parametrize("firstn", [True, False], ids=["firstn", "indep"])
def test_vectorized_choose_args_matches_scalar(firstn):
    """VERDICT r4 missing #7 (weak #3): vectorized choose_args — balancer
    weight_set (multi-position) + ids overrides must match the scalar
    oracle bit-exact on a randomized map, firstn and indep."""
    from ceph_tpu.crush.types import ChooseArg

    rng = np.random.default_rng(11)
    cmap = CrushMap()
    hosts = []
    dev = 0
    for h in range(8):
        n = int(rng.integers(2, 6))
        items = list(range(dev, dev + n))
        dev += n
        weights = [int(w) for w in rng.integers(1, 5, n) * 0x10000]
        hosts.append(cmap.make_straw2(1, items, weights))
    hw = [cmap.buckets[h].weight for h in hosts]
    root = cmap.make_straw2(3, hosts, hw)
    op = RULE_CHOOSELEAF_FIRSTN if firstn else RULE_CHOOSELEAF_INDEP
    ruleno = cmap.add_rule(Rule(steps=[
        (RULE_TAKE, root, 0), (op, 0, 1), (RULE_EMIT, 0, 0)]))
    # balancer-style overrides: per-position weight sets on the root and
    # two hosts, plus an ids remap on one host
    cargs = {}
    rb = cmap.buckets[root]
    cargs[root] = ChooseArg(weight_set=[
        [int(w) for w in rng.integers(1, 6, rb.size) * 0x10000]
        for _ in range(3)])
    for hid in (hosts[1], hosts[4]):
        hb = cmap.buckets[hid]
        ws = [[int(w) for w in rng.integers(0, 5, hb.size) * 0x8000]
              for _ in range(2)]
        cargs[hid] = ChooseArg(weight_set=ws)
    h6 = cmap.buckets[hosts[6]]
    cargs[hosts[6]] = ChooseArg(
        ids=[i + 1000 for i in h6.items])
    weights = np.full(cmap.max_devices, 0x10000, dtype=np.uint32)
    weights[rng.integers(0, dev, 3)] = 0x9000

    scalar = ScalarMapper(cmap)
    mapper = TensorMapper(cmap)
    n = 500
    result_max = 4
    res, rlen = mapper.do_rule_batch(
        ruleno, np.arange(n, dtype=np.uint32), result_max, weights,
        choose_args=cargs)
    res = np.asarray(res)
    rlen = np.asarray(rlen)
    bad = []
    for x in range(n):
        want = scalar.do_rule(ruleno, x, result_max, list(weights),
                              choose_args=cargs)
        got = [int(v) for v in res[x, : rlen[x]]]
        if got != want:
            bad.append((x, got, want))
    assert not bad, f"{len(bad)}/{n} mismatches, first: {bad[:5]}"
    # plain (no choose_args) placement still matches on the same mapper
    res0, rlen0 = mapper.do_rule_batch(
        ruleno, np.arange(50, dtype=np.uint32), result_max, weights)
    res0 = np.asarray(res0)
    for x in range(50):
        want = scalar.do_rule(ruleno, x, result_max, list(weights))
        assert [int(v) for v in np.asarray(res0)[x, : np.asarray(rlen0)[x]]] \
            == want
