"""Round-4 ADVICE regression tests: reqid duplicate detection (the
reference's pg_log dup tracking), stale-leader lease fencing, and
scrub-repair tie handling.

Reference seams: PGLog dup tracking (src/osd/PGLog.h, the
osd_pg_log_dups_tracked window), Paxos::handle_lease epoch check
(src/mon/Paxos.cc), and scrub auto-repair requiring an authoritative
copy (src/osd/PrimaryLogPG scrub repair).
"""

import asyncio

import pytest

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


async def _send_op_raw(objecter, pool_id, oid, ops, reqid):
    """Send one MOSDOp with a FIXED reqid and await its reply — lets a
    test deliver byte-identical duplicates the way a resend does."""
    pgid = objecter.object_pgid(pool_id, oid)
    primary = objecter._target_osd(pgid)
    addr = objecter.osdmap.osd_addrs[primary]
    fut = asyncio.get_event_loop().create_future()
    objecter._inflight[reqid] = fut
    await objecter.messenger.send_message(
        M.MOSDOp(reqid=reqid, pgid=pgid, oid=oid, ops=ops,
                 epoch=objecter.osdmap.epoch), tuple(addr))
    return await asyncio.wait_for(fut, timeout=30)


def test_duplicate_exec_returns_cached_reply():
    """A resent non-idempotent exec (inotable.alloc) must not allocate a
    second inode: the dup gets the original reply from the reqid cache."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("meta", "replicated",
                                            pg_num=8, size=2)
            obj = client.objecter
            reqid = (obj.client_name, 999_991)
            ops = [("exec", {"cls": "inotable", "method": "alloc",
                             "indata": b""})]
            r1 = await _send_op_raw(obj, pool, "ino_obj", ops, reqid)
            r2 = await _send_op_raw(obj, pool, "ino_obj", ops, reqid)
            assert r1.result == 0
            assert r2.result == r1.result
            assert r2.data == r1.data, \
                "duplicate exec re-executed: allocated a fresh inode"
            # a genuinely new reqid must still allocate the next inode
            r3 = await _send_op_raw(obj, pool, "ino_obj", ops,
                                    (obj.client_name, 999_992))
            assert r3.data != r1.data
        finally:
            await cluster.stop()

    run(scenario())


def test_duplicate_write_and_delete_cached():
    """A resent delete returns the original 0, not -ENOENT."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("dpool", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            obj = client.objecter
            await io.write_full("victim", b"payload")
            reqid = (obj.client_name, 999_993)
            ops = [("delete", {})]
            r1 = await _send_op_raw(obj, pool, "victim", ops, reqid)
            r2 = await _send_op_raw(obj, pool, "victim", ops, reqid)
            assert r1.result == 0
            assert r2.result == 0, \
                f"duplicate delete re-executed -> {r2.result}"
        finally:
            await cluster.stop()

    run(scenario())


def test_stale_leader_lease_ignored():
    """A lease carrying an older election epoch must neither refresh the
    peon's lease timer nor flip its forwarding target."""
    async def scenario():
        cluster = await start_cluster(2, n_mons=3)
        try:
            peon = next(m for m in cluster.mons if not m.is_leader)
            leader_rank = peon.leader_rank
            stale_epoch = peon.elector.epoch - 2
            before = peon._last_lease
            # time-semantic pacing, not a convergence wait: the lease
            # stamp must tick past `before` so the refresh assertion
            # below can distinguish the current-epoch lease landing
            await asyncio.sleep(0.05)  # graftlint: ignore[fixed-sleep-in-tests]
            # forge a lease from a deposed leader (older epoch, rank != now)
            fake_rank = next(r for r in range(3)
                             if r not in (leader_rank, peon.rank))
            await peon.ms_dispatch(None, M.MMonPaxos(
                op="lease", rank=fake_rank, epoch=stale_epoch,
                last_committed=0))
            assert peon.leader_rank == leader_rank, \
                "stale lease flipped the forwarding target"
            assert peon._last_lease == before, \
                "stale lease refreshed the lease timer"
            # current-epoch lease still lands
            await peon.ms_dispatch(None, M.MMonPaxos(
                op="lease", rank=leader_rank, epoch=peon.elector.epoch,
                last_committed=0))
            assert peon._last_lease > before
        finally:
            await cluster.stop()

    run(scenario())


def test_scrub_tie_marks_inconsistent_not_repaired():
    """size-2 pool, 1-1 crc split: scrub must record the object as
    inconsistent and must NOT push either copy over the other."""
    async def scenario():
        cluster = await start_cluster(2)
        try:
            client = await cluster.client()
            pool = await client.pool_create("two", "replicated",
                                            pg_num=8, size=2)
            io = client.ioctx(pool)
            await io.write_full("tied", b"good-data")
            pgid = client.objecter.object_pgid(pool, "tied")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            coll = f"pg_{pgid.pool}_{pgid.seed}"

            # converge-poll: wait for BOTH copies to land (the replica
            # apply is async) before corrupting one of them
            def _both_hold() -> bool:
                try:
                    return all(
                        cluster.osds[o].store.read(coll, "tied") ==
                        b"good-data" for o in acting)
                except Exception:
                    return False

            deadline = asyncio.get_event_loop().time() + 10.0
            while not _both_hold() and \
                    asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
            # corrupt the PRIMARY copy: under first-inserted tie-breaking
            # this bad copy would win and clobber the good replica
            from ceph_tpu.cluster.store import Transaction
            cluster.osds[primary].store.queue_transaction(
                Transaction().write(coll, "tied", 0, b"BAD!-data"))
            st = cluster.osds[primary].pgs[pgid]
            report = await cluster.osds[primary].scrub_pg(st)
            assert "tied" in report["inconsistent"]
            assert "tied" not in report["repaired"]
            replica = next(o for o in acting if o != primary)
            assert cluster.osds[replica].store.read(coll, "tied") == \
                b"good-data", "tie repair overwrote the good replica"
        finally:
            await cluster.stop()

    run(scenario())


from tests._flaky import contention_retry


@contention_retry()
def test_resend_after_primary_change_not_reexecuted():
    """ADVICE r5: the in-memory reqid cache dies with the primary, but
    client reqids ride the replicated pg log entries — a resend landing
    on the NEW primary must find the reqid in its log and refuse to
    re-apply the (non-idempotent) append."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("failover", "replicated",
                                            pg_num=4, size=3)
            obj = client.objecter
            io = client.ioctx(pool)
            await io.write_full("log", b"base")
            reqid = (obj.client_name, 999_995)
            ops = [("append", {"data": b"+one"})]
            r1 = await _send_op_raw(obj, pool, "log", ops, reqid)
            assert r1.result == 0
            assert await io.read("log") == b"base+one"
            # kill the primary, wait for a new acting primary
            pgid = obj.object_pgid(pool, "log")
            _, _, _, old_primary = obj.osdmap.pg_to_up_acting_osds(pgid)
            await cluster.osds[old_primary].stop()
            for _ in range(200):
                await asyncio.sleep(0.25)
                _, _, acting, primary = \
                    obj.osdmap.pg_to_up_acting_osds(pgid)
                if primary >= 0 and primary != old_primary \
                        and pgid in cluster.osds[primary].pgs:
                    break
            assert primary != old_primary, "no failover happened"
            # resend the SAME op to the new primary
            r2 = await _send_op_raw(obj, pool, "log", ops, reqid)
            assert r2.result == 0
            got = await io.read("log", timeout=60)
            assert got == b"base+one", \
                f"resend re-executed after failover: {got!r}"
        finally:
            await cluster.stop()

    run(scenario())
