"""SHEC plugin tests.

Coverage models the reference's TestErasureCodeShec*.cc: profile parsing
constraints, shingle-matrix structure, minimum_to_decode locality (reads
fewer than k chunks for a single erasure), and exhaustive erasure-pattern
recovery sweeps for SHEC(k=6, m=4, c=3).
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec import factory
from ceph_tpu.ec.interface import ECError
from ceph_tpu.ec.shec import ErasureCodeShec, make_shec, shec_coding_matrix


def test_profile_defaults():
    codec = make_shec({})
    assert (codec.k, codec.m, codec.c) == (4, 3, 2)
    assert codec.get_chunk_count() == 7
    assert codec.get_data_chunk_count() == 4


def test_profile_constraints():
    with pytest.raises(ECError):
        make_shec({"k": "4", "m": "3"})  # c missing
    with pytest.raises(ECError):
        make_shec({"k": "4", "m": "3", "c": "4"})  # c > m
    with pytest.raises(ECError):
        make_shec({"k": "13", "m": "3", "c": "2"})  # k > 12
    with pytest.raises(ECError):
        make_shec({"k": "12", "m": "9", "c": "2"})  # k+m > 20
    with pytest.raises(ECError):
        make_shec({"k": "3", "m": "4", "c": "2"})  # m > k
    with pytest.raises(ECError):
        make_shec({"k": "4", "m": "3", "c": "2", "technique": "bogus"})


def test_shingle_matrix_has_zero_pattern():
    mat = shec_coding_matrix(6, 4, 3, technique=0)
    assert mat.shape == (4, 6)
    # shingled rows are sparse: zeros must exist (it is not a dense RS matrix)
    assert (mat == 0).sum() > 0
    # every data chunk is covered by at least one parity
    assert (mat != 0).any(axis=0).all()
    # every parity row uses at least one data chunk
    assert (mat != 0).any(axis=1).all()


def test_single_technique_matrix():
    mat = shec_coding_matrix(6, 4, 3, technique=1)
    assert mat.shape == (4, 6)
    assert (mat != 0).any(axis=0).all()


def test_roundtrip_no_erasure():
    codec = make_shec({"k": "6", "m": "4", "c": "3"})
    data = bytes(range(256)) * 24
    n = codec.get_chunk_count()
    chunks = codec.encode(range(n), data)
    assert len(chunks) == n
    assert codec.decode_concat(chunks)[: len(data)] == data


@pytest.mark.parametrize("n_erasures", [1, 2, 3])
def test_exhaustive_erasure_recovery(n_erasures):
    """SHEC(6,4,3) must recover every <= c erasure pattern (the reference's
    TestErasureCodeShec_all sweep, ErasureCodeShec.cc:69-121 decode path)."""
    codec = make_shec({"k": "6", "m": "4", "c": "3"})
    n = codec.get_chunk_count()
    data = np.random.default_rng(3).integers(0, 256, 6000, dtype=np.uint8).tobytes()
    chunks = codec.encode(range(n), data)
    for erase in itertools.combinations(range(n), n_erasures):
        avail = {i: c for i, c in chunks.items() if i not in erase}
        decoded = codec.decode(set(erase), avail)
        for e in erase:
            assert np.array_equal(decoded[e], chunks[e]), \
                f"pattern {erase}: chunk {e} mismatch"


def test_minimum_to_decode_reads_fewer_than_k():
    """The SHEC selling point: a single data-chunk erasure is recovered
    from fewer than k chunks (locality of the shingled parity)."""
    codec = make_shec({"k": "6", "m": "4", "c": "3"})
    n = codec.get_chunk_count()
    smaller_than_k = 0
    for erased in range(codec.k):
        minimum = codec.minimum_to_decode({erased}, set(range(n)) - {erased})
        assert erased not in minimum
        # must be recoverable, and never need more than k chunks
        assert len(minimum) <= codec.k
        if len(minimum) < codec.k:
            smaller_than_k += 1
        # the minimum really is sufficient: decode from exactly that set
        data = b"m" * 3000
        chunks = codec.encode(range(n), data)
        decoded = codec.decode({erased}, {i: chunks[i] for i in minimum})
        assert np.array_equal(decoded[erased], chunks[erased])
    assert smaller_than_k > 0, "no single-erasure pattern was local"


def test_minimum_to_decode_nothing_missing():
    codec = make_shec({"k": "6", "m": "4", "c": "3"})
    n = codec.get_chunk_count()
    assert codec.minimum_to_decode({2, 3}, set(range(n))) <= set(range(n))


def test_unrecoverable_pattern_raises():
    codec = make_shec({"k": "4", "m": "3", "c": "2"})
    n = codec.get_chunk_count()
    data = b"u" * 1000
    chunks = codec.encode(range(n), data)
    # erase more than the code can ever tolerate (all parities + 2 data)
    erase = {0, 1, 4, 5, 6}
    avail = {i: c for i, c in chunks.items() if i not in erase}
    with pytest.raises(ECError):
        codec.decode({0, 1}, avail)


def test_decode_table_cache_hit():
    codec = make_shec({"k": "6", "m": "4", "c": "3"})
    n = codec.get_chunk_count()
    data = b"c" * 3000
    chunks = codec.encode(range(n), data)
    avail = {i: c for i, c in chunks.items() if i != 2}
    codec.decode({2}, avail)
    assert len(codec._plan_cache) >= 1
    before = len(codec._plan_cache)
    codec.decode({2}, avail)  # same pattern: cache hit, no new entry
    assert len(codec._plan_cache) == before


def test_batch_decode_matches_single():
    codec = make_shec({"k": "6", "m": "4", "c": "3"})
    rng = np.random.default_rng(11)
    batch = rng.integers(0, 256, (8, 6, 96), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(batch))
    full = np.concatenate([batch, parity], axis=1)
    out = np.asarray(codec.decode_batch((1,), full))
    assert np.array_equal(out[:, 0, :], batch[:, 1, :])


def test_registry_exposes_shec():
    codec = factory({"plugin": "shec", "k": "6", "m": "4", "c": "3"})
    assert isinstance(codec, ErasureCodeShec)


def test_batch_decode_parity_erasure():
    """Parity-shard loss recovery through the batched path (the cluster
    recovery case that used to raise NotImplementedError)."""
    codec = make_shec({"k": "6", "m": "4", "c": "3"})
    rng = np.random.default_rng(12)
    batch = rng.integers(0, 256, (8, 6, 96), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(batch))
    full = np.concatenate([batch, parity], axis=1)
    # single parity erasure
    out = np.asarray(codec.decode_batch((7,), full))
    assert np.array_equal(out[:, 0, :], parity[:, 1, :])
    # mixed data + parity erasures (the bench config's pattern)
    zeroed = full.copy()
    for e in (0, 3, 7):
        zeroed[:, e, :] = 0
    out = np.asarray(codec.decode_batch((0, 3, 7), zeroed))
    assert np.array_equal(out[:, 0, :], batch[:, 0, :])
    assert np.array_equal(out[:, 1, :], batch[:, 3, :])
    assert np.array_equal(out[:, 2, :], parity[:, 1, :])


def test_batch_decode_want_subset():
    codec = make_shec({"k": "6", "m": "4", "c": "3"})
    rng = np.random.default_rng(13)
    batch = rng.integers(0, 256, (4, 6, 96), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(batch))
    full = np.concatenate([batch, parity], axis=1)
    zeroed = full.copy()
    for e in (2, 8):
        zeroed[:, e, :] = 0
    # erasures include the absent parity; want only the data shard
    out = np.asarray(codec.decode_batch((2, 8), zeroed, want=(2,)))
    assert out.shape[1] == 1
    assert np.array_equal(out[:, 0, :], batch[:, 2, :])


def test_shec_wide_w_roundtrip():
    """VERDICT r4 missing #6: w in {16, 32} via the gfw machinery —
    encode + multi-erasure decode, scalar and batched paths (the byte
    goldens vs the C oracle live in test_ec_golden.py)."""
    import numpy as np

    from ceph_tpu.ec import factory

    rng = np.random.default_rng(5)
    for w in (16, 32):
        codec = factory({"plugin": "shec", "k": "6", "m": "4", "c": "3",
                         "w": str(w)})
        assert codec.w == w
        obj = rng.integers(0, 256, codec.get_alignment() * 2,
                           dtype=np.uint8).tobytes()
        chunks = codec.encode(set(range(10)), obj)
        avail = {i: c for i, c in chunks.items() if i not in (0, 3, 7)}
        assert codec.decode_concat(avail)[:len(obj)] == obj
        # minimum_to_decode stays shingle-local (fewer than full k+m)
        minimum = codec.minimum_to_decode({0}, set(range(10)) - {0})
        assert len(minimum) <= codec.k
        # batched path
        S = codec.get_alignment() // codec.k
        data = rng.integers(0, 256, (4, 6, S), dtype=np.uint8)
        par = np.asarray(codec.encode_batch(data))
        full = np.concatenate([data, par], axis=1)
        got = np.asarray(codec.decode_batch((1, 5, 8), full))
        assert np.array_equal(got, full[:, [1, 5, 8], :]), w
