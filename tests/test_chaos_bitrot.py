"""End-to-end bit-rot (satellite): flip ONE bit of one EC shard on disk
via the disk injector, then prove deep scrub sees the csum mismatch and
repairs the shard through planar decode — without any client read
noticing.
"""

import asyncio

import pytest

from ceph_tpu.chaos.disk import DiskInjector
from ceph_tpu.chaos.rng import stream
from ceph_tpu.ops import crc32c as crcmod

EC_PROFILE = {"plugin": "jerasure", "technique": "reed_sol_van",
              "k": "2", "m": "1"}


def run(coro):
    return asyncio.run(coro)


@pytest.mark.chaos
def test_ec_shard_bitrot_detected_and_repaired_by_scrub():
    async def scenario():
        from ceph_tpu.cluster.vstart import start_cluster

        cluster = await start_cluster(4)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "rot", "erasure", pg_num=4,
                ec_profile=dict(EC_PROFILE))
            io = client.ioctx(pool)
            payload = bytes(range(256)) * 24
            await io.write_full("victim", payload, timeout=60)

            pgid = client.objecter.object_pgid(pool, "victim")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            shard_osd = next(o for o in acting
                             if o >= 0 and o != primary
                             and o in cluster.osds)
            store = cluster.osds[shard_osd].store
            # converge-poll: the ack covers shard durability, but the
            # replica's journal drain to the readable store is async —
            # wait for the shard bytes instead of hoping a fixed sleep
            # outlasts a loaded host
            deadline = asyncio.get_event_loop().time() + 10.0
            while asyncio.get_event_loop().time() < deadline:
                try:
                    if store.read(coll, "victim"):
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.02)
            clean_shard = bytes(store.read(coll, "victim"))

            # ONE silent bit flip via the disk injector: version and
            # hinfo_crc xattr untouched, so only a crc-verifying reader
            # can see it
            inj = DiskInjector(stream(13, "rot"))
            inj.flip_bit(store, coll, "victim")
            rotten = bytes(store.read(coll, "victim"))
            assert rotten != clean_shard
            stored_crc = int(store.getattr(coll, "victim", "hinfo_crc"))
            assert crcmod.crc32c(0xFFFFFFFF, rotten) != stored_crc

            # deep scrub: csum mismatch detected, shard rebuilt through
            # (planar) decode from the healthy members
            posd = cluster.osds[primary]
            report = await posd.scrub_pg(posd.pgs[pgid])
            assert report["inconsistent"] == ["victim"]
            assert report["repaired"] == ["victim"]
            # converge-poll (round 12 deflake): the repair push applies
            # asynchronously on the shard holder — poll instead of
            # hoping a fixed sleep outlasts a loaded host
            deadline = asyncio.get_event_loop().time() + 10.0
            healed = bytes(store.read(coll, "victim"))
            while healed != clean_shard and \
                    asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.05)
                healed = bytes(store.read(coll, "victim"))
            assert healed == clean_shard
            assert crcmod.crc32c(0xFFFFFFFF, healed) == stored_crc
            # clients read the original bytes end-to-end
            assert await io.read("victim", timeout=60) == payload
            # and a re-scrub is clean
            report = await posd.scrub_pg(posd.pgs[pgid])
            assert report["inconsistent"] == []
        finally:
            await cluster.stop()

    run(scenario())
