"""Erasure-code codec tests: roundtrips, erasure recovery, reference semantics."""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec import factory, matrices
from ceph_tpu.ec.interface import ECError
from ceph_tpu.ops import gf8


def roundtrip(codec, data: bytes, erase):
    n = codec.get_chunk_count()
    chunks = codec.encode(range(n), data)
    assert len(chunks) == n
    blocksize = codec.get_chunk_size(len(data))
    for c in chunks.values():
        assert len(c) == blocksize
    avail = {i: c for i, c in chunks.items() if i not in erase}
    out = codec.decode_concat(avail)
    assert out[: len(data)] == data
    # every erased chunk reconstructs bit-exactly
    decoded = codec.decode(set(erase), avail)
    for e in erase:
        assert np.array_equal(decoded[e], chunks[e]), f"chunk {e} mismatch"


PROFILES = [
    {"plugin": "jerasure", "technique": "reed_sol_van", "k": "4", "m": "2"},
    {"plugin": "jerasure", "technique": "reed_sol_van", "k": "8", "m": "4"},
    {"plugin": "jerasure", "technique": "reed_sol_r6_op", "k": "4"},
    {"plugin": "jerasure", "technique": "cauchy_orig", "k": "3", "m": "2",
     "packetsize": "8"},
    {"plugin": "jerasure", "technique": "cauchy_good", "k": "4", "m": "2",
     "packetsize": "8"},
    {"plugin": "isa", "technique": "reed_sol_van", "k": "4", "m": "2"},
    {"plugin": "isa", "technique": "cauchy", "k": "8", "m": "4"},
    {"plugin": "isa", "k": "7", "m": "3"},
]


@pytest.mark.parametrize("profile", PROFILES, ids=lambda p: "-".join(p.values()))
def test_roundtrip_all_single_and_double_erasures(profile):
    codec = factory(profile)
    k, n = codec.get_data_chunk_count(), codec.get_chunk_count()
    m = n - k
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, 1000, dtype=np.uint8).tobytes()
    for e in range(n):
        roundtrip(codec, data, [e])
    if m >= 2:
        for pair in itertools.combinations(range(n), 2):
            roundtrip(codec, data, list(pair))


def test_too_many_erasures_raises():
    codec = factory({"plugin": "jerasure", "technique": "reed_sol_van",
                     "k": "2", "m": "1"})
    chunks = codec.encode(range(3), b"hello world" * 10)
    del chunks[0], chunks[1]
    with pytest.raises(ECError):
        codec.decode({0}, chunks)


def test_minimum_to_decode():
    codec = factory({"plugin": "jerasure", "technique": "reed_sol_van",
                     "k": "4", "m": "2"})
    # all wanted available -> itself
    assert codec.minimum_to_decode({0, 1}, {0, 1, 2}) == {0, 1}
    # greedy first-k of available (reference ErasureCode.cc:91-108)
    assert codec.minimum_to_decode({0}, {1, 2, 3, 4, 5}) == {1, 2, 3, 4}
    with pytest.raises(ECError):
        codec.minimum_to_decode({0}, {1, 2, 3})


def test_chunk_size_rules():
    # jerasure reed_sol: pad object to k*w*4 then divide (ErasureCodeJerasure.cc:74)
    j = factory({"plugin": "jerasure", "technique": "reed_sol_van", "k": "4", "m": "2"})
    assert j.get_chunk_size(512) == 128  # 512 % (k*w*4 = 128) == 0 -> 512/4
    assert j.get_chunk_size(1) == 32  # padded up to alignment 128 -> /4
    # isa: ceil(object/k) rounded to 32 (ErasureCodeIsa.cc:65-78)
    i = factory({"plugin": "isa", "k": "8", "m": "4"})
    assert i.get_chunk_size(4096 * 8) == 4096
    assert i.get_chunk_size(100) == 32


def test_systematic_data_chunks_unchanged():
    codec = factory({"plugin": "isa", "k": "4", "m": "2"})
    data = bytes(range(256)) * 2
    chunks = codec.encode(range(6), data)
    bs = codec.get_chunk_size(len(data))
    flat = np.frombuffer(data, dtype=np.uint8)
    for i in range(4):
        want = np.zeros(bs, dtype=np.uint8)
        seg = flat[i * bs : (i + 1) * bs]
        want[: len(seg)] = seg
        assert np.array_equal(chunks[i], want)


def test_isa_first_parity_row_is_xor():
    # vandermonde row 0 is all ones -> parity 0 == XOR of data chunks
    codec = factory({"plugin": "isa", "k": "5", "m": "2"})
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, 5 * 64, dtype=np.uint8).tobytes()
    chunks = codec.encode(range(7), data)
    xor = np.zeros_like(chunks[0])
    for i in range(5):
        xor ^= chunks[i]
    assert np.array_equal(chunks[5], xor)


def test_raid6_q_parity():
    codec = factory({"plugin": "jerasure", "technique": "reed_sol_r6_op", "k": "3"})
    rng = np.random.default_rng(8)
    data = rng.integers(0, 256, 3 * 96, dtype=np.uint8).tobytes()
    chunks = codec.encode(range(5), data)
    p = chunks[0] ^ chunks[1] ^ chunks[2]
    q = (gf8.gf_mul(chunks[0], 1) ^ gf8.gf_mul(chunks[1], 2) ^ gf8.gf_mul(chunks[2], 4))
    assert np.array_equal(chunks[3], p)
    assert np.array_equal(chunks[4], q)


def test_vandermonde_matrix_is_mds():
    # every k x k submatrix of [I; C] invertible for a few (k, m)
    for k, m in [(4, 2), (5, 3), (8, 4)]:
        gen = matrices.generator_matrix(
            matrices.reed_sol_vandermonde_coding_matrix(k, m)
        )
        for rows in itertools.combinations(range(k + m), k):
            gf8.gf_invert_matrix(gen[list(rows)])  # raises if singular


def test_cauchy_matrix_is_mds():
    for k, m in [(4, 2), (6, 3)]:
        gen = matrices.generator_matrix(matrices.isa_cauchy_matrix(k, m))
        for rows in itertools.combinations(range(k + m), k):
            gf8.gf_invert_matrix(gen[list(rows)])


def test_batch_encode_matches_single():
    codec = factory({"plugin": "isa", "k": "4", "m": "2"})
    rng = np.random.default_rng(9)
    batch = rng.integers(0, 256, (16, 4, 128), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(batch))
    assert parity.shape == (16, 2, 128)
    for b in range(16):
        want = gf8.gf_matmul_ref(codec.engine.coding, batch[b])
        assert np.array_equal(parity[b], want)


def test_batch_decode_matches_encode():
    codec = factory({"plugin": "isa", "k": "4", "m": "2"})
    rng = np.random.default_rng(10)
    batch = rng.integers(0, 256, (8, 4, 64), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(batch))
    full = np.concatenate([batch, parity], axis=1)  # (8, 6, 64)
    erasures = (1, 4)
    got = np.asarray(codec.decode_batch(erasures, full))
    assert np.array_equal(got[:, 0], batch[:, 1])
    assert np.array_equal(got[:, 1], parity[:, 0])


def test_decode_table_cache_reuse():
    codec = factory({"plugin": "isa", "k": "4", "m": "2"})
    data = bytes(1024)
    chunks = codec.encode(range(6), data)
    avail = {i: c for i, c in chunks.items() if i != 2}
    codec.decode({2}, avail)
    misses0 = codec.engine._decode_cache.misses
    codec.decode({2}, avail)
    assert codec.engine._decode_cache.misses == misses0
    assert codec.engine._decode_cache.hits >= 1


def test_chunk_mapping_parsing():
    # "mapping" profile key parsing (reference ErasureCode::to_mapping); the
    # mapping is an LRC-internal mechanism — plain codecs only parse it.
    codec = factory({"plugin": "jerasure", "technique": "reed_sol_van",
                     "k": "2", "m": "1", "mapping": "_DD"})
    assert codec.get_chunk_mapping() == [1, 2, 0]
    assert codec.chunk_index(0) == 1
    assert codec.chunk_index(1) == 2
    assert codec.chunk_index(2) == 0
