"""Divergent-log rewind + EC rollback (round-4 item 5).

Reference: PGLog::rewind_divergent_log (src/osd/PGLog.cc:287), the EC
rollback design (doc/dev/osd_internals/erasure_coding/ecbackend.rst:
10-27), and find_best_info's require_rollback MIN-last_update election —
an un-acked partial-stripe write applied on some shards only must be
ROLLED BACK during peering (restoring the exact pre-write shard bytes),
never blessed or object-copied forward.
"""

import asyncio
import pickle
import random

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster import pglog
from ceph_tpu.cluster.osd import OSDDaemon
from ceph_tpu.cluster.pg import PGRB
from ceph_tpu.cluster.vstart import _fast_config, start_cluster
from ceph_tpu.ops import crc32c as crcmod

EC_PROFILE = {"plugin": "jerasure", "technique": "reed_sol_van",
              "k": "2", "m": "1"}


def run(coro):
    return asyncio.run(coro)


def _shard_crc(osd, coll, oid):
    data = osd.store.read(coll, oid)
    return crcmod.crc32c(0xFFFFFFFF, bytes(data))


@contention_retry()
def test_ec_partial_write_rolls_back():
    """Primary applies its shard + log entry but the sub-writes never
    reach the replicas (crash mid-write).  Peering must elect the
    replicas' shorter log (min-rule) and REWIND the primary's divergent
    entry, restoring its pre-write shard bytes exactly (verified via
    per-shard crc), not copy objects around."""
    async def scenario():
        cfg = _fast_config()
        cfg.osd_client_op_timeout = 1.0   # the doomed write times out fast
        # load-deflake (round 11): under suite load a starved event loop
        # misses heartbeats/beacons, a false down-mark churns the map,
        # and peering rewinds the divergent entry EARLY — racing the
        # intermediate asserts below (seen as last_update "never
        # advancing": it had already been rewound).  Generous graces pin
        # peering to the explicit _recover_pg call; the invariants
        # stay strict.
        cfg.osd_heartbeat_grace = 30.0
        cfg.mon_osd_beacon_grace = 30.0
        # ... and pin BACKGROUND recovery out of the window too: an
        # incomplete boot-time round arms a delayed retry that can
        # fire mid-doomed-write and rewind the divergent entry before
        # the intermediate asserts observe it (round 12 retries rounds
        # more eagerly).  The test drives peering explicitly.
        cfg.osd_recovery_delay_start = 300.0
        cluster = await start_cluster(3, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rwnd", "erasure", pg_num=4,
                                            ec_profile=dict(EC_PROFILE))
            io = client.ioctx(pool)
            v1 = bytes(range(256)) * 32
            await io.write_full("victim", v1)

            pgid = client.objecter.object_pgid(pool, "victim")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            # converge-poll (not a fixed beat): every member's shard
            # apply must land before the crc/log snapshot below
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline and \
                    any(cluster.osds[o].store.stat(coll, "victim")
                        is None for o in acting):
                await asyncio.sleep(0.05)
            posd = cluster.osds[primary]
            st = posd.pgs[pgid]
            lu_before = st.last_update
            crc_before = _shard_crc(posd, coll, "victim")

            # crash-mid-write model: the sub-writes VANISH (sent into the
            # void, no error) — exactly what a primary death after the
            # local apply looks like; the op times out un-acked
            orig_send = posd._send_osd

            async def drop_subwrites(osd, msg):
                if isinstance(msg, M.MOSDECSubOpWrite):
                    return  # swallowed: replicas never see it
                return await orig_send(osd, msg)

            posd._send_osd = drop_subwrites
            pobj = posd.osdmap.pools[pool]
            r = await posd._op_write_full(pobj, st, "victim", b"Z" * 8192)
            posd._send_osd = orig_send
            assert r == -110, "doomed write must time out un-acked"
            # local shard applied + logged, replicas never saw it
            assert st.last_update > lu_before
            assert _shard_crc(posd, coll, "victim") != crc_before
            assert st.last_complete < st.last_update
            rb = posd.store.omap_get(coll, PGRB)
            assert rb, "no rollback record captured for the shard write"

            # peering (what the restarted primary runs): the replicas'
            # log wins under the EC min-rule; our entry rewinds
            await posd._recover_pg(st)
            assert st.last_update == lu_before, "divergent entry survived"
            assert _shard_crc(posd, coll, "victim") == crc_before, \
                "rewind did not restore the pre-write shard bytes"
            # the object still reads back as v1 for clients
            assert await io.read("victim", timeout=60) == v1
        finally:
            await cluster.stop()

    run(scenario())


def test_ec_divergent_replica_rewinds_on_instruction():
    """A REPLICA holding a divergent entry (it applied a sub-write the
    other members never got, then the primary's log moved on without it)
    is rolled back by the primary's rewind instruction during peering."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rwnd2", "erasure", pg_num=4,
                                            ec_profile=dict(EC_PROFILE))
            io = client.ioctx(pool)
            v1 = b"stable-state" * 100
            await io.write_full("obj", v1)
            pgid = client.objecter.object_pgid(pool, "obj")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            # converge-poll: the replica's shard + log entry must land
            # before crc_before/lu snapshot below (fixed beat flaked)
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline and \
                    any(cluster.osds[o].store.stat(coll, "obj") is None
                        for o in acting):
                await asyncio.sleep(0.05)
            replica = next(o for o in acting if o != primary)
            rosd = cluster.osds[replica]
            rst = rosd.pgs[pgid]
            crc_before = _shard_crc(rosd, coll, "obj")
            lu = rst.last_update

            # forge a divergent sub-write on the replica only (the shard
            # apply + entry the reference's crashed primary would have
            # fanned out to just this member)
            fake_v = (rosd.osdmap.epoch, lu[1] + 1)
            shard = int(rosd.store.getattr(coll, "obj", "shard"))
            rosd._apply_shard(pgid, "obj", shard, b"G" * 1024, 0, 1024,
                              {"size": 2048, "version": fake_v[1]})
            rosd._log_mutation(rst, "modify", "obj", fake_v)
            assert rst.last_update == fake_v
            assert _shard_crc(rosd, coll, "obj") != crc_before

            # primary peers: sees the replica ahead, instructs rewind
            posd = cluster.osds[primary]
            await posd._recover_pg(posd.pgs[pgid])
            for _ in range(50):
                if rst.last_update == lu:
                    break
                await asyncio.sleep(0.1)
            assert rst.last_update == lu, "replica kept divergent entry"
            assert _shard_crc(rosd, coll, "obj") == crc_before, \
                "replica shard bytes not restored"
            assert await io.read("obj", timeout=60) == v1
        finally:
            await cluster.stop()

    run(scenario())


def test_stale_primary_shard_serves_committed_group():
    """A primary whose OWN shard is a stale older generation — the state
    an interrupted recovery pull leaves behind when no further map
    change retriggers peering — must serve reads from the newest
    COMMITTED shard group at the GROUP's size, never the group's bytes
    truncated to the local size attr (graft-chaos: obj read back as g2
    bytes at g1's length).  Scrub must then flag + rebuild the stale
    shard even though its crc is self-consistent.

    Round 16: automatic READ-repair would heal the stale shard before
    the scrub half of this test could see it (that path has its own
    coverage in tests/test_integrity.py), so this anchor runs with
    osd_read_repair=0 — detection-only — to keep exercising the scrub
    generation-divergence machinery."""
    from ceph_tpu.cluster.store import Transaction

    async def scenario():
        cfg = _fast_config()
        cfg.osd_read_repair = 0
        cluster = await start_cluster(4, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("stale", "erasure", pg_num=4,
                                            ec_profile=dict(EC_PROFILE))
            io = client.ioctx(pool)
            g1 = b"g1-" * 340                 # 1020 bytes
            g2 = b"g2-xyz" * 180              # 1080 bytes
            await io.write_full("obj", g1)
            pgid = client.objecter.object_pgid(pool, "obj")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            posd = cluster.osds[primary]
            # capture the primary's complete g1 shard state
            old_bytes = bytes(posd.store.read(coll, "obj"))
            old_attrs = {k: posd.store.getattr(coll, "obj", k)
                         for k in ("shard", "size", "hinfo_crc")}
            old_ver = posd.store.get_version(coll, "obj")
            await io.write_full("obj", g2)    # acked: every shard at g2

            # surgically regress ONLY the primary's shard back to g1
            # (bytes + attrs + version all self-consistent, crc clean)
            txn = (Transaction()
                   .write(coll, "obj", 0, old_bytes)
                   .truncate(coll, "obj", len(old_bytes)))
            for k, v in old_attrs.items():
                txn.setattr(coll, "obj", k, v)
            txn.set_version(coll, "obj", old_ver)
            posd.store.queue_transaction(txn)

            # read must be the committed generation, whole — not g2
            # bytes cut to g1's 1020
            assert await io.read("obj", timeout=60) == g2

            # scrub sees the generation divergence and rebuilds the
            # stale shard from the committed group
            st = posd.pgs[pgid]
            rep = await posd.scrub_pg(st)
            assert "obj" in rep["inconsistent"], \
                "scrub missed the stale (old-generation) shard"
            assert "obj" in rep["repaired"]
            assert posd.store.getattr(coll, "obj", "size") == \
                str(len(g2)).encode()
            assert await io.read("obj", timeout=60) == g2
        finally:
            await cluster.stop()

    run(scenario())


@pytest.mark.chaos
@pytest.mark.slow
def test_thrash_primaries_mid_ec_write():
    """Thrasher variant bouncing OSDs mid-write on an EC pool (round-4
    item 5 gate), now a seeded chaos scenario: restart events race the
    write bursts on a deterministic schedule; afterwards every acked
    object must hold SOME whole submitted payload (at-least-once — a
    timed-out write may land after its client gave up, but torn or
    mixed-generation bytes never pass) and a full scrub pass finds zero
    silent shard divergence."""
    from ceph_tpu.chaos.scenario import builtin_scenarios, run_scenario

    v = run(run_scenario(builtin_scenarios()["thrash-ec-midwrite"], 11))
    assert v.passed, v.failures
    assert v.counters.get("daemon_restarts") == 3
