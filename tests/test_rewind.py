"""Divergent-log rewind + EC rollback (round-4 item 5).

Reference: PGLog::rewind_divergent_log (src/osd/PGLog.cc:287), the EC
rollback design (doc/dev/osd_internals/erasure_coding/ecbackend.rst:
10-27), and find_best_info's require_rollback MIN-last_update election —
an un-acked partial-stripe write applied on some shards only must be
ROLLED BACK during peering (restoring the exact pre-write shard bytes),
never blessed or object-copied forward.
"""

import asyncio
import pickle
import random

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster import pglog
from ceph_tpu.cluster.osd import OSDDaemon
from ceph_tpu.cluster.pg import PGRB
from ceph_tpu.cluster.vstart import _fast_config, start_cluster
from ceph_tpu.ops import crc32c as crcmod

EC_PROFILE = {"plugin": "jerasure", "technique": "reed_sol_van",
              "k": "2", "m": "1"}


def run(coro):
    return asyncio.run(coro)


def _shard_crc(osd, coll, oid):
    data = osd.store.read(coll, oid)
    return crcmod.crc32c(0xFFFFFFFF, bytes(data))


@contention_retry()
def test_ec_partial_write_rolls_back():
    """Primary applies its shard + log entry but the sub-writes never
    reach the replicas (crash mid-write).  Peering must elect the
    replicas' shorter log (min-rule) and REWIND the primary's divergent
    entry, restoring its pre-write shard bytes exactly (verified via
    per-shard crc), not copy objects around."""
    async def scenario():
        cfg = _fast_config()
        cfg.osd_client_op_timeout = 1.0   # the doomed write times out fast
        cluster = await start_cluster(3, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rwnd", "erasure", pg_num=4,
                                            ec_profile=dict(EC_PROFILE))
            io = client.ioctx(pool)
            v1 = bytes(range(256)) * 32
            await io.write_full("victim", v1)
            await asyncio.sleep(0.05)

            pgid = client.objecter.object_pgid(pool, "victim")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            posd = cluster.osds[primary]
            st = posd.pgs[pgid]
            lu_before = st.last_update
            crc_before = _shard_crc(posd, coll, "victim")

            # crash-mid-write model: the sub-writes VANISH (sent into the
            # void, no error) — exactly what a primary death after the
            # local apply looks like; the op times out un-acked
            orig_send = posd._send_osd

            async def drop_subwrites(osd, msg):
                if isinstance(msg, M.MOSDECSubOpWrite):
                    return  # swallowed: replicas never see it
                return await orig_send(osd, msg)

            posd._send_osd = drop_subwrites
            pobj = posd.osdmap.pools[pool]
            r = await posd._op_write_full(pobj, st, "victim", b"Z" * 8192)
            posd._send_osd = orig_send
            assert r == -110, "doomed write must time out un-acked"
            # local shard applied + logged, replicas never saw it
            assert st.last_update > lu_before
            assert _shard_crc(posd, coll, "victim") != crc_before
            assert st.last_complete < st.last_update
            rb = posd.store.omap_get(coll, PGRB)
            assert rb, "no rollback record captured for the shard write"

            # peering (what the restarted primary runs): the replicas'
            # log wins under the EC min-rule; our entry rewinds
            await posd._recover_pg(st)
            assert st.last_update == lu_before, "divergent entry survived"
            assert _shard_crc(posd, coll, "victim") == crc_before, \
                "rewind did not restore the pre-write shard bytes"
            # the object still reads back as v1 for clients
            assert await io.read("victim", timeout=60) == v1
        finally:
            await cluster.stop()

    run(scenario())


def test_ec_divergent_replica_rewinds_on_instruction():
    """A REPLICA holding a divergent entry (it applied a sub-write the
    other members never got, then the primary's log moved on without it)
    is rolled back by the primary's rewind instruction during peering."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rwnd2", "erasure", pg_num=4,
                                            ec_profile=dict(EC_PROFILE))
            io = client.ioctx(pool)
            v1 = b"stable-state" * 100
            await io.write_full("obj", v1)
            await asyncio.sleep(0.05)
            pgid = client.objecter.object_pgid(pool, "obj")
            coll = f"pg_{pgid.pool}_{pgid.seed}"
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            replica = next(o for o in acting if o != primary)
            rosd = cluster.osds[replica]
            rst = rosd.pgs[pgid]
            crc_before = _shard_crc(rosd, coll, "obj")
            lu = rst.last_update

            # forge a divergent sub-write on the replica only (the shard
            # apply + entry the reference's crashed primary would have
            # fanned out to just this member)
            fake_v = (rosd.osdmap.epoch, lu[1] + 1)
            shard = int(rosd.store.getattr(coll, "obj", "shard"))
            rosd._apply_shard(pgid, "obj", shard, b"G" * 1024, 0, 1024,
                              {"size": 2048, "version": fake_v[1]})
            rosd._log_mutation(rst, "modify", "obj", fake_v)
            assert rst.last_update == fake_v
            assert _shard_crc(rosd, coll, "obj") != crc_before

            # primary peers: sees the replica ahead, instructs rewind
            posd = cluster.osds[primary]
            await posd._recover_pg(posd.pgs[pgid])
            for _ in range(50):
                if rst.last_update == lu:
                    break
                await asyncio.sleep(0.1)
            assert rst.last_update == lu, "replica kept divergent entry"
            assert _shard_crc(rosd, coll, "obj") == crc_before, \
                "replica shard bytes not restored"
            assert await io.read("obj", timeout=60) == v1
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_thrash_primaries_mid_ec_write():
    """Thrasher variant targeting primaries mid-write on an EC pool
    (round-4 item 5 gate): writes race primary kills; afterwards every
    ACKED write must read back and un-acked partials must have been
    rolled back or completed — never silent shard divergence (verified
    via scrub over every object)."""
    async def scenario():
        rng = random.Random(11)
        cfg = _fast_config()
        cfg.mon_osd_down_out_interval = 60.0
        cluster = await start_cluster(4, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create("pthrash", "erasure", pg_num=4,
                                            ec_profile=dict(EC_PROFILE))
            io = client.ioctx(pool)
            acked = {}
            attempted = {}   # oid -> every payload ever submitted

            async def put(i, gen, timeout=60):
                oid = f"obj{i}"
                data = f"g{gen}-{i}-".encode() * 100
                attempted.setdefault(oid, set()).add(data)
                try:
                    await io.write_full(oid, data, timeout=timeout)
                    acked[oid] = data
                except (IOError, OSError, TimeoutError):
                    pass

            for round_no in range(3):
                for i in range(4):
                    await put(i, round_no)
                # find the primary of a random object and bounce it while
                # writes are in flight
                oid = f"obj{rng.randrange(4)}"
                pgid = client.objecter.object_pgid(pool, oid)
                _, _, _, primary = \
                    client.objecter.osdmap.pg_to_up_acting_osds(pgid)
                if primary < 0 or primary not in cluster.osds:
                    continue
                writes = asyncio.gather(
                    *[put(i, round_no + 10, timeout=20) for i in range(4)],
                    return_exceptions=True)
                await asyncio.sleep(rng.random() * 0.05)
                stopped = cluster.osds.pop(primary)
                store = stopped.store
                await stopped.stop()
                await writes
                osd = OSDDaemon(primary, cluster.mon_addr, config=cfg,
                                store=store)
                await osd.start()
                cluster.osds[primary] = osd
                deadline = asyncio.get_event_loop().time() + 20
                while asyncio.get_event_loop().time() < deadline:
                    if cluster.mon.osdmap.osd_up[primary]:
                        break
                    await asyncio.sleep(0.05)
                await asyncio.sleep(1.0)

            # convergence: every object must hold SOME whole submitted
            # payload (a timed-out write may legitimately land after its
            # client gave up — at-least-once semantics — but torn or
            # mixed-generation content is never acceptable)
            for oid, data in sorted(acked.items()):
                got = await io.read(oid, timeout=60)
                assert got in attempted[oid], \
                    (oid, got[:24], data[:24])
            # no silent shard divergence: scrub every PG, expect zero
            # inconsistent objects after recovery settles (generous
            # deadline: under xdist CPU contention recovery rounds and
            # scrubs can each take seconds)
            deadline = asyncio.get_event_loop().time() + 90
            while True:
                bad = []
                for o in cluster.osds.values():
                    for st in list(o.pgs.values()):
                        if st.primary != o.osd_id:
                            continue
                        rep = await o.scrub_pg(st)
                        bad.extend(rep["inconsistent"])
                if not bad or asyncio.get_event_loop().time() > deadline:
                    break
                await asyncio.sleep(1.0)
            assert not bad, f"divergent shards after thrash: {bad}"
        finally:
            await cluster.stop()

    run(scenario())
