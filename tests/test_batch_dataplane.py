"""Round-11 batched data plane: bit-exactness + unit coverage.

The coalesced tick must be invisible in the bytes: N concurrent writes
through sharded dispatch + per-tick stripe-batch coalescing produce
byte-identical shards (and stored CRCs) to the same writes issued
serially through the round-10 per-op path — including mixed-profile
ticks and the 1-op-tick degenerate case.  Unit level, the multi-op
encode and the batched row CRC must match their per-op/host
equivalents exactly.
"""

import asyncio

import numpy as np
import pytest

from tests._flaky import contention_retry

from ceph_tpu.cluster.vstart import _fast_config, start_cluster
from ceph_tpu.ec import factory
from ceph_tpu.ec.stripe import (
    StripeInfo,
    encode_stripes,
    encode_stripes_multi,
)
from ceph_tpu.ops import crc32c as crcmod


def run(coro):
    return asyncio.run(coro)


def _coll(pgid):
    return f"pg_{pgid.pool}_{pgid.seed}"


# ------------------------------------------------------------- unit level


def test_encode_stripes_multi_bit_exact_and_crcs():
    """One coalesced dispatch == N per-op dispatches, byte for byte;
    batch CRCs == the host ceph_crc32c each shard row would get."""
    codec = factory({"plugin": "jerasure", "technique": "reed_sol_van",
                     "k": "2", "m": "1"})
    sinfo = StripeInfo(2, 4096)
    rng = np.random.default_rng(11)
    datas = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
             for n in (8192, 40960, 1, 8192, 0, 12345)]
    multi = encode_stripes_multi(codec, sinfo, datas,
                                 want_crcs=[True] * len(datas))
    for data, (shards, crcs) in zip(datas, multi):
        solo = encode_stripes(codec, sinfo, data)
        assert shards.shape == solo.shape
        assert np.array_equal(shards, solo)
        assert crcs is not None and len(crcs) == shards.shape[0]
        for row, crc in zip(shards, crcs):
            assert crc == crcmod.crc32c(0xFFFFFFFF, row.tobytes())


def test_encode_stripes_multi_single_op_degenerate():
    """The 1-op tick: no coalescing partner, still bit-exact."""
    codec = factory({"plugin": "jerasure", "technique": "reed_sol_van",
                     "k": "2", "m": "1"})
    sinfo = StripeInfo(2, 4096)
    data = bytes(range(256)) * 64
    [(shards, crcs)] = encode_stripes_multi(codec, sinfo, [data], [True])
    assert np.array_equal(shards, encode_stripes(codec, sinfo, data))
    assert crcs == [crcmod.crc32c(0xFFFFFFFF, r.tobytes())
                    for r in shards]


def test_crc32c_rows_matches_host():
    rng = np.random.default_rng(7)
    # block-aligned rows: the device batch + vectorized fold path
    rows = rng.integers(0, 256, (5, 3 * 4096), dtype=np.uint8)
    got = crcmod.crc32c_rows(rows)
    assert got == [crcmod.crc32c(0xFFFFFFFF, r.tobytes()) for r in rows]
    # non-multiple length: the per-row host fallback
    odd = rng.integers(0, 256, (3, 1000), dtype=np.uint8)
    assert crcmod.crc32c_rows(odd) == \
        [crcmod.crc32c(0xFFFFFFFF, r.tobytes()) for r in odd]
    # empty rows
    assert crcmod.crc32c_rows(np.zeros((2, 0), dtype=np.uint8)) == \
        [0xFFFFFFFF, 0xFFFFFFFF]


def test_batch_attribution_amortized_stage_math():
    """The coalescer's amortized marks: batch_wait + batch_encode
    partition the parked->encoded window, batch_encode gets exactly
    the tick's wall / batch size, and the stage sums stay equal to the
    traced total (the attribution invariant)."""
    from ceph_tpu.trace.attribution import attribute_events

    # an op parked at t=1.0; tick ran 2.0 -> 5.0 with 3 ops coalesced
    share = (5.0 - 2.0) / 3
    evs = [(0.0, "initiated"), (0.5, "dispatched"),
           (1.0, "batch_parked"),
           (5.0 - share, "batch_tick"), (5.0, "batch_encoded"),
           (5.2, "done")]
    stages, total = attribute_events(evs)
    assert abs(sum(stages.values()) - total) < 1e-9
    assert abs(stages["batch_encode"] - share) < 1e-9
    assert abs(stages["batch_wait"] - (4.0 - share)) < 1e-9
    assert stages["op_prepare"] == pytest.approx(0.5)


def test_commit_frontier_blocks_out_of_order_acks():
    """The pipelined-write watermark invariant: a later write's acks
    arriving first must NOT advance last_complete past an earlier
    still-pending write; a FAILED earlier write unblocks the later one
    (the pre-pipeline skip semantics)."""
    from ceph_tpu.cluster.pg import PGState
    from ceph_tpu.osdmap.osdmap import PGid

    from ceph_tpu.cluster.pg import PGLogMixin

    class _Store:
        def omap_get(self, coll, oid):
            return {}

        def queue_transaction(self, txn):
            pass

    class _Host(PGLogMixin):
        def __init__(self):
            self.store = _Store()

    h = _Host()
    st = PGState(PGid(1, 0))
    zero = st.last_complete
    v5, v6, v7 = (1, 5), (1, 6), (1, 7)
    for v in (v5, v6, v7):
        h._frontier_open(st, v)
    # commit starts log before their acks: the head covers the opens
    # (round 12: the watermark can never pass the log head)
    st.last_update = v7
    # v6 acks first: watermark must NOT move (v5 still pending)
    h._frontier_done(st, v6, ok=True)
    assert st.last_complete == zero
    # direct advances (recovery-style) are clamped below pending too
    h._advance_last_complete(st, v7)
    assert st.last_complete == zero
    # v5 fails: removed without blessing, v6's ack now advances to 6
    h._frontier_done(st, v5, ok=False)
    assert st.last_complete == v6
    # v7 acks: contiguous prefix advances to 7
    h._frontier_done(st, v7, ok=True)
    assert st.last_complete == v7


def test_frontier_rebuild_and_learn():
    """Round-12 crash-restart reconstruction: logged entries above the
    persisted watermark re-register as OPEN frontier entries, a
    post-restart fully-acked write can NOT advance the watermark past
    them, and an authoritative learn (peering roll-forward / primary
    entry stream) resolves them — while a rewind drops them."""
    from ceph_tpu.cluster.pg import PGLogMixin, PGState
    from ceph_tpu.cluster.pglog import LogEntry, PGLog
    from ceph_tpu.osdmap.osdmap import PGid
    from ceph_tpu.utils import PerfCounters

    class _Store:
        def omap_get(self, coll, oid):
            return {}

        def queue_transaction(self, txn):
            pass

    class _Host(PGLogMixin):
        def __init__(self):
            self.store = _Store()
            self.perf = PerfCounters("t")

    h = _Host()
    st = PGState(PGid(1, 0))
    st.last_complete = (1, 5)
    st.log = PGLog(entries=[
        LogEntry(op="modify", oid=f"o{s}", version=(1, s))
        for s in (4, 5, 6, 7, 8)])
    st.last_update = (1, 8)
    h._frontier_rebuild(st)
    # only the entries ABOVE the persisted watermark are open
    assert list(st.pipeline_pending) == [(1, 6), (1, 7), (1, 8)]
    assert st.frontier_recovering == {(1, 6), (1, 7), (1, 8)}
    # a new write fully acks out of order: watermark must NOT move
    h._frontier_open(st, (1, 9))
    st.last_update = (1, 9)
    h._frontier_done(st, (1, 9), ok=True)
    assert st.last_complete == (1, 5)
    # ... but reads may serve the resolved entry (read-your-ack)
    assert st.frontier_acked(9) and not st.frontier_acked(7)
    # peering verified every member holds up to 7: 6,7 resolve; 8 stays
    h._frontier_learn(st, (1, 7))
    assert st.last_complete == (1, 7)
    assert list(st.pipeline_pending) == [(1, 8), (1, 9)]
    assert st.frontier_recovering == {(1, 8)}
    # ... and verifying up to 8 sweeps straight through the resolved 9
    h._frontier_learn(st, (1, 8))
    assert st.last_complete == (1, 9)
    assert not st.pipeline_pending and not st.frontier_recovering

    # the rewind path, on a fresh reconstruction: divergent open
    # entries leave the frontier with the log (they can never ack)
    st2 = PGState(PGid(1, 1))
    st2.last_complete = (1, 2)
    st2.log = PGLog(entries=[
        LogEntry(op="modify", oid=f"r{s}", version=(1, s))
        for s in (3, 4)])
    st2.last_update = (1, 4)
    h._frontier_rebuild(st2)
    assert set(st2.pipeline_pending) == {(1, 3), (1, 4)}
    h.rewind_divergent_log(st2, (1, 3))
    assert list(st2.pipeline_pending) == [(1, 3)]
    assert st2.frontier_recovering == {(1, 3)}


def test_fast_config_enables_batched_data_plane():
    """The vstart config (tests, bench, chaos scenarios incl. the
    tier-1 overload-smoke run) exercises sharded dispatch + coalescing;
    plain Config() keeps the zero-default per-op path for bisection."""
    from ceph_tpu.utils import Config

    cfg = _fast_config()
    assert cfg.osd_op_shards > 0 and cfg.osd_batch_tick_ops > 0
    # round 18: the client edge coalesces too — same anchor rule
    assert cfg.objecter_batch_tick_ops > 0
    plain = Config()
    assert plain.osd_op_shards == 0 and plain.osd_batch_tick_ops == 0
    assert plain.objecter_batch_tick_ops == 0


# ---------------------------------------------------------- cluster level


async def _write_workload(cluster, concurrent: bool):
    """The shared workload: full writes across two EC profiles (a
    mixed-profile tick when concurrent) + an RMW partial write + a
    1-op-tick straggler + a replicated pool (full, partial, append,
    truncate, delete — the round-12 pipelined verbs).  Returns
    {pool_name: (pool_id, [oids])}."""
    client = await cluster.client()
    pool_a = await client.pool_create(
        "bxa", "erasure", pg_num=4,
        ec_profile={"plugin": "jerasure", "technique": "reed_sol_van",
                    "k": "2", "m": "1"})
    pool_b = await client.pool_create(
        "bxb", "erasure", pg_num=4,
        ec_profile={"plugin": "jerasure", "technique": "reed_sol_van",
                    "k": "3", "m": "2"})
    pool_r = await client.pool_create("bxr", "replicated", pg_num=4,
                                      size=3)
    io_a = client.ioctx(pool_a)
    io_b = client.ioctx(pool_b)
    io_r = client.ioctx(pool_r)
    rng = np.random.default_rng(42)
    jobs = []
    oids_a, oids_b = [], []
    for i in range(6):
        oid = f"obj_a{i}"
        oids_a.append(oid)
        payload = rng.integers(0, 256, 65536 + i * 4096,
                               dtype=np.uint8).tobytes()
        jobs.append((io_a, oid, payload))
    for i in range(4):
        oid = f"obj_b{i}"
        oids_b.append(oid)
        payload = rng.integers(0, 256, 49152, dtype=np.uint8).tobytes()
        jobs.append((io_b, oid, payload))
    if concurrent:
        await asyncio.gather(*(io.write_full(oid, payload, timeout=120)
                               for io, oid, payload in jobs))
    else:
        for io, oid, payload in jobs:
            await io.write_full(oid, payload, timeout=120)
    # RMW partial overwrite crossing a stripe boundary (no batch crc)
    patch = rng.integers(0, 256, 10000, dtype=np.uint8).tobytes()
    await io_a.write("obj_a0", patch, offset=5000, timeout=120)
    # EC append + truncate: round-12 pipelined compound verbs
    await io_a.append("obj_a1", b"\x5a" * 4096)
    await io_a.truncate("obj_a2", 30000)
    # 1-op tick: a lone write with nothing to coalesce against
    await io_a.write_full("obj_a_solo", b"\xa5" * 20480, timeout=120)
    oids_a.append("obj_a_solo")
    # replicated verbs through the same frontier path
    oids_r = []
    for i in range(3):
        oid = f"obj_r{i}"
        oids_r.append(oid)
        await io_r.write_full(
            oid, rng.integers(0, 256, 16384, dtype=np.uint8).tobytes(),
            timeout=120)
    await io_r.write("obj_r0", b"\x0f" * 777, offset=100, timeout=120)
    await io_r.append("obj_r1", b"\xf0" * 512)
    await io_r.truncate("obj_r2", 5000)
    await io_r.write_full("obj_r_gone", b"bye" * 100, timeout=120)
    await io_r.remove("obj_r_gone")
    oids_r.append("obj_r_gone")  # snapshot proves absence on BOTH paths
    return client, {"bxa": (pool_a, oids_a), "bxb": (pool_b, oids_b),
                    "bxr": (pool_r, oids_r)}


def _shard_snapshot(cluster, client, pools):
    """Every member's stored shard state per object: (bytes, shard,
    size, hinfo_crc) — the on-disk truth the two paths must agree on."""
    out = {}
    for pname, (pool, oids) in pools.items():
        for oid in oids:
            pgid = client.objecter.object_pgid(pool, oid)
            coll = _coll(pgid)
            for osd_id, osd in cluster.osds.items():
                if osd.store.stat(coll, oid) is None:
                    continue
                out[(pname, oid, osd_id)] = (
                    bytes(osd.store.read(coll, oid)),
                    osd.store.getattr(coll, oid, "shard"),
                    osd.store.getattr(coll, oid, "size"),
                    osd.store.getattr(coll, oid, "hinfo_crc"),
                )
    return out


@contention_retry()
def test_coalesced_writes_bit_exact_vs_per_op_path():
    """THE round-11 acceptance invariant: concurrent writes through
    sharded dispatch + coalescing leave every OSD's stored shards and
    CRCs byte-identical to the same writes issued serially through the
    legacy per-op path (mixed-profile ticks + RMW + 1-op tick
    included)."""
    async def run_path(coalesced: bool):
        cfg = _fast_config()
        if not coalesced:
            # the full round-10 serial anchor: per-op dispatch/encode
            # AND full-PG-lock commits (no pipelined frontier)
            cfg.osd_op_shards = 0
            cfg.osd_batch_tick_ops = 0
            cfg.osd_pipeline_writes = 0
        cluster = await start_cluster(5, config=cfg)
        try:
            client, pools = await _write_workload(
                cluster, concurrent=coalesced)
            snap = _shard_snapshot(cluster, client, pools)
            if coalesced:
                # every full write really rode the coalescer
                ticks = sum(o.perf.get("osd_batch_ticks")
                            for o in cluster.osds.values())
                coalesced_ops = sum(
                    o.perf.get("osd_batch_coalesced_ops")
                    for o in cluster.osds.values())
                assert ticks > 0 and coalesced_ops >= 12
            return snap
        finally:
            await cluster.stop()

    batched = run(run_path(True))
    serial = run(run_path(False))
    assert set(batched) == set(serial)
    for key in sorted(serial):
        assert batched[key] == serial[key], key


@contention_retry()
def test_client_batched_frames_bit_exact_vs_per_op_frames():
    """THE round-18 acceptance invariant: the SAME concurrent workload
    through MOSDOpBatch client frames vs legacy per-op MOSDOp frames
    (OSD-interior coalescing identical on both sides) leaves every
    OSD's stored shards and CRCs byte-identical — mixed verbs
    (write/RMW/append/truncate/delete), replicated + EC pools, and the
    1-op-tick straggler included."""
    async def run_path(client_batched: bool):
        cfg = _fast_config()
        if not client_batched:
            # the anchor: per-op client frames, everything else equal
            cfg.objecter_batch_tick_ops = 0
        cluster = await start_cluster(5, config=cfg)
        try:
            client, pools = await _write_workload(
                cluster, concurrent=True)
            snap = _shard_snapshot(cluster, client, pools)
            frames = sum(o.perf.get("osd_client_batch_frames")
                         for o in cluster.osds.values())
            items = sum(o.perf.get("osd_client_batch_items")
                        for o in cluster.osds.values())
            if client_batched:
                # the workload really rode batched client frames
                assert frames > 0 and items >= frames
                assert client.objecter.flow_counters()[
                    "client_batch_ticks"] > 0
            else:
                assert frames == 0 and items == 0
            return snap
        finally:
            await cluster.stop()

    batched = run(run_path(True))
    anchor = run(run_path(False))
    assert set(batched) == set(anchor)
    for key in sorted(anchor):
        assert batched[key] == anchor[key], key


@contention_retry()
def test_coalesced_concurrent_appends_apply_exactly_once():
    """Same-object concurrency under sharded dispatch: every append
    lands exactly once and the object stays readable (per-object
    ordering lives inside one shard by PG affinity)."""
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "bxo", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            await io.write_full("log", b"", timeout=120)
            pieces = [bytes([65 + i]) * 512 for i in range(8)]
            await asyncio.gather(
                *(io.append("log", p) for p in pieces))
            data = await io.read("log", timeout=120)
            assert len(data) == sum(len(p) for p in pieces)
            for p in pieces:
                assert data.count(p[:1]) == len(p)
        finally:
            await cluster.stop()

    run(scenario())
