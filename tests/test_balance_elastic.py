"""graft-balance end-to-end gates (round-21 satellites).

Four contracts:

1. **expand-drain-smoke in-band** — the tier-1 elastic scenario (grow
   3 -> 6 under writes, rebalance, drain back) passes its judges with
   a fixed seed; the seeded plan replays bit-identically.
2. **PG-split dup protection across the seam** — a mutation logged
   pre-split on an object that MIGRATES to a child PG is refused as a
   dup when resent post-split (pg.py's log split carries the reqid
   index with the objects), and every acked pre-split byte reads back.
3. **Disabled subsystem is provably a no-op** — with the default
   ``mgr_balancer_enabled=0``, a loaded cluster with a mgr shows zero
   balancer rounds, zero upmap items, zero reshape ops.
"""

import asyncio

import pytest

from tests._flaky import contention_retry

from ceph_tpu.chaos.balance import (
    build_elastic_plan,
    elastic_scenarios,
    run_elastic,
)
from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.vstart import _fast_config, start_cluster
from ceph_tpu.osdmap.osdmap import PGid


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------- seeded planning


def test_elastic_plan_bit_identical_replay():
    sc = elastic_scenarios(0.06)["expand-drain"]
    assert build_elastic_plan(sc, 7) == build_elastic_plan(sc, 7)
    assert build_elastic_plan(sc, 7) != build_elastic_plan(sc, 8)
    # the smoke shape is scale-independent: the listing's cheap entry
    smoke_a = elastic_scenarios(0.03)["expand-drain-smoke"]
    smoke_b = elastic_scenarios(1.0)["expand-drain-smoke"]
    assert smoke_a == smoke_b


# ------------------------------------------------ the tier-1 e2e smoke


@pytest.mark.chaos
@contention_retry(attempts=2)
def test_expand_drain_smoke_passes():
    """The full elastic cycle at tier-1 size: load, grow 3->6, batched
    rebalance, HEALTH_OK bound, move budget, drain back, judged
    durability/acting/health/lockdep + SLO gates."""
    sc = elastic_scenarios(0.03)["expand-drain-smoke"]
    v = run(run_elastic(sc, 7))
    assert v.passed, v.failures


# -------------------------------- dup protection across the split seam


@contention_retry(attempts=4)
def test_pg_split_dup_protection_and_read_your_ack():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("seam", "replicated",
                                            pg_num=4, size=3)
            io = client.ioctx(pool)
            payload = {f"seam-{i}": (b"acked-%d " % i) * 40
                       for i in range(24)}
            for k, v in payload.items():
                await io.write_full(k, v)

            # pick an object that will MIGRATE: post-split seed >= 4
            def seed_at(oid, pg_num, mask):
                from ceph_tpu.ops.jenkins import str_hash_rjenkins
                from ceph_tpu.osdmap.osdmap import ceph_stable_mod
                return ceph_stable_mod(
                    str_hash_rjenkins(oid.encode()), pg_num, mask)

            mover = next(k for k in payload
                         if seed_at(k, 8, 7) >= 4)
            parent = client.objecter.object_pgid(pool, mover)

            # capture the pre-split logged reqid of the mover's write
            # from the parent primary's log
            _, _, _, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(parent)
            pst = cluster.osds[primary].pgs[parent]
            entry = next(e for e in pst.log.entries
                         if e.oid == mover
                         and getattr(e, "client_reqid", None))
            reqid = tuple(entry.client_reqid)

            await client.pool_set("seam", "pg_num", 8)
            for _ in range(300):
                if all(o.osdmap.pools[pool].pg_num == 8
                       for o in cluster.osds.values() if not o._stopped):
                    break
                await asyncio.sleep(0.1)

            child = client.objecter.object_pgid(pool, mover)
            assert child.seed >= 4, "picked object did not migrate"
            assert child != parent

            # read-your-ack through the seam: every acked byte reads
            for k, v in payload.items():
                assert await io.read(k, timeout=60) == v, k

            # resend the pre-split mutation to the child's primary with
            # its ORIGINAL reqid, as a non-idempotent op (append).  The
            # migrated log must refuse it as a dup: success reply, no
            # bytes applied, counted by osd_dup_ops_from_log.
            _, _, _, cprimary = \
                client.objecter.osdmap.pg_to_up_acting_osds(child)
            osd = cluster.osds[cprimary]
            cst = osd.pgs[child]
            assert cst.log.has_reqid(reqid), \
                "reqid index did not migrate with the split"

            replies = []

            class _Conn:
                async def send(self, msg):
                    replies.append(msg)

            msg = M.MOSDOp(reqid=reqid, pgid=child, oid=mover,
                           ops=[("append", {"data": b"DOUBLE-APPLY"})],
                           epoch=osd.osdmap.epoch)
            before = osd.perf.get("osd_dup_ops_from_log")
            await osd._handle_client_op(_Conn(), msg)
            # execution is detached from dispatch (sharded op queue):
            # wait for the reply to come back through the fake conn
            for _ in range(200):
                if replies:
                    break
                await asyncio.sleep(0.05)
            assert replies and replies[-1].result == 0, replies
            assert osd.perf.get("osd_dup_ops_from_log") == before + 1
            assert await io.read(mover, timeout=60) == payload[mover], \
                "dup resend re-applied across the split seam"
        finally:
            await cluster.stop()

    run(scenario())


# ----------------------------------------- disabled subsystem is a no-op


def test_disabled_balance_subsystem_is_noop():
    async def scenario():
        cfg = _fast_config()  # mgr_balancer_enabled defaults to 0
        cluster = await start_cluster(4, config=cfg, with_mgr=True)
        try:
            client = await cluster.client()
            pool = await client.pool_create("idle", "replicated",
                                            pg_num=32, size=2)
            io = client.ioctx(pool)
            for i in range(24):
                await io.write_full(f"idle-{i}", b"x" * 512)
            # give any (wrongly) armed background loop time to tick
            await asyncio.sleep(max(
                0.3, cluster.mgr.config.mgr_balancer_interval / 8))
            assert getattr(cluster.mgr, "_balance_task", None) is None
            assert getattr(cluster.mgr, "_autoscale_task", None) is None
            # the counter families exist (scrape contract) and are zero
            for name in ("mgr_balancer_rounds",
                         "mgr_balancer_candidates",
                         "mgr_balancer_moves_proposed",
                         "mgr_balancer_moves_committed",
                         "mgr_autoscale_rounds",
                         "mgr_autoscale_splits"):
                assert cluster.mgr.perf.get(name) == 0, name
            # and the subsystem left no fingerprints on the map
            assert cluster.mon.osdmap.pg_upmap_items == {}
            assert cluster.mgr.reshaper.ops == {}
            status = await cluster.daemon_command("mgr",
                                                  "balance status")
            assert status["enabled"] is False
            assert status["reshape_ops"] == []
        finally:
            await cluster.stop()

    run(scenario())
