"""bench.py timing trust model: untrusted numbers can never be headline.

BENCH_NOTES.md round 5 showed `pipelined_untrusted` timings sample
host/tunnel enqueue rate, not device throughput — rounds 1-4 published
fiction that way.  The guard: a row whose mode is not `device_loop`-class
must carry ``"untrusted": true`` and a NULL ``vs_baseline``, so no
consumer of BENCH_r*.json can mistake an enqueue rate for a measured
speedup.  This test pins the JSON shape of both row classes.
"""

import importlib.util
import json
import pathlib
import sys


def _load_bench():
    path = pathlib.Path(__file__).parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("bench_module", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_module"] = mod
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()
PROV = {"baseline": 10.5, "baseline_src": "measured"}


def test_untrusted_rows_lose_ratio_and_are_flagged():
    row = bench._metric_row("ec_encode_x", 49.8, "GB/s", 4.7, PROV,
                            "pipelined_untrusted", 49.0, 50.0)
    assert row["untrusted"] is True
    assert row["vs_baseline"] is None
    # provenance stays so the reader can see what WOULD have been claimed
    assert row["baseline"] == 10.5
    assert row["mode"] == "pipelined_untrusted"
    # and the row keeps serializing cleanly
    assert json.loads(json.dumps(row)) == row


def test_device_loop_rows_keep_ratio_and_are_not_flagged():
    row = bench._metric_row("ec_encode_x", 49.8, "GB/s", 4.7, PROV,
                            "device_loop", 49.0, 50.0)
    assert "untrusted" not in row
    assert row["vs_baseline"] == 4.7
    assert row["min"] == 49.0 and row["max"] == 50.0


def test_extra_fields_ride_through():
    row = bench._metric_row("cluster_io", 6.18, "MB/s", None,
                            {"baseline": None, "baseline_src": "unmeasured"},
                            "cluster_vstart", iops=5.9)
    assert row["iops"] == 5.9
    assert "untrusted" not in row
    assert row["vs_baseline"] is None
