"""crc32c: known vectors, ceph semantics, combine/zeros, device batch."""

import numpy as np
import pytest

from ceph_tpu.ops import crc32c as c


def _ref_crc(crc, data):
    for b in data:
        crc = int(c.CRC_TABLE[(crc ^ b) & 0xFF]) ^ (crc >> 8)
    return crc


def test_standard_check_value():
    # standard CRC-32C("123456789") with init/final inversion = 0xE3069283
    raw = c.crc32c(0xFFFFFFFF, b"123456789")
    assert (raw ^ 0xFFFFFFFF) == 0xE3069283


def test_matches_bytewise_reference():
    rng = np.random.default_rng(0)
    for n in [0, 1, 7, 255, 4096, 10000]:
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert c.crc32c(0xFFFFFFFF, data) == _ref_crc(0xFFFFFFFF, data)
        assert c.crc32c(0, data) == _ref_crc(0, data)


def test_zeros_and_null_buffer():
    for n in [1, 5, 100, 4096]:
        want = _ref_crc(0xDEADBEEF, bytes(n))
        assert c.crc32c_zeros(0xDEADBEEF, n) == want
        # ceph null-buffer convention
        assert c.crc32c(0xDEADBEEF, None, n) == want


def test_combine():
    rng = np.random.default_rng(1)
    a = rng.integers(0, 256, 1000, dtype=np.uint8).tobytes()
    b = rng.integers(0, 256, 777, dtype=np.uint8).tobytes()
    crc_a = c.crc32c(0xFFFFFFFF, a)
    crc_b = c.crc32c(0, b)
    assert c.crc32c_combine(crc_a, crc_b, len(b)) == c.crc32c(0xFFFFFFFF, a + b)


def test_device_batch_matches_host():
    rng = np.random.default_rng(2)
    for block in [32, 512]:
        data = rng.integers(0, 256, (64, block), dtype=np.uint8)
        got = np.asarray(c.crc32c_batch(data))
        want = np.array(
            [c.crc32c(0xFFFFFFFF, row.tobytes()) for row in data],
            dtype=np.uint32,
        )
        assert np.array_equal(got, want)
    # non-default seed
    data = rng.integers(0, 256, (8, 64), dtype=np.uint8)
    got = np.asarray(c.crc32c_batch(data, seed=123))
    want = np.array([c.crc32c(123, r.tobytes()) for r in data], dtype=np.uint32)
    assert np.array_equal(got, want)
