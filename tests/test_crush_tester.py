"""CrushTester batch verifier + device classes + non-straw2 bucket algs
through the full rule VM.

Reference: CrushTester::test distribution checks (CrushTester.cc:472),
CrushWrapper device classes (shadow per-class trees).
"""

import pytest

from ceph_tpu.crush import CrushMap, Rule, ScalarMapper, Tunables, Bucket
from ceph_tpu.crush.tester import CrushTester
from ceph_tpu.crush.types import (
    CRUSH_ITEM_NONE,
    ChooseArg,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_EMIT,
    RULE_TAKE,
    build_hierarchy,
)


def _flat_map(alg: str, n: int = 12, numrep: int = 3):
    cmap = CrushMap(Tunables())
    root = cmap.add_bucket(Bucket(
        id=0, type=3, alg=alg, items=list(range(n)),
        weights=[0x10000 * (1 + i % 3) for i in range(n)]), name="root")
    cmap.add_rule(Rule(steps=[
        (RULE_TAKE, root, 0),
        (RULE_CHOOSE_FIRSTN, numrep, 0),
        (RULE_EMIT, 0, 0)]))
    return cmap


@pytest.mark.parametrize("alg", ["straw2", "list", "tree", "straw"])
def test_tester_distribution_tracks_weights(alg):
    cmap = _flat_map(alg)
    report = CrushTester(cmap).test(0, 3, 0, 2047)
    assert report.n_inputs == 2048
    assert not report.bad_mappings
    assert report.total_placements == 2048 * 3
    # distribution follows the 1:2:3 weight pattern within tolerance
    assert report.max_deviation < 0.03, report.summary()
    heavy = report.device_counts[2]   # weight 3
    light = report.device_counts[0]   # weight 1
    assert heavy > light * 1.8, report.summary()


def test_tester_reports_bad_mappings():
    # 3 devices, numrep 4: every mapping is short
    cmap = _flat_map("straw2", n=3, numrep=4)
    report = CrushTester(cmap).test(0, 4, 0, 63)
    assert len(report.bad_mappings) == 64


def test_tester_respects_reweight():
    cmap = _flat_map("straw2")
    w = [0x10000] * 12
    w[0] = 0  # fully reweighted out
    report = CrushTester(cmap).test(0, 3, 0, 1023, weights=w)
    assert report.device_counts.get(0, 0) == 0


def test_choose_args_shift_distribution():
    cmap = _flat_map("straw2")
    # flatten every weight to equal via choose_args: distribution evens out
    cmap.choose_args["balanced"] = {
        -1: ChooseArg(weight_set=[[0x10000] * 12])}
    base = CrushTester(cmap).test(0, 3, 0, 2047)
    bal = CrushTester(cmap).test(0, 3, 0, 2047, choose_args="balanced")
    spread_base = max(base.device_counts.values()) / \
        min(base.device_counts.values())
    spread_bal = max(bal.device_counts.values()) / \
        min(bal.device_counts.values())
    assert spread_bal < spread_base
    assert spread_bal < 1.25


def test_device_classes_shadow_tree():
    cmap, _ = build_hierarchy(4, 2, numrep=3)
    # tag half the devices ssd, half hdd
    for dev in range(8):
        cmap.set_device_class(dev, "ssd" if dev % 2 == 0 else "hdd")
    root = max(cmap.buckets,
               key=lambda b: cmap.buckets[b].type)
    shadow = cmap.class_root(root, "ssd")
    rule = cmap.add_rule(Rule(steps=[
        (RULE_TAKE, shadow, 0),
        (RULE_CHOOSELEAF_FIRSTN, 2, 1),
        (RULE_EMIT, 0, 0)]))
    sm = ScalarMapper(cmap)
    for x in range(128):
        out = sm.do_rule(rule, x, 2, [0x10000] * 8)
        for d in out:
            if d != CRUSH_ITEM_NONE:
                assert cmap.device_class[d] == "ssd", (x, out)
    # shadow weight = exactly the 4 ssd devices at 0x10000 each
    assert cmap.buckets[shadow].weight == 4 * 0x10000
    with pytest.raises(ValueError):
        cmap.class_root(root, "nvme")
