"""Checksummer + xxhash tests."""

import numpy as np
import pytest

from ceph_tpu.ops.checksum import Checksummer, xxhash32, xxhash64


def test_xxh32_known_vectors():
    # published XXH32 vectors
    assert xxhash32(b"") == 0x02CC5D05
    assert xxhash32(b"", seed=1) == 0x0B2CB792
    assert xxhash32(b"a") == 0x550D7456
    assert xxhash32(b"abc") == 0x32D153FF
    assert xxhash32(b"Hello, world!") == 0x31B7405D


def test_xxh64_known_vectors():
    assert xxhash64(b"") == 0xEF46DB3751D8E999
    assert xxhash64(b"a") == 0xD24EC4F1A98C6E5B
    assert xxhash64(b"abc") == 0x44BC2CF5AD770999


def test_checksummer_roundtrip():
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, 4096 * 4, dtype=np.uint8).tobytes()
    for algo in ("crc32c", "crc32c_16", "crc32c_8", "xxhash32", "xxhash64"):
        cs = Checksummer(algo)
        vec = cs.calculate(4096, data)
        assert len(vec) == 4 * cs.VALUE_SIZE[algo]
        assert cs.verify(4096, data, vec) is None
        # corrupt second block
        bad = bytearray(data)
        bad[5000] ^= 0xFF
        assert cs.verify(4096, bytes(bad), vec) == 4096


def test_crc32c_batch_path_matches_scalar():
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, 512 * 16, dtype=np.uint8).tobytes()
    cs = Checksummer("crc32c")
    batched = cs.calculate(512, data)          # 16 blocks -> batch path
    scalar = b"".join(
        cs.calculate(512, data[i * 512 : (i + 1) * 512]) for i in range(16))
    assert batched == scalar
