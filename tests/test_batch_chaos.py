"""Round-12 crash-safe batched data plane: batch-aware fault injection,
tick-boundary crash points, frontier recovery, and the per-item failure
semantics of the sub-write batcher.

Tier-1 pieces are structural (unit semantics + the seeded batch-smoke
scenario with its replay contract); the heavier crash-point matrix and
the rolling-restart soak are slow-marked.
"""

import asyncio

import pytest

from ceph_tpu.chaos.counters import CHAOS
from ceph_tpu.chaos.net import NetInjector
from ceph_tpu.chaos.rng import stream
from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.vstart import _fast_config, start_cluster


def run(coro):
    return asyncio.run(coro)


def _counters():
    return dict(CHAOS.dump()["chaos"])


# ------------------------------------------------ batch-frame injection


def _frame(n):
    return M.MOSDECSubOpWriteBatch(
        items=[M.MOSDECSubOpWrite(reqid=("c", i), shard=i % 3)
               for i in range(n)],
        epoch=1)


def test_batch_item_drop_partial_and_deterministic():
    """Item drop delivers a PARTIAL frame (never empties it), counts
    the loss, and replays bit-identically from the same seed."""
    before = _counters().get("net_batch_item_drops", 0)
    inj = NetInjector(stream(5, "t"), batch_item_drop=0.5)
    frame = _frame(12)
    inj.mutate_batch(frame)
    assert 1 <= len(frame.items) < 12
    dropped = 12 - len(frame.items)
    assert _counters()["net_batch_item_drops"] == before + dropped
    # same seed, same frame shape -> identical surviving membership
    frame2 = _frame(12)
    NetInjector(stream(5, "t"), batch_item_drop=0.5).mutate_batch(frame2)
    assert [it.reqid for it in frame2.items] == \
        [it.reqid for it in frame.items]
    # extreme rate still leaves one item (whole-frame loss is
    # chaos_net_drop's job, which keeps retransmission semantics)
    frame3 = _frame(6)
    NetInjector(stream(1, "x"), batch_item_drop=1.0).mutate_batch(frame3)
    assert len(frame3.items) == 1


def test_batch_ack_dup_and_reorder():
    inj = NetInjector(stream(9, "a"), batch_ack_dup=1.0)
    reply = M.MOSDECSubOpWriteBatchReply(
        results=[(("c", i), 0, i) for i in range(4)])
    inj.mutate_batch(reply)
    assert len(reply.results) == 8  # every entry duplicated
    inj2 = NetInjector(stream(9, "b"), batch_ack_reorder=1.0)
    reply2 = M.MOSDECSubOpWriteBatchReply(
        results=[(("c", i), 0, i) for i in range(8)])
    orig = list(reply2.results)
    inj2.mutate_batch(reply2)
    assert sorted(reply2.results) == sorted(orig)  # same set, any order


def test_injector_none_with_only_batch_rates_off():
    from ceph_tpu.utils import Config

    cfg = Config()
    assert NetInjector.from_config(cfg, "osd.0") is None
    cfg.chaos_net_batch_item_drop = 0.3
    inj = NetInjector.from_config(cfg, "osd.0")
    assert inj is not None and inj.batch_item_drop == 0.3


# ------------------------------- sub-write batcher per-item semantics


class _FakeOSD:
    """Just enough OSD for SubWriteBatcher: recordable sends with
    per-target failure injection."""

    def __init__(self):
        from ceph_tpu.utils import Config, PerfCounters

        self._stopped = False
        self.config = Config(osd_batch_tick_ops=16)
        self.perf = PerfCounters("osd.fake")
        self.sent = []          # (target, type-name, n_items)
        self.fail_targets = set()
        self.gate = None        # optional: holds sends until released

        class _Map:
            epoch = 7

        self.osdmap = _Map()
        self._tasks = set()

    def _track(self, task):
        from ceph_tpu.utils.tasks import track_task

        return track_task(self._tasks, task)

    def _chaos_point(self, name):
        pass

    async def _send_osd(self, target, msg):
        if self.gate is not None:
            await self.gate.wait()
        if target in self.fail_targets:
            raise ConnectionError(f"peer osd.{target} dead")
        n = len(msg.items) if hasattr(msg, "items") else 1
        self.sent.append((target, type(msg).__name__, n))


def test_subwrite_batcher_failure_unacks_only_affected_ops():
    """THE per-item failure contract: a failed send of one peer's frame
    must fail exactly the ops whose sub-writes rode it — the other
    peer's frames (other ops' shards) deliver, and nothing waits
    forever."""
    from ceph_tpu.cluster.batcher import SubWriteBatcher

    async def scenario():
        osd = _FakeOSD()
        b = SubWriteBatcher(osd)
        osd.fail_targets = {1}

        async def op(name):
            # one op fans out to peers 1 and 2, like an EC stripe
            results = await asyncio.gather(
                b.send(1, M.MOSDECSubOpWrite(reqid=(name, 1), shard=0)),
                b.send(2, M.MOSDECSubOpWrite(reqid=(name, 1), shard=1)),
                return_exceptions=True)
            return results

        rx, ry = await asyncio.gather(op("x"), op("y"))
        for res in (rx, ry):
            assert isinstance(res[0], ConnectionError)  # peer 1 leg
            assert res[1] is None                       # peer 2 leg
        # peer 2 actually received both ops' sub-writes
        assert sum(n for t, _k, n in osd.sent if t == 2) == 2
        # a transient failure must not wedge the path: heal peer 1 and
        # a NEW send succeeds (the worker re-arms; nothing waits
        # forever behind the dead frame)
        osd.fail_targets = set()
        ok = await asyncio.wait_for(
            b.send(1, M.MOSDECSubOpWrite(reqid=("z", 1), shard=0)),
            timeout=5.0)
        assert ok is None
        assert any(t == 1 for t, _k, _n in osd.sent)

    run(scenario())


def test_subwrite_batcher_coalesces_same_target_into_one_frame():
    """Items queued while a frame is in flight ride the NEXT frame
    together: one MOSDECSubOpWriteBatch, one transport ack."""
    from ceph_tpu.cluster.batcher import SubWriteBatcher

    async def scenario():
        osd = _FakeOSD()
        osd.gate = asyncio.Event()
        b = SubWriteBatcher(osd)
        first = asyncio.ensure_future(
            b.send(3, M.MOSDECSubOpWrite(reqid=("a", 1), shard=0)))
        await asyncio.sleep(0)  # worker parks inside the gated send
        rest = [asyncio.ensure_future(
            b.send(3, M.MOSDECSubOpWrite(reqid=(f"b{i}", 1), shard=0)))
            for i in range(3)]
        await asyncio.sleep(0)
        osd.gate.set()
        await asyncio.gather(first, *rest)
        kinds = [(k, n) for _t, k, n in osd.sent]
        # first item went alone (self-clocking); the 3 queued behind it
        # shared ONE multi-item frame
        assert ("MOSDECSubOpWrite", 1) in kinds
        assert ("MOSDECSubOpWriteBatch", 3) in kinds

    run(scenario())


# ----------------------------------------------- crash points (cluster)


def test_crash_point_fires_and_cluster_recovers():
    """Arm commit_pre_fanout on a primary: the daemon power-cuts itself
    mid-write (after frontier open + local apply, before any sub-write
    leaves), the cluster's bookkeeping absorbs the crash, and after a
    revive every acked write reads back bit-exact — the write caught by
    the crash either fails or lands whole via client retry, never
    torn."""

    async def scenario():
        import os

        cluster = await start_cluster(4, config=_fast_config())
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "cp", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            datas = {f"o{i}": os.urandom(8192) for i in range(4)}
            for oid, d in datas.items():
                await io.write_full(oid, d)
            pgid = client.objecter.object_pgid(pool, "o0")
            _, _, _, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            before = _counters().get("crash_points_fired", 0)
            cluster.osds[primary].config.injectargs(
                {"chaos_crash_point": "commit_pre_fanout"})
            # the overwrite that trips the crash retries onto the
            # post-peering acting set and must land whole
            new = os.urandom(8192)
            await io.write_full("o0", new, timeout=60)
            datas["o0"] = new
            await cluster.drain_chaos()
            assert _counters()["crash_points_fired"] == before + 1
            assert primary not in cluster.osds  # bookkeeping coherent
            await cluster.revive_osd(primary)
            deadline = asyncio.get_event_loop().time() + 30
            while asyncio.get_event_loop().time() < deadline:
                if cluster.mon.osdmap.osd_up[primary]:
                    break
                await asyncio.sleep(0.1)
            for oid, d in datas.items():
                got = None
                err = None
                while asyncio.get_event_loop().time() < deadline:
                    try:
                        got = await io.read(oid, timeout=30)
                        err = None
                    except (IOError, OSError) as e:
                        err = e
                        await asyncio.sleep(0.25)
                        continue
                    if got == d:
                        break
                    await asyncio.sleep(0.25)
                assert got == d, (oid, err)
        finally:
            await cluster.stop()

    run(scenario())


# ------------------------------------------------- builtin scenarios


@pytest.mark.chaos
def test_batch_smoke_scenario(tmp_path):
    """Tier-1 batch-chaos gate: seeded partial-frame drops + dup'd/
    shuffled batched acks + one tick-boundary crash point under
    concurrent EC writes on FileStore — zero durability/frontier
    violations, and the fault SCHEDULE (crash point, victim, skip
    count) resolves bit-identically from the seed.  (The double-run
    verdict-replay gate is the slow-marked twin below — one scenario
    run keeps the load-sensitive tier-1 budget honest.)"""
    from ceph_tpu.chaos.scenario import (
        build_schedule,
        builtin_scenarios,
        run_scenario,
    )

    sc = builtin_scenarios()["batch-smoke"]
    s1, s2 = build_schedule(sc, 31), build_schedule(sc, 31)
    assert s1 == s2
    cp = [e for e in s1 if e["action"] == "crash_point"]
    assert cp and cp[0]["args"]["point"] == "commit_mid_fanout"
    assert "at" in cp[0]["args"]  # seed-resolved deterministic timing
    # schedules vary across seeds (seed-driven, not hardcoded)
    assert any(build_schedule(sc, s) != s1 for s in range(8))
    v1 = run(run_scenario(sc, 31, tmpdir=str(tmp_path / "a")))
    assert v1.passed, v1.failures
    assert v1.schedule == s1


@pytest.mark.chaos
@pytest.mark.slow
def test_batch_smoke_scenario_replays_bit_identical(tmp_path):
    """The full replay contract: batch-smoke TWICE from one seed —
    identical schedule, identical PASS verdict, and the injected
    per-item batch faults provably fired."""
    from ceph_tpu.chaos.scenario import builtin_scenarios, run_scenario

    sc = builtin_scenarios()["batch-smoke"]
    v1 = run(run_scenario(sc, 31, tmpdir=str(tmp_path / "a")))
    v2 = run(run_scenario(sc, 31, tmpdir=str(tmp_path / "b")))
    assert v1.passed, v1.failures
    assert v2.passed, v2.failures
    assert v1.replay_key() == v2.replay_key()
    # the injected batch faults actually fired (frame composition is
    # transport-timing dependent, so judged across the two runs; the
    # mutator's per-item semantics are unit-proven deterministically)
    drops = v1.counters.get("net_batch_item_drops", 0) + \
        v2.counters.get("net_batch_item_drops", 0)
    assert drops > 0, (v1.counters, v2.counters)


@pytest.mark.chaos
@pytest.mark.slow
def test_batch_kill_midtick_scenario(tmp_path):
    """Crash points across the commit pipeline (peer mid-batch-apply,
    post-encode, pre-frontier-done) + per-item drops: durability +
    frontier + scrub all hold."""
    from ceph_tpu.chaos.scenario import builtin_scenarios, run_scenario

    v = run(run_scenario(builtin_scenarios()["batch-kill-midtick"], 17,
                         tmpdir=str(tmp_path)))
    assert v.passed, v.failures
    assert v.counters.get("crash_points_fired", 0) >= 1


@pytest.mark.chaos
@pytest.mark.slow
def test_rolling_restart_sharded_scenario(tmp_path):
    """ROADMAP item-5 flavor: bounce several OSDs under sustained
    writes on the sharded WQ — bounded time-to-HEALTH_OK (the health
    invariant inside converge_timeout) with zero durability/frontier
    violations, and the frontier watermark monotone across every
    store-preserving bounce."""
    from ceph_tpu.chaos.scenario import builtin_scenarios, run_scenario

    v = run(run_scenario(
        builtin_scenarios()["rolling-restart-sharded"], 13,
        tmpdir=str(tmp_path)))
    assert v.passed, v.failures
    assert v.counters.get("daemon_restarts") == 4


# ------------------------------------- tick composition determinism


def test_sharded_wq_tick_composition_is_seed_stable():
    """Chaos replays on the sharded WQ: PG->shard placement is a pure
    function (same pgid, same shard, across runs and processes), so a
    seeded scenario's ops meet the same shard queues both runs; the
    fault side (schedules, batch mutations, crash skip counts) derives
    from seeded streams — together the replay contract of
    test_batch_smoke_scenario_replays_bit_identical."""
    from ceph_tpu.cluster.sharded_wq import ShardedOpWQ
    from ceph_tpu.osdmap.osdmap import PGid

    class _O:
        class config:
            osd_op_queue = "fifo"
            osd_batch_tick_ops = 16

    a = ShardedOpWQ(_O(), 4)
    b = ShardedOpWQ(_O(), 4)
    for pool in range(3):
        for seed in range(32):
            assert a.shard_for(PGid(pool, seed)).idx == \
                b.shard_for(PGid(pool, seed)).idx
    # and the batch mutator consumes per-frame draws deterministically
    inj1 = NetInjector(stream(3, "net:osd.1"), batch_item_drop=0.4)
    inj2 = NetInjector(stream(3, "net:osd.1"), batch_item_drop=0.4)
    for n in (4, 7, 2, 9):
        f1, f2 = _frame(n), _frame(n)
        inj1.mutate_batch(f1)
        inj2.mutate_batch(f2)
        assert [i.reqid for i in f1.items] == [i.reqid for i in f2.items]
