"""LRC plugin tests.

Scenario coverage mirrors the reference's TestErasureCodeLrc.cc: kml profile
generation, explicit mapping+layers profiles, locality-aware
minimum_to_decode (single erasure reads only the local group), layered
encode/decode roundtrips, and rule-step generation.
"""

import json

import numpy as np
import pytest

from ceph_tpu.ec import factory
from ceph_tpu.ec.interface import ECError
from ceph_tpu.ec.lrc import ErasureCodeLrc, make_lrc


def test_kml_profile_generation():
    codec = make_lrc({"k": "4", "m": "2", "l": "3"})
    # (k+m)/l = 2 local groups of l+1 = 4 slots each
    assert codec.get_chunk_count() == 8
    assert codec.get_data_chunk_count() == 4
    # one global layer + one local layer per group
    assert len(codec.layers) == 3
    assert codec.layers[0].chunks_map == "DDc_DDc_"
    assert codec.layers[1].chunks_map == "DDDc____"
    assert codec.layers[2].chunks_map == "____DDDc"
    # kml-generated internals are not exposed through the profile
    assert "mapping" not in codec.get_profile()
    assert "layers" not in codec.get_profile()


def test_kml_constraint_errors():
    with pytest.raises(ECError):
        make_lrc({"k": "4", "m": "2"})  # l missing
    with pytest.raises(ECError):
        make_lrc({"k": "4", "m": "2", "l": "5"})  # (k+m) % l != 0
    with pytest.raises(ECError):
        make_lrc({"k": "4", "m": "2", "l": "3", "mapping": "DD"})


def test_kml_roundtrip_single_erasure():
    codec = make_lrc({"k": "4", "m": "2", "l": "3"})
    data = bytes(range(256)) * 13
    n = codec.get_chunk_count()
    chunks = codec.encode(range(n), data)
    assert len(chunks) == n
    for erase in range(n):
        avail = {i: c for i, c in chunks.items() if i != erase}
        decoded = codec.decode({erase}, avail)
        assert np.array_equal(decoded[erase], chunks[erase]), f"chunk {erase}"
    assert codec.decode_concat(chunks)[: len(data)] == data


def test_kml_roundtrip_double_erasure():
    codec = make_lrc({"k": "4", "m": "2", "l": "3"})
    data = np.random.default_rng(7).integers(0, 256, 4096, dtype=np.uint8).tobytes()
    n = codec.get_chunk_count()
    chunks = codec.encode(range(n), data)
    # erase one data chunk in each local group: each local layer recovers its own
    avail = {i: c for i, c in chunks.items() if i not in (0, 4)}
    decoded = codec.decode({0, 4}, avail)
    assert np.array_equal(decoded[0], chunks[0])
    assert np.array_equal(decoded[4], chunks[4])


def test_minimum_to_decode_is_local():
    """A single erasure must read only the local group's survivors (size l),
    not k chunks from across the stripe (reference ErasureCodeLrc.cc:572)."""
    codec = make_lrc({"k": "4", "m": "2", "l": "3"})
    n = codec.get_chunk_count()
    # chunk 1 lives in local group {0,1,2,3} (layer map DDDc____)
    want = {1}
    avail = set(range(n)) - {1}
    minimum = codec.minimum_to_decode(want, avail)
    assert minimum == {0, 2, 3}
    assert len(minimum) == 3  # l survivors, not k=4

    # nothing missing: read exactly what is wanted
    assert codec.minimum_to_decode({2, 5}, set(range(n))) == {2, 5}


def test_minimum_to_decode_falls_back_to_global():
    codec = make_lrc({"k": "4", "m": "2", "l": "3"})
    n = codec.get_chunk_count()
    # two erasures in one local group exceed the local layer's m=1:
    # the global layer must take over
    want = {0}
    avail = set(range(n)) - {0, 1}
    minimum = codec.minimum_to_decode(want, avail)
    # recoverable: the read set must exclude the erased chunks
    assert 0 not in minimum and 1 not in minimum
    assert minimum <= avail
    # and decode proves it
    data = bytes(range(128)) * 31
    chunks = codec.encode(range(n), data)
    decoded = codec.decode({0, 1}, {i: chunks[i] for i in avail})
    assert np.array_equal(decoded[0], chunks[0])
    assert np.array_equal(decoded[1], chunks[1])


def test_minimum_to_decode_unrecoverable():
    codec = make_lrc({"k": "4", "m": "2", "l": "3"})
    n = codec.get_chunk_count()
    # 3 erasures in one group (2 data + its global parity + local parity
    # leaves too little): drop 0,1,2,3 entirely — clearly unrecoverable
    with pytest.raises(ECError):
        codec.minimum_to_decode({0}, set(range(n)) - {0, 1, 2, 3})


def test_explicit_layers_profile():
    profile = {
        "plugin": "lrc",
        "mapping": "__DD__DD",
        "layers": json.dumps([
            ["_cDD_cDD", ""],
            ["cDDD____", ""],
            ["____cDDD", ""],
        ]),
    }
    codec = factory(profile)
    assert codec.get_chunk_count() == 8
    assert codec.get_data_chunk_count() == 4
    data = bytes(range(64)) * 61
    chunks = codec.encode(range(8), data)
    for erase in range(8):
        avail = {i: c for i, c in chunks.items() if i != erase}
        decoded = codec.decode({erase}, avail)
        assert np.array_equal(decoded[erase], chunks[erase])
    assert codec.decode_concat(chunks)[: len(data)] == data


def test_layer_profile_override():
    profile = {
        "mapping": "DD__DD__",
        "layers": json.dumps([
            ["DDc_DDc_", {"plugin": "isa", "technique": "reed_sol_van"}],
            ["DDDc____", ""],
            ["____DDDc", ""],
        ]),
    }
    codec = make_lrc(profile)
    assert codec.layers[0].profile["plugin"] == "isa"
    assert codec.layers[1].profile["plugin"] == "jerasure"
    data = b"x" * 4096
    chunks = codec.encode(range(8), data)
    avail = {i: c for i, c in chunks.items() if i != 5}
    decoded = codec.decode({5}, avail)
    assert np.array_equal(decoded[5], chunks[5])


def test_rule_steps_kml():
    codec = make_lrc({"k": "4", "m": "2", "l": "3",
                      "crush-locality": "rack",
                      "crush-failure-domain": "host"})
    ops = [(s.op, s.type, s.n) for s in codec.rule_steps]
    assert ops == [("choose", "rack", 2), ("chooseleaf", "host", 4)]


def test_create_rule_steps():
    from ceph_tpu.crush import types as ct

    codec = make_lrc({"k": "4", "m": "2", "l": "3",
                      "crush-locality": "rack",
                      "crush-failure-domain": "host"})
    cmap, _ = ct.build_three_level(3, 2, 2)
    ruleno = codec.create_rule("lrcrule", cmap)
    rule = cmap.rules[ruleno]
    opcodes = [s[0] for s in rule.steps]
    assert opcodes == [
        ct.RULE_SET_CHOOSELEAF_TRIES, ct.RULE_SET_CHOOSE_TRIES,
        ct.RULE_TAKE, ct.RULE_CHOOSE_INDEP, ct.RULE_CHOOSELEAF_INDEP,
        ct.RULE_EMIT,
    ]
    assert rule.type == 3
    assert rule.max_size == 8


def test_crush_steps_json_profile():
    profile = {
        "mapping": "DD__DD__",
        "layers": json.dumps([
            ["DDc_DDc_", ""],
            ["DDDc____", ""],
            ["____DDDc", ""],
        ]),
        "crush-steps": json.dumps([["choose", "rack", 2],
                                   ["chooseleaf", "host", 4]]),
    }
    codec = make_lrc(profile)
    ops = [(s.op, s.type, s.n) for s in codec.rule_steps]
    assert ops == [("choose", "rack", 2), ("chooseleaf", "host", 4)]


def test_registry_exposes_lrc():
    codec = factory({"plugin": "lrc", "k": "4", "m": "2", "l": "3"})
    assert isinstance(codec, ErasureCodeLrc)


def test_batch_encode_matches_single():
    import numpy as np

    codec = make_lrc({"plugin": "lrc", "k": "4", "m": "2", "l": "3"})
    n = codec.get_chunk_count()
    k = codec.get_data_chunk_count()
    rng = np.random.default_rng(21)
    batch = rng.integers(0, 256, (4, k, 64), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(batch))
    assert parity.shape == (4, n - k, 64)
    # compare each stripe against the single-stripe encode_chunks path
    for b in range(4):
        chunks = {
            codec.chunk_index(i): batch[b, i].copy() for i in range(k)
        }
        for i in range(k, n):
            chunks[codec.chunk_index(i)] = np.zeros(64, dtype=np.uint8)
        codec.encode_chunks(chunks)
        for i in range(n - k):
            pos = codec.chunk_index(k + i)
            assert np.array_equal(parity[b, i], chunks[pos]), (b, i)


def test_batch_decode_roundtrip():
    import numpy as np

    codec = make_lrc({"plugin": "lrc", "k": "4", "m": "2", "l": "3"})
    n = codec.get_chunk_count()
    k = codec.get_data_chunk_count()
    rng = np.random.default_rng(22)
    batch = rng.integers(0, 256, (4, k, 64), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(batch))
    full = np.concatenate([batch, parity], axis=1)
    # single local erasure: recovered from its local group
    zeroed = full.copy()
    zeroed[:, 1, :] = 0
    out = np.asarray(codec.decode_batch((1,), zeroed))
    assert np.array_equal(out[:, 0, :], batch[:, 1, :])
    # two erasures incl. a coding chunk
    zeroed = full.copy()
    zeroed[:, 0, :] = 0
    zeroed[:, k, :] = 0
    out = np.asarray(codec.decode_batch((0, k), zeroed))
    assert np.array_equal(out[:, 0, :], batch[:, 0, :])
    assert np.array_equal(out[:, 1, :], parity[:, 0, :])
