"""RBD real snapshots + clone/copy-up (round-4: the snapshot axis wired
through librbd's surface).

Reference: librbd snap_create (selfmanaged RADOS snaps + SnapContext),
snap_set + point-in-time reads, librbd::CloneRequest (COW children) and
CopyupRequest (partial child write materializes the parent object)."""

import asyncio

import pytest

from ceph_tpu.cluster.rbd import RBD
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


def test_rbd_snapshot_point_in_time_read():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rbds", "replicated",
                                            pg_num=8, size=2)
            rbd = RBD(client.ioctx(pool))
            await rbd.create("img", size=1 << 20, stripe_unit=4096,
                             stripe_count=2, object_size=16384)
            img = await rbd.open("img")
            v1 = bytes(range(256)) * 256            # 64 KiB
            await img.write(8192, v1)
            sid = await img.snap_create("s1")
            assert img.snap_list() == {"s1": sid}
            # overwrite part of the snapped range
            await img.write(12000, b"Y" * 30000)
            head = await img.read(8192, len(v1))
            assert head[12000 - 8192:12000 - 8192 + 30000] == b"Y" * 30000
            # the snap still reads the ORIGINAL bytes
            assert await img.read(8192, len(v1), snap_name="s1") == v1
            # a write AFTER the snap to a previously untouched region
            # must not appear in the snap
            await img.write(200000, b"Z" * 5000)
            assert await img.read(200000, 5000, snap_name="s1") == \
                b"\0" * 5000
            assert await img.read(200000, 5000) == b"Z" * 5000
        finally:
            await cluster.stop()

    run(scenario())


def test_rbd_snapshot_on_ec_pool():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "rbdecs", "erasure", pg_num=8,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            rbd = RBD(client.ioctx(pool))
            await rbd.create("eimg", size=1 << 20, stripe_unit=8192,
                             stripe_count=1, object_size=32768)
            img = await rbd.open("eimg")
            v1 = b"ec-snap-payload!" * 2048          # 32 KiB
            await img.write(0, v1)
            await img.snap_create("es1")
            await img.write(0, b"N" * len(v1))
            assert await img.read(0, len(v1)) == b"N" * len(v1)
            assert await img.read(0, len(v1), snap_name="es1") == v1
        finally:
            await cluster.stop()

    run(scenario())


def test_rbd_clone_and_copyup():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rbdc", "replicated",
                                            pg_num=8, size=2)
            rbd = RBD(client.ioctx(pool))
            await rbd.create("parent", size=1 << 20, stripe_unit=4096,
                             stripe_count=1, object_size=16384)
            parent = await rbd.open("parent")
            base = bytes(range(256)) * 128           # 32 KiB
            await parent.write(0, base)
            await parent.snap_create("gold")
            # parent diverges after the snap
            await parent.write(0, b"P" * 1000)

            await rbd.clone("parent", "gold", "child")
            child = await rbd.open("child")
            assert child.size() == 1 << 20
            # child reads fall through to the parent SNAP (not its head)
            assert await child.read(0, len(base)) == base
            # partial child write triggers copy-up: the rest of that
            # object must still show parent-snap bytes, not zeros
            await child.write(100, b"c" * 50)
            got = await child.read(0, 16384)
            expect = bytearray(base[:16384])
            expect[100:150] = b"c" * 50
            assert got == bytes(expect)
            # the parent snap and head are untouched by child writes
            assert await parent.read(0, 150, snap_name="gold") == base[:150]
            assert (await parent.read(0, 1000)) == b"P" * 1000
            # writes beyond parent data stay child-local
            await child.write(500000, b"only-child" * 10)
            assert await child.read(500000, 100) == b"only-child" * 10
        finally:
            await cluster.stop()

    run(scenario())


def test_rbd_snap_remove_triggers_trim():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client = await cluster.client()
            pool = await client.pool_create("rbdt", "replicated",
                                            pg_num=8, size=2)
            rbd = RBD(client.ioctx(pool))
            await rbd.create("timg", size=1 << 20)
            img = await rbd.open("timg")
            await img.write(0, b"A" * 4096)
            await img.snap_create("t1")
            await img.write(0, b"B" * 4096)
            assert await img.read(0, 4096, snap_name="t1") == b"A" * 4096
            await img.snap_remove("t1")
            assert img.snap_list() == {}
            with pytest.raises(KeyError):
                await img.read(0, 10, snap_name="t1")
            # head unaffected
            assert await img.read(0, 4096) == b"B" * 4096
        finally:
            await cluster.stop()

    run(scenario())
