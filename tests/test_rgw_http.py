"""RGW HTTP frontend: S3 REST + auth + multipart (round-4, VERDICT r3
missing #9; reference rgw_civetweb_frontend.cc / rgw_rest_s3.cc /
rgw_auth_s3.cc / multipart ops in rgw_op.cc)."""

import asyncio
import re
import time

import pytest

from ceph_tpu.cluster.rgw import RGW
from ceph_tpu.cluster.rgw_http import RGWFrontend
from ceph_tpu.cluster.vstart import start_cluster


async def _http(addr, method, path, body=b"", headers=None):
    """Minimal HTTP/1.1 client: -> (status, headers, body)."""
    reader, writer = await asyncio.open_connection(*addr)
    headers = dict(headers or {})
    headers["Content-Length"] = str(len(body))
    headers["Host"] = "s3.local"
    req = f"{method} {path} HTTP/1.1\r\n" + "".join(
        f"{k}: {v}\r\n" for k, v in headers.items()) + "\r\n"
    writer.write(req.encode() + body)
    await writer.drain()
    status_line = (await reader.readline()).decode()
    status = int(status_line.split(" ", 2)[1])
    rh = {}
    while True:
        line = (await reader.readline()).decode().strip()
        if not line:
            break
        k, v = line.split(":", 1)
        rh[k.strip().lower()] = v.strip()
    # HEAD advertises the entity's Content-Length but carries no body
    n = 0 if method == "HEAD" else int(rh.get("content-length", "0"))
    data = await reader.readexactly(n)
    writer.close()
    return status, rh, data


async def _gateway(cluster, accounts=None):
    client = await cluster.client()
    pool = await client.pool_create("rgw", "replicated", pg_num=8, size=2)
    fe = RGWFrontend(RGW(client.ioctx(pool)), accounts=accounts)
    addr = await fe.start()
    return fe, addr


def test_s3_rest_end_to_end():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            fe, addr = await _gateway(cluster)
            st, _, _ = await _http(addr, "PUT", "/bkt")
            assert st == 200
            st, h, _ = await _http(
                addr, "PUT", "/bkt/hello.txt", b"payload-bytes",
                {"Content-Type": "text/plain",
                 "x-amz-meta-owner": "round4"})
            assert st == 200 and "etag" in h
            st, h, body = await _http(addr, "GET", "/bkt/hello.txt")
            assert st == 200 and body == b"payload-bytes"
            assert h["content-type"] == "text/plain"
            assert h["x-amz-meta-owner"] == "round4"
            st, h, _ = await _http(addr, "HEAD", "/bkt/hello.txt")
            assert st == 200 and h["content-length"] == "13"
            # listing with prefix/marker XML
            for k in ("a/1", "a/2", "b/1"):
                await _http(addr, "PUT", f"/bkt/{k}", b"x")
            st, _, body = await _http(addr, "GET", "/bkt?prefix=a/")
            assert st == 200
            keys = re.findall(r"<Key>(.*?)</Key>", body.decode())
            assert keys == ["a/1", "a/2"]
            st, _, body = await _http(addr, "GET", "/")
            assert st == 200 and b"<Name>bkt</Name>" in body
            st, _, _ = await _http(addr, "DELETE", "/bkt/hello.txt")
            assert st == 204
            st, _, _ = await _http(addr, "GET", "/bkt/hello.txt")
            assert st == 404
            await fe.stop()
        finally:
            await cluster.stop()

    asyncio.run(scenario())


def test_s3_auth_required_and_enforced():
    async def scenario():
        cluster = await start_cluster(2)
        try:
            fe, addr = await _gateway(
                cluster, accounts={"AKIDEMO": "sekrit"})
            # unauthenticated -> 403
            st, _, body = await _http(addr, "PUT", "/locked")
            assert st == 403 and b"AccessDenied" in body
            # bad signature -> 403
            now = str(time.time())
            st, _, _ = await _http(addr, "PUT", "/locked", headers={
                "Authorization": "AWS AKIDEMO:deadbeef",
                "x-amz-date": now})
            assert st == 403
            # good signature -> 200, and the whole surface works signed
            def signed(method, path, body=b""):
                date = str(time.time())
                return {"Authorization": RGWFrontend.sign(
                    method, path, date, "AKIDEMO", "sekrit", body=body),
                    "x-amz-date": date}

            st, _, _ = await _http(addr, "PUT", "/locked",
                                   headers=signed("PUT", "/locked"))
            assert st == 200
            st, _, _ = await _http(addr, "PUT", "/locked/k", b"v",
                                   signed("PUT", "/locked/k", b"v"))
            assert st == 200
            st, _, body = await _http(addr, "GET", "/locked/k",
                                      headers=signed("GET", "/locked/k"))
            assert st == 200 and body == b"v"
            # ADVICE r4: a captured signature must not authorize a
            # DIFFERENT body (body digest is signed)...
            cap = signed("PUT", "/locked/k", b"v")
            st, _, _ = await _http(addr, "PUT", "/locked/k", b"EVIL", cap)
            assert st == 403
            # ...and a stale date is rejected (replay window)
            old = str(time.time() - 3600)
            st, _, _ = await _http(addr, "PUT", "/locked/k", b"v", {
                "Authorization": RGWFrontend.sign(
                    "PUT", "/locked/k", old, "AKIDEMO", "sekrit",
                    body=b"v"),
                "x-amz-date": old})
            assert st == 403
            await fe.stop()
        finally:
            await cluster.stop()

    asyncio.run(scenario())


def test_s3_multipart_upload():
    async def scenario():
        cluster = await start_cluster(2)
        try:
            fe, addr = await _gateway(cluster)
            await _http(addr, "PUT", "/mp")
            st, _, body = await _http(addr, "POST", "/mp/big?uploads")
            assert st == 200
            upload_id = re.search(r"<UploadId>(\w+)</UploadId>",
                                  body.decode()).group(1)
            p1, p2, p3 = b"A" * 7000, b"B" * 5000, b"C" * 100
            for n, part in ((2, p2), (1, p1), (3, p3)):  # out of order
                st, h, _ = await _http(
                    addr, "PUT",
                    f"/mp/big?partNumber={n}&uploadId={upload_id}", part)
                assert st == 200 and "etag" in h
            st, _, body = await _http(
                addr, "POST", f"/mp/big?uploadId={upload_id}")
            assert st == 200 and b"CompleteMultipartUploadResult" in body
            st, _, body = await _http(addr, "GET", "/mp/big")
            assert st == 200
            assert body == p1 + p2 + p3, "parts assembled out of order"
            # parts cleaned up: only the assembled object remains
            st, _, listing = await _http(addr, "GET", "/mp")
            assert re.findall(r"<Key>(.*?)</Key>", listing.decode()) \
                == ["big"]
            # completed upload id is gone
            st, _, _ = await _http(
                addr, "PUT", f"/mp/big?partNumber=1&uploadId={upload_id}",
                b"zz")
            assert st == 404
            await fe.stop()
        finally:
            await cluster.stop()

    asyncio.run(scenario())


def test_swift_api_surface():
    """The gateway's SECOND protocol (reference rgw_rest_swift.cc):
    container/object verbs + tempauth-lite tokens over the same core —
    an S3-written object reads back via Swift and vice versa."""
    async def scenario():
        cluster = await start_cluster(2)
        try:
            fe, addr = await _gateway(
                cluster, accounts={"swifty": "s3cr3t"})
            tok = {"X-Auth-Token": RGWFrontend.swift_token(
                "swifty", "s3cr3t")}
            # unauthenticated -> 401
            st, _, _ = await _http(addr, "PUT", "/swift/v1/cont")
            assert st == 401
            # container lifecycle
            st, _, _ = await _http(addr, "PUT", "/swift/v1/cont",
                                   headers=tok)
            assert st == 201
            st, _, _ = await _http(addr, "PUT", "/swift/v1/cont",
                                   headers=tok)
            assert st == 202           # already exists: Swift says 202
            # object put/get with user metadata
            st, h, _ = await _http(
                addr, "PUT", "/swift/v1/cont/obj.txt", b"swift-body",
                {**tok, "Content-Type": "text/plain",
                 "X-Object-Meta-Color": "blue"})
            assert st == 201 and "etag" in h
            st, h, body = await _http(addr, "GET", "/swift/v1/cont/obj.txt",
                                      headers=tok)
            assert st == 200 and body == b"swift-body"
            assert h["x-object-meta-color"] == "blue"
            # container listing (plain text, one key per line)
            st, _, body = await _http(addr, "GET", "/swift/v1/cont",
                                      headers=tok)
            assert st == 200 and body == b"obj.txt\n"
            # account listing
            st, _, body = await _http(addr, "GET", "/swift/v1",
                                      headers=tok)
            assert st == 200 and b"cont" in body
            # expired token refused
            st, _, _ = await _http(
                addr, "GET", "/swift/v1/cont",
                headers={"X-Auth-Token": RGWFrontend.swift_token(
                    "swifty", "s3cr3t", ttl=-5)})
            assert st == 401
            # token issuance endpoint (tempauth /auth/v1.0 analog)
            st, h, _ = await _http(addr, "GET", "/swift/auth", headers={
                "X-Auth-User": "swifty", "X-Auth-Key": "s3cr3t"})
            assert st == 200 and h.get("x-auth-token")
            st, _, _ = await _http(
                addr, "GET", "/swift/v1/cont",
                headers={"X-Auth-Token": h["x-auth-token"]})
            assert st == 200
            # cross-protocol: the same accounts sign S3 requests, and
            # the S3 side sees the Swift-written object
            date = str(time.time())
            sig = {"Authorization": RGWFrontend.sign(
                "GET", "/cont/obj.txt", date, "swifty", "s3cr3t"),
                "x-amz-date": date}
            st, _, body = await _http(addr, "GET", "/cont/obj.txt",
                                      headers=sig)
            assert st == 200 and body == b"swift-body"
            # delete via Swift
            st, _, _ = await _http(addr, "DELETE",
                                   "/swift/v1/cont/obj.txt", headers=tok)
            assert st == 204
            st, _, _ = await _http(addr, "GET", "/swift/v1/cont/obj.txt",
                                   headers=tok)
            assert st == 404
            await fe.stop()
        finally:
            await cluster.stop()

    asyncio.run(scenario())


def test_swift_edge_semantics():
    """Round-4 review fixes: prefix guard, directory markers, 409 on
    non-empty delete, 412 on bad limit, total object count header."""
    async def scenario():
        cluster = await start_cluster(2)
        try:
            fe, addr = await _gateway(cluster)
            # an S3 bucket literally named 'swift' stays on the S3 path
            st, _, _ = await _http(addr, "PUT", "/swift")
            assert st == 200
            st, _, _ = await _http(addr, "PUT", "/swift/v1.txt", b"s3!")
            assert st == 200
            st, _, body = await _http(addr, "GET", "/swift/v1.txt")
            assert st == 200 and body == b"s3!"
            # swift proper (no accounts -> open)
            st, _, _ = await _http(addr, "PUT", "/swift/v1/c")
            assert st == 201
            # pseudo-directory marker keeps its trailing slash
            st, _, _ = await _http(addr, "PUT", "/swift/v1/c/dir/", b"")
            assert st == 201
            st, _, _ = await _http(addr, "PUT", "/swift/v1/c/dir", b"real")
            assert st == 201
            st, _, listing = await _http(addr, "GET", "/swift/v1/c")
            assert set(listing.decode().split()) == {"dir", "dir/"}
            # total count header, independent of the page limit
            st, h, _ = await _http(addr, "GET", "/swift/v1/c?limit=1")
            assert h["x-container-object-count"] == "2"
            # bad limit -> 412, not 500
            st, _, _ = await _http(addr, "GET", "/swift/v1/c?limit=abc")
            assert st == 412
            # delete non-empty -> 409
            st, _, _ = await _http(addr, "DELETE", "/swift/v1/c")
            assert st == 409
            # account endpoint refuses mutations
            st, _, _ = await _http(addr, "DELETE", "/swift/v1")
            assert st == 405
            await fe.stop()
        finally:
            await cluster.stop()

    asyncio.run(scenario())
