"""MDS daemon: server-side metadata authority, journal replay, leases
(round-4 item 7).

Reference: MDSRank (src/mds/MDSRank.cc) request serving + boot replay,
MDLog write-ahead journaling (src/mds/journal.cc), Locker caps/leases
(src/mds/Locker.cc).  Single active MDS; the cls-atomic dirfrag engine
(cluster/fs.py) stays the storage layer underneath.
"""

import asyncio

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster.mds import JOURNAL_OID, MDSClient
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


async def _fs_cluster():
    cluster = await start_cluster(3)
    admin = await cluster.client()
    meta = await admin.pool_create("fsmeta", "replicated", pg_num=8, size=2)
    data = await admin.pool_create("fsdata", "replicated", pg_num=8, size=2)
    await cluster.start_mds(meta, data)
    # converge-poll to a wall deadline for the MDS registration
    # (round-11/12 pattern: iteration-bounded polls under host load
    # are fixed sleeps in disguise)
    deadline = asyncio.get_event_loop().time() + 20
    while asyncio.get_event_loop().time() < deadline:
        await admin.objecter._refresh_map()
        if getattr(admin.objecter.osdmap, "mds_addr", None):
            break
        await asyncio.sleep(0.05)
    assert getattr(admin.objecter.osdmap, "mds_addr", None), \
        "MDS never registered in the map"
    return cluster, admin, meta, data


def test_mds_namespace_and_file_io():
    async def scenario():
        cluster, admin, meta, data = await _fs_cluster()
        try:
            fs = MDSClient(admin, data)
            await fs.mkdir("/dir")
            await fs.create("/dir/file")
            payload = b"mds-routed-metadata, direct data" * 100
            await fs.write("/dir/file", 0, payload)
            assert await fs.read("/dir/file") == payload
            st = await fs.stat("/dir/file")
            assert st.size == len(payload)
            assert await fs.listdir("/dir") == ["file"]
            await fs.rename("/dir/file", "/dir/renamed")
            assert await fs.listdir("/dir") == ["renamed"]
            assert await fs.read("/dir/renamed") == payload
            await fs.unlink("/dir/renamed")
            with pytest.raises(FileNotFoundError):
                await fs.stat("/dir/renamed")
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_two_clients_coherent_under_concurrency():
    """Two clients hammer the same directory with creates + renames; the
    MDS serializes them — every op lands exactly once, names never
    duplicate or vanish (the round-4 'Done' gate for item 7)."""
    async def scenario():
        cluster, admin, meta, data = await _fs_cluster()
        try:
            c2 = await cluster.client("second")
            fs1 = MDSClient(admin, data)
            fs2 = MDSClient(c2, data)
            await fs1.mkdir("/race")

            async def creator(fs, tag, n):
                made = []
                for i in range(n):
                    try:
                        await fs.create(f"/race/{tag}{i}")
                        made.append(f"{tag}{i}")
                    except FileExistsError:
                        pass
                return made

            made1, made2 = await asyncio.gather(
                creator(fs1, "a", 8), creator(fs2, "b", 8))
            # exclusive-create semantics survived concurrency
            names = set(await fs1.listdir("/race"))
            assert set(made1) | set(made2) <= names
            assert len(names) == len(made1) + len(made2)
            # concurrent rename racing a create of the same target:
            # exactly one wins, nothing is lost
            r1 = fs1.rename("/race/a0", "/race/target")
            r2 = fs2.rename("/race/b0", "/race/target")
            results = await asyncio.gather(r1, r2, return_exceptions=True)
            fs1._lease.clear()
            names = set(await fs1.listdir("/race"))
            assert "target" in names
            survivors = {"a0", "b0"} & names
            failures = [r for r in results if isinstance(r, Exception)]
            # one rename won; the loser either failed loudly or
            # overwrote (last-writer-wins rename both being legal), but
            # no name may silently duplicate
            assert len(survivors) + 1 + len(names - {"target", "a0", "b0"}) \
                == len(names)
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_mds_restart_replays_journal():
    """Kill the MDS after journal append but before dirfrag apply; the
    restarted MDS must replay the event (MDSRank boot replay)."""
    async def scenario():
        cluster, admin, meta, data = await _fs_cluster()
        try:
            fs = MDSClient(admin, data)
            await fs.mkdir("/jd")
            await fs.create("/jd/before")
            # forge a journalled-but-unapplied event, as a crash between
            # append and apply would leave it
            mds = cluster.mds
            import pickle

            seq = mds._seq + 1
            await mds._journal_append(seq, ("create", "/jd/orphan"))
            await mds.stop()

            await cluster.start_mds(meta, data)
            deadline = asyncio.get_event_loop().time() + 20
            while asyncio.get_event_loop().time() < deadline:
                await admin.objecter._refresh_map()
                a = getattr(admin.objecter.osdmap, "mds_addr", None)
                if a and tuple(a) == tuple(cluster.mds_addr):
                    break
                await asyncio.sleep(0.05)
            else:
                raise TimeoutError(
                    "restarted MDS never re-registered in the map")
            fs2 = MDSClient(admin, data)
            names = set(await fs2.listdir("/jd"))
            assert "orphan" in names, "journal replay missed the event"
            assert "before" in names
            # the replayed event is applied-through (no double replay)
            assert cluster.mds._seq >= seq
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_mds_lease_caching():
    """stat/listdir replies carry a lease: repeated lookups inside the
    TTL are served from the client cache; mutations invalidate it."""
    async def scenario():
        cluster, admin, meta, data = await _fs_cluster()
        try:
            fs = MDSClient(admin, data)
            await fs.mkdir("/ld")
            await fs.create("/ld/f")
            before = cluster.mds.perf.get("mds_requests")
            for _ in range(5):
                await fs.stat("/ld/f")     # leased: one round-trip only
            mid = cluster.mds.perf.get("mds_requests")
            assert mid == before + 1
            await fs.create("/ld/g")        # mutation drops the lease
            await fs.listdir("/ld")
            assert cluster.mds.perf.get("mds_requests") > mid
        finally:
            await cluster.stop()

    run(scenario())
