"""RBD journaling + rbd-mirror replication (round-4, VERDICT r3
missing #10; reference src/journal/ + src/tools/rbd_mirror/)."""

import asyncio

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster.rbd import RBD
from ceph_tpu.cluster.rbd_mirror import MirrorDaemon
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


async def _pools(cluster):
    client = await cluster.client()
    a = await client.pool_create("site_a", "replicated", pg_num=8, size=2)
    b = await client.pool_create("site_b", "replicated", pg_num=8, size=2)
    return client, a, b


def test_journal_records_and_mirror_replays():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client, a, b = await _pools(cluster)
            rbd_a = RBD(client.ioctx(a))
            await rbd_a.create("img", size=1 << 20, journaling=True)
            img = await rbd_a.open("img")
            blob1 = bytes(range(256)) * 64
            await img.write(4096, blob1)
            await img.write(100_000, b"tail" * 50)

            mirror = MirrorDaemon(client.ioctx(a), client.ioctx(b))
            applied = await mirror.sync_once()
            assert applied == 2
            rbd_b = RBD(client.ioctx(b))
            mirrored = await rbd_b.open("img")
            assert await mirrored.read(4096, len(blob1)) == blob1
            assert await mirrored.read(100_000, 200) == b"tail" * 50
            # committed position trimmed the source journal
            omap = await client.ioctx(a).omap_get("rbd_journal.img")
            assert [k for k in omap if not k.startswith("_")] == []
            # idempotent: nothing new -> nothing replayed
            assert await mirror.sync_once() == 0

            # continuous replication incl. resize
            await img.resize(2 << 20)
            await img.write((1 << 20) + 5000, b"grown!" * 10)
            assert await mirror.sync_once() == 2
            mirrored = await rbd_b.open("img")
            assert mirrored.size() == 2 << 20
            assert await mirrored.read((1 << 20) + 5000, 60) == \
                b"grown!" * 10
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_mirror_daemon_background_catchup():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client, a, b = await _pools(cluster)
            rbd_a = RBD(client.ioctx(a))
            await rbd_a.create("live", size=1 << 20, journaling=True)
            img = await rbd_a.open("live")
            mirror = MirrorDaemon(client.ioctx(a), client.ioctx(b),
                                  poll_interval=0.05)
            mirror.start()
            payloads = []
            for i in range(5):
                p = f"gen{i}-".encode() * 100
                await img.write(i * 10_000, p)
                payloads.append((i * 10_000, p))
            # converge-poll to a wall deadline (round-11/12 pattern):
            # no fixed pacing sleeps — the journal preserves event
            # order however the poller's wakeups land, and an
            # iteration-bounded loop under host load is just a fixed
            # sleep in disguise
            deadline = asyncio.get_event_loop().time() + 20
            while asyncio.get_event_loop().time() < deadline and \
                    mirror.replayed < 5:
                await asyncio.sleep(0.05)
            assert mirror.replayed >= 5, "mirror never caught up"
            await mirror.stop()
            rbd_b = RBD(client.ioctx(b))
            mirrored = await rbd_b.open("live")
            for off, p in payloads:
                assert await mirrored.read(off, len(p)) == p, off
        finally:
            await cluster.stop()

    run(scenario())


def test_unjournaled_image_not_mirrored():
    async def scenario():
        cluster = await start_cluster(2)
        try:
            client, a, b = await _pools(cluster)
            rbd_a = RBD(client.ioctx(a))
            await rbd_a.create("plain", size=1 << 20)   # no journaling
            img = await rbd_a.open("plain")
            await img.write(0, b"local-only")
            mirror = MirrorDaemon(client.ioctx(a), client.ioctx(b))
            assert await mirror.sync_once() == 0
            with pytest.raises(FileNotFoundError):
                await RBD(client.ioctx(b)).open("plain")
        finally:
            await cluster.stop()

    run(scenario())
