"""Cluster-layer tests for the non-MDS EC plugin families: LRC and SHEC
pools end-to-end, including parity-shard loss and recovery.

The tier-3 analog of qa/standalone/erasure-code/test-erasure-code.sh's
per-plugin pool matrix (reference :21-53 creates EC pools for every
plugin and reads back with injected chunk deletion).
"""

import asyncio

from tests._flaky import contention_retry
import pytest

from ceph_tpu.cluster.vstart import _fast_config, start_cluster


def run(coro):
    return asyncio.run(coro)


def _coll(pgid):
    return f"pg_{pgid.pool}_{pgid.seed}"


@contention_retry()
def test_lrc_pool_end_to_end():
    async def scenario():
        cluster = await start_cluster(8)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "lrcp", "erasure", pg_num=4,
                ec_profile={"plugin": "lrc", "k": "4", "m": "2", "l": "3"})
            io = client.ioctx(pool)
            payload = b"lrc-payload" * 400
            await io.write_full("obj", payload, timeout=120)
            assert await io.read("obj", timeout=120) == payload

            # kill a shard holder; degraded read must still work
            pgid = client.objecter.object_pgid(pool, "obj")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            victim = next(o for o in acting if o != primary and o >= 0)
            await cluster.kill_osd(victim)
            await cluster.wait_down(victim)
            assert await io.read("obj", timeout=60) == payload
        finally:
            await cluster.stop()

    run(scenario())


def test_shec_pool_parity_shard_loss_recovers():
    """Losing a PARITY shard of a shec pool re-protects via the batched
    parity-recovery path (the NotImplementedError hole VERDICT r2 called
    out, reference ErasureCodeShec.cc:526-756)."""
    async def scenario():
        cfg = _fast_config()
        # 8 osds for 7 shards: a replacement member must exist after the
        # parity holder dies, or CRUSH can never fill the hole
        cluster = await start_cluster(8, config=cfg)
        try:
            client = await cluster.client()
            profile = {"plugin": "shec", "k": "4", "m": "3", "c": "2"}
            pool = await client.pool_create("shecp", "erasure", pg_num=4,
                                            ec_profile=dict(profile))
            io = client.ioctx(pool)
            payload = b"shec-payload" * 300
            await io.write_full("obj", payload, timeout=120)
            assert await io.read("obj", timeout=120) == payload

            pgid = client.objecter.object_pgid(pool, "obj")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            k = 4
            # shard ids follow acting positions; pick a parity holder
            parity_holders = [o for i, o in enumerate(acting)
                              if i >= k and o >= 0 and o != primary]
            victim = parity_holders[0]
            await cluster.kill_osd(victim)
            await cluster.wait_down(victim)

            # degraded read (parity loss doesn't block data)
            assert await io.read("obj", timeout=60) == payload

            # after auto-out + remap, recovery must rebuild the parity
            # shard on the replacement member (batched parity decode)
            deadline = asyncio.get_event_loop().time() + 20
            reprotected = False
            while asyncio.get_event_loop().time() < deadline:
                _, _, acting2, _ = \
                    cluster.mon.osdmap.pg_to_up_acting_osds(pgid)
                live = [o for o in acting2 if o >= 0 and o in cluster.osds]
                if victim not in acting2 and len(live) == len(acting):
                    holders = 0
                    for i, o in enumerate(acting2):
                        if o < 0 or o not in cluster.osds:
                            continue
                        osd = cluster.osds[o]
                        if osd.store.stat(_coll(pgid), "obj") is not None:
                            holders += 1
                    if holders == len(acting):
                        reprotected = True
                        break
                await asyncio.sleep(0.2)
            assert reprotected, "shec parity shard was never rebuilt"
            unrecoverable = sum(o.perf.get("osd_unrecoverable")
                                for o in cluster.osds.values())
            assert unrecoverable == 0
            assert await io.read("obj", timeout=60) == payload
        finally:
            await cluster.stop()

    run(scenario())


def test_jerasure_cauchy_pool_end_to_end():
    """A packet-interleaved bit-matrix codec through the cluster stripe
    path (batch layout consistent with single-stripe encode)."""
    async def scenario():
        cfg = _fast_config()
        # stripe unit must be a multiple of w*packetsize for the packet
        # layout; choose packetsize = 64 -> 8*64 = 512 divides 4096
        cluster = await start_cluster(4, config=cfg)
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "cauchyp", "erasure", pg_num=4,
                ec_profile={"plugin": "jerasure", "technique": "cauchy_good",
                            "k": "2", "m": "1", "packetsize": "64"})
            io = client.ioctx(pool)
            payload = b"cauchy-bytes" * 500
            await io.write_full("obj", payload, timeout=120)
            assert await io.read("obj", timeout=120) == payload
            # partial overwrite through the RMW path
            await io.write("obj", b"PATCH" * 100, offset=1000, timeout=120)
            expect = bytearray(payload)
            expect[1000:1000 + 500] = b"PATCH" * 100
            assert await io.read("obj", timeout=120) == bytes(expect)
        finally:
            await cluster.stop()

    run(scenario())
