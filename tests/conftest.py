"""Test configuration: force a virtual 8-device CPU mesh.

Must run before jax is imported anywhere.  Multi-chip sharding tests use this
virtual mesh; real-TPU benchmarking goes through bench.py, which does not
import this file.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# Persistent compilation cache: XLA compiles dominate suite runtime (the
# codec/mapper shapes recompile identically every run); caching them keeps
# the full suite inside the CI/driver time budget after the first run.
# Set BOTH the env vars and (post-import) the config knobs: pytest plugins
# can import jax before this conftest, after which the env is ignored.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/ceph_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
jax.config.update(
    "jax_persistent_cache_min_entry_size_bytes",
    int(os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]))

import pytest  # noqa: E402


def pytest_collection_modifyitems(items):
    """``soak`` and ``race`` are slow-implied (pytest.ini): every test
    carrying either mark also gets ``slow``, so the tier-1 gate's
    ``-m 'not slow'`` always deselects them without each test having
    to remember both marks — a soak (or a full-scale race-sanitizer
    scenario) accidentally landing on the bench hot path would violate
    the BENCH_NOTES round-13 contract."""
    for item in items:
        if ("soak" in item.keywords or "race" in item.keywords) \
                and "slow" not in item.keywords:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _lockdep_reset():
    """Reset the global lockdep state between tests: ordering edges are
    process-wide, so without this a (legitimate) A->B order learned in
    one test poisons a (legitimate) B->A order in the next into a false
    cycle; stale held entries from a crashed task would do the same."""
    from ceph_tpu.utils.lockdep import DepLock, LockDep

    LockDep.instance().reset()
    DepLock._held.clear()
    yield
    LockDep.instance().reset()
    DepLock._held.clear()
