"""Test configuration: force a virtual 8-device CPU mesh.

Must run before jax is imported anywhere.  Multi-chip sharding tests use this
virtual mesh; real-TPU benchmarking goes through bench.py, which does not
import this file.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
