"""Multi-active MDS subtree partitioning + CephFS snapshots (VERDICT r4
missing #3; reference src/mds/Migrator.h:52 export_dir and
src/mds/SnapServer.h snaptable / .snap paths)."""

import asyncio

import pytest

from tests._flaky import contention_retry

from ceph_tpu.cluster.mds import MDSClient
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


async def _fs_cluster(cluster, ranks=2):
    client = await cluster.client()
    meta = await client.pool_create("meta", "replicated", pg_num=4, size=2)
    data = await client.pool_create("data", "replicated", pg_num=4, size=2)
    daemons = []
    for r in range(ranks):
        daemons.append(await cluster.start_mds(meta, data, rank=r))
    await client.objecter._refresh_map()
    return client, meta, data, daemons


@contention_retry()
def test_subtree_export_and_routing():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client, meta, data, (mds0, mds1) = await _fs_cluster(cluster)
            fs = MDSClient(client, data, meta_pool=meta)
            await fs.mkdir("/a")
            await fs.mkdir("/b")
            await fs.create("/a/f1")
            await fs.write("/a/f1", 0, b"before-export")

            # move /a to rank 1 (Migrator::export_dir analog)
            await fs.export_dir("/a", 1)
            assert fs._owner_rank("/a/f1") == 1
            assert fs._owner_rank("/b/x") == 0

            # ops on /a now serve from rank 1; /b stays on rank 0
            before = mds1.perf.dump()[f"mds.1"].get("mds_requests", 0)
            await fs.create("/a/f2")
            await fs.write("/a/f2", 0, b"on-rank-1")
            assert await fs.read("/a/f2") == b"on-rank-1"
            assert await fs.read("/a/f1") == b"before-export"
            after = mds1.perf.dump()[f"mds.1"].get("mds_requests", 0)
            assert after > before, "rank 1 never served /a"
            await fs.create("/b/g1")
            assert sorted(await fs.listdir("/b")) == ["g1"]

            # a STALE client (fresh handle, default map) bounces off
            # rank 0 and retargets via the ESTALE hint
            c2 = await cluster.client("second")
            fs2 = MDSClient(c2, data, meta_pool=meta)
            assert await fs2.read("/a/f2") == b"on-rank-1"
            assert mds0.perf.dump()["mds.0"].get("mds_bounced", 0) >= 1

            # cross-subtree rename is EXDEV (early multi-active rule)
            with pytest.raises(OSError) as ei:
                await fs.rename("/a/f2", "/b/f2")
            assert ei.value.errno == 18
            # same-subtree rename still works
            await fs.rename("/b/g1", "/b/g2")
            assert sorted(await fs.listdir("/b")) == ["g2"]

            # rank-1 restart replays ITS journal and keeps serving
            await mds1.stop()
            await cluster.start_mds(meta, data, rank=1)
            assert await fs.read("/a/f1", ) == b"before-export"
            await fs.create("/a/f3")
            assert "f3" in await fs.listdir("/a")
        finally:
            await cluster.stop()

    run(scenario())


@contention_retry()
def test_fs_snapshots():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            client, meta, data, _ = await _fs_cluster(cluster, ranks=1)
            fs = MDSClient(client, data, meta_pool=meta)
            await fs.mkdir("/d")
            await fs.create("/d/file")
            await fs.write("/d/file", 0, b"version-1")
            await fs.create("/d/gone")
            await fs.write("/d/gone", 0, b"doomed")

            await fs.snap_create("/d", "s1")

            # post-snap mutations: overwrite, add, remove
            await fs.write("/d/file", 0, b"VERSION-2")
            await fs.create("/d/new")
            await fs.unlink("/d/gone")

            # live view
            assert await fs.read("/d/file") == b"VERSION-2"
            assert sorted(await fs.listdir("/d")) == ["file", "new"]
            # snapshot view: data AND namespace at snap time
            assert await fs.read("/d/.snap/s1/file") == b"version-1"
            assert sorted(await fs.listdir("/d/.snap/s1")) == \
                ["file", "gone"]
            assert await fs.read("/d/.snap/s1/gone") == b"doomed"
            # .snap listing names the snapshots
            assert await fs.listdir("/d/.snap") == ["s1"]
            # snapshots are read-only
            with pytest.raises(PermissionError):
                await fs.write("/d/.snap/s1/file", 0, b"nope")

            # second snapshot layers correctly
            await fs.snap_create("/d", "s2")
            await fs.write("/d/file", 0, b"version-3")
            assert await fs.read("/d/.snap/s1/file") == b"version-1"
            assert await fs.read("/d/.snap/s2/file") == b"VERSION-2"
            assert await fs.read("/d/file") == b"version-3"

            # snap_rm removes the view
            await fs.snap_rm("/d", "s1")
            assert await fs.listdir("/d/.snap") == ["s2"]
            with pytest.raises(FileNotFoundError):
                await fs.read("/d/.snap/s1/file")
        finally:
            await cluster.stop()

    run(scenario())
