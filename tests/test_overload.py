"""Graceful degradation under overload (round 10 acceptance gates).

Tier-1 tests stay STRUCTURAL (counters, invariants, bit-exact reads) —
the bench host is load-sensitive, so no timing thresholds here.  The
timing-based goodput criterion ("within 20% of the admission budget")
is slow-marked.

Covers: admission pushback driving the client AIMD congestion window,
deadline propagation + dead-work shedding at the mclock dequeue,
degraded k-of-n EC reads with a dead shard holder (hedge/promotion),
the OSD byte-throttle held through dispatch (release-after-drain
regression + throttle_wait attribution), and the seeded overload-smoke
chaos scenario.
"""

import asyncio
import os

import pytest

from ceph_tpu.cluster.vstart import _fast_config, start_cluster


def run(coro):
    return asyncio.run(coro)


def _sum_counter(cluster, name: str) -> int:
    return sum(osd.perf.get(name) for osd in cluster.osds.values())


# ------------------------------------------------- admission + AIMD cwnd


def test_admission_pushback_drives_client_cwnd():
    """A 12-op burst against a 1-op admission budget: every op still
    lands (AIMD retries absorb the pushback), the OSDs counted explicit
    THROTTLED rejects, and the client's congestion window shrank from
    its ceiling — backpressure, not timeouts, did the flow control."""

    async def scenario():
        config = _fast_config()
        config.osd_op_throttle_ops = 1
        cluster = await start_cluster(3, config=config)
        try:
            client = await cluster.client()
            pool = await client.pool_create("ovl", pg_num=8, size=3)
            io = client.ioctx(pool)
            datas = {f"o{i}": os.urandom(4096) + bytes([i])
                     for i in range(12)}
            await asyncio.gather(*[io.write_full(oid, d)
                                   for oid, d in datas.items()])
            for oid, d in datas.items():
                assert await io.read(oid) == d
            cwnd = client.objecter.cwnd
            rejects = _sum_counter(cluster, "osd_throttle_rejects")
            return cwnd.pushbacks, cwnd.window, cwnd.ceiling, rejects
        finally:
            await cluster.stop()

    pushbacks, window, ceiling, rejects = run(scenario())
    assert rejects > 0, "budget 1 vs 12 concurrent ops never pushed back"
    assert pushbacks > 0
    assert window < ceiling  # multiplicative decrease engaged


def test_throttle_noop_when_budgets_off():
    """Default budgets (0) are a provable no-op: no pushbacks, window
    stays at the ceiling — the chaos-injector contract."""

    async def scenario():
        cluster = await start_cluster(3, config=_fast_config())
        try:
            client = await cluster.client()
            pool = await client.pool_create("noop", pg_num=4, size=3)
            io = client.ioctx(pool)
            await asyncio.gather(*[io.write_full(f"n{i}", b"x" * 1024)
                                   for i in range(8)])
            cwnd = client.objecter.cwnd
            return (cwnd.pushbacks, cwnd.window, cwnd.ceiling,
                    _sum_counter(cluster, "osd_throttle_rejects"))
        finally:
            await cluster.stop()

    pushbacks, window, ceiling, rejects = run(scenario())
    assert pushbacks == 0 and rejects == 0
    assert window == float(ceiling)


# ------------------------------------------- deadline shedding (mclock)


def test_mclock_limit_sheds_expired_ops_at_dequeue():
    """Six concurrent writes to one hot object through a 2 op/s mclock
    limit, each with a 1.2s deadline: the L-tag pacing pushes the tail
    of the queue past its deadline, the OSD sheds those at dequeue
    (counted), and NO op is acked after its deadline — the overload
    acceptance invariant at micro scale."""

    async def scenario():
        config = _fast_config()
        config.osd_op_queue = "mclock"
        cluster = await start_cluster(3, config=config)
        try:
            client = await cluster.client()
            pool = await client.pool_create("dl", pg_num=4, size=3)
            io = client.ioctx(pool)
            # warm: the qos entity registers + the object exists
            await io.write_full("hot", b"warm")
            entity = client.objecter.client_name.split("#", 1)[0]
            for osd in cluster.osds.values():
                osd.set_qos(entity, reservation=0.0, weight=1.0,
                            limit=2.0)
            loop = asyncio.get_event_loop()
            deadline_s = 1.2
            late_acks = []

            async def put(i):
                t0 = loop.time()
                try:
                    await io.write_full("hot", bytes([i]) * 512,
                                        timeout=deadline_s)
                except (IOError, OSError, TimeoutError):
                    return 0
                if loop.time() - t0 > deadline_s + 0.25:
                    late_acks.append(i)
                return 1

            acked = sum(await asyncio.gather(*[put(i) for i in range(6)]))
            # converge-poll (round 12 deflake): wait for the drain
            # loop's dead-work purge to sweep the expired tail instead
            # of a fixed sleep — on a loaded host the purge wake can
            # slip well past its nominal 0.25s cadence
            deadline = loop.time() + 10.0
            shed = 0
            while loop.time() < deadline:
                shed = _sum_counter(cluster, "osd_ops_shed_expired")
                if shed > 0:
                    break
                await asyncio.sleep(0.05)
            return acked, shed, late_acks
        finally:
            await cluster.stop()

    acked, shed, late_acks = run(scenario())
    assert late_acks == [], f"ops acked past their deadline: {late_acks}"
    assert shed > 0, "expired queued ops were executed instead of shed"
    assert acked >= 1  # the head of the queue still made it


# -------------------------------------------- degraded-mode EC reads


def test_ec_read_completes_k_of_n_with_dead_shard_holder():
    """Kill the first shard holder the primary would contact, then read
    WITHOUT waiting for a map change: the gather promotes/hedges to the
    surviving shard and the read returns bit-exact — a dead holder
    degrades latency, not availability."""

    async def scenario():
        from ceph_tpu.chaos.daemons import DaemonInjector

        cluster = await start_cluster(4, config=_fast_config())
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "deg", "erasure", pg_num=2,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            data = os.urandom(64 * 1024)
            await io.write_full("obj", data)
            pgid = client.objecter.object_pgid(pool, "obj")
            _, _, acting, primary = \
                client.objecter.osdmap.pg_to_up_acting_osds(pgid)
            # the first peer the fast-path gather contacts: lowest
            # shard index whose holder is not the primary
            victim = next(o for o in acting if o != primary)
            await DaemonInjector(cluster).kill_osd(victim)
            # read IMMEDIATELY — the map still lists the dead holder
            got = await io.read("obj")
            posd = cluster.osds[primary]
            degraded = (posd.perf.get("osd_ec_hedged_reads") +
                        posd.perf.get("osd_ec_hedge_promotions"))
            return got == data, degraded
        finally:
            await cluster.stop()

    bit_exact, degraded = run(scenario())
    assert bit_exact
    assert degraded >= 1, \
        "read served without hedging/promoting around the dead holder"


def test_ec_fastk_read_counts_and_stays_bit_exact():
    """Healthy-cluster fast path: reads resolve from the first k clean
    shards (counter fires) and every byte matches."""

    async def scenario():
        cluster = await start_cluster(4, config=_fast_config())
        try:
            client = await cluster.client()
            pool = await client.pool_create(
                "fk", "erasure", pg_num=2,
                ec_profile={"plugin": "jerasure",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "1"})
            io = client.ioctx(pool)
            blobs = {f"f{i}": os.urandom(32 * 1024) for i in range(4)}
            for oid, d in blobs.items():
                await io.write_full(oid, d)
            ok = all([(await io.read(oid)) == d
                      for oid, d in blobs.items()])
            return ok, _sum_counter(cluster, "osd_ec_fastk_reads")
        finally:
            await cluster.stop()

    ok, fastk = run(scenario())
    assert ok
    assert fastk >= 1


# ------------------------- byte throttle held through dispatch (regression)


def test_byte_throttle_release_after_dispatch_and_attribution():
    """Regression for osd_client_message_size_cap releases: with a cap
    admitting ~1.5 writes, three concurrent 100 KiB writes to one PG
    serialize through the byte budget, ALL complete (the blocked sender
    resumes when the queue drains), and the wait lands in op
    attribution as the throttle_wait stage."""

    async def scenario():
        from ceph_tpu.trace.attribution import aggregate_tracker

        config = _fast_config()
        config.osd_client_message_size_cap = 150_000
        # per-op frames: the byte-budget release under test is a
        # per-MESSAGE property.  The round-18 client coalescer would
        # pack all three writes into ONE MOSDOpBatch frame, which the
        # cap admits as a single oversize message and never blocks.
        config.objecter_batch_tick_ops = 0
        cluster = await start_cluster(3, config=config)
        try:
            client = await cluster.client()
            pool = await client.pool_create("thr", pg_num=2, size=3)
            io = client.ioctx(pool)
            payloads = [bytes([i]) * 100_000 for i in range(3)]
            await asyncio.gather(*[io.write_full("hot", p)
                                   for p in payloads])
            got = await io.read("hot")
            pgid = client.objecter.object_pgid(pool, "hot")
            primary = client.objecter._target_osd(pgid)
            rep = aggregate_tracker(cluster.osds[primary].tracker,
                                    match="write_full")
            return got in payloads, rep["stages"]
        finally:
            await cluster.stop()

    consistent, stages = run(scenario())
    assert consistent  # releases worked: every blocked write drained
    assert "throttle_wait" in stages, stages
    assert stages["throttle_wait"]["s"] > 0


# --------------------------------------------- attribution stage contract


def test_attribution_books_overload_stages_with_full_coverage():
    """The round-6 trust model with backpressure enabled: timelines
    carrying throttle/shed/hedge marks attribute every nanosecond to
    exactly one stage (sums == traced total), with the new stage names."""
    from ceph_tpu.trace.attribution import attribute_events

    events = [
        (0.00, "objecter:submit"),
        (0.05, "objecter:throttle_wait"),      # cwnd gate wait
        (0.06, "objecter:send"),
        (0.07, "msgr:osd.0:recv"),
        (0.09, "throttle:osd.0:acquired"),     # byte-budget wait
        (0.10, "dispatched"),
        (0.12, "ec_sub_read_sent"),
        (0.15, "ec_hedge_sent"),               # straggler hedge
        (0.18, "sub_read_acked"),
        (0.19, "done"),
    ]
    stages, total = attribute_events(events)
    assert stages["throttle_wait"] == pytest.approx(0.05 + 0.02)
    assert stages["hedge"] == pytest.approx(0.03)
    assert sum(stages.values()) == pytest.approx(total)

    shed_stages, shed_total = attribute_events(
        [(0.0, "initiated"), (0.4, "shed_expired")])
    assert shed_stages == {"shed": pytest.approx(0.4)}
    assert shed_total == pytest.approx(0.4)


# --------------------------------------------------- chaos scenario gates


@pytest.mark.chaos
def test_overload_smoke_scenario():
    """Tier-1 overload smoke: a 4x-budget zipfian burst on a healthy
    cluster — shed count > 0, zero acked-past-deadline ops, durability
    + health converge.  Structural verdicts only (load-sensitive host)."""
    from ceph_tpu.chaos.scenario import builtin_scenarios, run_scenario

    v = run(run_scenario(builtin_scenarios()["overload-smoke"], 23))
    assert v.passed, v.failures
    assert v.acked_objects > 0


@pytest.mark.chaos
@pytest.mark.slow
def test_overload_shed_scenario():
    """The full acceptance gate: zipfian bursts at 4x admission budget
    + a killed shard holder mid-run.  Durability invariants + zero
    acked-but-expired ops + shed > 0 + HEALTH clear at convergence."""
    from ceph_tpu.chaos.scenario import builtin_scenarios, run_scenario

    v = run(run_scenario(builtin_scenarios()["overload-shed"], 29))
    assert v.passed, v.failures
    assert v.acked_objects > 0


@pytest.mark.slow
def test_goodput_within_20pct_of_admission_budget():
    """No congestion collapse: goodput at 4x offered load stays within
    20% of goodput at exactly-budget load (the AIMD window converges on
    the admission budget instead of thrashing).  Timing-based — slow."""

    async def phase(io, workers: int, secs: float, tag: str) -> int:
        loop = asyncio.get_event_loop()
        stop_at = loop.time() + secs
        counts = [0] * workers

        async def worker(w: int):
            i = 0
            while loop.time() < stop_at:
                try:
                    await io.write_full(f"{tag}_{w}_{i % 8}",
                                        b"g" * 16384, timeout=10.0)
                    counts[w] += 1
                except (IOError, OSError, TimeoutError):
                    pass
                i += 1

        await asyncio.gather(*[worker(w) for w in range(workers)])
        return sum(counts)

    async def scenario():
        config = _fast_config()
        config.osd_op_throttle_ops = 4
        cluster = await start_cluster(3, config=config)
        try:
            client = await cluster.client()
            pool = await client.pool_create("gp", pg_num=8, size=3)
            io = client.ioctx(pool)
            await io.write_full("warm", b"w" * 16384)
            at_budget = await phase(io, 4, 4.0, "a")
            overloaded = await phase(io, 16, 4.0, "b")
            return at_budget, overloaded
        finally:
            await cluster.stop()

    at_budget, overloaded = run(scenario())
    assert at_budget > 0
    assert overloaded >= 0.8 * at_budget, \
        f"goodput collapsed under 4x load: {overloaded} vs {at_budget}"
