"""RGW multisite sync (VERDICT r4 missing #5; reference rgw_sync.cc /
rgw_data_sync.cc): two zones, bilog-driven incremental sync, full-sync
bootstrap after trim, and active-active without echo loops."""

import asyncio

from ceph_tpu.cluster.rgw import RGW
from ceph_tpu.cluster.rgw_sync import RGWSyncAgent
from ceph_tpu.cluster.vstart import start_cluster


def run(coro):
    return asyncio.run(coro)


async def _zones(cluster):
    client = await cluster.client()
    pa = await client.pool_create("zone_a", "replicated", pg_num=4, size=2)
    pb = await client.pool_create("zone_b", "replicated", pg_num=4, size=2)
    za = RGW(client.ioctx(pa), zone="a")
    zb = RGW(client.ioctx(pb), zone="b")
    return za, zb


def test_incremental_and_full_sync():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            za, zb = await _zones(cluster)
            await za.create_bucket("bkt")
            for i in range(5):
                await za.put_object("bkt", f"k{i}", b"v%d" % i,
                                    user_meta={"n": str(i)})
            agent = RGWSyncAgent(za, zb)
            n = await agent.sync_once()
            assert n == 5
            # bucket + objects + metadata (incl. etag) replicated
            assert await zb.list_buckets() == ["bkt"]
            meta, data = await zb.get_object("bkt", "k3")
            assert data == b"v3" and meta.user_meta == {"n": "3"}
            src_meta = await za.head_object("bkt", "k3")
            assert meta.etag == src_meta.etag

            # incremental: only NEW changes apply on the next pass
            await za.put_object("bkt", "k5", b"v5")
            await za.delete_object("bkt", "k0")
            n = await agent.sync_once()
            assert n == 2
            assert (await zb.get_object("bkt", "k5"))[1] == b"v5"
            try:
                await zb.head_object("bkt", "k0")
                raise AssertionError("delete did not sync")
            except FileNotFoundError:
                pass
            # idempotent: nothing new -> nothing applied
            assert await agent.sync_once() == 0

            # full-sync bootstrap: a FRESH destination whose marker is
            # behind a trimmed log window
            za.BILOG_MAX = 3
            for i in range(8):
                await za.put_object("bkt", f"burst{i}", b"b%d" % i)
            client = await cluster.client("second")
            pc = await client.pool_create("zone_c", "replicated",
                                          pg_num=4, size=2)
            zc = RGW(client.ioctx(pc), zone="c")
            agent2 = RGWSyncAgent(za, zc)
            await agent2.sync_once()
            assert agent2.stats["full_syncs"] >= 1
            listing = await zc.list_objects("bkt")
            assert {m.key for m in listing.keys} == \
                {m.key for m in (await za.list_objects("bkt")).keys}
        finally:
            await cluster.stop()

    run(scenario())


def test_active_active_no_echo():
    async def scenario():
        cluster = await start_cluster(3)
        try:
            za, zb = await _zones(cluster)
            await za.create_bucket("aa")
            ab = RGWSyncAgent(za, zb)   # a -> b
            ba = RGWSyncAgent(zb, za)   # b -> a
            await za.put_object("aa", "from_a", b"A")
            await ab.sync_once()
            await zb.put_object("aa", "from_b", b"B")
            # several rounds both ways: converged, no ping-pong growth
            for _ in range(4):
                na = await ab.sync_once()
                nb = await ba.sync_once()
            assert (await za.get_object("aa", "from_b"))[1] == b"B"
            assert (await zb.get_object("aa", "from_a"))[1] == b"A"
            # steady state: no further applies in either direction
            assert await ab.sync_once() == 0
            assert await ba.sync_once() == 0
            assert ab.stats["skipped_echo"] >= 1 or \
                ba.stats["skipped_echo"] >= 1

            # background daemons converge a live write
            ab.interval = ba.interval = 0.1
            ab.start(); ba.start()
            await za.put_object("aa", "live", b"L")
            for _ in range(100):
                try:
                    if (await zb.get_object("aa", "live"))[1] == b"L":
                        break
                except FileNotFoundError:
                    pass
                await asyncio.sleep(0.1)
            assert (await zb.get_object("aa", "live"))[1] == b"L"
            await ab.stop(); await ba.stop()
        finally:
            await cluster.stop()

    run(scenario())
