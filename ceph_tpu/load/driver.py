"""graft-load: deterministic open-loop traffic driver.

ROADMAP item 3's workload generator: thousands of simulated clients
multiplexed over a BOUNDED pool of objecter sessions (the reference's
librados apps share a handful of RADOS connections the same way), each
client an independent seeded arrival process (fixed-rate or Poisson)
drawing verbs from a weighted mix (librados write/read/RMW/append/
delete, RBD striped image I/O + snapshot lifecycle + clone reads, RGW
object puts + full multipart transactions) and object targets from
a zipfian hot-set — all declared as a ``LoadSpec`` and resolved by
``build_plan(spec, seed)`` into a concrete per-client op schedule with
the same replay-key determinism contract as chaos scenarios: the same
seed produces a bit-identical plan, and ``plan_key`` is the replay
witness.

The driver is OPEN-LOOP: ops fire at their scheduled times whether or
not earlier ops completed (offered load is the independent variable the
saturation search in ``ramp.py`` sweeps; a closed loop would let the
cluster set its own pace and hide the knee).  ``max_inflight`` is a
runaway safety cap only — real flow control is the objecter's AIMD
congestion window, which is part of what the SLO judge grades.

Namespaces keep durability judgeable: ``write`` verbs target ``obj*``
oids with whole-payload ``write_full`` (last-acked-payload readback is
well-defined, chaos-style), while ``rmw``/``append``/``delete`` mutate
a separate ``mob*`` namespace whose byte history is deliberately not
durability-tracked (mixed mutations to one oid have no single expected
payload).  Reads hit the tracked namespace.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

from ceph_tpu.load.dist import (
    arrival_offsets,
    client_stream,
    pick_weighted,
    zipf_pick,
)
from ceph_tpu.utils.tasks import track_task

# librados-only default mix (RBD/RGW verbs opt in per spec)
DEFAULT_VERBS: Tuple[Tuple[str, float], ...] = (
    ("write", 4.0), ("read", 3.0), ("rmw", 1.0), ("append", 1.0),
    ("delete", 0.5))

DEFAULT_GATES: Tuple[Tuple[str, float], ...] = (
    ("goodput_min_frac", 0.5),   # scraped acked ops >= frac * offered
    ("p99_ms", 5000.0),          # scraped op-latency histogram p99
    ("cwnd_floor", 2.0),         # AIMD window converged, not collapsed
    ("qos_reservation_min", 0.0))  # dmclock conformance under contention


@dataclass(frozen=True)
class LoadSpec:
    """One declarative traffic shape (the chaos ``Scenario`` analog)."""

    name: str
    clients: int = 64                  # simulated clients
    sessions: int = 4                  # bounded objecter session pool
    rate: float = 1.0                  # ops/s per client (offered)
    duration: float = 3.0              # offered-load window, seconds
    arrival: str = "poisson"           # "poisson" | "fixed"
    verbs: Tuple[Tuple[str, float], ...] = DEFAULT_VERBS
    objects: int = 64                  # hot-object space per namespace
    zipf_alpha: float = 1.2
    payload: int = 2048                # approx bytes per write payload
    op_deadline: float = 25.0          # client budget per op (seconds)
    max_inflight: int = 512            # open-loop runaway cap
    # cluster shape
    osds: int = 3
    pool_kind: str = "replicated"      # "replicated" | "erasure"
    pool_size: int = 3
    pg_num: int = 4
    ec_profile: Optional[Tuple[Tuple[str, str], ...]] = None
    store: str = "mem"                 # "mem" | "file" | "blue"
    config: Tuple[Tuple[str, object], ...] = ()
    # SLO gate thresholds (see slo.judge)
    gates: Tuple[Tuple[str, float], ...] = DEFAULT_GATES

    def gate(self, name: str, default: float = 0.0) -> float:
        return dict(self.gates).get(name, default)

    def offered_ops(self, plan: List[List[Dict]]) -> int:
        return sum(len(ops) for ops in plan)

    def scaled(self, factor: float) -> "LoadSpec":
        """The same shape at ``factor``x the offered rate (ramp steps)."""
        return replace(self, rate=self.rate * factor)


# ----------------------------------------------------------------- plan


def build_plan(spec: LoadSpec, seed: int) -> List[List[Dict]]:
    """Resolve the spec to a concrete per-client op schedule.  Every
    random choice (arrival times, verbs, object ranks, payload nonces,
    offsets) comes from the client's OWN seeded stream, so the plan is
    a pure function of (spec, seed) — the determinism artifact the
    replay tests compare."""
    plan: List[List[Dict]] = []
    for cid in range(spec.clients):
        rng = client_stream(seed, cid)
        ops: List[Dict] = []
        for t in arrival_offsets(rng, spec.rate, spec.duration,
                                 spec.arrival):
            verb = pick_weighted(rng, spec.verbs)
            rank = zipf_pick(rng, spec.objects, spec.zipf_alpha)
            ops.append({"t": round(t, 6), "verb": verb, "obj": rank,
                        "nonce": rng.randrange(1 << 30)})
        plan.append(ops)
    return plan


def plan_key(plan: List[List[Dict]]) -> str:
    """Replay witness: sha256 over the canonical plan encoding (two
    runs of one seed must produce the same key bit-for-bit)."""
    blob = json.dumps(plan, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


# --------------------------------------------------------------- result


@dataclass
class LoadResult:
    """Client-observed outcome of one load window (the scrape-side
    telemetry lives in the slo snapshots, taken by the runner)."""

    spec_name: str
    seed: int
    plan_key: str
    offered: int = 0
    completed: Dict[str, int] = field(default_factory=dict)
    errors: Dict[str, int] = field(default_factory=dict)
    read_misses: int = 0
    late_acks: List[str] = field(default_factory=list)
    elapsed: float = 0.0               # before-scrape -> after-scrape
    # durability bookkeeping (soak): last acked payload per tracked oid
    acked: Dict[str, bytes] = field(default_factory=dict)
    attempted: Dict[str, set] = field(default_factory=dict)

    def count(self, table: Dict[str, int], key: str) -> None:
        table[key] = table.get(key, 0) + 1

    @property
    def acked_ops(self) -> int:
        return sum(self.completed.values())

    def as_dict(self) -> Dict:
        return {"spec": self.spec_name, "seed": self.seed,
                "plan_key": self.plan_key, "offered": self.offered,
                "completed": dict(self.completed),
                "errors": dict(self.errors),
                "read_misses": self.read_misses,
                "late_acks": len(self.late_acks),
                "elapsed_s": round(self.elapsed, 3)}


# -------------------------------------------------------------- context


class LoadContext:
    """A booted cluster + bounded session pool + workload surfaces
    (librados pool, RBD image + clone, RGW bucket), reusable across
    load windows (the ramp sweeps many windows over one cluster)."""

    RBD_IMAGE = "load_img"
    RBD_CLONE = "load_clone"
    RBD_SNAP = "load_base"
    RBD_SIZE = 8 << 20
    RGW_BUCKET = "loadb"

    def __init__(self):
        self.cluster = None
        self.sessions: List = []
        self.pool: Optional[int] = None
        self._owns_cluster = False
        self._images: Dict[int, object] = {}
        self._clones: Dict[int, object] = {}
        self._rgws: Dict[int, object] = {}
        self._rbd_ready = False
        self._rgw_ready = False

    @classmethod
    async def create(cls, spec: LoadSpec, seed: int, cluster=None,
                     tmpdir: Optional[str] = None) -> "LoadContext":
        from ceph_tpu.chaos.scenario import store_factory_for
        from ceph_tpu.cluster.vstart import _fast_config, start_cluster

        ctx = cls()
        if cluster is None:
            cfg = _fast_config()
            # soaks bounce daemons across minutes of wall time: a
            # crashed OSD must not be auto-marked OUT before its
            # scheduled revive (chaos scenarios use 120s; soak rounds
            # plus invariant sweeps outlive that)
            cfg.mon_osd_down_out_interval = 600.0
            cfg.chaos_seed = seed          # seeded messenger/backoff jitter
            for k, v in spec.config:
                cfg.set(k, v)
            cluster = await start_cluster(
                spec.osds, config=cfg, with_mgr=True,
                store_factory=store_factory_for(spec, tmpdir))
            ctx._owns_cluster = True
        ctx.cluster = cluster
        admin = await cluster.client(name="load_admin") \
            if not cluster.clients else cluster.clients[0]
        if spec.pool_kind == "erasure":
            ctx.pool = await admin.pool_create(
                f"load_{spec.name}"[:24], "erasure", pg_num=spec.pg_num,
                ec_profile=dict(spec.ec_profile or ()))
        else:
            ctx.pool = await admin.pool_create(
                f"load_{spec.name}"[:24], "replicated",
                pg_num=spec.pg_num, size=spec.pool_size)
        for j in range(spec.sessions):
            ctx.sessions.append(await cluster.client(name=f"load{j}"))
        verbs = {v for v, _w in spec.verbs}
        if verbs & {"rbd_write", "rbd_read", "rbd_snap",
                    "rbd_clone_read"}:
            await ctx._setup_rbd()
        if "rbd_clone_read" in verbs:
            await ctx._setup_rbd_clone()
        if verbs & {"rgw_put", "rgw_get", "rgw_multipart"}:
            await ctx._setup_rgw()
        return ctx

    def io(self, j: int):
        return self.sessions[j % len(self.sessions)].ioctx(self.pool)

    async def _setup_rbd(self) -> None:
        from ceph_tpu.cluster.rbd import RBD

        rbd = RBD(self.io(0))
        try:
            await rbd.create(self.RBD_IMAGE, self.RBD_SIZE,
                             stripe_unit=64 << 10, stripe_count=2,
                             object_size=1 << 20)
        except FileExistsError:
            pass
        for j in range(len(self.sessions)):
            self._images[j] = await RBD(self.io(j)).open(self.RBD_IMAGE)
        self._rbd_ready = True

    async def _setup_rbd_clone(self) -> None:
        """Parent data + snapshot + COW clone for the rbd_clone_read
        verb: clone reads exercise the copy-up fall-through path under
        load (unwritten child extents resolve to the parent snap)."""
        from ceph_tpu.cluster.rbd import RBD

        img = self._images[0]
        if self.RBD_SNAP not in img.snap_list():
            await img.write(0, b"load-clone-parent-" * 512)
            try:
                await img.snap_create(self.RBD_SNAP)
            except FileExistsError:
                pass
        try:
            await RBD(self.io(0)).clone(self.RBD_IMAGE, self.RBD_SNAP,
                                        self.RBD_CLONE)
        except FileExistsError:
            pass
        for j in range(len(self.sessions)):
            self._clones[j] = await RBD(self.io(j)).open(self.RBD_CLONE)

    async def _setup_rgw(self) -> None:
        from ceph_tpu.cluster.rgw import RGW

        for j in range(len(self.sessions)):
            self._rgws[j] = RGW(self.io(j))
        try:
            await self._rgws[0].create_bucket(self.RGW_BUCKET)
        except FileExistsError:
            pass
        self._rgw_ready = True

    async def close(self) -> None:
        if self._owns_cluster and self.cluster is not None:
            await self.cluster.stop()


# --------------------------------------------------------------- runner


async def drive(ctx: LoadContext, spec: LoadSpec, seed: int,
                plan: Optional[List[List[Dict]]] = None,
                record_acked: bool = False) -> LoadResult:
    """Fire one open-loop window of ``plan`` over the context's session
    pool and wait for every op to resolve.  Pure client side — no
    scraping; the runner (``run_load`` / ramp / soak) brackets this
    with slo snapshots."""
    if plan is None:
        plan = build_plan(spec, seed)
    result = LoadResult(spec_name=spec.name, seed=seed,
                        plan_key=plan_key(plan),
                        offered=spec.offered_ops(plan))
    loop = asyncio.get_event_loop()
    sem = asyncio.Semaphore(spec.max_inflight)
    op_tasks: set = set()
    t0 = loop.time() + 0.05

    async def fire(cid: int, op: Dict) -> None:
        async with sem:
            await _one_op(ctx, spec, cid, op, result, record_acked)

    async def client_loop(cid: int, ops: List[Dict]) -> None:
        for op in ops:
            delay = t0 + op["t"] - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            # open loop: the op is a free-running task; completion of
            # earlier ops never gates later arrivals
            track_task(op_tasks, loop.create_task(fire(cid, op)))

    async def report_loop() -> None:
        # stream each session's AIMD/flow-control counters to the mgr
        # while the window runs, so the post-window scrape sees them
        while True:
            for c in ctx.sessions:
                await c.objecter.mgr_report()
            await asyncio.sleep(0.25)

    reporter = loop.create_task(report_loop())
    try:
        await asyncio.gather(*[client_loop(cid, ops)
                               for cid, ops in enumerate(plan)])
        while op_tasks:
            # _one_op contains its own error accounting; anything that
            # escapes here is a driver bug and should fail the run
            await asyncio.gather(*list(op_tasks))
    finally:
        reporter.cancel()
        try:
            await reporter
        except asyncio.CancelledError:
            pass
        if op_tasks:
            # abnormal exit (an escaped driver bug, or the window task
            # itself cancelled): the free-running op tasks must not
            # keep firing at a context the caller is about to close
            for t in list(op_tasks):
                t.cancel()
            drained = await asyncio.gather(*list(op_tasks),
                                           return_exceptions=True)
            for exc in drained:
                if isinstance(exc, Exception):
                    result.count(result.errors, "driver_abort")
    for c in ctx.sessions:
        await c.objecter.mgr_report()    # final cwnd state for the scrape
    return result


async def _one_op(ctx: LoadContext, spec: LoadSpec, cid: int, op: Dict,
                  result: LoadResult, record_acked: bool) -> None:
    """Serve one planned op on the client's assigned session.  Expected
    I/O failures are counted, never raised (open-loop drivers judge by
    counters, not exceptions)."""
    j = cid % len(ctx.sessions)
    io = ctx.io(j)
    verb, rank, nonce = op["verb"], op["obj"], op["nonce"]
    loop = asyncio.get_event_loop()
    start = loop.time()
    timeout = spec.op_deadline
    # EVERY verb carries the client deadline end-to-end now (round 15:
    # the RBD/RGW libraries thread ONE wall deadline through their
    # internal fan-out via utils.deadline), so every ack is judged
    # against the zero-acked-past-deadline criterion
    deadline_tracked = True
    acked = False
    try:
        if verb == "write":
            oid = f"obj{rank}"
            data = _payload(spec, cid, oid, nonce)
            if record_acked:
                result.attempted.setdefault(oid, set()).add(data)
            await io.write_full(oid, data, timeout=timeout)
            if record_acked:
                result.acked[oid] = data
        elif verb == "read":
            try:
                await io.read(f"obj{rank}", timeout=timeout)
            except FileNotFoundError:
                result.read_misses += 1
        elif verb == "rmw":
            data = _payload(spec, cid, f"mob{rank}", nonce)[:256]
            await io.write(f"mob{rank}", data,
                           offset=nonce % 4096, timeout=timeout)
        elif verb == "append":
            await io.append(f"mob{rank}",
                            _payload(spec, cid, f"mob{rank}", nonce)[:256],
                            timeout=timeout)
        elif verb == "delete":
            try:
                await io.remove(f"mob{rank}", timeout=timeout)
            except FileNotFoundError:
                result.read_misses += 1
        elif verb == "rbd_write":
            img = ctx._images[j]
            off = (nonce % (ctx.RBD_SIZE - (64 << 10))) & ~0xFFF
            await img.write(off, _payload(spec, cid, "rbd", nonce)[:16384],
                            timeout=timeout)
        elif verb == "rbd_read":
            img = ctx._images[j]
            off = (nonce % (ctx.RBD_SIZE - (64 << 10))) & ~0xFFF
            await img.read(off, 16384, timeout=timeout)
        elif verb == "rbd_snap":
            # snapshot lifecycle under load: create + drop ONE snap on
            # a unique name, both halves inside the one op budget
            from ceph_tpu.utils.deadline import deadline_of, remaining

            img = ctx._images[j]
            name = f"ls-c{cid}-{nonce}"
            dl = deadline_of(timeout)
            await img.snap_create(name, timeout=remaining(dl))
            try:
                await img.snap_remove(name, timeout=remaining(dl))
            except (KeyError, FileNotFoundError):
                # a concurrent snap_create's header save won the race
                # (load images share handles); the stray snap is
                # harmless to the ack bookkeeping
                pass
        elif verb == "rbd_clone_read":
            img = ctx._clones[j]
            off = (nonce % (ctx.RBD_SIZE - (64 << 10))) & ~0xFFF
            await img.read(off, 16384, timeout=timeout)
        elif verb == "rgw_put":
            await ctx._rgws[j].put_object(
                ctx.RGW_BUCKET, f"k{rank}",
                _payload(spec, cid, "rgw", nonce)[:4096],
                timeout=timeout)
        elif verb == "rgw_get":
            try:
                await ctx._rgws[j].get_object(ctx.RGW_BUCKET, f"k{rank}",
                                              timeout=timeout)
            except (FileNotFoundError, KeyError):
                result.read_misses += 1
        elif verb == "rgw_multipart":
            # a full 2-part multipart transaction (initiate -> parts ->
            # complete) through the durable registry, one op budget
            from ceph_tpu.utils.deadline import deadline_of, remaining

            rgw = ctx._rgws[j]
            key = f"mpl{rank}"
            dl = deadline_of(timeout)
            uid = await rgw.create_multipart(ctx.RGW_BUCKET, key,
                                             timeout=remaining(dl))
            half = _payload(spec, cid, "mp", nonce)[:2048]
            for n in (1, 2):
                await rgw.upload_part(ctx.RGW_BUCKET, key, uid, n,
                                      half, timeout=remaining(dl))
            await rgw.complete_multipart(ctx.RGW_BUCKET, key, uid,
                                         timeout=remaining(dl))
        else:
            raise ValueError(f"unknown load verb {verb!r}")
        acked = True
    except (IOError, OSError, TimeoutError) as e:
        result.count(result.errors, type(e).__name__)
    if acked:
        result.count(result.completed, verb)
        elapsed = loop.time() - start
        if deadline_tracked and elapsed > timeout + 0.25:
            # the zero acked-past-deadline criterion (chaos "deadline"
            # invariant): an ack after the client's budget means
            # deadline shedding failed somewhere in the stack
            result.late_acks.append(
                f"deadline: {verb} obj{rank} acked {elapsed:.2f}s after "
                f"submit, past its {timeout}s budget")


def _payload(spec: LoadSpec, cid: int, oid: str, nonce: int) -> bytes:
    tag = f"load-c{cid}-{oid}-{nonce}-".encode()
    return tag * max(1, spec.payload // len(tag))


async def run_load(spec: LoadSpec, seed: int, ctx: Optional[LoadContext]
                   = None, tmpdir: Optional[str] = None,
                   record_acked: bool = False):
    """One judged load window: boot (or reuse) a context, snapshot
    telemetry, drive the plan, snapshot again.  Returns
    ``(result, report)`` where the report's gate verdicts are computed
    from the scraped/dumped telemetry (slo.judge)."""
    from ceph_tpu.load import slo

    owns = ctx is None
    if ctx is None:
        ctx = await LoadContext.create(spec, seed, tmpdir=tmpdir)
    try:
        before = await slo.snapshot(ctx.cluster)
        result = await drive(ctx, spec, seed, record_acked=record_acked)
        # let the final heartbeat-carried MMgrReports land before the
        # closing scrape (heartbeat interval is 0.1s under _fast_config)
        await asyncio.sleep(0.4)
        after = await slo.snapshot(ctx.cluster)
        result.elapsed = max(1e-6, after.stamp - before.stamp)
        report = slo.judge(spec, result, before, after)
        if not report.passed and \
                getattr(ctx.cluster.config, "blackbox_enabled", 0):
            # graft-blackbox: a failed SLO judgment IS a trigger — the
            # bundle snapshots the cluster while the breach evidence
            # (historic ops, flight rings) is still in the rings
            # the reason stays a pure function of (spec, seed) — gate
            # counts/values are wire-level and ride the detail — so the
            # bundle path and replay_key are seeded-replay stable
            rec = await ctx.cluster.blackbox_trigger(
                "slo_gate",
                f"load {spec.name} seed={seed} failed SLO gates",
                detail={"spec": spec.name, "seed": seed,
                        "gates": report.failing_gates()},
                clients=ctx.sessions)
            report.postmortem = (rec or {}).get("path")
        return result, report
    finally:
        if owns:
            await ctx.close()


# -------------------------------------------------------------- builtins


def builtin_specs() -> Dict[str, LoadSpec]:
    """The shipped load-spec library (scripts/load.py `list`)."""
    return {
        # tier-1 smoke: ~64 simulated clients over a 4-session pool,
        # librados mix, toy cluster — every SLO gate must pass and the
        # plan must replay bit-identically from its seed
        "smoke": LoadSpec(
            name="smoke", clients=64, sessions=4, rate=1.2,
            duration=2.5, objects=32, payload=2048, osds=3, pg_num=4),
        # minimal shape for CLI exit-code tests (fast boot + window)
        "smoke-micro": LoadSpec(
            name="smoke-micro", clients=16, sessions=2, rate=1.5,
            duration=1.2, objects=16, payload=1024, osds=3, pg_num=4),
        # every front door at once: librados + RBD striped image I/O,
        # snapshots and clone reads + RGW object puts and multipart
        # transactions through rgw.py (round 15 verbs included)
        "mixed": LoadSpec(
            name="mixed", clients=96, sessions=6, rate=1.0,
            duration=3.0, objects=48, payload=4096, osds=3, pg_num=8,
            verbs=(("write", 3.0), ("read", 2.0), ("rmw", 1.0),
                   ("append", 1.0), ("rbd_write", 1.5),
                   ("rbd_read", 1.0), ("rgw_put", 1.5),
                   ("rgw_get", 1.0), ("rbd_snap", 0.5),
                   ("rbd_clone_read", 0.8), ("rgw_multipart", 0.8))),
        # the ramp shape: EC pool behind a deliberate admission budget,
        # so stepping the offered rate eventually trips pushback and
        # the knee is a real saturation point (AIMD cwnd + goodput
        # gates do the judging)
        "ramp-ec": LoadSpec(
            name="ramp-ec", clients=64, sessions=4, rate=0.8,
            duration=2.5, objects=32, payload=4096, osds=4,
            pool_kind="erasure", pool_size=3, pg_num=8,
            ec_profile=(("plugin", "jerasure"),
                        ("technique", "reed_sol_van"),
                        ("k", "2"), ("m", "1")),
            verbs=(("write", 4.0), ("read", 3.0), ("rmw", 1.0),
                   ("append", 1.0)),
            config=(("osd_op_throttle_ops", 24),)),
        # round 16: the verified-read path at rate — read-dominant mix
        # over an EC pool with verify-on-read (default on), judged by
        # the same gates plus the integrity-counters presence row
        "read-heavy": LoadSpec(
            name="read-heavy", clients=64, sessions=4, rate=1.2,
            duration=2.5, objects=32, payload=4096, osds=4,
            pool_kind="erasure", pool_size=3, pg_num=8,
            ec_profile=(("plugin", "jerasure"),
                        ("technique", "reed_sol_van"),
                        ("k", "2"), ("m", "1")),
            verbs=(("write", 1.5), ("read", 6.0), ("append", 0.5))),
        # round 16: reads racing the scheduled deep scrubber — scrub
        # traffic yields to client admission pressure while the SLO
        # gates (p99/goodput/deadline) must still hold
        "scrub-concurrent": LoadSpec(
            name="scrub-concurrent", clients=48, sessions=4, rate=1.0,
            duration=2.5, objects=24, payload=4096, osds=4,
            pool_kind="erasure", pool_size=3, pg_num=8,
            ec_profile=(("plugin", "jerasure"),
                        ("technique", "reed_sol_van"),
                        ("k", "2"), ("m", "1")),
            config=(("osd_scrub_interval", 0.5),),
            verbs=(("write", 2.0), ("read", 5.0), ("rmw", 0.5))),
        # dmclock conformance under contention: mclock queue with a
        # client reservation, so the conformance gate judges served_
        # reservation from the scrape
        "qos": LoadSpec(
            name="qos", clients=48, sessions=4, rate=1.5,
            duration=2.5, objects=24, payload=2048, osds=3, pg_num=4,
            config=(("osd_op_queue", "mclock"),
                    ("osd_mclock_default_reservation", 20.0),
                    ("osd_op_throttle_ops", 16)),
            gates=DEFAULT_GATES[:-1] + (("qos_reservation_min", 1.0),)),
    }
