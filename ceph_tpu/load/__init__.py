"""graft-load: deterministic traffic driver + SLO judge + soak.

The round-13 subsystem in the graft-chaos/graft-trace lineage
(ROADMAP item 3):

- ``dist``    — THE seeded samplers (zipfian popularity, arrival
                processes, weighted verb mixes), shared with chaos
- ``driver``  — ``LoadSpec`` + open-loop driver: simulated clients
                multiplexed over a bounded objecter session pool
- ``slo``     — gate verdicts computed from exported telemetry only
                (Prometheus scrape, mon health, admin-socket dumps)
- ``ramp``    — saturation search -> ``LOAD_r*.json`` artifact
- ``soak``    — sustained traffic x seeded chaos fault schedules,
                judged by durability + frontier invariants

Submodules are imported directly (``from ceph_tpu.load import
driver``); this package init stays import-free because chaos/scenario
imports ``load.dist`` — pulling driver/soak here would cycle back into
chaos.
"""

__all__ = ["dist", "driver", "slo", "ramp", "soak"]
