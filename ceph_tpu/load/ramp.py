"""Saturation search: step the offered rate until an SLO gate breaks.

The knee is the highest offered rate at which EVERY gate still passes
— the number a capacity planner actually wants, and the one the
reference's production deployments size clusters by (PAPER.md L5/L6).
One cluster is booted and reused across steps (counter deltas make each
window self-contained), the offered rate doubles per step, and the
sweep stops at the first failing step (or when the scale list runs
out).

The result is a ``LOAD_r*.json`` artifact beside the BENCH records,
carrying the SAME trust-model stamps bench.py enforces: mode
``cluster_vstart``, a NULL ``vs_baseline`` (load artifacts are never a
baseline ratio), and ``session_only: true`` — the dev host is
load-sensitive (BENCH_NOTES round 12), so absolute knee numbers only
compare WITHIN one session; cross-session judgments use gate verdicts,
not ops/s.
"""

from __future__ import annotations

import asyncio
import glob
import json
import os
import re
from typing import Dict, List, Optional, Sequence

from ceph_tpu.load.driver import LoadContext, LoadSpec, run_load

DEFAULT_SCALES: Sequence[float] = (1, 2, 4, 8, 16, 32, 64)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


async def ramp(spec: LoadSpec, seed: int,
               scales: Sequence[float] = DEFAULT_SCALES,
               tmpdir: Optional[str] = None) -> Dict:
    """Run the sweep; returns the artifact document (unwritten)."""
    ctx = await LoadContext.create(spec, seed, tmpdir=tmpdir)
    steps: List[Dict] = []
    knee: Optional[Dict] = None
    try:
        for scale in scales:
            step_spec = spec.scaled(scale)
            result, report = await run_load(step_spec, seed, ctx=ctx)
            offered_rate = result.offered / max(1e-6, step_spec.duration)
            p99_row = next((r for r in report.rows
                            if r["gate"] == "p99"), {})
            goodput_row = next((r for r in report.rows
                                if r["gate"] == "goodput"), {})
            step = {
                "scale": scale,
                "offered_ops_s": round(offered_rate, 1),
                "offered_ops": result.offered,
                "acked_ops_scraped": goodput_row.get("value"),
                "p99_ms": p99_row.get("value"),
                "passed": report.passed,
                "gates": report.as_rows(),
                # traceability (round 17): the failing gates' observed
                # vs threshold values and the graft-blackbox bundle a
                # failed judgment triggered — the artifact alone
                # diagnoses a failed step
                "failed_gates": report.failing_gates(),
                "postmortem": report.postmortem,
                "client": result.as_dict(),
            }
            steps.append(step)
            if report.passed:
                knee = {"scale": scale,
                        "offered_ops_s": step["offered_ops_s"],
                        "acked_ops_scraped": step["acked_ops_scraped"],
                        "p99_ms": step["p99_ms"]}
            else:
                break
            # quiesce between steps so one window's stragglers don't
            # bleed into the next window's scrape delta
            await asyncio.sleep(0.5)
    finally:
        await ctx.close()
    return {
        "kind": "graft-load ramp",
        "spec": spec.name,
        "seed": seed,
        "mode": "cluster_vstart",
        "vs_baseline": None,
        "baseline_src": "unmeasured",
        "session_only": True,
        "load_sensitive_host": True,
        "excluded_from_vs_baseline": True,
        "steps": steps,
        "knee": knee,
    }


def next_round() -> int:
    """Artifact numbering follows the existing BENCH/LOAD trajectory
    (the run_tpu_checks convention)."""
    rounds = [0]
    for pat in ("BENCH_r*.json", "LOAD_r*.json"):
        for path in glob.glob(os.path.join(_REPO, pat)):
            m = re.search(r"_r(\d+)\.json$", path)
            if m:
                rounds.append(int(m.group(1)))
    return max(rounds) + 1


def write_artifact(doc: Dict, out: Optional[str] = None) -> str:
    path = out or os.path.join(_REPO, f"LOAD_r{next_round():02d}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return path


def format_table(doc: Dict) -> str:
    """The worked ramp table (README / `scripts/load.py report`)."""
    lines = [f"ramp {doc['spec']} seed={doc['seed']} "
             f"(mode={doc['mode']}, session-only numbers)",
             f"{'scale':>6} {'offered/s':>10} {'acked':>8} "
             f"{'p99 ms':>9}  gates"]
    for s in doc["steps"]:
        failed = [r["gate"] for r in s["gates"] if not r["passed"]]
        lines.append(
            f"{s['scale']:>6g} {s['offered_ops_s']:>10} "
            f"{s['acked_ops_scraped'] if s['acked_ops_scraped'] is not None else '-':>8} "
            f"{s['p99_ms'] if s['p99_ms'] is not None else '-':>9}  "
            + ("ALL PASS" if s["passed"] else
               "FAIL: " + ",".join(failed)))
    knee = doc.get("knee")
    lines.append("knee: " + (
        f"{knee['offered_ops_s']} offered ops/s (scale {knee['scale']})"
        if knee else "NONE — no step passed every gate"))
    return "\n".join(lines)
