"""Seeded distributions for graft-load (and graft-chaos).

THE one implementation of object-popularity and arrival-process
sampling, shared by the chaos scenario runner and the load driver so
"zipfian hot objects" means the same bytes-on-the-wire everywhere
(round 13 moved ``_zipf_pick`` here from ``chaos/scenario.py``; the
chaos runner re-imports it, preserving its stream consumption exactly —
one ``rng.random()`` per pick — so existing seeded scenarios replay
unchanged).

Everything here draws from a caller-supplied ``random.Random``; stream
derivation stays in ``chaos/rng.py`` (``stream(seed, name)``), and each
simulated client gets its own named stream (``client_stream``) so
adding or removing one client never perturbs another's schedule — the
same replay-key determinism contract as chaos injectors.
"""

from __future__ import annotations

import bisect
import random
from itertools import accumulate
from typing import Dict, List, Sequence, Tuple

_ZIPF_CUM: Dict[Tuple[int, float], List[float]] = {}


def zipf_pick(rng: random.Random, n: int, alpha: float = 1.2) -> int:
    """Rank drawn from a zipfian over [0, n): a few hot objects take
    most writes (the million-client hot-set shape, ROADMAP item 3).
    Cumulative weights are precomputed per (n, alpha) — one rng draw
    and a binary search per pick, so stream consumption is exactly one
    ``random()`` call (the chaos seed-replay contract depends on it)."""
    cum = _ZIPF_CUM.get((n, alpha))
    if cum is None:
        cum = _ZIPF_CUM[(n, alpha)] = list(accumulate(
            1.0 / ((r + 1) ** alpha) for r in range(n)))
    x = rng.random() * cum[-1]
    return min(bisect.bisect_left(cum, x), n - 1)


def client_stream(seed: int, client_id: int,
                  tag: str = "sched") -> random.Random:
    """The independent rng stream for one simulated client (per-client
    streams, like per-injector chaos streams: one client's draws never
    shift another's).  The chaos import is deliberately lazy: chaos/
    scenario imports THIS module for the shared zipf sampler, and a
    module-level import back into the chaos package would cycle."""
    from ceph_tpu.chaos.rng import stream

    return stream(seed, f"load:client{client_id}:{tag}")


def arrival_offsets(rng: random.Random, rate: float, duration: float,
                    process: str = "poisson") -> List[float]:
    """Open-loop arrival times in [0, duration) for one client.

    ``poisson``: exponential inter-arrival gaps at ``rate`` ops/s (the
    memoryless per-client arrival process a large independent client
    population aggregates to).  ``fixed``: evenly spaced at 1/rate with
    a seeded phase, so a fleet of fixed-rate clients doesn't arrive in
    lockstep.  Both consume the rng deterministically."""
    if rate <= 0 or duration <= 0:
        return []
    out: List[float] = []
    if process == "fixed":
        gap = 1.0 / rate
        t = rng.random() * gap          # seeded phase
        while t < duration:
            out.append(t)
            t += gap
    elif process == "poisson":
        t = rng.expovariate(rate)
        while t < duration:
            out.append(t)
            t += rng.expovariate(rate)
    else:
        raise ValueError(f"unknown arrival process {process!r}")
    return out


def pick_weighted(rng: random.Random,
                  choices: Sequence[Tuple[str, float]]) -> str:
    """One weighted draw (verb-mix selection): a single ``random()``
    call walked over cumulative weights, so verb mixes of any length
    consume the stream identically."""
    total = sum(w for _, w in choices)
    x = rng.random() * total
    cum = 0.0
    for name, w in choices:
        cum += w
        if x <= cum:
            return name
    return choices[-1][0]
