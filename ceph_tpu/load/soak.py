"""Soak runner: sustained graft-load traffic composed with graft-chaos.

ROADMAP item 3's long-horizon half: rounds of open-loop mixed-verb
traffic racing a SEEDED fault schedule — the same ``Event`` vocabulary,
schedule resolution, and injector machinery as chaos scenarios
(including PR 9's tick/commit crash points), with the durability +
frontier invariants as the verdict.  Deliberately slow-marked and
excluded from ``vs_baseline`` by contract (BENCH_NOTES round 13): a
soak proves invariants under sustained fire, it never produces a
timing headline.

Determinism contract: the fault schedule and the per-round load plans
resolve from the seed exactly like a chaos scenario
(``Verdict.replay_key`` is reused verbatim), so a failing soak replays
with ``scripts/load.py soak --scenario <name> --seed <n>``.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ceph_tpu.chaos.counters import CHAOS
from ceph_tpu.chaos.daemons import DaemonInjector
from ceph_tpu.chaos.rng import stream
from ceph_tpu.chaos.scenario import (
    Event,
    Scenario,
    Verdict,
    apply_event,
    build_schedule,
    ev,
    heal_cluster,
    judge_invariants,
    wait_converged,
)
from ceph_tpu.load.driver import LoadContext, LoadSpec, build_plan, drive


@dataclass(frozen=True)
class SoakSpec:
    """Sustained load + a seeded fault schedule + invariant verdict."""

    name: str
    load: LoadSpec
    rounds: int = 3
    events: Tuple[Event, ...] = ()
    # ordering matters on a slow host: acting + frontier RETRY until
    # peering/recovery complete, so durability reads a converged
    # cluster instead of racing a mid-recovery one (a soak's FileStore
    # crash replays can outlast the check window when the host is
    # degraded — observed as "0 of k shard ranges" false failures)
    invariants: Tuple[str, ...] = ("acting", "frontier", "durability",
                                   "deadline", "health", "lockdep")
    converge_timeout: float = 90.0

    def schedule_shell(self) -> Scenario:
        """A chaos Scenario carrying just what ``build_schedule`` needs
        (cluster shape + events), so soak fault plans resolve through
        the SAME seeded resolver as chaos scenarios."""
        return Scenario(
            name=self.name, osds=self.load.osds,
            pool_kind=self.load.pool_kind, pool_size=self.load.pool_size,
            pg_num=self.load.pg_num, ec_profile=self.load.ec_profile,
            rounds=self.rounds, events=self.events, store=self.load.store)


async def run_soak(spec: SoakSpec, seed: int,
                   tmpdir: Optional[str] = None) -> Verdict:
    """Boot, sustain traffic through the fault schedule, heal,
    converge, judge by invariants.  Returns a chaos ``Verdict`` (same
    replay-key contract)."""
    schedule = build_schedule(spec.schedule_shell(), seed)
    rot = stream(seed, "bitrot")
    counters0 = dict(CHAOS.dump()["chaos"])
    ctx = await LoadContext.create(spec.load, seed, tmpdir=tmpdir)
    cluster = ctx.cluster
    dmn = DaemonInjector(cluster)
    acked: Dict[str, bytes] = {}
    attempted: Dict[str, set] = {}
    failures = []
    late_acks = []
    postmortem_path: Optional[str] = None
    try:
        io = ctx.io(0)
        for rnd in range(spec.rounds):
            evs = [e for e in schedule if e["round"] == rnd]
            for e in [e for e in evs if not e["during_writes"]
                      and not e.get("after_writes")]:
                await apply_event(cluster, dmn, ctx.sessions[0], io, e,
                                  rot, acked, ctx.pool)
            mid = [e for e in evs if e["during_writes"]]
            # each round drives one full load window; mid-round events
            # fire a beat into it (racing the in-flight traffic, the
            # chaos during_writes contract)
            plan = build_plan(spec.load, seed + rnd * 1000003)
            window = asyncio.get_event_loop().create_task(
                drive(ctx, spec.load, seed, plan=plan,
                      record_acked=True))
            try:
                if mid:
                    await asyncio.sleep(0.2 + rot.random() * 0.2)
                    for e in mid:
                        await apply_event(cluster, dmn, ctx.sessions[0],
                                          io, e, rot, acked, ctx.pool)
                result = await window
            except BaseException:
                # a failed mid-round injection must not orphan the
                # in-flight window: drain it before teardown so the
                # original failure surfaces clean
                window.cancel()
                try:
                    await window
                except (asyncio.CancelledError, Exception):
                    pass
                raise
            late_acks += result.late_acks
            for oid, data in result.acked.items():
                acked[oid] = data
            for oid, tries in result.attempted.items():
                attempted.setdefault(oid, set()).update(tries)
            for e in [e for e in evs if e.get("after_writes")]:
                await apply_event(cluster, dmn, ctx.sessions[0], io, e,
                                  rot, acked, ctx.pool)

        # -- heal + converge + judge: the chaos seams, verbatim
        #    (durability judges in attempted mode: zipf hot objects
        #    race concurrent writers by design) -----------------------
        await heal_cluster(cluster, dmn)
        await wait_converged(cluster, spec.converge_timeout)
        failures += await judge_invariants(
            cluster, dmn, io, spec.invariants, acked,
            attempted=attempted, mode="attempted",
            timeout=spec.converge_timeout, deadline_misses=late_acks)
        if failures and getattr(cluster.config, "blackbox_enabled", 0):
            # graft-blackbox: a convicted soak triggers a bundle before
            # teardown (same seam as a chaos conviction)
            # reason carries only the failure HEAD (invariant name):
            # full strings embed wall timings and ride in the detail —
            # the reason feeds the bundle's deterministic replay_key
            pm_rec = await cluster.blackbox_trigger(
                "chaos_conviction",
                f"soak {spec.name} seed={seed} convicted: "
                f"{failures[0].split(':', 1)[0]}",
                detail={"scenario": spec.name, "seed": seed,
                        "failures": list(failures)},
                clients=ctx.sessions)
            postmortem_path = (pm_rec or {}).get("path")
    finally:
        await ctx.close()
    counters1 = CHAOS.dump()["chaos"]
    delta = {k: counters1[k] - counters0.get(k, 0) for k in counters1
             if counters1[k] - counters0.get(k, 0)}
    return Verdict(name=spec.name, seed=seed, schedule=schedule,
                   passed=not failures, failures=failures,
                   acked_objects=len(acked), counters=delta,
                   gates=[{"gate": "invariants", "value": len(failures),
                           "threshold": 0, "passed": not failures}],
                   postmortem=postmortem_path)


def builtin_soaks() -> Dict[str, SoakSpec]:
    """The shipped soak library (scripts/load.py `list`)."""
    return {
        # the round-13 acceptance soak: sustained mixed-verb EC traffic
        # on a durable store racing tick/commit crash points, judged by
        # durability + frontier (slow; never on the bench hot path)
        "soak-mixed-crash": SoakSpec(
            name="soak-mixed-crash",
            load=LoadSpec(
                name="soak-mixed-crash", clients=48, sessions=4,
                rate=1.2, duration=2.5, objects=24, payload=2048,
                osds=5, pool_kind="erasure", pool_size=3, pg_num=8,
                ec_profile=(("plugin", "jerasure"),
                            ("technique", "reed_sol_van"),
                            ("k", "2"), ("m", "1")),
                store="file", op_deadline=12.0,
                verbs=(("write", 4.0), ("read", 3.0), ("rmw", 1.0),
                       ("append", 1.0))),
            rounds=3,
            events=(
                ev(0, "net", target="all_osds",
                   chaos_net_batch_item_drop=0.05),
                ev(0, "crash_point", point="tick_post_encode",
                   during_writes=True),
                ev(1, "revive_osd"),
                ev(1, "crash_point", point="commit_mid_fanout",
                   during_writes=True),
                ev(2, "revive_osd"),
            ),
            invariants=("acting", "frontier", "durability", "deadline",
                        "health", "lockdep"),
            converge_timeout=150.0),
        # replicated bounce soak on MemStore-free durable stores: the
        # rolling-restart shape under sustained mixed traffic
        "soak-rolling-restart": SoakSpec(
            name="soak-rolling-restart",
            load=LoadSpec(
                name="soak-rolling-restart", clients=48, sessions=4,
                rate=1.2, duration=2.5, objects=24, payload=2048,
                osds=5, pg_num=8, store="file", op_deadline=12.0,
                verbs=(("write", 4.0), ("read", 3.0), ("append", 1.0))),
            rounds=3,
            events=(
                ev(0, "restart_osd", during_writes=True),
                ev(1, "restart_osd", during_writes=True),
                ev(2, "restart_osd", during_writes=True),
            ),
            invariants=("acting", "frontier", "durability", "deadline",
                        "health", "lockdep"),
            converge_timeout=120.0),
    }
