"""SLO judge: declarative gates evaluated from EXPORTED telemetry.

The contract (ROADMAP item 3): every gate verdict is computed from what
the cluster actually exports — the mgr Prometheus text scrape, the
mon's health command, and admin-socket dumps — never from reaching into
daemon internals.  A production operator could compute the identical
verdicts from the identical endpoints; that is the point.  (The single
exception is the ``deadline`` gate: "zero acks past the client's
budget" is by definition client-observed, exactly like the chaos
``deadline`` invariant.)

Gates:

====================  ==================================================
``goodput``           scraped served-op delta >= ``goodput_min_frac`` x
                      the offered op count (``ceph_osd_client_ops``)
``p99``               scraped op-latency histogram p99 over the window
                      <= ``p99_ms`` (``ceph_osd_op_lat_hist`` buckets)
``cwnd``              the client AIMD window CONVERGED, not collapsed:
                      either no pushback ever arrived (wide open) or
                      the post-window window floor >= ``cwnd_floor``
                      (``ceph_client_cwnd`` / ``_pushbacks``)
``qos``               dmclock conformance visible on the scrape; under
                      declared contention (``qos_reservation_min`` > 0)
                      reservation-driven dequeues actually happened
``health``            SLOW_OPS and LOOP_LAG clear at window end (mon
                      health checks)
``deadline``          zero acked-past-deadline ops (client-observed)
====================  ==================================================
"""

from __future__ import annotations

import asyncio
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_SERIES = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[-+0-9.eEinfa]+)$")
_LABEL = re.compile(r'(\w+)="([^"]*)"')


def parse_prometheus(text: str) -> Dict[str, List[Tuple[Dict[str, str],
                                                        float]]]:
    """Prometheus text exposition -> {metric: [(labels, value), ...]}.
    Tiny on purpose: exactly the subset ``render_prometheus`` emits."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SERIES.match(line)
        if not m:
            continue
        labels = dict(_LABEL.findall(m.group("labels") or ""))
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        out.setdefault(m.group("name"), []).append((labels, value))
    return out


@dataclass
class TelemetrySnapshot:
    """One scrape of everything the judge is allowed to look at."""

    prom: Dict[str, List[Tuple[Dict[str, str], float]]]
    health: Dict
    dmclock: Dict[str, Dict]
    stamp: float = 0.0


async def snapshot(cluster) -> TelemetrySnapshot:
    """Collect the exported views: mgr Prometheus text (admin-command
    scrape — same exposition the HTTP endpoint serves), mon health, and
    per-OSD ``dump_dmclock`` admin dumps."""
    text = await cluster.daemon_command("mgr", "prometheus metrics")
    health = await cluster.clients[0].objecter.mon_command(
        {"prefix": "health"})
    dm: Dict[str, Dict] = {}
    for osd_id in sorted(cluster.osds):
        dm[f"osd.{osd_id}"] = await cluster.daemon_command(
            f"osd.{osd_id}", "dump_dmclock")
    return TelemetrySnapshot(prom=parse_prometheus(text), health=health,
                             dmclock=dm,
                             stamp=asyncio.get_event_loop().time())


# ------------------------------------------------------------- helpers


def counter_sum(snap: TelemetrySnapshot, metric: str,
                daemon_prefix: str = "osd.") -> float:
    return sum(v for labels, v in snap.prom.get(metric, ())
               if labels.get("daemon", "").startswith(daemon_prefix))


def counter_delta(before: TelemetrySnapshot, after: TelemetrySnapshot,
                  metric: str, daemon_prefix: str = "osd.") -> float:
    return counter_sum(after, metric, daemon_prefix) - \
        counter_sum(before, metric, daemon_prefix)


def _bucket_table(snap: TelemetrySnapshot, metric: str) -> Dict[Tuple[str,
                                                                      str],
                                                                float]:
    out: Dict[Tuple[str, str], float] = {}
    for labels, v in snap.prom.get(f"{metric}_bucket", ()):
        out[(labels.get("daemon", ""), labels.get("le", ""))] = v
    return out


def hist_quantile(before: TelemetrySnapshot, after: TelemetrySnapshot,
                  metric: str, q: float) -> Optional[float]:
    """Quantile of the WINDOW's samples from cumulative-bucket deltas,
    merged across daemons.  Returns the bucket upper bound (same units
    as the histogram ``_sum`` — seconds for latency histograms), None
    when the window recorded no samples, or ``inf`` when the quantile
    falls in the ``+Inf`` bucket — the caller's <= gate must FAIL on
    overflow (clamping to the top finite bound would let an
    arbitrarily bad tail pass the ceiling)."""
    b0, b1 = _bucket_table(before, metric), _bucket_table(after, metric)
    per_le: Dict[str, float] = {}
    for key, v in b1.items():
        d = v - b0.get(key, 0.0)
        if d > 0:
            per_le[key[1]] = per_le.get(key[1], 0.0) + d
    if not per_le:
        return None
    total = per_le.pop("+Inf", None)
    finite = sorted((float(le), c) for le, c in per_le.items())
    if total is None:
        total = max((c for _, c in finite), default=0.0)
    if total <= 0:
        return None
    want = q * total
    for le, cum in finite:
        if cum >= want:
            return le
    return float("inf")


# --------------------------------------------------------------- gates


@dataclass
class SLOReport:
    """Gate verdicts for one load window (rides the LOAD_r* artifact)."""

    rows: List[Dict] = field(default_factory=list)
    # path of the graft-blackbox bundle a failing judgment triggered
    # (None when passing or when the recorder is off) — artifact
    # traceability: a failed run is diagnosable from the artifact alone
    postmortem: Optional[str] = None

    @property
    def passed(self) -> bool:
        return all(r["passed"] for r in self.rows)

    def failures(self) -> List[str]:
        return [f"{r['gate']}: value={r['value']} "
                f"threshold={r['threshold']} ({r.get('note', '')})"
                for r in self.rows if not r["passed"]]

    def failing_gates(self) -> List[Dict]:
        """Observed-vs-threshold rows for every failed gate — what the
        postmortem trigger detail and the artifact record."""
        return [{"gate": r["gate"], "value": r["value"],
                 "threshold": r["threshold"]}
                for r in self.rows if not r["passed"]]

    def as_rows(self) -> List[Dict]:
        return [dict(r) for r in self.rows]


def _row(report: SLOReport, gate: str, value, threshold, passed: bool,
         source: str, note: str = "") -> None:
    report.rows.append({"gate": gate, "value": value,
                        "threshold": threshold, "passed": bool(passed),
                        "source": source, "note": note})


def judge(spec, result, before: TelemetrySnapshot,
          after: TelemetrySnapshot) -> SLOReport:
    """Evaluate every gate for one window.  ``spec`` is the LoadSpec
    (thresholds), ``result`` the LoadResult (offered count + the
    client-observed deadline bookkeeping)."""
    report = SLOReport()

    # goodput: served client ops on the scrape vs what we offered
    served = counter_delta(before, after, "ceph_osd_client_ops")
    floor = spec.gate("goodput_min_frac", 0.5) * max(1, result.offered)
    _row(report, "goodput", round(served, 1), round(floor, 1),
         served >= floor, "scrape:ceph_osd_client_ops",
         f"offered={result.offered} over {spec.duration}s")

    # p99 latency from the scraped histogram delta
    ceil_s = spec.gate("p99_ms", 5000.0) / 1000.0
    p99 = hist_quantile(before, after, "ceph_osd_op_lat_hist", 0.99)
    if p99 is None:
        note, value = "no samples in window", None
    elif p99 == float("inf"):
        # stay JSON-clean in the artifact: the overflow is a string
        note, value = "p99 beyond the largest histogram bucket", "+Inf"
    else:
        note, value = "", round(p99 * 1000.0, 3)
    _row(report, "p99", value, spec.gate("p99_ms", 5000.0),
         p99 is not None and p99 <= ceil_s,
         "scrape:ceph_osd_op_lat_hist", note)

    # AIMD congestion window: converged, not collapsed.  Zero pushbacks
    # means the window never constrained (a provable no-op) and passes;
    # with pushbacks, the surviving window must stay off the floor.
    cwnds = [v for labels, v in after.prom.get("ceph_client_cwnd", ())
             if labels.get("daemon", "").startswith("client.load")]
    pushbacks = counter_delta(before, after, "ceph_client_cwnd_pushbacks",
                              daemon_prefix="client.load")
    cwnd_floor = spec.gate("cwnd_floor", 2.0)
    if not cwnds:
        _row(report, "cwnd", None, cwnd_floor, False,
             "scrape:ceph_client_cwnd",
             "no client sessions on the scrape (mgr_report missing)")
    elif pushbacks == 0:
        _row(report, "cwnd", min(cwnds), cwnd_floor, True,
             "scrape:ceph_client_cwnd", "no pushback: window wide open")
    else:
        _row(report, "cwnd", min(cwnds), cwnd_floor,
             min(cwnds) >= cwnd_floor, "scrape:ceph_client_cwnd",
             f"{int(pushbacks)} pushbacks in window")

    # dmclock conformance: the counters must be ON the scrape; under
    # declared contention, reservation-driven dequeues happened
    res_min = spec.gate("qos_reservation_min", 0.0)
    mclock = any(d.get("enabled") for d in after.dmclock.values())
    present = "ceph_osd_qos_served_reservation" in after.prom and \
        "ceph_osd_qos_evicted" in after.prom
    if not mclock:
        _row(report, "qos", None, res_min, present,
             "scrape+admin:dump_dmclock",
             "osd_op_queue=fifo: conformance not applicable; counters "
             + ("exported" if present else "MISSING from scrape"))
    else:
        res = counter_delta(before, after,
                            "ceph_osd_qos_served_reservation")
        _row(report, "qos", round(res, 1), res_min,
             present and res >= res_min, "scrape+admin:dump_dmclock",
             f"evicted={int(counter_sum(after, 'ceph_osd_qos_evicted'))}")

    # health: the overload warnings stayed clear at window end
    checks = (after.health or {}).get("checks", {}) or {}
    bad = sorted(set(checks) & {"SLOW_OPS", "LOOP_LAG"})
    _row(report, "health", bad or "clear", "no SLOW_OPS/LOOP_LAG",
         not bad, "mon:health",
         "; ".join(str(checks[k]) for k in bad))

    # control plane (round 14): the vectorized-churn counters must be
    # ON the scrape (epochs applied, PGs re-peered, the peering
    # duration histogram, skip-to-full events), and an optional
    # map_epochs_min floor gates churn keep-up — storm soaks set it,
    # steady-state specs leave it 0 (counters-present only)
    epochs_min = spec.gate("map_epochs_min", 0.0)
    applied = counter_delta(before, after, "ceph_osd_map_epochs_applied")
    cp_present = all(
        name in after.prom for name in (
            "ceph_osd_map_epochs_applied", "ceph_osd_pgs_repeered",
            "ceph_osd_map_skip_to_full",
            "ceph_osd_peering_lat_hist_bucket"))
    _row(report, "map_churn", round(applied, 1), epochs_min,
         cp_present and applied >= epochs_min,
         "scrape:ceph_osd_map_epochs_applied",
         "" if cp_present
         else "control-plane counters MISSING from scrape")

    # integrity & full-protection counters (round 16): the verified
    # read / read-repair / scheduled-scrub / cluster-full telemetry
    # must be ON the scrape (an operator alerts on these; a refactor
    # dropping them from export would blind every such alert), and an
    # optional repairs floor gates corruption soaks — steady-state
    # specs leave it 0 (counters-present only, like map_churn)
    repairs_min = spec.gate("integrity_repairs_min", 0.0)
    repairs = counter_delta(before, after, "ceph_osd_read_repairs") + \
        counter_delta(before, after, "ceph_osd_scrub_errors_repaired")
    integ_present = all(
        name in after.prom for name in (
            "ceph_osd_read_repairs", "ceph_osd_read_shard_crc_errors",
            "ceph_osd_scrub_errors_repaired", "ceph_osd_full_rejects",
            "ceph_osd_read_batch_ticks"))
    _row(report, "integrity", round(repairs, 1), repairs_min,
         integ_present and repairs >= repairs_min,
         "scrape:ceph_osd_read_repairs",
         "" if integ_present
         else "integrity/full counters MISSING from scrape")

    # balance counters (round 21): the mgr's balancer/autoscaler/reshape
    # families must be ON the scrape even with the subsystem disabled
    # (declared at mgr init — all-zeros is the provable-no-op witness),
    # and an optional committed-moves floor gates convergence scenarios
    # — steady-state specs leave it 0 (counters-present only)
    moves_min = spec.gate("balance_moves_min", 0.0)
    committed = counter_delta(before, after,
                              "ceph_mgr_balancer_moves_committed",
                              daemon_prefix="mgr.")
    bal_present = all(
        name in after.prom for name in (
            "ceph_mgr_balancer_rounds", "ceph_mgr_balancer_candidates",
            "ceph_mgr_balancer_moves_committed",
            "ceph_mgr_balancer_throttled", "ceph_mgr_autoscale_rounds"))
    _row(report, "balance", round(committed, 1), moves_min,
         bal_present and committed >= moves_min,
         "scrape:ceph_mgr_balancer_moves_committed",
         "" if bal_present
         else "mgr balance counters MISSING from scrape")

    # deadline: zero acks past the client budget (client-observed —
    # the one gate that cannot come from a scrape by definition)
    _row(report, "deadline", len(result.late_acks), 0,
         not result.late_acks, "client:driver",
         result.late_acks[0] if result.late_acks else "")
    return report
