"""Rule family ``jax-hygiene``: tracer/host-sync discipline in jitted code.

PR 2's planar rewrite showed the costliest bugs here are structural:
a hidden host sync inside a device loop silently serializes dispatch
(and on the axon tunnel also invalidates the timing trust model — see
BENCH_NOTES.md), and Python control flow on a tracer either fails at
trace time or bakes one branch in forever.  No runtime assertion
catches these until a bench regresses; this pass finds them in the AST.

What counts as "traced code": functions decorated ``@jax.jit`` /
``@partial(jax.jit, ...)``, functions/lambdas wrapped ``jax.jit(f)``,
bodies handed to ``jax.lax.scan``, and the step/feedback callables
handed to the bench device-loop harness (``device_loop_slope`` /
``_bench_device_loop``) — the measured region of the timing contract.

Checks inside traced code:
- host materialization of a traced parameter: ``np.asarray``/``np.array``
  /``float``/``int``/``bool`` applied to a non-static parameter
  (static_argnums-named params are host values and exempt);
- ``.block_until_ready()`` / ``.item()`` anywhere;
- ``time.*`` wall-clock calls (they run at TRACE time, not step time);
- Python ``if``/``while`` branching on a bare non-static parameter
  (``.shape``/``.ndim``/``.dtype``/``len()``/``isinstance``/``is None``
  uses are static and exempt).

Module scope: any ``jnp.*(...)`` call in a top-level statement traces
and compiles at import — flagged (host-side ``np`` tables are fine).

Resolution is by direct parameter reference (no dataflow), following the
deviant-behavior school: high-precision, low-noise checks that hold as
a zero-findings tier-1 gate.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set, Tuple

from ceph_tpu.analysis.astutil import dotted, names_in, param_names, \
    walk_functions
from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "jax-hygiene"

_JIT_NAMES = {"jax.jit", "jit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}
_SCAN_NAMES = {"jax.lax.scan", "lax.scan"}
_DEVICE_LOOP_NAMES = {"device_loop_slope", "_bench_device_loop"}
_HOST_COERCE = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "float", "int", "bool"}
_TIME_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
               "time.sleep", "time.process_time", "datetime.datetime.now"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _static_argnums(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """(static positions, static param names) from a jit/partial call —
    both keywords honored, int and str constants respectively."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        vals = list(kw.value.elts) \
            if isinstance(kw.value, (ast.Tuple, ast.List)) else [kw.value]
        for v in vals:
            if isinstance(v, ast.Constant):
                if isinstance(v.value, int):
                    nums.add(v.value)
                elif isinstance(v.value, str):
                    names.add(v.value)
    return nums, names


def _jit_decorator(fn) -> Optional[Tuple[Set[int], Set[str]]]:
    """(static_argnums, static_argnames) if ``fn`` is decorated jitted,
    else None."""
    for dec in fn.decorator_list:
        d = dotted(dec)
        if d in _JIT_NAMES:
            return set(), set()
        if isinstance(dec, ast.Call):
            dc = dotted(dec.func)
            if dc in _JIT_NAMES:
                return _static_argnums(dec)
            if dc in _PARTIAL_NAMES and dec.args \
                    and dotted(dec.args[0]) in _JIT_NAMES:
                return _static_argnums(dec)
    return None


def _collect_traced(module) -> List[Tuple[str, ast.AST, Set[str]]]:
    """(symbol, fn_node, static_param_names) for every traced function/
    lambda in the module."""
    # keep duplicates: bench_ec defines `step` once per workload branch,
    # and a dict keyed by qualified name would silently drop all but one
    fns = list(walk_functions(module.tree))
    by_name: dict = {}
    for sym, fn in fns:
        by_name.setdefault(fn.name, []).append((sym, fn))

    traced: dict = {}
    _NO_STATICS = (set(), set())

    def add(sym, fn, statics):
        if fn in traced:
            return
        nums, names = statics
        params = param_names(fn)
        static_names = {params[i] for i in nums if i < len(params)}
        static_names |= names & set(params)
        traced[fn] = (sym, static_names)

    for sym, fn in fns:
        statics = _jit_decorator(fn)
        if statics is not None:
            add(sym, fn, statics)

    def mark_by_ref(node: ast.AST, owner_sym: str, statics):
        if isinstance(node, ast.Lambda):
            add(f"{owner_sym}.<lambda>" if owner_sym else "<lambda>",
                node, statics)
        elif isinstance(node, ast.Name):
            for s, f in by_name.get(node.id, []):
                add(s, f, statics)

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        cn = dotted(node.func)
        sym = ""
        if cn in _JIT_NAMES and node.args:
            mark_by_ref(node.args[0], sym, _static_argnums(node))
        elif cn in _SCAN_NAMES and node.args:
            mark_by_ref(node.args[0], sym, _NO_STATICS)
        elif cn is not None and cn.split(".")[-1] in _DEVICE_LOOP_NAMES:
            for arg in node.args[:2]:
                mark_by_ref(arg, sym, _NO_STATICS)
    return [(sym, fn, statics) for fn, (sym, statics) in traced.items()]


def _bare_tracer_refs(test: ast.AST, tracers: Set[str]) -> Set[str]:
    """Non-static param names used 'bare' in a branch test — excluding
    static uses (.shape/.ndim/.dtype/.size, len(), isinstance(),
    ``is None`` checks)."""
    bare: Set[str] = set()

    def visit(node):
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return  # x.shape is static under trace
            visit(node.value)
            return
        if isinstance(node, ast.Call):
            cn = dotted(node.func)
            if cn in ("len", "isinstance", "getattr", "hasattr", "type"):
                return
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                visit(a)
            return
        if isinstance(node, ast.Compare):
            ops_static = all(isinstance(o, (ast.Is, ast.IsNot))
                             for o in node.ops)
            if ops_static:
                return  # `x is None` style identity checks are host-side
        if isinstance(node, ast.Name):
            if node.id in tracers:
                bare.add(node.id)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(test)
    return bare


def _check_traced_fn(module, sym: str, fn, static_names: Set[str],
                     findings: List[Finding]):
    params = set(param_names(fn))
    if params and param_names(fn)[0] in ("self", "cls"):
        params.discard(param_names(fn)[0])
    tracers = params - static_names

    def flag(node, msg):
        findings.append(Finding(
            rule=RULE, path=module.relpath, line=node.lineno,
            symbol=sym or "<lambda>", message=msg))

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                cn = dotted(node.func)
                if cn in _HOST_COERCE:
                    ref = tracers & set().union(
                        *(names_in(a) for a in node.args), set())
                    if ref:
                        flag(node,
                             f"host materialization {cn}() of traced "
                             f"value {sorted(ref)[0]!r} inside jitted/"
                             f"device-loop code (host sync)")
                elif cn in _TIME_CALLS:
                    flag(node,
                         f"wall-clock call {cn}() inside traced code "
                         f"runs at trace time, not per step")
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "block_until_ready":
                    flag(node,
                         "block_until_ready() inside traced code "
                         "(host sync in the measured region)")
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item" and not node.args:
                    flag(node,
                         ".item() inside traced code forces a host "
                         "readback")
            elif isinstance(node, (ast.If, ast.While)):
                bare = _bare_tracer_refs(node.test, tracers)
                if bare:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    flag(node,
                         f"Python `{kind}` branches on traced value "
                         f"{sorted(bare)[0]!r}; use lax.cond/select or "
                         f"hoist the decision to host metadata")


def _module_scope_jnp(module, findings: List[Finding]):
    for stmt in module.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Import, ast.ImportFrom)):
            continue
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                cn = dotted(node.func)
                if cn is not None and (cn.startswith("jnp.") or
                                       cn.startswith("jax.numpy.")):
                    findings.append(Finding(
                        rule=RULE, path=module.relpath, line=node.lineno,
                        symbol="",
                        message=f"module-scope {cn}() computes on device "
                                f"at import time; build host-side (np) "
                                f"and convert inside a function"))


def check(modules, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        for sym, fn, static_names in _collect_traced(m):
            _check_traced_fn(m, sym, fn, static_names, findings)
        _module_scope_jnp(m, findings)
    return findings
