"""Rule family ``await-atomicity``: stale shared-state snapshots across
await boundaries in the cluster data plane.

The costliest bug class of this reproduction is the await-interleaving
race: a coroutine snapshots shared cluster state, awaits, and then acts
on the stale snapshot — PR 9's superseded-PGState ack-wait persist
(``_advance_last_complete`` wrote a watermark through a PGState the PG
had left and rejoined around), PR 11's stale self-info peering wedge
(the roll-forward floor rested on an ``infos`` snapshot taken before
``_sync_self_from`` advanced the primary's own log), and PR 12's stale
RBD handle ``snap_remove`` were all exactly this shape, and every one
was found by a lucky chaos seed.  This pass convicts the shape
statically, the way the lock-order rule convicts deadlocks before any
test interleaves them.

Flagged inside ``async def``s under the cluster scope, driven by a
declared watch-list of known-mutable hot state (``WATCHED_STATE`` — the
DEVICE_CALLS idiom: adding a field to the list is a one-line diff):

- **stale-snapshot-across-await**: a local bound from a watched
  attribute read, where an ``await`` separates the binding from a later
  use and nothing revalidates in between.  Revalidation = re-binding
  the name after the await, or a test (``if``/``while``/``assert``/
  conditional expression) that mentions BOTH the name and its watched
  source — the PR-9 fix's ``pgs.get(st.pgid) is not st`` identity
  recheck is the canonical form.
- **check-then-act-across-await**: a conditional whose test reads a
  watched attribute and whose body awaits and THEN mutates state
  through that same attribute without re-checking — the classic
  check/act window where the checked predicate no longer holds.
- **lock-window-escape**: a local bound from a watched attribute read
  INSIDE an ``async with DepLock(...)`` block and used after the block
  exits — the lock made the snapshot consistent, leaving the window
  un-makes it.  (The sanctioned split-commit pattern — commit section
  under the lock, ack-wait outside — stays legal exactly when the
  post-window code revalidates, which is what the PR-9/PR-12 fixes
  added; un-revalidated escapes land here.)

The analysis is lexical (source order approximates control flow, the
standard linter trade): it can miss loop-carried staleness and may flag
a snapshot whose await is on an unrelated branch.  Deliberate,
documented windows carry a ``graftlint: ignore[await-atomicity]``
pragma at the use site or a justified baseline entry — every remnant is
then a visible, reviewed inventory row of the repo's await windows.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.analysis.astutil import dotted, walk_functions
from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "await-atomicity"

# async daemon code the rule polices — the cluster data/control plane
# (same shape as the task-spawn scope; pinned by the graftlint scope
# tests so a refactor can't silently drop cluster/ coverage)
SCOPE = ("ceph_tpu/cluster/",)

# The watch-list: attribute names whose read is a SNAPSHOT of shared
# mutable cluster state that concurrent tasks advance across awaits.
# Chosen for the hot races this repo has already paid for: the per-OSD
# PG registry (PR 9), PGState commit watermarks and membership (PR 9 /
# PR 11 / the frontier), and the in-flight pipeline map.  osdmap/epoch
# reads are deliberately NOT listed: epochs are versioned values whose
# staleness the map-subscription protocol already handles by design.
WATCHED_STATE = frozenset({
    "pgs", "_pgs",                       # OSD pgid -> PGState registry
    "acting", "up",                      # PG membership (peering moves it)
    "last_update", "last_complete",      # log head / commit watermark
    "pipeline_pending",                  # in-flight commit frontier
    "frontier_recovering",               # boot-reconstructed open entries
})

FIX = ("revalidate after the await (re-read the attribute, or "
       "identity-check the snapshot against its source) or pragma the "
       "documented window")

# mutating method names: a call through the snapshot/watched attr that
# writes state (the check-then-act "act" half, and a stale-snapshot use
# that is definitely not a harmless read)
_MUTATORS = frozenset({
    "append", "add", "pop", "remove", "discard", "clear", "update",
    "setdefault", "insert", "extend",
})


def _walk_shallow(root: ast.AST):
    """ast.walk that does not descend into nested function bodies —
    nested defs run on their own schedule and are analysed on their
    own when ``walk_functions`` yields them."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _watched_reads(expr: ast.AST) -> Set[str]:
    """Watched attribute names read anywhere inside ``expr``."""
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in WATCHED_STATE:
            out.add(node.attr)
        elif isinstance(node, ast.Name) and node.id in WATCHED_STATE:
            out.add(node.id)
    return out


def _pos(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def _end(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "end_lineno", getattr(node, "lineno", 0)),
            getattr(node, "end_col_offset", 0))


_INF = (10 ** 9, 0)


def _scope_end(node: ast.AST, parents: Dict[ast.AST, ast.AST],
               fn: ast.AST) -> Tuple[int, int]:
    """How far forward an await at ``node`` can flow: if an enclosing
    block ends in ``return``/``raise`` (the guard-clause idiom —
    ``if st is None: await reply(...); return``), executions that ran
    the await terminate inside that block and never reach code after
    it, so the await cannot stale-ify later uses."""
    cur = node
    p = parents.get(cur)
    while p is not None and cur is not fn:
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            break
        for _field, value in ast.iter_fields(p):
            if isinstance(value, list) and cur in value and value and \
                    isinstance(value[-1], (ast.Return, ast.Raise)) and \
                    p is not fn:
                return _end(value[-1])
        cur, p = p, parents.get(p)
    return _INF


class _FnScan:
    """One async function's lexical event streams."""

    def __init__(self, fn: ast.AsyncFunctionDef):
        self.fn = fn
        self._parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(fn):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        # (pos, name, watched_attrs, rhs_node) for x = <watched read>
        self.snapshots: List[Tuple[Tuple[int, int], str, Set[str]]] = []
        # (pos, end, reach) of suspension points (Await / AsyncWith /
        # AsyncFor): ``end`` closes the expression itself (arguments
        # evaluate BEFORE the suspension), ``reach`` bounds the code
        # the suspension can flow into
        self.awaits: List[Tuple[Tuple[int, int], Tuple[int, int],
                                Tuple[int, int]]] = []
        # name -> sorted positions of Store bindings (incl. the snapshot)
        self.stores: Dict[str, List[Tuple[int, int]]] = {}
        # name -> sorted positions of Load uses
        self.loads: Dict[str, List[Tuple[int, int]]] = {}
        # test expressions (if/while/assert/ternary/comprehension-if):
        # (pos, names mentioned, watched attrs mentioned)
        self.tests: List[Tuple[Tuple[int, int], Set[str], Set[str]]] = []
        self._walk(fn)

    def _note_test(self, expr: ast.AST) -> None:
        names = {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}
        self.tests.append((_pos(expr), names, _watched_reads(expr)))

    def _walk(self, root: ast.AST) -> None:
        def rec(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # nested defs run on their own schedule
                if isinstance(child, ast.Await):
                    self.awaits.append(
                        (_pos(child), _end(child),
                         _scope_end(child, self._parents, self.fn)))
                elif isinstance(child, (ast.AsyncWith, ast.AsyncFor)):
                    # the whole block is a suspension region, but its
                    # header expression still evaluates pre-suspension
                    self.awaits.append(
                        (_pos(child), _pos(child),
                         _scope_end(child, self._parents, self.fn)))
                if isinstance(child, (ast.If, ast.While)):
                    self._note_test(child.test)
                elif isinstance(child, ast.Assert):
                    self._note_test(child.test)
                elif isinstance(child, ast.IfExp):
                    self._note_test(child.test)
                elif isinstance(child, ast.comprehension):
                    for cond in child.ifs:
                        self._note_test(cond)
                if isinstance(child, ast.Assign):
                    watched = _watched_reads(child.value)
                    for t in child.targets:
                        if isinstance(t, ast.Name):
                            self.stores.setdefault(
                                t.id, []).append(_pos(child))
                            if watched:
                                self.snapshots.append(
                                    (_pos(child), t.id, watched))
                elif isinstance(child, ast.AnnAssign) and child.value:
                    if isinstance(child.target, ast.Name):
                        watched = _watched_reads(child.value)
                        self.stores.setdefault(
                            child.target.id, []).append(_pos(child))
                        if watched:
                            self.snapshots.append(
                                (_pos(child), child.target.id, watched))
                elif isinstance(child, ast.NamedExpr) and \
                        isinstance(child.target, ast.Name):
                    watched = _watched_reads(child.value)
                    self.stores.setdefault(
                        child.target.id, []).append(_pos(child))
                    if watched:
                        self.snapshots.append(
                            (_pos(child), child.target.id, watched))
                elif isinstance(child, (ast.For, ast.AsyncFor)):
                    # loop targets rebind on every iteration — a fresh
                    # binding for staleness purposes
                    for n in ast.walk(child.target):
                        if isinstance(n, ast.Name):
                            self.stores.setdefault(
                                n.id, []).append(_pos(child))
                elif isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        if item.optional_vars is not None:
                            for n in ast.walk(item.optional_vars):
                                if isinstance(n, ast.Name):
                                    self.stores.setdefault(
                                        n.id, []).append(_pos(child))
                if isinstance(child, ast.Name) and \
                        isinstance(child.ctx, ast.Load):
                    self.loads.setdefault(child.id, []).append(_pos(child))
                rec(child)

        rec(root)
        self.awaits.sort()

    def await_between(self, pos, use) -> bool:
        """Is there a suspension point between ``pos`` and ``use``
        whose post-await flow can reach ``use``?  A use inside the
        await expression itself evaluates pre-suspension and does not
        count."""
        return any(pos < a and end < use and use <= reach
                   for (a, end, reach) in self.awaits)

    def revalidated(self, name: str, watched: Set[str],
                    lo, hi) -> bool:
        """Is there a re-binding of ``name`` or a test mentioning both
        ``name`` and one of its watched sources in (lo, hi]?"""
        for p in self.stores.get(name, ()):
            if lo < p <= hi:
                return True
        for (p, names, attrs) in self.tests:
            if lo < p <= hi and name in names and (attrs & watched):
                return True
        return False


def _mutation_sites(body_nodes: List[ast.AST]) -> List[Tuple[Tuple[int, int],
                                                             ast.AST]]:
    """(pos, node) of state mutations lexically inside ``body_nodes``:
    attribute/subscript stores, augmented assigns, ``del``, and calls
    of mutating methods."""
    out = []
    for root in body_nodes:
        for node in _walk_shallow(root):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        out.append((_pos(node), t))
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, (ast.Attribute, ast.Subscript)):
                out.append((_pos(node), node.target))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        out.append((_pos(node), t))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                out.append((_pos(node), node.func.value))
    return out


def _check_stale_snapshot(m, sym: str, scan: _FnScan, windows,
                          findings: List[Finding]) -> None:
    reported: Set[Tuple[str, str]] = set()
    for (pos, name, watched) in scan.snapshots:
        if (name, min(watched)) in reported:
            continue
        if any(w_start <= pos <= w_end for (w_start, w_end) in windows):
            # snapshot taken inside an async-with DepLock window: the
            # lock IS the revalidation while the window lasts (the
            # sanctioned split-commit shape), and a value that OUTLIVES
            # the window is the escape variant's conviction — either
            # way, not this variant's call
            continue
        # the first awaited-across use that is not revalidated
        for use in sorted(scan.loads.get(name, ())):
            if use <= pos or not scan.await_between(pos, use):
                continue
            if scan.revalidated(name, watched, pos, use):
                break  # later uses read the revalidated binding
            attr = sorted(watched)[0]
            findings.append(Finding(
                rule=RULE, path=m.relpath, line=use[0], symbol=sym,
                message=f"stale-snapshot-across-await: {name!r} "
                        f"snapshots shared {attr!r} before an await "
                        f"and is used after it without revalidation; "
                        f"{FIX}"))
            reported.add((name, min(watched)))
            break


def _check_check_then_act(m, sym: str, fn: ast.AsyncFunctionDef,
                          findings: List[Finding]) -> None:
    for node in _walk_shallow(fn):
        if not isinstance(node, ast.If):
            continue
        watched = _watched_reads(node.test)
        if not watched:
            continue
        # an await inside the body, then a mutation through the same
        # watched attr after it, with no re-check of the attr between
        awaits = []
        for sub in node.body:
            for n in _walk_shallow(sub):
                if isinstance(n, (ast.Await, ast.AsyncWith, ast.AsyncFor)):
                    awaits.append(_pos(n))
        if not awaits:
            continue
        first_await = min(awaits)
        rechecks = []
        for sub in node.body:
            for n in _walk_shallow(sub):
                if isinstance(n, (ast.If, ast.While)) and \
                        (_watched_reads(n.test) & watched) and \
                        _pos(n) > first_await:
                    rechecks.append(_pos(n))
        for (mpos, target) in _mutation_sites(node.body):
            if mpos <= first_await:
                continue
            hit = _watched_reads(target) & watched
            if not hit:
                continue
            if any(r < mpos for r in rechecks):
                continue
            attr = sorted(hit)[0]
            findings.append(Finding(
                rule=RULE, path=m.relpath, line=mpos[0], symbol=sym,
                message=f"check-then-act-across-await: conditional on "
                        f"shared {attr!r} awaits and then mutates it "
                        f"without re-checking; {FIX}"))
            break


def _deplock_withs(fn: ast.AsyncFunctionDef, m, attr_map,
                   var_map) -> List[ast.AsyncWith]:
    """AsyncWith blocks in ``fn`` whose context manager resolves to a
    DepLock (by the lock-order rule's binding maps, plus the inline
    ``async with DepLock("x")`` form)."""
    from ceph_tpu.analysis import lockgraph

    out = []
    for node in _walk_shallow(fn):
        if not isinstance(node, ast.AsyncWith):
            continue
        for item in node.items:
            if lockgraph._resolve(item.context_expr, m.relpath,
                                  attr_map, var_map) is not None:
                out.append(node)
                break
    return out


def _check_lock_window_escape(m, sym: str, scan: _FnScan, windows,
                              findings: List[Finding]) -> None:
    reported: Set[str] = set()
    for (w_start, w_end) in windows:
        for (pos, name, watched) in scan.snapshots:
            if not (w_start <= pos <= w_end) or name in reported:
                continue
            for use in scan.loads.get(name, ()):
                if use <= w_end:
                    continue
                if scan.revalidated(name, watched, w_end, use):
                    break
                attr = sorted(watched)[0]
                findings.append(Finding(
                    rule=RULE, path=m.relpath, line=use[0], symbol=sym,
                    message=f"lock-window-escape: {name!r} snapshots "
                            f"shared {attr!r} inside an async-with "
                            f"DepLock window and is used after the "
                            f"lock is released without revalidation; "
                            f"{FIX}"))
                reported.add(name)
                break


def check(modules, ctx: LintContext) -> List[Finding]:
    from ceph_tpu.analysis import lockgraph

    findings: List[Finding] = []
    # DepLock bindings are collected over the WHOLE module set (like
    # the lock-order rule): a lock bound in pg.py resolves in osd.py
    attr_map, var_map = lockgraph.collect_bindings(modules)
    for m in modules:
        if not m.relpath.startswith(SCOPE):
            continue
        for sym, fn in walk_functions(m.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            scan = _FnScan(fn)
            windows = [(_pos(w), _end(w))
                       for w in _deplock_withs(fn, m, attr_map, var_map)]
            _check_stale_snapshot(m, sym, scan, windows, findings)
            _check_check_then_act(m, sym, fn, findings)
            _check_lock_window_escape(m, sym, scan, windows, findings)
    return findings
