"""Rule family ``asyncio-blocking``: event-loop stalls and untracked locks.

Every daemon here is a single asyncio event loop; one blocking call in
an ``async def`` stalls every op the daemon has in flight — the
symptom is a SLOW_OPS health warning with nothing actually wrong, the
kind of bug thrash tests only trip under load.  And a bare
``asyncio.Lock()`` in cluster code is invisible to lockdep: its
orderings never enter the runtime graph, so neither the runtime
checker nor the static lock-order pass can prove anything about it.

Checks:
- blocking calls inside ``async def`` bodies: ``time.sleep``, builtin
  ``open()``, ``os.system``/``os.popen``, the ``subprocess`` family,
  ``urllib.request.urlopen``, ``socket.create_connection`` (nested
  ``def``s are skipped — they may run anywhere);
- ``asyncio.Lock()`` / ``asyncio.Semaphore()`` construction anywhere
  under ``ceph_tpu/cluster/``: use ``DepLock(name)`` so the lock's
  orderings join the lockdep graphs (``asyncio.Condition`` is exempt:
  lockdep has no wait/notify model to track it with).
"""

from __future__ import annotations

import ast
from typing import List

from ceph_tpu.analysis.astutil import dotted, walk_functions
from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "asyncio-blocking"

_BLOCKING = {
    "time.sleep": "asyncio.sleep",
    "os.system": "asyncio.create_subprocess_shell",
    "os.popen": "asyncio.create_subprocess_shell",
    "subprocess.run": "asyncio.create_subprocess_exec",
    "subprocess.call": "asyncio.create_subprocess_exec",
    "subprocess.check_call": "asyncio.create_subprocess_exec",
    "subprocess.check_output": "asyncio.create_subprocess_exec",
    "subprocess.Popen": "asyncio.create_subprocess_exec",
    "urllib.request.urlopen": "an executor",
    "socket.create_connection": "asyncio.open_connection",
    "open": "an executor (or do the IO before going async)",
}

_UNTRACKED_LOCKS = {"asyncio.Lock", "asyncio.Semaphore",
                    "asyncio.BoundedSemaphore"}

# the lockdep implementation itself wraps asyncio.Lock — that is the
# one sanctioned constructor
_LOCKDEP_MODULE = "ceph_tpu/utils/lockdep.py"


def _async_body_calls(fn: ast.AsyncFunctionDef):
    """Calls lexically in the async function's own body, skipping
    nested function/lambda definitions."""

    def rec(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                yield child
            yield from rec(child)

    yield from rec(fn)


def check(modules, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        for sym, fn in walk_functions(m.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for call in _async_body_calls(fn):
                cn = dotted(call.func)
                if cn in _BLOCKING:
                    findings.append(Finding(
                        rule=RULE, path=m.relpath, line=call.lineno,
                        symbol=sym,
                        message=f"blocking {cn}() inside async def stalls "
                                f"the daemon's event loop; use "
                                f"{_BLOCKING[cn]}"))
        if m.relpath.startswith("ceph_tpu/cluster/"):
            for node in ast.walk(m.tree):
                hit = None
                if isinstance(node, ast.Call):
                    if dotted(node.func) in _UNTRACKED_LOCKS:
                        hit = f"bare {dotted(node.func)}()"
                    else:
                        # constructor passed by reference:
                        # field(default_factory=asyncio.Lock)
                        for kw in node.keywords:
                            if dotted(kw.value) in _UNTRACKED_LOCKS:
                                hit = f"{dotted(kw.value)} factory"
                if hit is not None:
                    findings.append(Finding(
                        rule=RULE, path=m.relpath, line=node.lineno,
                        symbol="",
                        message=f"{hit} escapes lockdep coverage; use "
                                f"DepLock(name) so static+runtime lock "
                                f"graphs see it"))
    return findings
