"""Rule family ``task-spawn``: unbounded per-op task spawns in cluster/.

ROADMAP item 2 names the bug class: a daemon that spawns an asyncio
task per op (or per map change, per retry, per dropped frame) and
either discards the handle or parks it in a grow-only list keeps one
dead Task alive per event for the daemon's life — graft-chaos runs
found the messenger doing exactly this before PR 4 added the
self-discarding ``_track`` registry.  This rule makes the pattern a
lint invariant for everything under ``ceph_tpu/cluster/``.

A ``create_task``/``ensure_future`` call is accepted when its result is

- passed straight into a call (``self._track(loop.create_task(...))``
  — the callee owns the lifetime);
- awaited (bounded by the awaiting coroutine);
- assigned to an ATTRIBUTE or subscript (a replace-on-rearm slot like
  ``self._relinger_task`` / ``self._retry_tasks[pgid]``);
- assigned to a name that the function then actually uses (handed to a
  tracker, given ``add_done_callback``, cancelled, stored).

It is flagged when the result is

- discarded (a bare expression statement), or
- fed straight into ``.append(...)`` / ``.add(...)`` (a grow-only
  registry with no discard path), or
- assigned to a name the function never touches again.

Round 13: the scope grew to ``ceph_tpu/load/`` — the open-loop driver
spawns one task per planned op, exactly the per-op shape this rule
polices.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ceph_tpu.analysis.astutil import dotted, walk_functions
from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "task-spawn"

# async daemon/driver code the rule polices (tests and scripts are
# callers, not long-lived event-loop residents)
# round 15: the cluster/ prefix covers the front-door libraries
# (rbd/rgw*/mds/fs/snaps) — pinned by tests/test_frontdoor.py.
SCOPE = ("ceph_tpu/cluster/", "ceph_tpu/load/",
         "ceph_tpu/osdmap/", "ceph_tpu/chaos/",
         "ceph_tpu/trace/flight.py", "ceph_tpu/trace/postmortem.py")

FIX = ("route it through a self-discarding tracker (the messenger "
       "_track pattern: set.add + add_done_callback(discard)) or a "
       "replace-on-rearm attribute slot")


def _is_spawn(call: ast.Call) -> bool:
    # match the ATTRIBUTE name, not a full dotted chain: the dominant
    # idiom is asyncio.get_event_loop().create_task(...), whose chain
    # contains a Call and so has no dotted name
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr in ("create_task", "ensure_future")
    return isinstance(f, ast.Name) and \
        f.id in ("create_task", "ensure_future")


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _name_reused(fn: ast.AST, assign: ast.Assign, name: str) -> bool:
    """Does the function touch ``name`` anywhere besides the binding
    assignment itself?  (Tracker call, add_done_callback, cancel,
    storing it — any later use counts as taking ownership.)"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == name and \
                not (isinstance(node.ctx, ast.Store) and
                     node in getattr(assign, "targets", ())):
            return True
    return False


def _classify(fn: ast.AST, call: ast.Call,
              parents: Dict[ast.AST, ast.AST]) -> Optional[str]:
    """None when the spawn is tracked; else a short defect description."""
    parent = parents.get(call)
    if isinstance(parent, ast.Await):
        return None
    if isinstance(parent, ast.Call) and call in parent.args:
        callee = dotted(parent.func) or ""
        if callee.endswith(".append") or callee.endswith(".add"):
            return (f"task handle fed straight into {callee}() — a "
                    f"grow-only registry keeps one dead Task per spawn")
        return None  # handed to a tracker/helper: the callee owns it
    if isinstance(parent, ast.Expr):
        return "task handle discarded — the spawn is untracked"
    if isinstance(parent, ast.Assign):
        target = parent.targets[0] if len(parent.targets) == 1 else None
        if isinstance(target, (ast.Attribute, ast.Subscript)):
            return None  # replace-on-rearm slot
        if isinstance(target, ast.Name):
            if _name_reused(fn, parent, target.id):
                return None
            return (f"task bound to {target.id!r} but never tracked, "
                    f"awaited, or cancelled")
    return None  # unusual shapes (tuple targets, comprehensions): pass


def _nearest_fn(node: ast.AST,
                parents: Dict[ast.AST, ast.AST]) -> Optional[ast.AST]:
    p = parents.get(node)
    while p is not None and not isinstance(
            p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        p = parents.get(p)
    return p


def check(modules, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if not m.relpath.startswith(SCOPE):
            continue
        parents = _parents(m.tree)
        for sym, fn in walk_functions(m.tree):
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call) and _is_spawn(node)):
                    continue
                if _nearest_fn(node, parents) is not fn:
                    continue  # reported against the nested function
                defect = _classify(fn, node, parents)
                if defect is not None:
                    findings.append(Finding(
                        rule=RULE, path=m.relpath, line=node.lineno,
                        symbol=sym,
                        message=f"unbounded per-op task spawn: {defect}; "
                                f"{FIX}"))
    return findings
