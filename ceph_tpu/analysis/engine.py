"""graftlint rule engine: module loading, rule dispatch, reporting.

Deviant-behavior checking (Engler et al., SOSP'01) as a harness: each
rule module contributes ``check(modules, ctx)`` returning findings; the
engine parses the file set once, runs every rule, applies inline
pragmas and the suppression baseline, and renders one report.  The last
report is cached process-wide so a live daemon can serve it over the
admin socket (``graftlint report``) without re-walking the repo on
every command.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

# directories never linted: the corpus holds deliberately-bad fixtures,
# __pycache__/caches hold no source of ours
EXCLUDE_GLOBS = (
    "*/lint_corpus/*", "*/__pycache__/*", "*/.git/*",
    "*/node_modules/*", "*/.ipynb_checkpoints/*",
)

# inline suppression: a finding whose source line (or the line above)
# carries ``graftlint: ignore[rule-name]`` is dropped at the source
PRAGMA = "graftlint: ignore["


@dataclass(frozen=True)
class Finding:
    rule: str       # rule family, e.g. "lock-order"
    path: str       # repo-relative posix path
    line: int
    symbol: str     # enclosing class.function, or "" at module scope
    message: str    # stable text: no line numbers, safe as baseline key

    @property
    def baseline_key(self) -> str:
        # line numbers drift with unrelated edits; identity is
        # rule + file + symbol + message
        return f"{self.rule}::{self.path}::{self.symbol}::{self.message}"

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}{sym}: {self.message}"


@dataclass
class Module:
    """One parsed source file."""

    path: str        # absolute
    relpath: str     # repo-relative posix
    source: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)

    def pragma_suppressed(self, rule: str, line: int) -> bool:
        tag = f"{PRAGMA}{rule}]"
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines) and tag in self.lines[ln - 1]:
                return True
        return False


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)   # unsuppressed
    suppressed: List[Finding] = field(default_factory=list)  # baselined
    stale_baseline: List[str] = field(default_factory=list)
    files_checked: int = 0
    parse_errors: List[str] = field(default_factory=list)
    lock_graph: Optional[dict] = None   # set by the lockgraph rule
    # raw (held, acquired) -> (path, line) map for DOT export; not
    # JSON-serialized (tuple keys), hence outside lock_graph
    static_edges_raw: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return not self.findings and not self.parse_errors

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def summary(self) -> dict:
        return {
            "ok": self.ok,
            "files_checked": self.files_checked,
            "findings": len(self.findings),
            "suppressed": len(self.suppressed),
            "stale_baseline": len(self.stale_baseline),
            "by_rule": self.counts(),
            "parse_errors": self.parse_errors,
            "lock_graph": self.lock_graph,
        }

    def to_json(self) -> dict:
        return {
            **self.summary(),
            "finding_list": [vars(f) | {"key": f.baseline_key}
                             for f in self.findings],
            "suppressed_list": [f.baseline_key for f in self.suppressed],
        }

    def render_text(self) -> str:
        out = [f.render() for f in self.findings]
        out += [f"PARSE ERROR: {e}" for e in self.parse_errors]
        c = self.counts()
        tail = ", ".join(f"{k}={v}" for k, v in sorted(c.items())) or "clean"
        out.append(
            f"graftlint: {self.files_checked} files, "
            f"{len(self.findings)} finding(s) ({tail}), "
            f"{len(self.suppressed)} baselined")
        if self.stale_baseline:
            out.append(
                f"note: {len(self.stale_baseline)} stale baseline "
                f"entr{'y' if len(self.stale_baseline) == 1 else 'ies'} "
                f"(finding no longer fires; prune the baseline)")
        return "\n".join(out)


def repo_root() -> str:
    """The repo root: the directory holding the ceph_tpu package."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(here)


def default_paths(root: Optional[str] = None) -> List[str]:
    """The whole-repo file set: the package, scripts, bench + entry, and
    the test suite (minus the deliberately-bad lint corpus)."""
    root = root or repo_root()
    roots = [os.path.join(root, d) for d in ("ceph_tpu", "scripts", "tests")]
    singles = [os.path.join(root, f) for f in ("bench.py", "__graft_entry__.py")]
    out = []
    for r in roots:
        for dirpath, dirnames, filenames in os.walk(r):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "lint_corpus")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    out.extend(p for p in singles if os.path.exists(p))
    return out


def _excluded(path: str) -> bool:
    p = path.replace(os.sep, "/")
    return any(fnmatch.fnmatch(p, g) for g in EXCLUDE_GLOBS)


def load_modules(paths: Sequence[str],
                 root: Optional[str] = None,
                 respect_excludes: bool = False) -> tuple:
    """Parse the file set; returns (modules, parse_errors).  Exclusion
    globs apply only on request — an explicitly listed file is always
    linted (that is how the corpus self-tests lint tests/lint_corpus)."""
    root = root or repo_root()
    modules, errors = [], []
    for path in paths:
        if respect_excludes and _excluded(path):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{os.path.relpath(path, root)}: {e}")
            continue
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        modules.append(Module(path=path, relpath=rel, source=source,
                              tree=tree, lines=source.splitlines()))
    return modules, errors


class LintContext:
    """Cross-rule state: runtime lock edges to merge, collected lock
    graph (for DOT export), engine options."""

    def __init__(self, runtime_edges: Optional[Dict[str, list]] = None):
        # name -> iterable of successor names (the runtime lockdep dump)
        self.runtime_edges = runtime_edges or {}
        self.lock_graph: Optional[dict] = None  # filled by lockgraph rule
        self.static_edges_raw: Optional[dict] = None  # ditto, for DOT


def all_rules():
    """The registered rule families, import-cycle-free."""
    from ceph_tpu.analysis import async_errors, asyncio_rules, \
        awaitrace, device_dispatch, jax_hygiene, lockgraph, \
        planar_hygiene, rpc_timeout, symmetry, taskspawn, testsleep

    return [lockgraph, jax_hygiene, symmetry, asyncio_rules, taskspawn,
            rpc_timeout, device_dispatch, async_errors, planar_hygiene,
            awaitrace, testsleep]


# cached last report (admin socket `graftlint report` serves this)
_LAST_REPORT: Optional[Report] = None


def last_report(run_if_missing: bool = True) -> Optional[dict]:
    """The most recent lint summary, running a fresh whole-repo lint
    (with the shipped baseline) when none is cached."""
    global _LAST_REPORT
    if _LAST_REPORT is None and run_if_missing:
        from ceph_tpu.analysis.baseline import default_baseline_path, \
            load_baseline

        _LAST_REPORT = run_lint(baseline=load_baseline(
            default_baseline_path()))
    return _LAST_REPORT.summary() if _LAST_REPORT is not None else None


def run_lint(paths: Optional[Sequence[str]] = None,
             rules=None,
             baseline: Optional[set] = None,
             runtime_edges: Optional[Dict[str, list]] = None,
             root: Optional[str] = None) -> Report:
    """Parse ``paths`` (default: the whole repo), run every rule family,
    apply pragma + baseline suppression, cache and return the Report."""
    global _LAST_REPORT
    root = root or repo_root()
    explicit = paths is not None
    if paths is None:
        paths = default_paths(root)
    modules, errors = load_modules(paths, root,
                                   respect_excludes=not explicit)
    ctx = LintContext(runtime_edges=runtime_edges)
    findings: List[Finding] = []
    for rule_mod in (rules if rules is not None else all_rules()):
        findings.extend(rule_mod.check(modules, ctx))
    by_rel = {m.relpath: m for m in modules}
    findings = [f for f in findings
                if not (f.path in by_rel and
                        by_rel[f.path].pragma_suppressed(f.rule, f.line))]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    baseline = baseline or set()
    kept = [f for f in findings if f.baseline_key not in baseline]
    suppressed = [f for f in findings if f.baseline_key in baseline]
    live_keys = {f.baseline_key for f in findings}
    stale = sorted(k for k in baseline if k not in live_keys)

    report = Report(findings=kept, suppressed=suppressed,
                    stale_baseline=stale, files_checked=len(modules),
                    parse_errors=errors, lock_graph=ctx.lock_graph)
    report.static_edges_raw = ctx.static_edges_raw
    # cache WHOLE-REPO runs only: `graftlint report` must never serve a
    # subset lint (e.g. a single-file run from a test or tool) as if it
    # were the repo's state
    if not explicit:
        _LAST_REPORT = report
    return report


def dump_report_json(report: Report) -> str:
    return json.dumps(report.to_json(), indent=2, sort_keys=True)
