"""graftlint: whole-repo AST static analysis (the denc/lockdep of this
port, moved to lint time).

The reference ships correctness tooling that turns latent bugs into loud
failures (src/common/lockdep.cc, the denc round-trip asserts); our
runtime half (`ceph_tpu.utils.lockdep`) only fires on orderings a test
happens to execute.  This package finds the same bug classes
structurally, before anything runs:

- ``lockgraph``     lock-order graph extraction over every ``DepLock``
                    nesting; merged with the runtime lockdep edges the
                    whole-program graph must stay acyclic.
- ``jax_hygiene``   host syncs / tracer leaks inside jitted code and
                    the bench device loops (the timing trust model).
- ``symmetry``      encode/decode field symmetry for wire structs and
                    codec plans (the denc analog).
- ``asyncio_rules`` blocking calls inside ``async def`` and bare
                    ``asyncio.Lock`` in cluster/ escaping lockdep.
- ``taskspawn``     unbounded per-op task spawns in cluster/ (discarded
                    handles, grow-only registries) — every spawn needs
                    a self-discarding tracker or a bounded slot.
- ``rpc_timeout``   bare ``await fut`` on RPC futures in cluster/ (no
                    timeout/deadline: a lost reply hangs the coroutine
                    for the daemon's lifetime).

`engine.run_lint` drives the rules over a file set; `baseline` carries
per-finding suppressions so accepted pre-existing findings don't block
the tier-1 gate while anything NEW fails loudly.
"""

from ceph_tpu.analysis.engine import (  # noqa: F401
    Finding, Report, run_lint, last_report, default_paths,
)
from ceph_tpu.analysis.baseline import (  # noqa: F401
    load_baseline, write_baseline,
)
