"""Rule family ``rpc-timeout``: cluster RPC awaits that can hang forever.

Every cross-daemon wait in ``ceph_tpu/cluster/`` rides an
``asyncio.Future`` — either ``loop.create_future()`` (reply waiters) or
the OSD's ``_make_waiter()`` (sub-op ack accumulators).  A *bare*
``await fut`` on one of these has no timeout and no deadline: if the
peer dies, the reply frame is lost past replay, or the waiter is
orphaned by a map change, the coroutine hangs for the daemon's lifetime
— the op it serves never fails, never retries, and never frees its
admission budget.  Chaos runs only catch the instances the fault
schedule happens to hit; this rule catches the pattern statically.

Every legitimate wait wraps the future: ``asyncio.wait_for(fut, t)``
bounds it, ``fut.done()``/``fut.result()`` polls it.  The rule flags an
``await`` whose operand is a bare name bound (in the same function)
from a future-constructing call.
"""

from __future__ import annotations

import ast
from typing import List

from ceph_tpu.analysis.astutil import dotted, walk_functions
from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "rpc-timeout"

# call names (last dotted segment) that mint RPC futures in cluster code
_FUT_MAKERS = frozenset({"create_future", "_make_waiter"})

# round 13: graft-load's async driver joined the scope (a hung wait in
# the driver wedges the whole offered-load window the same way).
# round 15: the cluster/ prefix COVERS the front-door libraries
# (rbd.py, rgw.py, rgw_http.py, rgw_sync.py, mds.py, fs.py, snaps.py)
# — asserted by tests/test_frontdoor.py so a future scope refactor
# cannot silently drop them.
SCOPE = ("ceph_tpu/cluster/", "ceph_tpu/load/",
         "ceph_tpu/osdmap/", "ceph_tpu/chaos/",
         "ceph_tpu/trace/flight.py", "ceph_tpu/trace/postmortem.py")


def _future_names(fn: ast.AsyncFunctionDef) -> set:
    """Names assigned from a future-constructing call anywhere in the
    function body (nested defs included: a closure awaiting its parent's
    future hangs the same way).  Covers plain, annotated
    (``fut: asyncio.Future = ...``), and chained
    (``fut = self._waiter = ...``) assignments — all shapes cluster
    code actually uses to bind RPC futures."""
    names = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if not isinstance(value, ast.Call):
            continue
        # the callee's terminal name, robust to chained receivers like
        # asyncio.get_event_loop().create_future() (dotted() bails on
        # call-chains)
        func = value.func
        if isinstance(func, ast.Attribute):
            callee = func.attr
        else:
            callee = (dotted(func) or "").split(".")[-1]
        if callee not in _FUT_MAKERS:
            continue
        for target in targets:
            if isinstance(target, ast.Name):
                names.add(target.id)
    return names


def check(modules, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if not m.relpath.startswith(SCOPE):
            continue
        for sym, fn in walk_functions(m.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            futs = _future_names(fn)
            if not futs:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Await) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id in futs:
                    findings.append(Finding(
                        rule=RULE, path=m.relpath, line=node.lineno,
                        symbol=sym,
                        message=f"bare 'await {node.value.id}' on an RPC "
                                f"future can hang forever; wrap in "
                                f"asyncio.wait_for with a timeout or "
                                f"deadline"))
    return findings
