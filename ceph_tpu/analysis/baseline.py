"""Suppression baseline: accepted pre-existing findings.

The tier-1 gate requires ZERO unsuppressed findings; anything the team
has looked at and accepted lives here as a stable baseline key
(rule::path::symbol::message — no line numbers, so unrelated edits
don't invalidate entries).  The file is JSON so diffs review cleanly:

    {"version": 1, "suppressions": [{"key": "...", "reason": "..."}]}

A stale entry (its finding no longer fires) is reported by the CLI so
the baseline shrinks monotonically instead of fossilizing.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional, Set


def default_baseline_path(root: Optional[str] = None) -> str:
    from ceph_tpu.analysis.engine import repo_root

    return os.path.join(root or repo_root(), "GRAFTLINT_BASELINE.json")


def load_baseline(path: str) -> Set[str]:
    """Baseline keys from ``path``; a missing file is an empty baseline."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError:
        return set()
    return {s["key"] for s in doc.get("suppressions", []) if "key" in s}


def write_baseline(path: str, findings: Iterable,
                   reason: str = "accepted pre-existing finding") -> int:
    """Write every finding's key as a suppression; returns the count."""
    entries = sorted({f.baseline_key for f in findings})
    doc = {
        "version": 1,
        "comment": "graftlint suppression baseline; keys are "
                   "rule::path::symbol::message (line-number free). "
                   "Remove entries as the findings are fixed.",
        "suppressions": [{"key": k, "reason": reason} for k in entries],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return len(entries)
