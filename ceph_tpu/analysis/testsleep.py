"""Rule ``fixed-sleep-in-tests``: bare constant sleeps in the test
suite.

A ``await asyncio.sleep(0.2)`` before an assertion is a guess about
how long the cluster needs — right on the laptop that wrote it, flaky
under CI load, and the class PRs 9–19 have been deflaking one file at
a time.  The repo's sanctioned shape is the wall-deadline converge
poll::

    deadline = loop.time() + 5.0
    while loop.time() < deadline and not cond():
        await asyncio.sleep(0.02)
    assert cond()

which this rule recognises lexically: a constant-duration sleep INSIDE
a ``while`` loop is the poll interval of a bounded retry and is legal.
A constant-duration sleep NOT inside a loop is a bare timing guess and
is flagged.

Exemptions:

- ``sleep(0)`` — a pure cooperative yield, not a wait (scheduling
  semantics, not timing);
- variable durations (``sleep(dt)``, ``sleep(interval)``) — the
  constant-guess smell is about literals;
- genuinely time-semantic pacing (e.g. spacing two wall-clock
  timestamps apart) carries an inline
  ``graftlint: ignore[fixed-sleep-in-tests]`` pragma with the reason
  in a comment — the baseline for this rule is pinned at ZERO, so the
  pragma is the only sanctioned escape and every use is visible at the
  call site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ceph_tpu.analysis.astutil import dotted, walk_functions
from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "fixed-sleep-in-tests"

SCOPE = ("tests/",)

FIX = ("convert to a wall-deadline converge-poll (loop until the "
       "condition or a deadline), or pragma a genuinely time-semantic "
       "pacing sleep with the reason")

_SLEEP_CALLEES = frozenset({
    "asyncio.sleep", "time.sleep", "sleep",
})


def _const_duration(call: ast.Call) -> Optional[float]:
    """The literal duration if the first argument is a numeric
    constant, else None."""
    if not call.args or call.keywords:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and \
            isinstance(arg.value, (int, float)) and \
            not isinstance(arg.value, bool):
        return float(arg.value)
    return None


def _in_loop(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    p = parents.get(node)
    while p is not None:
        if isinstance(p, (ast.While, ast.For, ast.AsyncFor)):
            return True
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return False
        p = parents.get(p)
    return False


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _nearest_fn(node: ast.AST,
                parents: Dict[ast.AST, ast.AST]) -> Optional[ast.AST]:
    p = parents.get(node)
    while p is not None and not isinstance(
            p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        p = parents.get(p)
    return p


def check(modules, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if not m.relpath.startswith(SCOPE):
            continue
        parents = _parents(m.tree)
        for sym, fn in walk_functions(m.tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or \
                        _nearest_fn(node, parents) is not fn:
                    continue
                callee = dotted(node.func)
                if callee not in _SLEEP_CALLEES:
                    continue
                dur = _const_duration(node)
                if dur is None or dur == 0:
                    continue
                if _in_loop(node, parents):
                    continue  # poll interval of a converge loop
                findings.append(Finding(
                    rule=RULE, path=m.relpath, line=node.lineno,
                    symbol=sym,
                    message=f"bare constant {callee}({dur:g}) outside "
                            f"a converge-poll loop; {FIX}"))
    return findings
