"""graft-race dynamic half, part 2: the cross-task access tracker.

The schedule shim (``ceph_tpu/utils/schedfuzz.py``) makes hostile
interleavings HAPPEN; this module makes them VISIBLE.  Hot cluster
seams carry two probes, piggybacked on the same per-task bookkeeping
lockdep already maintains:

- ``note_read(key, field)``  — a task snapshotted watched shared state
  (a PGState pulled from the registry at commit start, a self-info
  captured at recovery round start);
- ``note_write(key, field)`` — a task mutated that state (the registry
  entry replaced by peering, the log head advanced by a commit).

A conviction is a WRITE-AFTER-READ window that closed dirty: task B
wrote ``key`` after task A read it, A and B held no common DepLock at
their probes (``DepLock._held`` snapshots), and A finished without
ever RE-reading the key.  A later ``note_read`` by the same task
cancels the pending conviction — that is exactly what a revalidation
(the PR-9 identity re-check, the PR-11 self-info refresh) looks like
at runtime, so fixed code convicts nothing while reverting either fix
re-convicts under the race smoke.  Each finding carries both probe
stacks, tasks, ticks, and held-lock sets — the interleaving is
attributed, not just detected.

No-op contract (the NULL_FLIGHT shape, ``ceph_tpu/trace/flight.py``):
the module-global ``TRACKER`` is the falsy ``NULL_RACE`` singleton
unless a race run installs a real tracker, and every probe site guards
with one truthiness test — the disabled hot path is one global load
plus one bool, allocating and retaining nothing (pinned by
tests/test_racecheck.py).

This module never imports cluster code at module level (the probes
import US); the scenario runner below resolves its imports lazily.
"""

from __future__ import annotations

import asyncio
import dataclasses
import tempfile
import traceback
from typing import Dict, List, Optional, Tuple

from ceph_tpu.utils.lockdep import DepLock


class _NullRace:
    """Shared disabled tracker: one falsy test at every probe site,
    zero allocation, zero retention (the NULL_FLIGHT analog)."""

    __slots__ = ()

    enabled = False

    def __bool__(self) -> bool:
        return False

    def note_read(self, key, field: str = "") -> None:
        pass

    def note_write(self, key, field: str = "") -> None:
        pass

    def advance_tick(self) -> None:
        pass

    def findings(self) -> List[Dict]:
        return []

    def report(self) -> Dict:
        return {"enabled": False, "seed": 0, "ticks": 0,
                "reads": 0, "writes": 0, "findings": []}


NULL_RACE = _NullRace()


class _Probe:
    """One probe firing: who, where, when, holding what."""

    __slots__ = ("seq", "tick", "task", "task_name", "held", "site",
                 "stack")

    def __init__(self, seq: int, tick: int, task, held: List[str],
                 stack: List[str]):
        self.seq = seq
        self.tick = tick
        self.task = task
        self.task_name = task.get_name() if task is not None else "<no-task>"
        self.held = held
        self.site = stack[-1] if stack else "<unknown>"
        self.stack = stack

    def as_dict(self) -> Dict:
        return {"task": self.task_name, "tick": self.tick,
                "seq": self.seq, "held": list(self.held),
                "site": self.site, "stack": list(self.stack)}


class RaceTracker:
    """The enabled tracker (installed per race run, never by default).

    Read records are kept per (key, task): a task's LATEST read of a
    key is the one that matters — re-reading IS revalidation.  A write
    over another live task's un-revalidated read with disjoint held
    locks opens a pending conviction; it becomes a finding only if the
    reader finishes without re-reading (``findings()`` checks
    ``task.done()``, so a scenario judges after its tasks drained)."""

    enabled = True

    def __init__(self, seed: int = 0, stack_depth: int = 6,
                 max_findings: int = 64):
        self.seed = seed
        self.stack_depth = stack_depth
        self.max_findings = max_findings
        self._seq = 0
        self._tick = 0
        self._reads: Dict[Tuple, Dict[int, _Probe]] = {}
        self._pending: List[Dict] = []
        self._convicted: set = set()
        self.reads = 0
        self.writes = 0

    def __bool__(self) -> bool:
        return True

    # -- probe plumbing ------------------------------------------------------

    def advance_tick(self) -> None:
        self._tick += 1

    def _probe(self) -> Optional[_Probe]:
        try:
            task = asyncio.current_task()
        except RuntimeError:
            task = None
        if task is None:
            return None  # probes outside a task can't interleave
        held = list(DepLock._held.get(id(task), ()))
        stack = []
        for fr in traceback.extract_stack(limit=self.stack_depth + 2)[:-2]:
            fn = fr.filename
            cut = fn.rfind("ceph_tpu")
            stack.append(f"{fn[cut:] if cut >= 0 else fn}:"
                         f"{fr.lineno}:{fr.name}")
        self._seq += 1
        return _Probe(self._seq, self._tick, task, held, stack)

    def note_read(self, key, field: str = "") -> None:
        """A task snapshotted (or re-read: revalidated) watched state."""
        p = self._probe()
        if p is None:
            return
        self.reads += 1
        k = (key, field)
        self._reads.setdefault(k, {})[id(p.task)] = p
        # a re-read cancels this task's pending convictions on the key:
        # the task looked again after the write — the fixed shape
        self._pending = [
            pc for pc in self._pending
            if not (pc["k"] == k and pc["reader_task"] is p.task
                    and pc["write"].seq < p.seq)]

    def note_write(self, key, field: str = "") -> None:
        """A task mutated watched state: convict every OTHER live
        task still holding an un-revalidated read of it, unless a
        common DepLock serialized the pair."""
        p = self._probe()
        if p is None:
            return
        self.writes += 1
        k = (key, field)
        readers = self._reads.get(k, {})
        for rp in list(readers.values()):
            if rp.task is p.task:
                # a task's own write neither convicts (no interleave)
                # nor revalidates (its local snapshot is still stale —
                # the single-task half of PR 11); the record stands
                # for later cross-task writes
                continue
            if rp.task.done():
                # the reader finished before this write: window closed
                readers.pop(id(rp.task), None)
                continue
            if set(rp.held) & set(p.held):
                continue  # a common lock serialized read and write
            sig = (k, rp.site, p.site)
            if sig in self._convicted:
                continue
            if len(self._pending) >= self.max_findings:
                continue
            self._convicted.add(sig)
            self._pending.append({"k": k, "reader_task": rp.task,
                                  "read": rp, "write": p})

    # -- judgment ------------------------------------------------------------

    def findings(self) -> List[Dict]:
        """Pending convictions whose reader finished without re-reading
        — the write-after-read window provably closed dirty."""
        out = []
        for pc in self._pending:
            if not pc["reader_task"].done():
                continue  # still open: not judgeable yet
            if pc["reader_task"].cancelled():
                # a cancelled reader (power-cut daemon, scenario
                # teardown) unwound without acting on the snapshot —
                # never a conviction, or every chaos kill would convict
                # its own victim's in-flight commits
                continue
            key, field = pc["k"]
            out.append({
                "rule": "write-after-read",
                "key": repr(key), "field": field,
                "message": (f"task {pc['write'].task_name!r} wrote "
                            f"{key!r}/{field} at tick "
                            f"{pc['write'].tick} after task "
                            f"{pc['read'].task_name!r} read it at tick "
                            f"{pc['read'].tick}; no common lock, no "
                            f"revalidation before the reader finished"),
                "read": pc["read"].as_dict(),
                "write": pc["write"].as_dict(),
            })
        return out

    def report(self) -> Dict:
        fnd = self.findings()
        return {"enabled": True, "seed": self.seed, "ticks": self._tick,
                "reads": self.reads, "writes": self.writes,
                "pending_open": sum(
                    1 for pc in self._pending
                    if not pc["reader_task"].done()),
                "findings": fnd}


# -- the global probe target -------------------------------------------------

TRACKER = NULL_RACE


def install(tracker):
    """Swap the probe target; returns the previous one (restore it)."""
    global TRACKER
    prev = TRACKER
    TRACKER = tracker
    return prev


def uninstall() -> None:
    global TRACKER
    TRACKER = NULL_RACE


def from_config(config):
    """NULL_RACE unless ``race_check_enabled=1`` (the blackbox/trace
    factory contract: default-off is a provable no-op)."""
    if not getattr(config, "race_check_enabled", 0):
        return NULL_RACE
    return RaceTracker(seed=getattr(config, "race_check_seed", 0))


# -- the seeded race run -----------------------------------------------------


def race_run(scenario_name: str, seed: int, tmpdir: Optional[str] = None,
             shrink: bool = False):
    """One scenario under the perturbed loop with the tracker armed.

    Returns ``(verdict, race_report, trace_digest)``.  Imports resolve
    lazily — the probes import this module, so the module must never
    import cluster code at its top.  ``shrink`` scales the workload
    down (fewer objects, smaller payloads, tamer bursts) for the
    budget-bounded tier-1 smoke; rounds are preserved so the event
    schedule (kills, revives, crash points) stays valid."""
    from ceph_tpu.chaos.scenario import builtin_scenarios, run_scenario
    from ceph_tpu.utils.schedfuzz import SchedFuzzLoop

    scens = builtin_scenarios()
    if scenario_name not in scens:
        raise KeyError(scenario_name)
    sc = scens[scenario_name]
    if shrink:
        sc = dataclasses.replace(
            sc, objects_per_round=min(4, sc.objects_per_round),
            payload_repeat=min(10, sc.payload_repeat),
            burst_concurrency=min(4, sc.burst_concurrency))
    tracker = RaceTracker(seed=seed)
    prev = install(tracker)
    loop = SchedFuzzLoop(seed, on_tick=tracker.advance_tick)
    own_tmp = None
    if tmpdir is None:
        # file-store scenarios need a backing dir; own it for the run
        own_tmp = tempfile.TemporaryDirectory(prefix="race_run_")
        tmpdir = own_tmp.name
    try:
        asyncio.set_event_loop(loop)
        verdict = loop.run_until_complete(run_scenario(sc, seed, tmpdir))
    finally:
        install(prev)
        asyncio.set_event_loop(None)
        loop.close()
        if own_tmp is not None:
            own_tmp.cleanup()
    return verdict, tracker.report(), loop.trace_digest()
