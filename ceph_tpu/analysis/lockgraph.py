"""Rule family ``lock-order``: whole-program static lock graph.

Reference lockdep (src/common/lockdep.cc) learns ordering edges at
RUNTIME — it only sees orderings a test happens to execute.  This pass
extracts the edges statically: every ``DepLock("name")`` binding is
collected (self-attribute, dataclass field factory, or local variable),
then every function body is walked with a held-stack over ``async
with`` nesting, producing held->acquired edges with file:line
provenance.  The static edges are merged with the runtime lockdep dump
(when provided) and the merged graph must be ACYCLIC — a cycle is a
deadlock that some interleaving can reach, reported before any test
runs it.

Nesting is mostly INTERPROCEDURAL here (a PG-lock holder calls into the
messenger, which takes the session lock), so the walk propagates
through calls: each function's intra-procedural acquisitions are
closed over the called-name graph to a fixpoint, and a call made while
holding L contributes L -> every lock the callee (by name) can reach.
Calls spawned via ``create_task``/``ensure_future``/``gather`` are
excluded — they do not run under the caller's locks.

Limitations (documented, deliberate): resolution is by attribute/
variable NAME, not points-to analysis — two locks bound to the same
attribute name merge, and same-named methods union their acquisitions
(conservative: may create edges, never misses a DepLock nesting);
nested function defs reset the held stack (a callback does not
necessarily run under its definition site's locks).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.analysis.astutil import const_str, dotted, walk_functions
from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "lock-order"

Edge = Tuple[str, str]


def _deplock_name(node: ast.AST) -> Optional[str]:
    """The lock name if ``node`` contains a DepLock("name") call."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = dotted(sub.func)
            if fn is not None and fn.split(".")[-1] == "DepLock" and sub.args:
                return const_str(sub.args[0])
    return None


def collect_bindings(modules) -> Tuple[Dict[str, str], Dict[Tuple[str, str], str]]:
    """(attr -> lock name, (relpath, var) -> lock name) over the repo."""
    attr_map: Dict[str, str] = {}
    var_map: Dict[Tuple[str, str], str] = {}

    for m in modules:

        def visit(node, scope: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.Assign, ast.AnnAssign)):
                    name = _deplock_name(child.value) \
                        if getattr(child, "value", None) else None
                    if name is not None:
                        targets = child.targets if isinstance(
                            child, ast.Assign) else [child.target]
                        for t in targets:
                            if isinstance(t, ast.Attribute):
                                attr_map[t.attr] = name
                            elif isinstance(t, ast.Name):
                                if scope == "class":
                                    attr_map[t.id] = name
                                var_map[(m.relpath, t.id)] = name
                if isinstance(child, ast.ClassDef):
                    visit(child, "class")
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)):
                    visit(child, "function")
                else:
                    visit(child, scope)

        visit(m.tree, "module")
    return attr_map, var_map


def _resolve(expr: ast.AST, relpath: str, attr_map, var_map) -> Optional[str]:
    direct = _deplock_name(expr) if isinstance(expr, ast.Call) else None
    if direct is not None:
        return direct
    if isinstance(expr, ast.Attribute):
        return attr_map.get(expr.attr)
    if isinstance(expr, ast.Name):
        return var_map.get((relpath, expr.id))
    return None


# calls whose arguments run as their OWN tasks, not under our locks
_SPAWN_CALLS = {"create_task", "ensure_future", "gather", "call_soon",
                "call_later", "run_in_executor", "to_thread", "start_server"}


def _call_bare_name(call: ast.Call) -> Optional[str]:
    fn = dotted(call.func)
    return fn.split(".")[-1] if fn else None


def _scan_fn(fn, relpath, attr_map, var_map):
    """(acquires, called_names) of one function body: lock names taken
    via ``async with`` (DepLock is async-only, so plain ``with`` can
    never be one — threading locks sharing an attribute name must not
    alias in), and bare names of AWAITED calls (a sync call cannot
    acquire an asyncio lock; spawn-wrapped and nested-def calls are
    excluded — they do not run under our locks)."""
    acquires: Set[str] = set()
    called: Set[str] = set()

    def rec(node, spawned: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            sp = spawned
            if isinstance(child, ast.Call) and \
                    _call_bare_name(child) in _SPAWN_CALLS:
                sp = True  # its args don't run under our locks
            if isinstance(child, ast.Await) and \
                    isinstance(child.value, ast.Call) and not spawned:
                name = _call_bare_name(child.value)
                if name is not None and name not in _SPAWN_CALLS:
                    called.add(name)
            if isinstance(child, ast.AsyncWith):
                for item in child.items:
                    name = _resolve(item.context_expr, relpath,
                                    attr_map, var_map)
                    if name is not None:
                        acquires.add(name)
            rec(child, sp)

    rec(fn, False)
    return acquires, called


def _reachable_locks(modules, attr_map, var_map) -> Dict[str, Set[str]]:
    """bare function name -> every lock a call to that name can acquire,
    closed transitively over the called-name graph (name-based union
    across same-named functions; fixpoint)."""
    acquires: Dict[str, Set[str]] = {}
    calls: Dict[str, Set[str]] = {}
    for m in modules:
        for sym, fn in walk_functions(m.tree):
            bare = sym.split(".")[-1]
            a, c = _scan_fn(fn, m.relpath, attr_map, var_map)
            acquires.setdefault(bare, set()).update(a)
            calls.setdefault(bare, set()).update(c)
    reach = {n: set(a) for n, a in acquires.items()}
    changed = True
    while changed:
        changed = False
        for n, outs in calls.items():
            cur = reach.setdefault(n, set())
            before = len(cur)
            for o in outs:
                cur |= reach.get(o, set())
            changed = changed or len(cur) != before
    return reach


def extract_static_edges(modules) -> Dict[Edge, Tuple[str, int]]:
    """held->acquired edges from every DepLock ``async with`` nesting,
    each with (relpath, line) provenance of the inner acquisition.
    Direct nesting AND call-through: a call made while holding L adds
    L -> every lock the callee can transitively acquire."""
    attr_map, var_map = collect_bindings(modules)
    reach = _reachable_locks(modules, attr_map, var_map)
    edges: Dict[Edge, Tuple[str, int]] = {}

    for m in modules:
        for sym, fn in walk_functions(m.tree):

            def walk(node, held: List[str], spawned: bool):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        continue  # callbacks don't inherit our held set
                    sp = spawned
                    if isinstance(child, ast.Call) and \
                            _call_bare_name(child) in _SPAWN_CALLS:
                        sp = True
                    if isinstance(child, ast.Await) and \
                            isinstance(child.value, ast.Call) and \
                            held and not spawned:
                        name = _call_bare_name(child.value)
                        for lock in (reach.get(name, ())
                                     if name not in _SPAWN_CALLS else ()):
                            for h in held:
                                if h != lock:
                                    edges.setdefault(
                                        (h, lock),
                                        (m.relpath, child.lineno))
                    if isinstance(child, ast.AsyncWith):
                        acquired = []
                        for item in child.items:
                            name = _resolve(item.context_expr, m.relpath,
                                            attr_map, var_map)
                            if name is None:
                                continue
                            for h in held:
                                if h != name:
                                    edges.setdefault(
                                        (h, name),
                                        (m.relpath, child.lineno))
                            held.append(name)
                            acquired.append(name)
                        walk(child, held, sp)
                        for _ in acquired:
                            held.pop()
                    else:
                        walk(child, held, sp)

            walk(fn, [], False)
    return edges


def find_cycle(succ: Dict[str, Set[str]]) -> Optional[List[str]]:
    """A cycle as [a, b, ..., a], or None if the graph is acyclic."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in set(succ) | {s for v in succ.values()
                                            for s in v}}
    path: List[str] = []

    def dfs(n) -> Optional[List[str]]:
        color[n] = GRAY
        path.append(n)
        for s in sorted(succ.get(n, ())):
            if color[s] == GRAY:
                return path[path.index(s):] + [s]
            if color[s] == WHITE:
                cyc = dfs(s)
                if cyc is not None:
                    return cyc
        path.pop()
        color[n] = BLACK
        return None

    for n in sorted(color):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc is not None:
                return cyc
    return None


def merged_graph(static_edges: Dict[Edge, Tuple[str, int]],
                 runtime_edges: Dict[str, list]) -> Dict[str, Set[str]]:
    succ: Dict[str, Set[str]] = {}
    for (a, b) in static_edges:
        succ.setdefault(a, set()).add(b)
    for a, outs in (runtime_edges or {}).items():
        for b in outs:
            if a != b:
                succ.setdefault(a, set()).add(b)
    return succ


def to_dot(static_edges: Dict[Edge, Tuple[str, int]],
           runtime_edges: Dict[str, list],
           cycle: Optional[List[str]] = None) -> str:
    """GraphViz DOT of the merged lock graph; static edges solid with
    provenance labels, runtime-only edges dashed, cycle edges red."""
    cyc_pairs = set()
    if cycle:
        cyc_pairs = {(cycle[i], cycle[i + 1]) for i in range(len(cycle) - 1)}
    lines = ["digraph lock_order {", '  rankdir=LR;',
             '  node [shape=box, fontname="monospace"];']
    seen = set()
    for (a, b), (path, ln) in sorted(static_edges.items()):
        attrs = [f'label="{path}:{ln}"']
        if (a, b) in cyc_pairs:
            attrs.append('color=red')
        lines.append(f'  "{a}" -> "{b}" [{", ".join(attrs)}];')
        seen.add((a, b))
    for a, outs in sorted((runtime_edges or {}).items()):
        for b in sorted(outs):
            if (a, b) in seen or a == b:
                continue
            attrs = ['style=dashed', 'label="runtime"']
            if (a, b) in cyc_pairs:
                attrs.append('color=red')
            lines.append(f'  "{a}" -> "{b}" [{", ".join(attrs)}];')
    lines.append("}")
    return "\n".join(lines)


def product_modules(modules):
    """Drop test modules: tests acquire deliberately-inverted orders to
    exercise runtime lockdep (and reset the graph between tests), so
    their orderings are not whole-program facts.  The lint corpus is
    exempt — its fixtures exist to be linted explicitly."""
    return [m for m in modules
            if not m.relpath.startswith("tests/")
            or m.relpath.startswith("tests/lint_corpus/")]


def check(modules, ctx: LintContext) -> List[Finding]:
    modules = product_modules(modules)
    static_edges = extract_static_edges(modules)
    succ = merged_graph(static_edges, ctx.runtime_edges)
    cycle = find_cycle(succ)
    ctx.static_edges_raw = static_edges
    ctx.lock_graph = {
        "locks": sorted(set(succ) | {s for v in succ.values() for s in v}),
        "static_edges": sorted(f"{a} -> {b} ({p}:{ln})"
                               for (a, b), (p, ln) in static_edges.items()),
        "runtime_edges": sorted(f"{a} -> {b}"
                                for a, outs in (ctx.runtime_edges or {}).items()
                                for b in outs if a != b),
        "acyclic": cycle is None,
        "cycle": cycle,
    }
    if cycle is None:
        return []
    # provenance: anchor the finding on the first static edge of the cycle
    path, line = "", 0
    for i in range(len(cycle) - 1):
        prov = static_edges.get((cycle[i], cycle[i + 1]))
        if prov is not None:
            path, line = prov
            break
    return [Finding(
        rule=RULE, path=path or "<runtime-only>", line=line, symbol="",
        message="lock ordering cycle in merged static+runtime graph: "
                + " -> ".join(cycle))]
