"""Small shared AST helpers for the graftlint rule modules."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple


def dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan'-style dotted name for Name/Attribute chains, else
    None (calls, subscripts etc. break the chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_functions(tree: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Yield (qualified_symbol, fn_node) for every (async) function,
    qualified through enclosing classes/functions."""

    def rec(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sym = f"{prefix}.{child.name}" if prefix else child.name
                yield sym, child
                yield from rec(child, sym)
            elif isinstance(child, ast.ClassDef):
                sym = f"{prefix}.{child.name}" if prefix else child.name
                yield from rec(child, sym)
            else:
                yield from rec(child, prefix)

    yield from rec(tree, "")


def param_names(fn: ast.AST) -> List[str]:
    """Positional parameter names of a FunctionDef/Lambda, in order."""
    a = fn.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


def names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}
