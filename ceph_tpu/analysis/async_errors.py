"""Rule family ``swallowed-async-error``: silently-dropped failures in
cluster/ async handlers.

Lost sub-op failures are exactly how un-acked shards leak: a replica
send that fails inside a broad ``except: pass`` (or an
``asyncio.gather(..., return_exceptions=True)`` whose result list is
discarded) leaves the primary's durability accounting silently short —
the op neither fails loudly nor retries, and the write it served claims
a durability it does not have.  graft-chaos only catches the instances
a fault schedule happens to hit; this rule catches the pattern
statically.

Two shapes are flagged, both only inside ``async def`` functions under
``ceph_tpu/cluster/``:

- a BARE/BROAD except whose body is only ``pass``: ``except:``,
  ``except Exception:``, or ``except BaseException:`` (bare ``except``
  additionally swallows ``CancelledError`` — a handler that eats its
  own cancellation).  Narrow, typed excepts (``except (ConnectionError,
  OSError):``) are deliberate protocol decisions and stay legal, as
  does any body that observes the failure (counter, log, retry).
- ``asyncio.gather(..., return_exceptions=True)`` whose result is
  discarded — a bare expression statement or a binding the function
  never reads.  With ``return_exceptions=True`` the gather NEVER
  raises; dropping the result list drops every child failure.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ceph_tpu.analysis.astutil import dotted, walk_functions
from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "swallowed-async-error"

# round 15: the cluster/ prefix covers the front-door libraries
# (rbd/rgw*/mds/fs/snaps) — pinned by tests/test_frontdoor.py.
# round 13: graft-load's async driver joined the scope — a load window
# that silently eats op failures reports a goodput it never served
SCOPE = ("ceph_tpu/cluster/", "ceph_tpu/load/",
         "ceph_tpu/osdmap/", "ceph_tpu/chaos/",
         "ceph_tpu/trace/flight.py", "ceph_tpu/trace/postmortem.py")

_BROAD = ("Exception", "BaseException")


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True  # bare except: eats CancelledError too
    name = dotted(h.type) or ""
    return name.split(".")[-1] in _BROAD


def _body_only_pass(h: ast.ExceptHandler) -> bool:
    return all(isinstance(s, ast.Pass) for s in h.body)


def _is_gather_re(call: ast.Call) -> bool:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        (dotted(f) or "").split(".")[-1]
    if name != "gather":
        return False
    for kw in call.keywords:
        if kw.arg == "return_exceptions" and \
                isinstance(kw.value, ast.Constant) and \
                kw.value.value is True:
            return True
    return False


def _gather_result_discarded(fn: ast.AST, call: ast.Call,
                             parents) -> Optional[str]:
    """None when the result is consumed; else a defect description."""
    parent = parents.get(call)
    if isinstance(parent, ast.Await):
        parent = parents.get(parent)
    if isinstance(parent, ast.Expr):
        return ("gather(..., return_exceptions=True) result discarded "
                "— every child failure is silently dropped")
    if isinstance(parent, ast.Assign):
        target = parent.targets[0] if len(parent.targets) == 1 else None
        if isinstance(target, ast.Name):
            name = target.id
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and node.id == name and \
                        isinstance(node.ctx, ast.Load):
                    return None
            return (f"gather(..., return_exceptions=True) result bound "
                    f"to {name!r} but never read — every child failure "
                    f"is silently dropped")
    return None


def _parents(tree: ast.AST):
    out = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _nearest_fn(node: ast.AST, parents) -> Optional[ast.AST]:
    p = parents.get(node)
    while p is not None and not isinstance(
            p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        p = parents.get(p)
    return p


def check(modules, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if not m.relpath.startswith(SCOPE):
            continue
        parents = _parents(m.tree)
        for sym, fn in walk_functions(m.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in ast.walk(fn):
                if _nearest_fn(node, parents) is not fn:
                    continue  # reported against the nested function
                if isinstance(node, ast.ExceptHandler) and \
                        _is_broad_handler(node) and \
                        _body_only_pass(node):
                    what = "bare 'except:'" if node.type is None else \
                        f"'except {dotted(node.type)}:'"
                    findings.append(Finding(
                        rule=RULE, path=m.relpath, line=node.lineno,
                        symbol=sym,
                        message=f"{what} with a pass-only body in an "
                                f"async handler swallows the failure "
                                f"(lost sub-op errors = leaked un-acked "
                                f"shards); narrow the exception types "
                                f"or observe the failure (counter/log/"
                                f"retry)"))
                elif isinstance(node, ast.Call) and _is_gather_re(node):
                    defect = _gather_result_discarded(fn, node, parents)
                    if defect is not None:
                        findings.append(Finding(
                            rule=RULE, path=m.relpath, line=node.lineno,
                            symbol=sym,
                            message=f"{defect}; iterate the results and "
                                    f"handle (or at least count) the "
                                    f"exceptions"))
    return findings
