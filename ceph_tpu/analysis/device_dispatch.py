"""Rule family ``per-op-device-dispatch``: device calls on per-op paths.

Round 11 contract (the batched data plane): EC stripe work in the
cluster data plane crosses the host/device boundary through the tick
coalescer (``cluster/batcher.py``), which turns every same-profile
write of a dispatch tick into ONE planar conversion + fused encode +
crc32c batch.  A device entry point (planar conversion, batch
encode/decode, batched crc) reachable PER OP inside a ``cluster/``
async handler silently defeats that: every op pays its own host/device
round trip again, and the cluster/device throughput gap the tick
closed re-opens without any test failing.

Flagged inside ``async def``s under ``ceph_tpu/cluster/`` (excluding
the coalescer module itself):

- a direct call to a device entry point
  (``codec.encode_planar(...)``, ``stripemod.encode_stripes(...)``);
- a device entry point handed as a CALLABLE to another call
  (``self._compute(stripemod.encode_stripes, ...)`` — the dominant
  idiom: the executor hop does not change who pays the dispatch).

Accepted remnants (the legacy ``osd_batch_tick_ops=0`` bisection path,
the not-yet-coalesced read/recovery decodes) live in the suppression
baseline, where removing one is a visible diff.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ceph_tpu.analysis.astutil import dotted, walk_functions
from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "per-op-device-dispatch"

# device entry points of the EC data plane: planar layout transforms,
# batch encode/decode dispatches, and the batched crc kernels
# (round 19 widened the set with the planar-at-rest multi entry points
# and the plane-major crc batch — the at-rest format must not become a
# license to hand-roll per-op dispatches outside the coalescer)
DEVICE_CALLS = frozenset({
    "to_planar", "encode_planar", "decode_planar",
    "encode_batch", "decode_batch",
    "encode_stripes", "decode_stripes", "reencode_stripes",
    "encode_stripes_multi", "crc32c_batch", "crc32c_rows",
    "encode_planes_multi", "decode_planes_multi",
    "reencode_planes_multi", "crc32c_planar_rows",
})

# the one sanctioned per-op dispatch seam: the tick coalescer
COALESCER = "ceph_tpu/cluster/batcher.py"

FIX = ("route it through the batch coalescer "
       "(cluster/batcher.py encode seam)")


def _device_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and node.attr in DEVICE_CALLS:
        return node.attr
    if isinstance(node, ast.Name) and node.id in DEVICE_CALLS:
        return node.id
    return None


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _nearest_fn(node: ast.AST,
                parents: Dict[ast.AST, ast.AST]) -> Optional[ast.AST]:
    p = parents.get(node)
    while p is not None and not isinstance(
            p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        p = parents.get(p)
    return p


def check(modules, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if not m.relpath.startswith("ceph_tpu/cluster/") or \
                m.relpath == COALESCER:
            continue
        parents = _parents(m.tree)
        for sym, fn in walk_functions(m.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or \
                        _nearest_fn(node, parents) is not fn:
                    continue
                name = _device_name(node.func)
                if name is not None:
                    findings.append(Finding(
                        rule=RULE, path=m.relpath, line=node.lineno,
                        symbol=sym,
                        message=f"device entry point {name}() called "
                                f"per-op in a cluster/ async handler; "
                                f"{FIX}"))
                    continue
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    aname = _device_name(arg)
                    if aname is not None:
                        callee = dotted(node.func) or "a call"
                        findings.append(Finding(
                            rule=RULE, path=m.relpath, line=node.lineno,
                            symbol=sym,
                            message=f"device callable {aname} handed "
                                    f"to {callee}() per-op in a "
                                    f"cluster/ async handler; {FIX}"))
    return findings
