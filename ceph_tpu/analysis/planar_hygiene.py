"""Rule family ``planar-conversion-hygiene``: at-rest layout seams.

Round 19 contract (planar at rest): with ``osd_ec_planar_at_rest=1``
EC shards LIVE as packed bit-plane matrices — in the store, on the
wire, and entering the kernels — and the byte view may materialize
only at the sanctioned seams (the coalesced encode's ingest, the read
assemble's egress, and declared relayout transitions).  A stray
conversion call in ``cluster/`` quietly re-opens the
convert-per-hop cost the format removed, without any test failing
until the perf gate notices.

Flagged under ``ceph_tpu/cluster/`` (excluding the coalescer module,
which IS the sanctioned dispatch seam):

- any call to a RAW layout transform (``to_planar``, ``to_batch``,
  ``from_batch``, ``rows_to_planes``, ``planes_to_rows``) — these
  belong in the ``ec/`` kernel seam modules only;
- a ``shard_to_planes(...)`` / ``planes_to_shard(...)`` call with NO
  explicit ``seam=`` keyword — the planar_store API makes every
  caller declare which seam books the conversion, and an undeclared
  call is exactly the silent hop this rule exists to catch;
- a call declaring ``seam="unseamed"`` — the steady-state counter
  those book is PINNED to zero by test, so a new unseamed site needs
  an inline pragma (and a story), like the store ``read()`` byte-view
  fallbacks carry.

``blob_to_planes``/``planes_to_blob`` are reshapes of the SAME bytes,
not conversions, and stay unflagged.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "planar-conversion-hygiene"

# raw layout transforms: never legal in cluster/ at all
RAW_CONVERSIONS = frozenset({
    "to_planar", "to_batch", "from_batch",
    "rows_to_planes", "planes_to_rows",
})

# seam-declaring transforms: legal with an explicit seam= keyword
SEAM_CONVERSIONS = frozenset({"shard_to_planes", "planes_to_shard"})

# the one sanctioned per-op dispatch seam: the tick coalescer
COALESCER = "ceph_tpu/cluster/batcher.py"

FIX = ("keep layout conversions at the sanctioned seams "
       "(ec/planar_store.py callers declare seam=)")


def _call_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def check(modules, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if not m.relpath.startswith("ceph_tpu/cluster/") or \
                m.relpath == COALESCER:
            continue
        from ceph_tpu.analysis.astutil import walk_functions

        fn_of = {}
        for sym, fn in walk_functions(m.tree):
            for node in ast.walk(fn):
                fn_of.setdefault(node, sym)
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            sym = fn_of.get(node, "")
            if name in RAW_CONVERSIONS:
                findings.append(Finding(
                    rule=RULE, path=m.relpath, line=node.lineno,
                    symbol=sym,
                    message=f"raw layout transform {name}() in a "
                            f"cluster/ module; {FIX}"))
                continue
            if name not in SEAM_CONVERSIONS:
                continue
            seam = next((kw for kw in node.keywords
                         if kw.arg == "seam"), None)
            if seam is None:
                findings.append(Finding(
                    rule=RULE, path=m.relpath, line=node.lineno,
                    symbol=sym,
                    message=f"{name}() without an explicit seam= "
                            f"declaration in a cluster/ module; {FIX}"))
            elif isinstance(seam.value, ast.Constant) and \
                    seam.value.value == "unseamed":
                findings.append(Finding(
                    rule=RULE, path=m.relpath, line=node.lineno,
                    symbol=sym,
                    message=f"{name}(seam=\"unseamed\") materializes a "
                            f"byte view outside the seams — the pinned "
                            f"steady-state counter; {FIX}"))
    return findings
