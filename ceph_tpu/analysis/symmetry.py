"""Rule family ``encode-decode``: wire/struct codec field symmetry.

The reference's denc layer asserts encode/decode round-trips; our
structs are hand-paired, so a field added to ``encode`` but forgotten
in ``decode`` only fails when a message of that exact shape crosses a
version boundary.  This pass checks symmetry statically, three ways:

1. Class struct codecs — a class with ``encode(self)`` (serializer
   taking no payload args) and a paired ``decode``: every ``self.X``
   the encoder reads must be restored by the decoder (constructor
   kwarg or attribute assignment), and vice versa.  Decoders that
   rebuild wholesale (``pickle.loads(...)`` returned directly, or a
   positional constructor call) are opaque-total and exempt.

2. Module function pairs ``_encode_X``/``_decode_X`` (the messenger
   handshake idiom): for each message class the encoder handles in an
   ``isinstance`` branch, the decoder must construct the same class,
   and the field sets (attrs read while encoding vs constructor kwargs
   while decoding) must match.  Messenger-stamped header fields
   (src/seq/sid/trace) are exempt.

3. Wire dataclasses — every ``@dataclass`` deriving from ``Message``
   must give EVERY field a default: peers at different versions omit
   fields they don't know, and a default-less field turns that into a
   constructor error instead of a graceful downgrade.  Version-guard
   constants in encode/decode bodies (``if v >= N:``) must be
   monotonically nondecreasing in source order, and never exceed the
   class's declared ``struct_v``/``STRUCT_V`` bound (the denc analog of
   DECODE_START/DECODE_FINISH version sanity).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.analysis.astutil import dotted, param_names
from ceph_tpu.analysis.engine import Finding, LintContext

RULE = "encode-decode"

# header fields stamped by the messenger, never hand-encoded
_HEADER_FIELDS = {"src", "seq", "sid", "trace"}
_VERSION_NAMES = {"v", "ver", "version", "struct_v"}


def _attr_reads(node: ast.AST, base: str) -> Set[str]:
    """Attributes read off ``base`` (e.g. self.X / msg.X) under node."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and \
                isinstance(sub.value, ast.Name) and sub.value.id == base \
                and not isinstance(getattr(sub, "ctx", None), ast.Store):
            out.add(sub.attr)
    return out


def _attr_writes(node: ast.AST) -> Set[str]:
    """Attributes assigned on ANY local object (t.ops = ..., self.X = ...)."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for tt in targets:
                    if isinstance(tt, ast.Attribute):
                        out.add(tt.attr)
        elif isinstance(sub, ast.AnnAssign) and \
                isinstance(sub.target, ast.Attribute):
            out.add(sub.target.attr)
    return out


def _ctor_calls(node: ast.AST, class_names: Set[str]) -> List[ast.Call]:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = dotted(sub.func)
            if fn is not None and fn.split(".")[-1] in class_names:
                out.append(sub)
    return out


def _returns_pickle_loads(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Return) and sub.value is not None:
            for c in ast.walk(sub.value):
                if isinstance(c, ast.Call) and \
                        dotted(c.func) in ("pickle.loads", "pickle.load"):
                    return True
    return False


def _version_guards(fn: ast.AST) -> List[Tuple[int, int]]:
    """(line, constant) for every ``<ver> >= N`` / ``<ver> > N`` guard,
    in source order."""
    out = []
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Compare) and len(sub.ops) == 1 and \
                isinstance(sub.ops[0], (ast.Gt, ast.GtE)) and \
                isinstance(sub.left, ast.Name) and \
                sub.left.id in _VERSION_NAMES and \
                isinstance(sub.comparators[0], ast.Constant) and \
                isinstance(sub.comparators[0].value, int):
            out.append((sub.lineno, sub.comparators[0].value))
    return sorted(out)


def _class_struct_v(cls: ast.ClassDef) -> Optional[int]:
    for stmt in cls.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name):
            targets, value = [stmt.target.id], stmt.value
        else:
            continue
        if any(t in ("struct_v", "STRUCT_V") for t in targets) and \
                isinstance(value, ast.Constant) and \
                isinstance(value.value, int):
            return value.value
    return None


def _check_version_guards(module, cls_name: str, fn, struct_v,
                          findings: List[Finding]):
    guards = _version_guards(fn)
    prev = None
    for line, const in guards:
        if prev is not None and const < prev:
            findings.append(Finding(
                rule=RULE, path=module.relpath, line=line,
                symbol=f"{cls_name}.{fn.name}" if cls_name else fn.name,
                message=f"version guards not monotonic: v>={const} after "
                        f"v>={prev} (fields must decode in version order)"))
        prev = const
        if struct_v is not None and const > struct_v:
            findings.append(Finding(
                rule=RULE, path=module.relpath, line=line,
                symbol=f"{cls_name}.{fn.name}" if cls_name else fn.name,
                message=f"version guard v>={const} exceeds declared "
                        f"struct_v={struct_v}"))


def _check_class_codecs(module, findings: List[Finding]):
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {s.name: s for s in node.body
                   if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}
        enc, dec = methods.get("encode"), methods.get("decode")
        if enc is None or dec is None:
            continue
        # struct serializers only: encode(self) with no payload params —
        # codec-transform encode(self, data, ...) APIs are not field
        # serialization and are exempt
        if [p for p in param_names(enc) if p not in ("self",)]:
            continue
        struct_v = _class_struct_v(node)
        _check_version_guards(module, node.name, enc, struct_v, findings)
        _check_version_guards(module, node.name, dec, struct_v, findings)

        encoded = _attr_reads(enc, "self") - _HEADER_FIELDS
        if not encoded:
            continue  # pickles self wholesale (or abstract): symmetric
        if _returns_pickle_loads(dec):
            continue  # opaque-total decode
        ctors = _ctor_calls(dec, {node.name, "cls"})
        if any(c.args for c in ctors):
            continue  # positional rebuild: can't map fields, assume total
        decoded = {kw.arg for c in ctors for kw in c.keywords
                   if kw.arg is not None}
        decoded |= _attr_writes(dec)
        sym = f"{node.name}.encode/decode"
        for f in sorted(encoded - decoded):
            findings.append(Finding(
                rule=RULE, path=module.relpath, line=dec.lineno, symbol=sym,
                message=f"field {f!r} is encoded but never restored by "
                        f"decode"))
        for f in sorted(decoded - encoded):
            findings.append(Finding(
                rule=RULE, path=module.relpath, line=enc.lineno, symbol=sym,
                message=f"field {f!r} is restored by decode but never "
                        f"encoded"))


def _isinstance_branches(fn: ast.AST, var: str) -> Dict[str, ast.If]:
    """class-name -> the `if isinstance(var, Cls)` branch node."""
    out: Dict[str, ast.If] = {}
    for sub in ast.walk(fn):
        if isinstance(sub, ast.If) and isinstance(sub.test, ast.Call) and \
                dotted(sub.test.func) == "isinstance" and \
                len(sub.test.args) == 2 and \
                isinstance(sub.test.args[0], ast.Name) and \
                sub.test.args[0].id == var:
            cls = dotted(sub.test.args[1])
            if cls is not None:
                out.setdefault(cls.split(".")[-1], sub)
    return out


def _check_fn_pairs(module, findings: List[Finding]):
    fns = {s.name: s for s in module.tree.body
           if isinstance(s, ast.FunctionDef)}
    for name, enc in fns.items():
        if not name.lstrip("_").startswith("encode"):
            continue
        dec_name = name.replace("encode", "decode", 1)
        dec = fns.get(dec_name)
        if dec is None or not param_names(enc):
            continue
        var = param_names(enc)[0]
        branches = _isinstance_branches(enc, var)
        if not branches:
            continue
        for cls, branch in branches.items():
            encoded = set()
            for stmt in branch.body:
                encoded |= _attr_reads(stmt, var)
            encoded -= _HEADER_FIELDS
            ctors = _ctor_calls(dec, {cls})
            if not ctors:
                findings.append(Finding(
                    rule=RULE, path=module.relpath, line=branch.lineno,
                    symbol=f"{name}/{dec_name}",
                    message=f"{cls} is encoded but {dec_name} never "
                            f"constructs it (no mirrored decode)"))
                continue
            if any(c.args for c in ctors):
                continue  # positional rebuild: assume total
            decoded = {kw.arg for c in ctors for kw in c.keywords
                       if kw.arg is not None}
            sym = f"{name}/{dec_name}:{cls}"
            for f in sorted(encoded - decoded):
                findings.append(Finding(
                    rule=RULE, path=module.relpath, line=branch.lineno,
                    symbol=sym,
                    message=f"field {f!r} is encoded but not decoded"))
            for f in sorted(decoded - encoded):
                findings.append(Finding(
                    rule=RULE, path=module.relpath, line=branch.lineno,
                    symbol=sym,
                    message=f"field {f!r} is decoded but never encoded"))


def _is_message_dataclass(node: ast.ClassDef) -> bool:
    has_dc = any((dotted(d) or "").split(".")[-1] == "dataclass"
                 or (isinstance(d, ast.Call) and
                     (dotted(d.func) or "").split(".")[-1] == "dataclass")
                 for d in node.decorator_list)
    derives = any((dotted(b) or "").split(".")[-1] in ("Message",)
                  for b in node.bases)
    return has_dc and derives


def _check_message_defaults(module, findings: List[Finding]):
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.ClassDef) and
                _is_message_dataclass(node)):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name) and \
                    stmt.value is None:
                findings.append(Finding(
                    rule=RULE, path=module.relpath, line=stmt.lineno,
                    symbol=node.name,
                    message=f"wire message field {stmt.target.id!r} has "
                            f"no default: an older peer omitting it "
                            f"breaks decode (version downgrade)"))


def check(modules, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        _check_class_codecs(m, findings)
        _check_fn_pairs(m, findings)
        _check_message_defaults(m, findings)
    return findings
