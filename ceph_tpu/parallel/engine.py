"""MeshECEngine: the sharded EC data plane over a jax.sharding.Mesh.

Round-4 generalization of the original demo pipeline (mesh.py kept for
the end-to-end step): arbitrary erasure patterns, delta-based RMW, and
mesh-sharded CRUSH placement — the storage analogs of a model's
sharded forward/backward.  Stripes shard over the ``data`` axis (our
batch axis = independent stripes, the framework's long-context analog)
and EC chunk rows lay out over the ``shard`` axis the way the
reference spreads shards across OSDs (src/osd/ECBackend.cc
handle_sub_write/handle_sub_read:921,986); XLA inserts the ICI
collectives (the decode all-gather is MOSDECSubOpRead's fan-out).

The engine exposes the SAME encode_batch/decode_batch contract as the
single-device codec engines (ec/codec.py), so the cluster's EC backend
can route through it unchanged (osd_ec_mesh config)."""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ceph_tpu.ops import gf8


class MeshECEngine:
    """Sharded GF(2^8) RS engine with the codec batch contract.

    Works for any codec whose engine exposes a ``coding`` matrix over
    GF(2^8) (jerasure reed_sol, ISA) — the same families the cluster's
    EC pools default to."""

    def __init__(self, mesh: Mesh, k: int, m: int,
                 coding: np.ndarray):
        self.mesh = mesh
        self.k, self.m = k, m
        self.n = k + m
        # host-side numpy: jit-time constants on the MESH backend (a
        # device-committed constant would pin the default backend and
        # poison dispatch, see ops/gf8 notes + memory)
        self.coding = np.asarray(coding, dtype=np.uint8)
        from ceph_tpu.ec import matrices

        self.generator = matrices.generator_matrix(self.coding)
        self._enc_bitmat = gf8.expand_bitmatrix(self.coding)
        self._enc_jit: Dict[Tuple, object] = {}
        self._dec_jit: Dict[Tuple, object] = {}
        self._rmw_jit: Dict[Tuple, object] = {}
        self._data_sh = NamedSharding(mesh, P("data", None, None))
        self._chunk_sh = NamedSharding(mesh, P("data", "shard", None))
        self._repl = NamedSharding(mesh, P())

    @staticmethod
    def _put(x, sharding):
        """Place ``x`` on the mesh WITHOUT touching the default backend.

        jax.device_put takes host numpy directly; routing through
        jnp.asarray first would commit the array to the *default*
        platform (the real TPU under axon) before the mesh placement —
        the exact failure that turned the round-4 multichip dryrun red
        (MULTICHIP_r04) and the closure-poison lesson in transfer form."""
        if not isinstance(x, jax.Array):
            x = np.asarray(x)
        return jax.device_put(x, sharding)

    # -- encode ------------------------------------------------------------

    def _build_encode(self):
        k, m = self.k, self.m
        enc = self._enc_bitmat

        def step(data):
            # ``enc`` stays host numpy: it lifts into the jaxpr as a
            # constant during tracing.  jnp.asarray here would eagerly
            # commit it to the DEFAULT backend mid-trace — a real-TPU
            # touch even when the mesh is the virtual CPU one.
            b, _, chunk = data.shape
            cols = data.transpose(1, 0, 2).reshape(k, b * chunk)
            parity = gf8.bitmatrix_matmul(enc, cols)
            return parity.reshape(m, b, chunk).transpose(1, 0, 2)

        return jax.jit(step, in_shardings=(self._data_sh,),
                       out_shardings=self._data_sh)

    def encode_batch(self, data):
        """(B, k, S) -> (B, m, S) parity, stripes sharded over 'data'."""
        if not self._enc_jit:
            self._enc_jit["fn"] = self._build_encode()
        data = self._put(data, self._data_sh)
        return self._enc_jit["fn"](data)

    # -- decode (arbitrary erasure pattern) --------------------------------

    def _decode_rows(self, src: Tuple[int, ...], want: Tuple[int, ...]):
        """GF coefficient rows mapping survivor rows ``src`` -> rows
        ``want`` (submatrix inversion, ec/codec.py decode_matrix)."""
        sub = self.generator[list(src)]
        inv = gf8.gf_invert_matrix(sub)
        rows = []
        for w in want:
            if w < self.k:
                rows.append(inv[w])
            else:
                # erased parity: compose its coding row with the inverse
                comp = np.zeros(self.k, dtype=np.uint8)
                for j in range(self.k):
                    c = int(self.coding[w - self.k, j])
                    if c:
                        comp ^= np.array(
                            [gf8.gf_mul(c, int(v)) for v in inv[j]],
                            dtype=np.uint8)
                rows.append(comp)
        return np.stack(rows)

    def _build_decode(self, src: Tuple[int, ...], want: Tuple[int, ...]):
        k = self.k
        bitmat = gf8.expand_bitmatrix(self._decode_rows(src, want))
        src_arr = np.asarray(src)

        def step(chunks):
            b, _, chunk = chunks.shape
            survivors = chunks[:, src_arr, :]
            cols = survivors.transpose(1, 0, 2).reshape(k, b * chunk)
            out = gf8.bitmatrix_matmul(bitmat, cols)
            return out.reshape(len(want), b, chunk).transpose(1, 0, 2)

        return jax.jit(step, in_shardings=(self._chunk_sh,),
                       out_shardings=self._data_sh)

    def decode_batch(self, erasures: Tuple[int, ...], chunks,
                     want: Tuple[int, ...] = None):
        """codec contract: chunks (B, k+m, S); rebuild ``want`` (default
        = erasures) from k survivors.  The survivor gather crosses the
        'shard' mesh axis — the ICI analog of the sub-read fan-out."""
        erasures = tuple(erasures)
        if want is None:
            want = erasures
        want = tuple(want)
        avail = tuple(i for i in range(self.n) if i not in erasures)
        src = avail[: self.k]
        key = (src, want)
        if key not in self._dec_jit:
            self._dec_jit[key] = self._build_decode(src, want)
        chunks = self._put(chunks, self._chunk_sh)
        return self._dec_jit[key](chunks)

    # -- RMW (delta parity update) -----------------------------------------

    def _build_rmw(self, col_start: int, width: int):
        k, m = self.k, self.m
        enc = self._enc_bitmat

        def step(chunks, update):
            # chunks: (B, k+m, S) current; update: (B, k, width) new data
            # columns [col_start, col_start+width).  Linear code =>
            # parity' = parity ^ encode(old_cols ^ new_cols): only the
            # touched columns move over the mesh, the RMW trick
            # ECBackend buys with sub-range reads (ECBackend.cc:1785)
            b = chunks.shape[0]
            old = jax.lax.dynamic_slice_in_dim(
                chunks[:, :k, :], col_start, width, axis=2)
            delta = old ^ update
            dcols = delta.transpose(1, 0, 2).reshape(k, b * width)
            pdelta = gf8.bitmatrix_matmul(enc, dcols)
            pdelta = pdelta.reshape(m, b, width).transpose(1, 0, 2)
            new_data = jax.lax.dynamic_update_slice_in_dim(
                chunks[:, :k, :], update, col_start, axis=2)
            old_parity = jax.lax.dynamic_slice_in_dim(
                chunks[:, k:, :], col_start, width, axis=2)
            new_parity = jax.lax.dynamic_update_slice_in_dim(
                chunks[:, k:, :], old_parity ^ pdelta, col_start, axis=2)
            return jnp.concatenate([new_data, new_parity], axis=1)

        return jax.jit(step, in_shardings=(self._chunk_sh, self._data_sh),
                       out_shardings=self._chunk_sh)

    def rmw_batch(self, chunks, update, col_start: int):
        """Partial-stripe overwrite: replace data columns
        [col_start, col_start+len) with ``update`` (B, k, width) and
        delta-update the parity in place."""
        if not isinstance(update, jax.Array):
            update = np.asarray(update)
        width = update.shape[2]
        key = (col_start, width)
        if key not in self._rmw_jit:
            self._rmw_jit[key] = self._build_rmw(col_start, width)
        chunks = self._put(chunks, self._chunk_sh)
        update = self._put(update, self._data_sh)
        return self._rmw_jit[key](chunks, update)


class MeshCodecAdapter:
    """Wraps a single-device EC codec so the cluster's EC pool batch
    paths (ec/stripe.py encode_stripes/decode_stripes) run on the mesh
    engine instead — the osd_ec_mesh seam.  Every other codec method
    (profiles, chunk math, scalar encode/decode) delegates unchanged.

    Arbitrary cluster batch sizes are padded up to the mesh's data axis
    (zero stripes encode to zero parity — the code is linear — so
    padding never changes real rows)."""

    def __init__(self, codec, mesh: Mesh):
        self._codec = codec
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        self._k, self._n = k, n
        self._mesh_engine = MeshECEngine(
            mesh, k, n - k, np.asarray(codec.engine.coding))
        self._data_axis = mesh.shape["data"]

    # the bit-planar entry points are single-device (the mesh engine
    # shards BYTE batches); hiding them steers ec/stripe.py's planar
    # routing back to encode_batch/decode_batch so mesh pools keep the
    # multi-chip data plane
    _SINGLE_DEVICE_ONLY = frozenset(
        {"planar_supported", "to_planar", "encode_planar", "decode_planar"})

    def __getattr__(self, name):
        if name in self._SINGLE_DEVICE_ONLY:
            raise AttributeError(name)
        return getattr(self._codec, name)

    def _pad(self, arr):
        b = arr.shape[0]
        pad = (-b) % self._data_axis
        if pad:
            arr = np.concatenate(
                [np.asarray(arr),
                 np.zeros((pad,) + arr.shape[1:], dtype=np.uint8)])
        return arr, b

    def encode_batch(self, data):
        data, b = self._pad(np.asarray(data))
        return self._mesh_engine.encode_batch(data)[:b]

    def decode_batch(self, erasures, chunks, want=None):
        chunks, b = self._pad(np.asarray(chunks))
        return self._mesh_engine.decode_batch(erasures, chunks, want)[:b]


def mesh_for_codec(codec, n_devices: int = 0) -> Mesh:
    """Mesh whose shard axis divides this codec's k+m (falling back to
    pure data parallelism when no shard split fits)."""
    try:
        devices = jax.devices()
    except RuntimeError:
        devices = jax.devices("cpu")
    n_dev = n_devices or len(devices)
    n = codec.get_chunk_count()
    shard_axis = 1
    for s in (4, 3, 2):
        if n_dev % s == 0 and n % s == 0:
            shard_axis = s
            break
    from ceph_tpu.parallel.mesh import make_mesh

    return make_mesh(n_dev, shard_axis=shard_axis)


def wrap_codec_for_mesh(codec, n_devices: int = 0):
    """Return a mesh-routed adapter for codecs with a GF(2^8) coding
    matrix, or the codec unchanged when it cannot ride the mesh engine
    (wide-w / bitmatrix families keep their single-device path)."""
    eng = getattr(codec, "engine", None)
    coding = getattr(eng, "coding", None)
    if coding is None or getattr(eng, "w", 8) != 8:
        return codec
    return MeshCodecAdapter(codec, mesh_for_codec(codec, n_devices))


def crush_batch_sharded(mesh: Mesh, mapper, ruleno: int, xs, result_max: int,
                        weights):
    """Whole-map CRUSH placement sharded over every mesh device: the
    per-x rule VM is embarrassingly parallel, so sharding xs over the
    flattened mesh scales placement linearly with chips (reference
    crush_do_rule is a per-x scalar loop, src/crush/mapper.c:883)."""
    n_dev = mesh.devices.size
    xs = np.asarray(xs, dtype=np.uint32)
    pad = (-len(xs)) % n_dev
    if pad:
        xs = np.concatenate([xs, np.zeros(pad, dtype=np.uint32)])
    x_sh = NamedSharding(mesh, P(("data", "shard")))
    w_sh = NamedSharding(mesh, P())
    # cache the sharded wrapper + the mesh-replicated map tensors ON the
    # mapper (so the cache dies with the map epoch and an id() reuse can
    # never serve a stale map), keyed by rule/result/mesh — repeat
    # placement calls hit XLA's jit cache instead of retracing +
    # re-transferring the whole map
    cache = getattr(mapper, "_sharded_cache", None)
    if cache is None:
        cache = mapper._sharded_cache = {}
    key = (ruleno, result_max, mesh)
    if key not in cache:
        fn, tensors = mapper.compiled_rule(ruleno, result_max)
        # the mapper's map tensors live on the DEFAULT backend (mapper.py
        # builds them with jnp.asarray); replicate them onto the mesh so
        # the sharded dispatch never mixes backends
        tensors = jax.device_put(tensors, w_sh)
        sharded = jax.jit(
            lambda x, w, t: fn(x, w, t),
            in_shardings=(x_sh, w_sh, None),
            out_shardings=(NamedSharding(mesh, P(("data", "shard"), None)),
                           x_sh),
        )
        cache[key] = (sharded, tensors)
    sharded, tensors = cache[key]
    res, lens = sharded(jax.device_put(xs, x_sh),
                        jax.device_put(
                            np.asarray(weights, dtype=np.uint32), w_sh),
                        tensors)
    if pad:
        res, lens = res[:-pad], lens[:-pad]
    return res, lens
