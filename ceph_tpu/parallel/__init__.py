"""Device-mesh sharding: the framework's distribution axes.

Ceph distributes by declustered sharding (PGs over OSDs) and intra-object
striping (SURVEY §2.2).  On TPU the same axes become mesh dimensions:

- ``data``  — the stripe batch (independent stripes; Ceph's PG/stripe
  parallelism).  Pure data parallelism over ICI.
- ``shard`` — the chunk axis (Ceph's per-OSD EC shards, ghobject shard_t).
  Tensor-parallel analog: each device group owns a subset of the k+m shards;
  decode gathers k survivors with XLA collectives.
"""

from ceph_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    distributed_ec_step,
)
from ceph_tpu.parallel.engine import (  # noqa: F401
    MeshECEngine,
    crush_batch_sharded,
)
