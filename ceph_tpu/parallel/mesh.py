"""Sharded erasure-coding steps over a jax.sharding.Mesh.

The multi-chip execution model for the framework's data plane: stripes are
sharded over the ``data`` axis, EC chunk shards over the ``shard`` axis
(mirroring how the reference spreads EC shards across OSDs,
src/osd/ECBackend.cc handle_sub_write/handle_sub_read), and XLA inserts the
ICI collectives — the all-gather of k survivor shards on decode is the moral
equivalent of ECBackend's MOSDECSubOpRead fan-out/gather (reference
ECBackend.cc:986,1141).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ceph_tpu.ops import gf8


def make_mesh(n_devices: int | None = None, shard_axis: int | None = None) -> Mesh:
    """Build a ('data', 'shard') mesh over the first n devices."""
    try:
        devices = jax.devices()
    except RuntimeError:
        # default platform failed to initialize entirely (e.g. a libtpu
        # version skew): the virtual CPU mesh is still usable
        devices = jax.devices("cpu")
    if n_devices is None:
        n_devices = len(devices)
    if len(devices) < n_devices:
        # default platform too small (e.g. one real TPU): fall back to the
        # virtual CPU mesh (xla_force_host_platform_device_count)
        devices = jax.devices("cpu")
    if len(devices) < n_devices:
        raise ValueError(
            f"need {n_devices} devices, have {len(devices)}"
        )
    devices = np.asarray(devices[:n_devices])
    if shard_axis is None:
        shard_axis = 4 if n_devices % 4 == 0 else (2 if n_devices % 2 == 0 else 1)
    data_axis = n_devices // shard_axis
    return Mesh(devices.reshape(data_axis, shard_axis), axis_names=("data", "shard"))


def distributed_ec_step(mesh: Mesh, k: int, m: int, batch: int, chunk: int):
    """Build a jitted full EC pipeline step over ``mesh``.

    The step is the storage analog of a training step: encode a stripe batch,
    lay chunks out over the shard axis, lose a shard, reconstruct it from k
    survivors, and verify — returning the global mismatch count (a psum-like
    reduction XLA derives from the sharded comparison).

    Shapes must divide the mesh: batch % data_axis == 0 and
    (k + m) % shard_axis == 0.
    """
    n = k + m
    assert batch % mesh.shape["data"] == 0, "batch must divide data axis"
    assert n % mesh.shape["shard"] == 0, "k+m must divide shard axis"

    from ceph_tpu.ec import matrices

    # Keep the matrices as host numpy: they become jit-time constants on the
    # mesh's backend.  jnp.asarray here would commit them to the *default*
    # backend, which may be a different platform than the mesh (the round-1
    # multichip dryrun crashed exactly this way: CPU mesh, TPU default).
    coding = matrices.isa_rs_matrix(k, m)
    enc_bitmat = gf8.expand_bitmatrix(coding)
    generator = matrices.generator_matrix(coding)
    # static single-erasure recovery: lose shard 0, decode from rows 1..k
    src_rows = tuple(range(1, k + 1))
    sub = generator[list(src_rows)]
    inv = gf8.gf_invert_matrix(sub)
    rec_bitmat = gf8.expand_bitmatrix(inv[0][None, :])

    data_sharding = NamedSharding(mesh, P("data", None, None))
    chunk_sharding = NamedSharding(mesh, P("data", "shard", None))

    def step(data):
        # data: (batch, k, chunk) uint8, sharded over the stripe batch
        b = data.shape[0]
        # enc_bitmat/rec_bitmat stay host numpy: they lift into the jaxpr
        # as constants; jnp.asarray here would eagerly commit them to the
        # default backend mid-trace (see MeshECEngine._put).
        cols = data.transpose(1, 0, 2).reshape(k, b * chunk)
        parity = gf8.bitmatrix_matmul(enc_bitmat, cols)
        parity = parity.reshape(m, b, chunk).transpose(1, 0, 2)
        chunks = jnp.concatenate([data, parity], axis=1)
        # distribute shards over the shard axis (Ceph: shards to distinct OSDs)
        chunks = jax.lax.with_sharding_constraint(chunks, chunk_sharding)
        # reconstruct shard 0 from k survivors (XLA gathers across 'shard')
        survivors = chunks[:, 1 : k + 1, :]
        scols = survivors.transpose(1, 0, 2).reshape(k, b * chunk)
        recon = gf8.bitmatrix_matmul(rec_bitmat, scols).reshape(b, chunk)
        mismatches = jnp.sum((recon != chunks[:, 0, :]).astype(jnp.int32))
        return mismatches, chunks

    jitted = jax.jit(
        step,
        in_shardings=(data_sharding,),
        out_shardings=(NamedSharding(mesh, P()), chunk_sharding),
    )
    example = np.random.default_rng(0).integers(
        0, 256, (batch, k, chunk), dtype=np.uint8
    )
    # device_put with the mesh sharding: the example lands on the mesh's
    # devices directly and never touches the default backend.
    return jitted, (jax.device_put(example, data_sharding),)
