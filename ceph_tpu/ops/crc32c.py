"""crc32c (Castagnoli) — host and batched-TPU checksumming.

Behavioral mirror of reference ceph_crc32c (src/include/crc32c.h:43,
src/common/sctp_crc32.c): a raw reflected CRC-32C table update from a caller
seed, with NO pre/post inversion, and the null-buffer convention meaning
"length zero bytes" (src/common/crc32c.cc:214-239 ceph_crc32c_zeros).

TPU-first design: CRC is GF(2)-linear in the message bits —
``update(seed, m) = A^len(seed) XOR L(m)`` — so a batch of fixed-size blocks
is ONE bit-matrix matmul on the MXU, reusing the erasure-code substrate
(ops/gf8.bitmatrix_matmul).  The combine/zero-extend operators are 32x32
GF(2) matrix powers, the same trick the reference's crc32c.cc:54+ uses for
crc_turbo_table.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

CRC32C_POLY_REFLECTED = 0x82F63B78

# hardware/SIMD crc32c when the image ships it (the reference's
# crc32c_intel / sctp_crc32 fast paths): google_crc32c computes the
# STANDARD finalized CRC-32C, which maps to our raw ceph_crc32c update
# exactly as update(seed, m) = extend(seed ^ ~0, m) ^ ~0 (verified in
# tests against the table path).  None -> the numpy table paths below.
try:
    import google_crc32c as _gcrc
except ImportError:  # pragma: no cover - image without the wheel
    _gcrc = None


def _build_table():
    tbl = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (CRC32C_POLY_REFLECTED if c & 1 else 0)
        tbl[i] = c
    return tbl


CRC_TABLE = _build_table()

# ---------------------------------------------------------------------------
# GF(2) 32x32 matrix algebra (matrices as 32 uint32 columns)
# ---------------------------------------------------------------------------


def _mat_vec(m: np.ndarray, v: int) -> int:
    out = 0
    vv = int(v)
    j = 0
    while vv:
        if vv & 1:
            out ^= int(m[j])
        vv >>= 1
        j += 1
    return out


def _mat_mat(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(a . b)[j] = a . b[j]; vectorized column combine."""
    bits = (b[:, None] >> np.arange(32)[None, :]) & 1      # (col j, bit i)
    sel = np.where(bits.astype(bool), a[None, :, ], 0)
    return np.bitwise_xor.reduce(sel, axis=1).astype(np.uint32)


def _identity():
    return (np.uint32(1) << np.arange(32)).astype(np.uint32)


def _zero_byte_op():
    """A_1: one zero-byte update, crc' = (crc >> 8) ^ tbl[crc & 0xff]."""
    cols = np.zeros(32, dtype=np.uint32)
    for j in range(32):
        e = 1 << j
        cols[j] = ((e >> 8) ^ int(CRC_TABLE[e & 0xFF])) & 0xFFFFFFFF
    return cols


_A1 = _zero_byte_op()


@functools.lru_cache(maxsize=256)
def _zeros_op(length: int) -> bytes:
    """A_1^length, cached (returned as bytes for hashability)."""
    result = _identity()
    sq = _A1.copy()
    n = length
    while n:
        if n & 1:
            result = _mat_mat(sq, result)
        sq = _mat_mat(sq, sq)
        n >>= 1
    return result.tobytes()


def _zeros_mat(length: int) -> np.ndarray:
    return np.frombuffer(_zeros_op(length), dtype=np.uint32)


# ---------------------------------------------------------------------------
# Host path
# ---------------------------------------------------------------------------


def crc32c(crc: int, data: Optional[bytes], length: Optional[int] = None) -> int:
    """ceph_crc32c semantics: raw update from seed; data=None means zeros."""
    crc &= 0xFFFFFFFF
    if data is None:
        if not length:
            return crc
        return crc32c_zeros(crc, length)
    buf = np.frombuffer(memoryview(data), dtype=np.uint8)
    if length is not None:
        buf = buf[:length]
    if len(buf) == 0:
        return crc
    if _gcrc is not None:
        # the C extension accepts only bytes proper: pass the caller's
        # bytes straight through, else one copy — still ~3x the table
        # paths end to end
        raw = data if isinstance(data, bytes) and length is None \
            else buf.tobytes()
        return _gcrc.extend(crc ^ 0xFFFFFFFF, raw) ^ 0xFFFFFFFF
    # block-parallel: split into lanes, CRC each lane vectorized bytewise,
    # then combine with the zero-extension operator
    lane = 4096
    if len(buf) <= lane:
        c = np.uint32(crc)
        for b in buf:
            c = CRC_TABLE[(c ^ b) & np.uint32(0xFF)] ^ (c >> np.uint32(8))
        return int(c)
    n_full = len(buf) // lane
    blocks = buf[: n_full * lane].reshape(n_full, lane)
    cs = np.zeros(n_full, dtype=np.uint32)
    for i in range(lane):
        cs = CRC_TABLE[(cs ^ blocks[:, i]) & np.uint32(0xFF)] ^ (cs >> np.uint32(8))
    # fold lanes left to right: crc = A^lane(crc) ^ lane_crc (lane seeded 0)
    total = crc
    for c in cs:
        total = crc32c_zeros(total, lane) ^ int(c)
    tail = buf[n_full * lane :]
    if len(tail):
        total = crc32c(total, tail.tobytes())
    return total & 0xFFFFFFFF


def crc32c_zeros(crc: int, length: int) -> int:
    """CRC across `length` zero bytes (reference crc32c.cc:214)."""
    return _mat_vec(_zeros_mat(length), crc)


def crc32c_combine(crc_a: int, crc_b: int, len_b: int) -> int:
    """CRC of a||b from crc(a) and crc(b) (b seeded with 0)."""
    return crc32c_zeros(crc_a, len_b) ^ crc_b


# ---------------------------------------------------------------------------
# Device path: batched fixed-size blocks as one GF(2) matmul
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=16)
def _message_bitmat(block: int) -> np.ndarray:
    """(32, 8*block) GF(2) matrix L with update(0, m) = L @ bits(m).

    Column (p, i): contribution of bit i of byte p, i.e.
    A_1^(block-1-p) . tbl[1 << i].
    """
    t_cols = np.array([CRC_TABLE[1 << i] for i in range(8)], dtype=np.uint32)
    m = np.zeros((32, 8 * block), dtype=np.uint8)
    p_op = _identity()
    for p in range(block - 1, -1, -1):
        cols = np.array([_mat_vec(p_op, int(c)) for c in t_cols], dtype=np.uint32)
        bits = (cols[None, :] >> np.arange(32)[:, None]) & 1  # (32, 8)
        m[:, 8 * p : 8 * p + 8] = bits.astype(np.uint8)
        p_op = _mat_mat(_A1, p_op)
    return m


def _crc32c_batch_jit():
    """Build the jitted device path lazily (jax import stays optional)."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.ops import gf8

    @jax.jit
    def fn(bitmat, data, const):
        # bitmatrix_matmul wants (k, n) columns: one block per column;
        # the WHOLE batch CRC is one dispatch — transpose, matmul, and the
        # byte->u32 recombination all inside the jit
        out_bytes = gf8.bitmatrix_matmul(bitmat, data.T)   # (4, N)
        crcs = (
            out_bytes[0].astype(jnp.uint32)
            | (out_bytes[1].astype(jnp.uint32) << 8)
            | (out_bytes[2].astype(jnp.uint32) << 16)
            | (out_bytes[3].astype(jnp.uint32) << 24)
        )
        return crcs ^ const

    return fn


_batch_jit = None


@functools.lru_cache(maxsize=16)
def _message_bitmat_dev(block: int):
    """Device-resident copy of the message matrix, cached per block size —
    re-uploading ~1 MiB per call would defeat the one-dispatch hot path.
    It stays a jit ARGUMENT (never a closure constant; axon constraint)."""
    import jax.numpy as jnp

    return jnp.asarray(_message_bitmat(block))


def crc32c_batch(data, seed: int = 0xFFFFFFFF):
    """(N, B) uint8 blocks -> (N,) uint32 CRCs, computed on device.

    Equivalent to [ceph_crc32c(seed, row) for row in data], as one MXU
    matmul (linearity: update(seed, m) = L(m) ^ update(seed, 0^B)).
    """
    import jax.numpy as jnp

    from ceph_tpu.utils.perf import KERNELS

    global _batch_jit
    if _batch_jit is None:
        _batch_jit = _crc32c_batch_jit()
    data = jnp.asarray(data)
    n, block = data.shape
    KERNELS.inc("crc32c_batch_calls")
    KERNELS.inc("crc32c_batch_bytes", int(n) * int(block))
    bitmat = _message_bitmat_dev(block)
    const = np.uint32(crc32c_zeros(seed, block))
    return _batch_jit(bitmat, data, const)


def _matvec_rows(m: np.ndarray, v: np.ndarray) -> np.ndarray:
    """The GF(2) 32x32 operator applied to a VECTOR of crc words
    (the _mat_vec loop vectorized across rows)."""
    bits = (v[:, None] >> np.arange(32, dtype=np.uint32)[None, :]) & 1
    sel = np.where(bits.astype(bool), m[None, :], 0)
    return np.bitwise_xor.reduce(sel, axis=1).astype(np.uint32)


def _fold_blocks(cs2d: np.ndarray, lane: int) -> np.ndarray:
    """(R, nb) per-block crcs (each seeded 0) -> (R,) ``update(0, row)``
    via a pairwise zero-extension tree: log2(nb) vectorized rounds
    instead of nb sequential folds.  Left-padding with zero crcs is the
    identity (leading zero bytes of a zero-seeded crc stay zero)."""
    r, nb = cs2d.shape
    pow2 = 1 << max(0, nb - 1).bit_length() if nb > 1 else 1
    if pow2 != nb:
        cs2d = np.concatenate(
            [np.zeros((r, pow2 - nb), np.uint32), cs2d], axis=1)
        nb = pow2
    span = 1
    while nb > 1:
        ext = _zeros_mat(lane * span)
        left = np.ascontiguousarray(cs2d[:, 0::2]).reshape(-1)
        right = np.ascontiguousarray(cs2d[:, 1::2]).reshape(-1)
        cs2d = (_matvec_rows(ext, left) ^ right).reshape(r, nb // 2)
        nb //= 2
        span *= 2
    return cs2d[:, 0]


_HOST_LANE = 512


def _block_crcs_host(arr: np.ndarray, lane: int) -> np.ndarray:
    """(R, L) rows -> (R, L/lane) zero-seeded per-block crcs with the
    table loop vectorized across EVERY block of every row: the python
    iteration count is the lane length, amortized over the whole batch
    (the CPU-backend stand-in for the device crc32c_batch matmul)."""
    r, length = arr.shape
    nb = length // lane
    bt = np.ascontiguousarray(arr.reshape(r * nb, lane).T)
    cs = np.zeros(r * nb, dtype=np.uint32)
    for i in range(lane):
        cs = CRC_TABLE[(cs ^ bt[i]) & np.uint32(0xFF)] ^ \
            (cs >> np.uint32(8))
    return cs.reshape(r, nb)


def crc32c_rows(rows, seed: int = 0xFFFFFFFF, block: int = 4096):
    """(R, L) uint8 rows -> list of R ``ceph_crc32c(seed, row)`` values,
    the bulk byte work batched across the whole row set.

    Device backends: rows are cut into fixed ``block`` columns and every
    block of every row rides ONE ``crc32c_batch`` matmul — the coalesced
    EC write path's "one crc32c batch per tick".  CPU backends skip the
    device hop (XLA:CPU emulates the GF(2) bit-matmul far below memory
    bandwidth — BENCH_NOTES round 11) and run the lane-vectorized host
    table loop over the same whole-batch block set.  Either way the
    per-block crcs fold per row with the zero-extension operator tree —
    linearity: ``update(s, a||b) = A^len(b)(update(s, a)) ^
    update(0, b)``.  Row lengths not divisible by the block fall back to
    the per-row host path.
    """
    arr = np.asarray(rows, dtype=np.uint8)
    if arr.ndim != 2:
        raise ValueError("rows must be 2-D")
    r, length = arr.shape
    if r == 0:
        return []
    if _gcrc is not None:
        # hardware crc: the per-row C pass beats any batching scheme
        return [crc32c(seed, row.tobytes()) for row in arr]
    import jax

    host = jax.default_backend() == "cpu"
    lane = _HOST_LANE if host else block
    if length == 0 or length % lane:
        return [crc32c(seed, row.tobytes()) for row in arr]
    if host:
        cs = _block_crcs_host(arr, lane)
    else:
        nb = length // lane
        cs = np.asarray(crc32c_batch(arr.reshape(r * nb, lane),
                                     seed=0)).reshape(r, nb)
    folded = _fold_blocks(cs, lane)
    # update(seed, row) = update(seed, 0^L) ^ update(0, row)
    head = np.uint32(crc32c_zeros(seed, length))
    return [int(c) for c in (folded ^ head)]


# ---------------------------------------------------------------------------
# Planar row view (round 19): CRC the BYTE stream of packed bit-planes
# without materializing it
# ---------------------------------------------------------------------------
#
# An at-rest planar shard (ec/planar_store.py) is its (8, cols) packed
# bit-plane matrix; its logical byte stream D (length M = 8*cols) never
# exists on the steady-state path.  CRC is GF(2)-linear in the message
# bits, and D = XOR_t S_t where S_t is the M-byte "spread" of plane t
# (S_t[8i+u] = bit t of D[8i+u], placed at bit position t), so
#
#   update(seed, D) = XOR_t update(0, S_t) ^ update(seed, 0^M)
#
# (the 8 linear-part constants cancel pairwise — 8 is even).  hinfo CRCs
# of planar shards therefore stay bit-identical to the byte anchor.

# cap on the full-length planar message matrix a device dispatch will
# build ((32, 8*M) uint8); past it the host spread path takes over
_PLANAR_DEV_MAX = 1 << 15


@functools.lru_cache(maxsize=16)
def _planar_message_bitmat_dev(length: int):
    """Device copy of ``_message_bitmat(length)`` column-permuted so it
    applies directly to a plane-group BLOB (8 rows of length/8 packed
    bytes, row-major): blob bit 8*(t*cols+i)+u is D-bit 8*(8i+u)+t."""
    import jax.numpy as jnp

    cols = length // 8
    base = _message_bitmat(length)
    t, i, u = np.meshgrid(np.arange(8), np.arange(cols), np.arange(8),
                          indexing="ij")
    src = (8 * (8 * i + u) + t).reshape(-1)
    return jnp.asarray(base[:, src])


def _planar_spread(planes: np.ndarray) -> np.ndarray:
    """(g8, cols) packed planes -> (g8, 8*cols) spread byte streams S_t
    (row 8g+t spreads plane t of group g)."""
    bits = np.unpackbits(planes, axis=1, bitorder="little")
    shifts = (np.arange(planes.shape[0], dtype=np.uint8) % 8)[:, None]
    return (bits << shifts).astype(np.uint8)


def crc32c_planar_rows(planes, seed: int = 0xFFFFFFFF):
    """(G*8, cols) packed bit-planes -> list of G ``ceph_crc32c(seed,
    byte_view)`` values, one per 8-row plane group, WITHOUT building the
    byte view.

    Rows come in eights (group g = rows 8g..8g+7 = one shard's at-rest
    planes, ec/planar_store.py layout).  Device backends run ONE
    ``crc32c_batch``-style matmul over the raw plane blobs with a
    column-permuted message matrix; host backends CRC the 8 spread
    streams per group through ``crc32c_rows`` and XOR-fold.  Both are
    bit-identical to ``crc32c(seed, planes_to_shard(group))``.
    """
    from ceph_tpu.utils.perf import KERNELS

    arr = np.ascontiguousarray(planes, dtype=np.uint8)
    if arr.ndim != 2 or arr.shape[0] % 8:
        raise ValueError("planes must be (G*8, cols)")
    g8, cols = arr.shape
    g = g8 // 8
    if g == 0:
        return []
    length = 8 * cols
    KERNELS.inc("crc32c_planar_calls")
    KERNELS.inc("crc32c_planar_bytes", g * length)
    if length == 0:
        return [crc32c(seed, b"")] * g
    if _gcrc is None and length <= _PLANAR_DEV_MAX:
        import jax

        if jax.default_backend() != "cpu":
            # one matmul over the at-rest blobs: no spread, no byte view
            global _batch_jit
            if _batch_jit is None:
                _batch_jit = _crc32c_batch_jit()
            import jax.numpy as jnp

            bitmat = _planar_message_bitmat_dev(length)
            const = np.uint32(crc32c_zeros(seed, length))
            blobs = jnp.asarray(arr.reshape(g, length))
            return [int(c) for c in np.asarray(
                _batch_jit(bitmat, blobs, const))]
    parts = np.asarray(crc32c_rows(_planar_spread(arr), seed=0),
                       dtype=np.uint32).reshape(g, 8)
    folded = np.bitwise_xor.reduce(parts, axis=1)
    head = np.uint32(crc32c_zeros(seed, length))
    return [int(c) for c in (folded ^ head)]
