"""SloppyCRCMap: best-effort per-extent write-path CRC tracking.

Behavioral mirror of reference src/common/SloppyCRCMap.{h,cc}: record a
crc32c per fixed-size block as writes happen, invalidate partially
overwritten blocks, and compare a read against the recorded CRCs to
catch bit-rot between write and read (the FileStore integrity option).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ceph_tpu.ops.crc32c import crc32c


class SloppyCRCMap:
    def __init__(self, block_size: int = 65536):
        self.block_size = block_size
        self.crc: Dict[int, int] = {}     # block index -> crc32c

    def write(self, offset: int, data: bytes) -> None:
        bs = self.block_size
        pos = offset
        end = offset + len(data)
        while pos < end:
            b = pos // bs
            bstart = b * bs
            if pos == bstart and end >= bstart + bs:
                # full block: record its crc
                chunk = data[pos - offset: pos - offset + bs]
                self.crc[b] = crc32c(0xFFFFFFFF, chunk)
                pos = bstart + bs
            else:
                # partial overwrite: the stored crc no longer applies
                self.crc.pop(b, None)
                pos = min(end, bstart + bs)

    def read(self, offset: int, data: bytes) -> List[Tuple[int, int, int]]:
        """Verify a read against recorded CRCs; returns mismatches as
        (block, expected, got) triples (reference read(...) conflict
        reporting)."""
        bs = self.block_size
        out = []
        pos = offset
        end = offset + len(data)
        while pos < end:
            b = pos // bs
            bstart = b * bs
            if pos == bstart and end >= bstart + bs and b in self.crc:
                got = crc32c(0xFFFFFFFF,
                             data[pos - offset: pos - offset + bs])
                if got != self.crc[b]:
                    out.append((b, self.crc[b], got))
            pos = min(end, bstart + bs)
        return out

    def truncate(self, size: int) -> None:
        last = size // self.block_size
        for b in [b for b in self.crc if b >= last]:
            del self.crc[b]
