"""rjenkins1 hashing — the hash under every CRUSH decision.

Behavioral mirror of reference src/crush/hash.c: the crush_hashmix 9-line
mix (hash.c:12-22), seed 1315423911 (:24), and the 1/2/3/4/5-ary variants
(:26-90).  Written over generic uint32 array ops so the same code runs on
numpy (host/scalar oracle) and jax.numpy (vectorized device path) — every
op is add/sub/xor/shift, which the VPU vectorizes trivially.
"""

from __future__ import annotations

import numpy as np

CRUSH_HASH_SEED = 1315423911
M32 = np.uint32(0xFFFFFFFF)  # typed: large literals overflow jnp's int32 parse


def _u(v):
    """Coerce plain Python ints to np.uint64 so the masked ops wrap
    correctly under NEP-50 numpy scalar semantics; arrays pass through."""
    return np.uint64(v) if isinstance(v, int) else v
CRUSH_HASH_RJENKINS1 = 0


def _mix(a, b, c):
    """One crush_hashmix round; args and results are uint32 arrays."""
    with np.errstate(over="ignore"):
        return _mix_body(a, b, c)


def _mix_body(a, b, c):
    a = (a - b) & M32
    a = (a - c) & M32
    a = a ^ (c >> 13)
    b = (b - c) & M32
    b = (b - a) & M32
    b = b ^ ((a << 8) & M32)
    c = (c - a) & M32
    c = (c - b) & M32
    c = c ^ (b >> 13)
    a = (a - b) & M32
    a = (a - c) & M32
    a = a ^ (c >> 12)
    b = (b - c) & M32
    b = (b - a) & M32
    b = b ^ ((a << 16) & M32)
    c = (c - a) & M32
    c = (c - b) & M32
    c = c ^ (b >> 5)
    a = (a - b) & M32
    a = (a - c) & M32
    a = a ^ (c >> 3)
    b = (b - c) & M32
    b = (b - a) & M32
    b = b ^ ((a << 10) & M32)
    c = (c - a) & M32
    c = (c - b) & M32
    c = c ^ (b >> 15)
    return a, b, c


_X = 231232
_Y = 1232


def hash1(a):
    a = _u(a)
    h = (CRUSH_HASH_SEED ^ a) & M32
    b = a
    x, y = _X, _Y
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


def hash2(a, b):
    a = _u(a)
    b = _u(b)
    h = (CRUSH_HASH_SEED ^ a ^ b) & M32
    x, y = _X, _Y
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def hash3(a, b, c):
    a = _u(a)
    b = _u(b)
    c = _u(c)
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c) & M32
    x, y = _X, _Y
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def hash4(a, b, c, d):
    a = _u(a)
    b = _u(b)
    c = _u(c)
    d = _u(d)
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d) & M32
    x, y = _X, _Y
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


def hash5(a, b, c, d, e):
    a = _u(a)
    b = _u(b)
    c = _u(c)
    d = _u(d)
    e = _u(e)
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d ^ e) & M32
    x, y = _X, _Y
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h


def str_hash_rjenkins(data: bytes) -> int:
    """ceph_str_hash_rjenkins (reference src/common/ceph_hash.cc:21-78):
    the object-name hash feeding pg selection."""
    with np.errstate(over="ignore"):
        a = np.uint64(0x9E3779B9)
        b = np.uint64(0x9E3779B9)
        c = np.uint64(0)
        k = 0
        length = len(data)
        left = length
        while left >= 12:
            a = (a + np.uint64(int.from_bytes(data[k : k + 4], "little"))) & M32
            b = (b + np.uint64(int.from_bytes(data[k + 4 : k + 8], "little"))) & M32
            c = (c + np.uint64(int.from_bytes(data[k + 8 : k + 12], "little"))) & M32
            a, b, c = _mix(a, b, c)
            k += 12
            left -= 12
        c = (c + np.uint64(length)) & M32
        tail = data[k:]
        t = tail + bytes(12 - len(tail))
        if left >= 9:
            c = (c + np.uint64(int.from_bytes(t[8:11], "little") << 8)) & M32
        if left >= 5:
            b = (b + np.uint64(int.from_bytes(t[4:8], "little")
                               & (0xFFFFFFFF >> (8 * (8 - min(left, 8)))))) & M32
        if left >= 1:
            a = (a + np.uint64(int.from_bytes(t[0:4], "little")
                               & (0xFFFFFFFF >> (8 * (4 - min(left, 4)))))) & M32
        a, b, c = _mix(a, b, c)
        return int(c)
