"""Kernel substrate: batched TPU primitives underlying the framework."""

from ceph_tpu.ops import gf8  # noqa: F401
