"""GF(2^w) arithmetic for w in {8, 16, 32} + w-bit-word device packing.

Behavioral reference: gf-complete's default fields (galois_init_default_field
— reference jerasure_init.cc:27-37 selects them), used by jerasure's matrix
codes for w in {8, 16, 32}.  Polynomials are gf-complete's defaults:

    w=8  : x^8  + x^4  + x^3 + x^2 + 1          (0x11d)
    w=16 : x^16 + x^12 + x^3 + x   + 1          (0x1100b)
    w=32 : x^32 + x^22 + x^2 + x   + 1          (0x100400007)

TPU-first design: identical to the w=8 path (ceph_tpu.ops.gf8) — multiply
by a constant ``a`` is GF(2)-linear, so each matrix entry expands to a
(w, w) bit-matrix and the whole encode/decode becomes ONE GF(2) matmul on
the MXU.  Only the *word* granularity changes: chunks are sequences of
little-endian w-bit words, so bit-row t of word-lane layout comes from
byte t//8, bit t%8.  Host-side helpers (matrix build/invert) are scalar
Python ints — they touch k x m entries, never data.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


class GFW:
    """Scalar GF(2^w) arithmetic over Python ints (host-side, tiny)."""

    POLY = {8: 0x11D, 16: 0x1100B, 32: 0x100400007}

    def __init__(self, w: int):
        if w not in self.POLY:
            raise ValueError(f"unsupported w={w}")
        self.w = w
        self.poly = self.POLY[w]
        self.mask = (1 << w) - 1

    def mul(self, a: int, b: int) -> int:
        """Carryless multiply mod the field polynomial."""
        a &= self.mask
        b &= self.mask
        r = 0
        while b:
            if b & 1:
                r ^= a
            b >>= 1
            a <<= 1
            if a >> self.w:
                a ^= self.poly
        return r

    def pow(self, a: int, n: int) -> int:
        r = 1
        a &= self.mask
        while n:
            if n & 1:
                r = self.mul(r, a)
            a = self.mul(a, a)
            n >>= 1
        return r

    def inv(self, a: int) -> int:
        if a == 0:
            raise ZeroDivisionError("gf inv(0)")
        return self.pow(a, (1 << self.w) - 2)

    def div(self, a: int, b: int) -> int:
        return self.mul(a, self.inv(b))

    def bitmat(self, a: int) -> np.ndarray:
        """(w, w) GF(2) matrix of multiply-by-a, LSB-first:
        out[t, u] = bit t of a * 2^u."""
        w = self.w
        out = np.zeros((w, w), dtype=np.uint8)
        for u in range(w):
            col = self.mul(a, 1 << u)
            for t in range(w):
                out[t, u] = (col >> t) & 1
        return out


@functools.lru_cache(maxsize=8)
def field(w: int) -> GFW:
    return GFW(w)


def expand_bitmatrix_w(mat: np.ndarray, w: int) -> np.ndarray:
    """Expand an (r, k) word matrix into its (rw, kw) GF(2) bit-matrix
    (generalizes gf8.expand_bitmatrix; same semantics as jerasure's
    jerasure_matrix_to_bitmatrix for any w)."""
    gf = field(w)
    mat = np.asarray(mat, dtype=np.uint64)
    r, k = mat.shape
    out = np.zeros((r * w, k * w), dtype=np.uint8)
    for i in range(r):
        for j in range(k):
            out[i * w:(i + 1) * w, j * w:(j + 1) * w] = gf.bitmat(int(mat[i, j]))
    return out


def gfw_invert_matrix(a: np.ndarray, w: int) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^w); scalar host math (k x k words).
    Equivalent of ISA-L gf_invert_matrix / jerasure invert_matrix for the
    wide fields."""
    gf = field(w)
    a = [[int(x) for x in row] for row in np.asarray(a, dtype=np.uint64)]
    n = len(a)
    inv = [[1 if i == j else 0 for j in range(n)] for i in range(n)]
    for col in range(n):
        pivot = next((r for r in range(col, n) if a[r][col]), None)
        if pivot is None:
            raise ValueError(f"singular at column {col}")
        if pivot != col:
            a[col], a[pivot] = a[pivot], a[col]
            inv[col], inv[pivot] = inv[pivot], inv[col]
        scale = gf.inv(a[col][col])
        a[col] = [gf.mul(x, scale) for x in a[col]]
        inv[col] = [gf.mul(x, scale) for x in inv[col]]
        for r in range(n):
            if r != col and a[r][col]:
                f = a[r][col]
                a[r] = [x ^ gf.mul(f, y) for x, y in zip(a[r], a[col])]
                inv[r] = [x ^ gf.mul(f, y) for x, y in zip(inv[r], inv[col])]
    return np.array(inv, dtype=np.uint64)


def gf2_invert_matrix(a: np.ndarray) -> np.ndarray:
    """Invert a 0/1 matrix over GF(2) (numpy, host).  Used to build decode
    matrices for the native bit-matrix codes (liberation family), the same
    solve jerasure performs on the bit-matrix itself."""
    a = np.array(a, dtype=np.uint8) & 1
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError("square matrix required")
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        rows = np.nonzero(a[col:, col])[0]
        if rows.size == 0:
            raise ValueError(f"singular at column {col}")
        pivot = col + int(rows[0])
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        elim = np.nonzero(a[:, col])[0]
        for r in elim:
            if r != col:
                a[r] ^= a[col]
                inv[r] ^= inv[col]
    return inv


# ---------------------------------------------------------------------------
# Device packing for w-bit little-endian words
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=1)
def unpack_bits_w(data, word_bytes: int):
    """(k, n) uint8 -> (k*w, n/word_bytes) int8 of {0,1}.

    Bit t of word lane = bit t%8 of byte t//8 (little-endian words, the
    layout galois_wNN_region_multiply sees on x86)."""
    k, n = data.shape
    nw = n // word_bytes
    words = data.reshape(k, nw, word_bytes)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (words[:, :, :, None] >> shifts) & jnp.uint8(1)   # (k, nw, wb, 8)
    w = word_bytes * 8
    return (
        bits.reshape(k, nw, w).transpose(0, 2, 1).reshape(k * w, nw)
        .astype(jnp.int8)
    )


@functools.partial(jax.jit, static_argnums=1)
def pack_bits_w(bits, word_bytes: int):
    """(r*w, nw) {0,1} -> (r, nw*word_bytes) uint8 (inverse of
    unpack_bits_w)."""
    w = word_bytes * 8
    rw, nw = bits.shape
    r = rw // w
    b = bits.reshape(r, word_bytes, 8, nw).astype(jnp.int32)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, None, :, None]
    by = jnp.sum(b * weights, axis=2)                         # (r, wb, nw)
    return by.transpose(0, 2, 1).reshape(r, nw * word_bytes).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=2)
def bitmatrix_matmul_w(bitmat, data, word_bytes: int):
    """Device GF matmul over w-bit words: ONE MXU int8 matmul.

    bitmat: (rw, kw) {0,1}; data: (k, n) uint8 of k chunks; returns (r, n).
    """
    d_bits = unpack_bits_w(data, word_bytes)
    acc = jax.lax.dot_general(
        bitmat.astype(jnp.int8), d_bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return pack_bits_w(acc & 1, word_bytes)


# ---------------------------------------------------------------------------
# Bit-planar layout for w-bit words (round 6; see gf8.py for the w=8 story)
# ---------------------------------------------------------------------------
#
# A shard row of L bytes = L/(w/8) little-endian w-bit words is stored as w
# PACKED bit-planes of L/w bytes each: plane t, packed byte i holds bit t of
# words 8i..8i+7 (word 8i+u at bit u), where bit t of a word is bit t%8 of
# byte t//8.  Rows are chunk-major (plane row j*w + t), matching
# expand_bitmatrix_w's row blocks, so gf8.planar_matmul serves every width —
# the operand is just bit-rows x packed columns.  Total bytes equal the byte
# layout for every w.


@functools.partial(jax.jit, static_argnums=1)
def bytes_to_planar_w(data, w: int):
    """(c, L) uint8 -> (c*w, L/w) packed bit-planes of w-bit words."""
    c, l = data.shape
    wb = w // 8
    npk = l // w                    # packed bytes per plane (= words/8)
    words = data.reshape(c, npk, 8, wb)                      # (c, i, u, byte)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (words[..., None] >> shifts) & jnp.uint8(1)       # (c,i,u,byte,bit)
    bits = bits.reshape(c, npk, 8, w)                        # t = byte*8+bit
    weights = (1 << jnp.arange(8, dtype=jnp.int32))          # weight by u
    planes = jnp.sum(bits.astype(jnp.int32) * weights[None, None, :, None],
                     axis=2)                                 # (c, i, t)
    return planes.transpose(0, 2, 1).reshape(c * w, npk).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=1)
def planar_to_bytes_w(planes, w: int):
    """(c*w, npk) packed bit-planes -> (c, npk*w) bytes (inverse)."""
    cw, npk = planes.shape
    c = cw // w
    wb = w // 8
    p = planes.reshape(c, w, npk)                            # (c, t, i)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (p[..., None] >> shifts) & jnp.uint8(1)           # (c, t, i, u)
    bits = bits.reshape(c, wb, 8, npk, 8)                    # (c,byte,bit,i,u)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))          # weight by bit
    by = jnp.sum(bits.astype(jnp.int32) *
                 weights[None, None, :, None, None], axis=2)  # (c,byte,i,u)
    return by.transpose(0, 2, 3, 1).reshape(c, npk * 8 * wb) \
        .astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=2)
def encode_batch_w(bitmat, data, word_bytes: int):
    """(B, k, S) -> (B, r, S) through the word-generalized matmul."""
    b, k, s = data.shape
    cols = data.transpose(1, 0, 2).reshape(k, b * s)
    out = bitmatrix_matmul_w(bitmat, cols, word_bytes)
    r = out.shape[0]
    return out.reshape(r, b, s).transpose(1, 0, 2)
