"""GF(2^8) arithmetic as TPU tensor ops.

Behavioral reference: the Galois-field kernels the reference's erasure-code
plugins call into — gf-complete/jerasure ``galois_w08_region_multiply`` /
``jerasure_matrix_encode`` (see reference src/erasure-code/jerasure/
ErasureCodeJerasure.cc:156,164) and ISA-L ``gf_mul``/``gf_inv``/
``ec_encode_data`` (reference src/erasure-code/isa/ErasureCodeIsa.cc:128,
274-305).  Both libraries use GF(2^8) with the primitive polynomial
x^8+x^4+x^3+x^2+1 (0x11d), so one substrate serves every codec family.

TPU-first design
----------------
The hot operation is the "GF matmul": ``C[i, n] = XOR_j gfmul(M[i, j], D[j, n])``
over megabytes of ``D``.  CPU libraries do this with PSHUFB nibble tables
(ISA-L) or log/antilog lookups (jerasure).  Neither maps to the MXU.  Instead
we use the fact that multiplication by a *constant* ``a`` is GF(2)-linear:
there is an 8x8 bit-matrix ``B_a`` with ``bits(a*x) = B_a @ bits(x) (mod 2)``.
Expanding every byte of the coding matrix this way turns the whole encode into
ONE dense GF(2) matmul:

    (8m x 8k bit-matrix) @ (8k x N bit-expanded data)  ->  mod 2  ->  pack

which the MXU executes as an int8 matmul followed by a parity mask.  The same
path serves decode (with an inverted matrix) and the bit-matrix codes
(cauchy/liberation families) natively — they *are* GF(2) matmuls.

Host-side helpers (table construction, matrix inversion for decode) are plain
numpy: they touch k x k bytes, not data.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# x^8 + x^4 + x^3 + x^2 + 1 — the polynomial shared by gf-complete (octal 0435,
# jerasure galois.c) and ISA-L (erasure_code tables).
GF_POLY = 0x11D


def _build_tables():
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    exp[255:510] = exp[0:255]
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def _build_mul_table():
    a = np.arange(256)
    la = GF_LOG[a][:, None]
    lb = GF_LOG[a][None, :]
    prod = GF_EXP[(la + lb) % 255]
    prod[0, :] = 0
    prod[:, 0] = 0
    return prod.astype(np.uint8)


# Full 256x256 product table; 64 KiB, host-resident.
GF_MUL = _build_mul_table()


def gf_mul(a, b):
    """Elementwise GF(2^8) product (numpy, host)."""
    return GF_MUL[np.asarray(a, dtype=np.uint8), np.asarray(b, dtype=np.uint8)]


def gf_inv(a):
    """Multiplicative inverse; a must be nonzero."""
    a = np.asarray(a, dtype=np.uint8)
    if np.any(a == 0):
        raise ZeroDivisionError("gf_inv(0)")
    return GF_EXP[255 - GF_LOG[a]]


def gf_div(a, b):
    return gf_mul(a, gf_inv(b))


def gf_pow(a, n):
    """a**n in GF(2^8)."""
    a = int(a)
    n = int(n)
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] * n) % 255])


def gf_matmul_ref(m, d):
    """Reference bytewise GF matmul on host numpy: (r,k) @ (k,n) -> (r,n).

    out[i, n] = XOR_j gfmul(m[i, j], d[j, n]).  Used as the correctness oracle
    for the device path and for tiny host-side work.
    """
    m = np.asarray(m, dtype=np.uint8)
    d = np.asarray(d, dtype=np.uint8)
    prod = GF_MUL[m[:, :, None], d[None, :, :]]
    return np.bitwise_xor.reduce(prod, axis=1)


# ---------------------------------------------------------------------------
# Bit-matrix machinery
# ---------------------------------------------------------------------------

def _build_bitmat_table():
    """BITMAT[a] is the 8x8 GF(2) matrix of multiply-by-a, LSB-first.

    BITMAT[a][t, u] = bit t of gfmul(a, 1 << u).
    """
    a = np.arange(256, dtype=np.uint8)
    basis = (1 << np.arange(8)).astype(np.uint8)          # columns: a * 2^u
    prods = GF_MUL[a[:, None], basis[None, :]]            # (256, 8)
    bits = (prods[:, None, :] >> np.arange(8)[None, :, None]) & 1  # (256, t, u)
    return bits.astype(np.uint8)


GF_BITMAT = _build_bitmat_table()


def expand_bitmatrix(m):
    """Expand a byte matrix (r, k) into its (8r, 8k) GF(2) bit-matrix.

    Block (i, j) is the multiply-by-``m[i, j]`` matrix, so that
    ``bitmatrix @ bits(d) == bits(m @gf d)`` columnwise.  This is the same
    construction jerasure's ``jerasure_matrix_to_bitmatrix`` performs for the
    cauchy/liberation code families (reference ErasureCodeJerasure.cc:301).
    """
    m = np.asarray(m, dtype=np.uint8)
    r, k = m.shape
    blocks = GF_BITMAT[m]                                 # (r, k, 8, 8)
    return blocks.transpose(0, 2, 1, 3).reshape(r * 8, k * 8)


@jax.jit
def unpack_bits(data):
    """(k, n) uint8 -> (8k, n) int8 of {0,1}, LSB-first within each byte."""
    k, n = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (data[:, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    return bits.reshape(k * 8, n).astype(jnp.int8)


@jax.jit
def pack_bits(bits):
    """(8r, n) {0,1} -> (r, n) uint8, LSB-first."""
    r8, n = bits.shape
    b = bits.reshape(r8 // 8, 8, n).astype(jnp.int32)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
    return jnp.sum(b * weights, axis=1).astype(jnp.uint8)


@jax.jit
def bitmatrix_matmul(bitmat, data):
    """Device GF matmul via one MXU int8 matmul.

    bitmat: (8r, 8k) {0,1} (from expand_bitmatrix, or a native bit-matrix
            code's matrix).
    data:   (k, n) uint8 — k source chunks of n bytes.
    returns (r, n) uint8 — r output chunks.
    """
    d_bits = unpack_bits(data)
    acc = jax.lax.dot_general(
        bitmat.astype(jnp.int8), d_bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return pack_bits(acc & 1)


def gf_matmul(m, data):
    """Convenience: device GF matmul from a byte matrix (host expand + jit)."""
    from ceph_tpu.utils.perf import KERNELS

    bitmat = jnp.asarray(expand_bitmatrix(m))
    data = jnp.asarray(data)
    KERNELS.inc("gf8_matmul_calls")
    KERNELS.inc("gf8_matmul_bytes", int(np.prod(data.shape)))
    return bitmatrix_matmul(bitmat, data)


# ---------------------------------------------------------------------------
# Bit-planar layout (round 6): the internal device format for EC batches
# ---------------------------------------------------------------------------
#
# A shard row of L bytes is stored as 8 PACKED bit-planes: plane t, packed
# byte i holds bit t of source bytes 8i..8i+7, with byte 8i+u at bit u.
# Rows are chunk-major — plane row j*8+t is bit-plane t of chunk j — which
# matches expand_bitmatrix's row blocks, so the planar GF(2) matmul uses
# the SAME bit-matrix as the byte path, no permutation.  Total size equals
# the byte layout (L bytes per chunk), so keeping batches planar costs no
# HBM capacity; what it buys is that encode/decode between conversions is
# a pure matmul — the per-call 8x {0,1} expansion and re-pack that
# dominated the round-5 HBM traffic (BENCH_NOTES.md) happens at most once
# per client op, at the host boundary.


@jax.jit
def bytes_to_planar(data):
    """(c, L) uint8 bytes -> (8c, L/8) packed bit-planes, chunk-major rows.

    planar[j*8 + t, i] bit u  ==  bit t of data[j, 8i + u].
    """
    c, l = data.shape
    nb = l // 8
    d = data.reshape(c, nb, 8)                               # (c, i, u)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (d[:, None, :, :] >> shifts[None, :, None, None]) & jnp.uint8(1)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))          # weight by u
    planes = jnp.sum(bits.astype(jnp.int32) * weights[None, None, None, :],
                     axis=3)                                 # (c, t, i)
    return planes.reshape(c * 8, nb).astype(jnp.uint8)


@jax.jit
def planar_to_bytes(planes):
    """(8c, nb) packed bit-planes -> (c, 8*nb) bytes (bytes_to_planar^-1)."""
    c8, nb = planes.shape
    c = c8 // 8
    p = planes.reshape(c, 8, nb)                             # (c, t, i)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (p[:, :, :, None] >> shifts[None, None, None, :]) & jnp.uint8(1)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))          # weight by t
    by = jnp.sum(bits.astype(jnp.int32) * weights[None, :, None, None],
                 axis=1)                                     # (c, i, u)
    return by.reshape(c, nb * 8).astype(jnp.uint8)


@jax.jit
def planar_matmul_xla(bitmat, planes):
    """GF(2) matmul directly on packed bit-planes (XLA reference path).

    bitmat: (rw, kw) {0,1} bit-matrix (chunk-major blocks, any w).
    planes: (kw, nb) packed bit-planes; returns (rw, nb) packed planes.
    Bit-exact with the byte path: planar_to_bytes(out) ==
    pack_bits of bitmatrix_matmul on the corresponding byte data.
    """
    kw, nb = planes.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((planes[:, :, None] >> shifts[None, None, :]) & jnp.uint8(1))
    bits = bits.reshape(kw, nb * 8).astype(jnp.int8)
    acc = jax.lax.dot_general(
        bitmat.astype(jnp.int8), bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    a = (acc & 1).reshape(acc.shape[0], nb, 8)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))
    return jnp.sum(a * weights[None, None, :], axis=2).astype(jnp.uint8)


def planar_matmul(bitmat, planes):
    """Planar GF(2) matmul entry point: packed bit-planes in AND out.

    Routes to the fused, K-stacked Pallas kernel on real TPU backends
    (gf8_pallas.planar_matmul: block-diagonal matrix stacking feeds the
    MXU a >=128-wide K dimension and the {0,1} expansion lives in VMEM
    only) and to planar_matmul_xla elsewhere.  Both paths are bit-exact.
    Works for any word width w — the operand is bit-rows x packed
    columns, w only determines how the caller packed the planes.
    """
    from ceph_tpu.ops import gf8_pallas
    from ceph_tpu.ops.profiling import record_planar_matmul

    planes = jnp.asarray(planes)
    use_pallas = gf8_pallas.planar_available()
    record_planar_matmul(tuple(bitmat.shape), int(np.prod(planes.shape)),
                         gf8_pallas.stack_groups(int(bitmat.shape[1]))
                         if use_pallas else 1)
    if use_pallas:
        return gf8_pallas.planar_matmul(bitmat, planes)
    return planar_matmul_xla(jnp.asarray(bitmat), planes)


# ---------------------------------------------------------------------------
# Matrix inversion (decode-matrix construction; host, k x k bytes)
# ---------------------------------------------------------------------------

class SingularMatrixError(ValueError):
    pass


def gf_invert_matrix(a):
    """Gauss-Jordan inversion over GF(2^8).

    Behavioral equivalent of ISA-L's ``gf_invert_matrix`` used by the decode
    path (reference src/erasure-code/isa/ErasureCodeIsa.cc:274).  Raises
    SingularMatrixError when not invertible.
    """
    a = np.array(a, dtype=np.uint8, copy=True)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError("square matrix required")
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if a[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise SingularMatrixError(f"singular at column {col}")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        scale = gf_inv(a[col, col])
        a[col] = gf_mul(a[col], scale)
        inv[col] = gf_mul(inv[col], scale)
        for row in range(n):
            if row != col and a[row, col] != 0:
                factor = a[row, col]
                a[row] ^= gf_mul(factor, a[col])
                inv[row] ^= gf_mul(factor, inv[col])
    return inv
