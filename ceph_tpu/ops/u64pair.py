"""Exact 64-bit unsigned arithmetic as uint32 pairs for the TPU VPU.

The straw2 draw (reference mapper.c:322-367) needs 64-bit fixed-point log
values and an exact truncating 64/32-bit division.  TPUs are 32-bit-native
(s64 is emulated and slow, and f64 is unavailable), so the mapper carries
(hi, lo) uint32 pairs and divides via precomputed Granlund-Montgomery
reciprocals: with r = floor(2^64 / w) (a pack-time per-item constant),
q̂ = (n * r) >> 64 is within 1 of n // w and one remainder comparison
corrects it.  Everything here is add/sub/shift/mul16 — pure VPU ops.

All functions take and return uint32 arrays (numpy or jax.numpy alike).
"""

from __future__ import annotations

import numpy as np


M16 = 0xFFFF
M32 = np.uint32(0xFFFFFFFF)  # typed: large literals overflow jnp's int32 parse


def pair(hi, lo):
    return hi, lo


def add(a, b):
    """(a_hi, a_lo) + (b_hi, b_lo) mod 2^64."""
    lo = (a[1] + b[1]) & M32
    carry = (lo < a[1]).astype(lo.dtype) if hasattr(lo, "astype") else int(lo < a[1])
    hi = (a[0] + b[0] + carry) & M32
    return hi, lo


def sub(a, b):
    """(a - b) mod 2^64."""
    lo = (a[1] - b[1]) & M32
    borrow = (a[1] < b[1]).astype(lo.dtype) if hasattr(lo, "astype") else int(a[1] < b[1])
    hi = (a[0] - b[0] - borrow) & M32
    return hi, lo


def shr(a, n: int):
    """Logical right shift by a static 0 < n < 32."""
    lo = ((a[1] >> n) | (a[0] << (32 - n))) & M32
    hi = a[0] >> n
    return hi, lo


def lt(a, b):
    return (a[0] < b[0]) | ((a[0] == b[0]) & (a[1] < b[1]))


def ge(a, b):
    return ~lt(a, b)


def eq(a, b):
    return (a[0] == b[0]) & (a[1] == b[1])


def mul32(a, b):
    """u32 x u32 -> u64 pair, via 16-bit limbs (no 64-bit hardware mul)."""
    a0, a1 = a & M16, a >> 16
    b0, b1 = b & M16, b >> 16
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> 16) + (p01 & M16) + (p10 & M16)
    lo = ((p00 & M16) | ((mid & M16) << 16)) & M32
    hi = (p11 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)) & M32
    return hi, lo


def mulhi64(n, r):
    """((n_hi, n_lo) * (r_hi, r_lo)) >> 64, exact.

    Requires the true product's bit 128 overflow-free, which holds for any
    u64 inputs (product < 2^128); result is the high u64 pair.
    """
    n_hi, n_lo = n
    r_hi, r_lo = r
    h1, _l1 = mul32(n_lo, r_lo)
    h2, l2 = mul32(n_lo, r_hi)
    h3, l3 = mul32(n_hi, r_lo)
    h4, l4 = mul32(n_hi, r_hi)
    # bits 32..63 column: h1 + l2 + l3 -> carries into bits 64+
    m1 = (h1 + l2) & M32
    c1 = (m1 < l2).astype(m1.dtype) if hasattr(m1, "astype") else int(m1 < l2)
    m2 = (m1 + l3) & M32
    c2 = (m2 < l3).astype(m2.dtype) if hasattr(m2, "astype") else int(m2 < l3)
    carry_mid = c1 + c2
    # bits 64..95 column: h2 + h3 + l4 + carry_mid
    s1 = (h2 + h3) & M32
    k1 = (s1 < h3).astype(s1.dtype) if hasattr(s1, "astype") else int(s1 < h3)
    s2 = (s1 + l4) & M32
    k2 = (s2 < l4).astype(s2.dtype) if hasattr(s2, "astype") else int(s2 < l4)
    s3 = (s2 + carry_mid) & M32
    k3 = (s3 < carry_mid).astype(s3.dtype) if hasattr(s3, "astype") else int(s3 < carry_mid)
    out_lo = s3
    out_hi = (h4 + k1 + k2 + k3) & M32
    return out_hi, out_lo


def mul_u32(n, w):
    """(n_hi, n_lo) * w (u32), low 64 bits."""
    h, lo = mul32(n[1], w)
    hi = (h + n[0] * w) & M32
    return hi, lo


def div_by_recip(n, w, r_hi, r_lo):
    """Exact n // w given r = floor(2^64/w) as (r_hi, r_lo); w >= 1.

    For w == 1 the reciprocal overflows u64; callers pass r = 2^64-1 and the
    correction step still lands on the exact quotient because the estimate
    is n - 1 (or n) and a single increment is applied when rem >= w.
    """
    q_hi, q_lo = mulhi64(n, (r_hi, r_lo))
    prod = mul_u32((q_hi, q_lo), w)
    rem = sub(n, prod)
    fix = ge(rem, (rem[0] * 0, w))  # rem >= (0, w)
    inc = fix.astype(q_lo.dtype) if hasattr(fix, "astype") else int(fix)
    lo = (q_lo + inc) & M32
    carry = (lo < q_lo).astype(lo.dtype) if hasattr(lo, "astype") else int(lo < q_lo)
    hi = (q_hi + carry) & M32
    return hi, lo
