"""Honest device-kernel timing: the on-device scan + slope harness.

This is the BENCH_NOTES.md round-5 methodology as a library: on the axon
tunnel ``jax.block_until_ready`` returns on enqueue-ack, NOT device
completion, so any direct timing measures host/tunnel dispatch rate.
The only trustworthy figure is the SLOPE between two ``lax.scan``
programs chaining L1 and L2 iterations of the workload inside one
dispatch (each iteration feeding a cheap xor of its output back into
the next so nothing can be hoisted), completion forced by a one-element
host readback — the dispatch/readback floor cancels exactly.

``device_loop_slope`` is that harness; bench.py and ad-hoc profiling
both call it, and a ``tag`` records the honest per-step seconds into
the process-wide KERNELS registry (``t_<tag>`` time counters) so
``perf dump`` carries real device timings next to the invocation/byte
counters.
"""

from __future__ import annotations

import statistics
import time
from typing import Optional

from ceph_tpu.utils.perf import KERNELS


def record_planar_matmul(bitmat_shape, payload_bytes: int,
                         groups: int = 1) -> None:
    """Device-kernel telemetry for the bit-planar GF(2) matmul path.

    Counts invocations and payload bytes separately from the byte-path
    ``ec_matmul`` counters so a perf dump shows how much traffic rides the
    new layout, records the K-stacking factor, and accounts the MXU
    shape-padding waste of the STACKED matrix: a block-diagonal g-stack
    occupies (g*rw, g*kw) tiles of which only g*rw*kw entries are useful —
    the gap between that and the 128-multiple tile grid is throughput the
    shape still leaves on the floor (zero when g*kw == 128 exactly).
    """
    rw, kw = int(bitmat_shape[0]), int(bitmat_shape[1])
    KERNELS.inc("planar_matmul_calls")
    KERNELS.inc("planar_matmul_bytes", int(payload_bytes))
    KERNELS.inc("planar_stack_groups", int(groups))
    srw, skw = rw * groups, kw * groups
    tiles = (-(-srw // 128) * 128) * (-(-skw // 128) * 128)
    useful = groups * rw * kw
    if useful:
        KERNELS.inc("planar_mxu_pad_bytes",
                    int(payload_bytes * (tiles - useful) / useful))


def record_planar_convert(direction: str, payload_bytes: int) -> None:
    """Layout-conversion telemetry: ``direction`` is ``to_planar`` or
    ``to_bytes``.  The layout contract promises at most one conversion
    each way per client op — a perf dump where convert bytes rival
    planar_matmul bytes means the contract is being violated somewhere."""
    KERNELS.inc(f"planar_convert_{direction}_calls")
    KERNELS.inc(f"planar_convert_{direction}_bytes", int(payload_bytes))
    KERNELS.inc("planar_convert_bytes", int(payload_bytes))


def record_planar_at_rest(event: str, payload_bytes: int) -> None:
    """Planar AT-REST conversion telemetry (round 19).

    With ``osd_ec_planar_at_rest=1`` shards are stored as packed
    bit-planes, so layout conversions may happen ONLY at the sanctioned
    seams.  ``event`` names which seam booked the conversion:

    - ``ingest``:  client bytes -> planes at the coalesced encode (the
      one unavoidable conversion per write tick);
    - ``egress``:  planes -> logical client bytes at the read assemble
      (the one unavoidable conversion per read);
    - ``relayout``: a mixed-generation transition (byte-at-rest object
      met a planar write or vice versa after the config gate flipped) —
      legal but expected to be rare;
    - ``unseamed``: a byte view materialized OUTSIDE the seams (e.g. a
      raw ``store.read`` of a planar object).  The steady-state
      contract pins this counter to ZERO; tests assert it stays there
      across write/read/RMW/recovery/deep-scrub.
    """
    KERNELS.inc(f"ec_planar_{event}_conversions")
    KERNELS.inc(f"ec_planar_{event}_bytes", int(payload_bytes))


def device_loop_slope(step, feedback, data, repeats: int = 3,
                      L1: int = 300, L2: int = 1200,
                      tag: Optional[str] = None):
    """Seconds-per-step of ``step`` with the repeat loop ON DEVICE.

    Builds two jitted scan programs chaining L1 and L2 iterations —
    each iteration feeds its output back into the next via ``feedback``
    (a cheap xor, <2% of the workload) — and forces completion with a
    one-element readback.  The per-iteration time is the slope
    ``(t_L2 - t_L1) / (L2 - L1)``.  Returns (median, best, worst)
    across conservative pairings of the repeat samples; ``tag`` also
    tincs the median into KERNELS as ``t_<tag>``.

    Lint contract: graftlint's jax-hygiene rule treats the ``step`` and
    ``feedback`` callables passed to THIS FUNCTION (matched by the
    names ``device_loop_slope`` / ``_bench_device_loop``) as traced
    code and statically rejects host syncs inside them — the measured
    region's timing trust model (BENCH_NOTES.md).  Renaming this
    function requires updating analysis/jax_hygiene.py or coverage is
    silently lost.
    """
    import jax
    import numpy as np

    tinyfn = jax.jit(lambda d: jax.tree_util.tree_leaves(d)[0].ravel()[:1])

    def make(L):
        @jax.jit
        def loop(d0):
            def body(d, _):
                out = step(d)
                return feedback(d, out), ()

            d, _ = jax.lax.scan(body, d0, None, length=L)
            return d

        return loop

    loops = {L: make(L) for L in (L1, L2)}

    def run(L):
        np.asarray(tinyfn(loops[L](data)))

    ts = {}
    for L in (L1, L2):
        run(L)  # compile + warm
        samples = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            run(L)
            samples.append(time.perf_counter() - t0)
        ts[L] = samples
    dL = L2 - L1
    # clamp against timing noise driving a slope to <= 0 (a negative or
    # infinite rate must never become the number of record)
    med = max((statistics.median(ts[L2]) - statistics.median(ts[L1])) / dL,
              1e-12)
    best = max((min(ts[L2]) - max(ts[L1])) / dL, 1e-12)
    worst = max((max(ts[L2]) - min(ts[L1])) / dL, 1e-12)
    if tag is not None:
        KERNELS.tinc(f"t_{tag}", med)
    return med, best, worst
