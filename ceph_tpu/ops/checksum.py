"""Typed checksum framework (reference src/common/Checksummer.h:12-27).

Algorithms: crc32c, crc32c_16, crc32c_8 (truncations, seed -1), xxhash32,
xxhash64 — applied per csum_block over an extent, as BlueStore does for its
per-blob checksums (reference BlueStore.cc:3703-3709 selection, :10177+
verify-on-read).  crc32c blocks ride the TPU batch path when uniform.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ceph_tpu.ops import crc32c as _crc

XXH32_P1, XXH32_P2, XXH32_P3, XXH32_P4, XXH32_P5 = (
    2654435761, 2246822519, 3266489917, 668265263, 374761393)
XXH64_P1, XXH64_P2, XXH64_P3, XXH64_P4, XXH64_P5 = (
    11400714785074694791, 14029467366897019727, 1609587929392839161,
    9650029242287828579, 2870177450012600261)

M32 = np.uint32(0xFFFFFFFF)


def _rotl32(x, r):
    return ((x << np.uint32(r)) | (x >> np.uint32(32 - r))).astype(np.uint32)


def _rotl64(x, r):
    return ((x << np.uint64(r)) | (x >> np.uint64(64 - r))).astype(np.uint64)


def xxhash32(data: bytes, seed: int = 0) -> int:
    """XXH32 (single buffer, numpy-accelerated stripes)."""
    buf = np.frombuffer(memoryview(data), dtype=np.uint8)
    n = len(buf)
    seed = np.uint32(seed)
    with np.errstate(over="ignore"):
        return _xxh32_body(buf, n, seed)


def _xxh32_body(buf, n, seed):
    p = 0
    if n >= 16:
        v = [seed + np.uint32(XXH32_P1) + np.uint32(XXH32_P2),
             seed + np.uint32(XXH32_P2), seed, seed - np.uint32(XXH32_P1)]
        nstripe = n // 16
        lanes = buf[: nstripe * 16].view("<u4").reshape(nstripe, 4)
        for i in range(nstripe):
            for j in range(4):
                v[j] = _rotl32(v[j] + lanes[i, j] * np.uint32(XXH32_P2), 13) \
                    * np.uint32(XXH32_P1)
        h = (_rotl32(v[0], 1) + _rotl32(v[1], 7) + _rotl32(v[2], 12)
             + _rotl32(v[3], 18))
        p = nstripe * 16
    else:
        h = seed + np.uint32(XXH32_P5)
    h = (h + np.uint32(n)).astype(np.uint32)
    while p + 4 <= n:
        lane = buf[p : p + 4].view("<u4")[0]
        h = _rotl32(h + lane * np.uint32(XXH32_P3), 17) * np.uint32(XXH32_P4)
        p += 4
    while p < n:
        h = _rotl32(h + buf[p] * np.uint32(XXH32_P5), 11) * np.uint32(XXH32_P1)
        p += 1
    h ^= h >> np.uint32(15)
    h = h * np.uint32(XXH32_P2)
    h ^= h >> np.uint32(13)
    h = h * np.uint32(XXH32_P3)
    h ^= h >> np.uint32(16)
    return int(h)


def xxhash64(data: bytes, seed: int = 0) -> int:
    buf = np.frombuffer(memoryview(data), dtype=np.uint8)
    n = len(buf)
    with np.errstate(over="ignore"):
        seed = np.uint64(seed)
        p = 0
        if n >= 32:
            v = [seed + np.uint64(XXH64_P1) + np.uint64(XXH64_P2),
                 seed + np.uint64(XXH64_P2), seed, seed - np.uint64(XXH64_P1)]
            nstripe = n // 32
            lanes = buf[: nstripe * 32].view("<u8").reshape(nstripe, 4)
            for i in range(nstripe):
                for j in range(4):
                    v[j] = _rotl64(v[j] + lanes[i, j] * np.uint64(XXH64_P2), 31) \
                        * np.uint64(XXH64_P1)
            h = (_rotl64(v[0], 1) + _rotl64(v[1], 7) + _rotl64(v[2], 12)
                 + _rotl64(v[3], 18))
            for j in range(4):
                h = (h ^ _rotl64(v[j] * np.uint64(XXH64_P2), 31)
                     * np.uint64(XXH64_P1)) * np.uint64(XXH64_P1) \
                    + np.uint64(XXH64_P4)
            p = nstripe * 32
        else:
            h = seed + np.uint64(XXH64_P5)
        h = (h + np.uint64(n)).astype(np.uint64)
        while p + 8 <= n:
            k = buf[p : p + 8].view("<u8")[0]
            k = _rotl64(k * np.uint64(XXH64_P2), 31) * np.uint64(XXH64_P1)
            h = _rotl64(h ^ k, 27) * np.uint64(XXH64_P1) + np.uint64(XXH64_P4)
            p += 8
        if p + 4 <= n:
            k = np.uint64(buf[p : p + 4].view("<u4")[0])
            h = _rotl64(h ^ (k * np.uint64(XXH64_P1)), 23) \
                * np.uint64(XXH64_P2) + np.uint64(XXH64_P3)
            p += 4
        while p < n:
            h = _rotl64(h ^ (buf[p] * np.uint64(XXH64_P5)), 11) \
                * np.uint64(XXH64_P1)
            p += 1
        h ^= h >> np.uint64(33)
        h = h * np.uint64(XXH64_P2)
        h ^= h >> np.uint64(29)
        h = h * np.uint64(XXH64_P3)
        h ^= h >> np.uint64(32)
    return int(h)


class Checksummer:
    """Per-block checksum calculate/verify (reference Checksummer.h)."""

    CSUM_NONE = "none"
    ALGORITHMS = ("none", "crc32c", "crc32c_16", "crc32c_8",
                  "xxhash32", "xxhash64")
    VALUE_SIZE = {"none": 0, "crc32c": 4, "crc32c_16": 2, "crc32c_8": 1,
                  "xxhash32": 4, "xxhash64": 8}

    def __init__(self, algorithm: str = "crc32c"):
        if algorithm not in self.ALGORITHMS:
            raise ValueError(f"unknown csum algorithm {algorithm}")
        self.algorithm = algorithm

    def _one(self, block: bytes) -> int:
        a = self.algorithm
        if a == "crc32c":
            return _crc.crc32c(0xFFFFFFFF, block)
        if a == "crc32c_16":
            return _crc.crc32c(0xFFFFFFFF, block) & 0xFFFF
        if a == "crc32c_8":
            return _crc.crc32c(0xFFFFFFFF, block) & 0xFF
        if a == "xxhash32":
            return xxhash32(block)
        if a == "xxhash64":
            return xxhash64(block)
        return 0

    def calculate(self, csum_block_size: int, data: bytes) -> bytes:
        """Per-block checksum vector, little-endian packed."""
        assert len(data) % csum_block_size == 0
        vsize = self.VALUE_SIZE[self.algorithm]
        if vsize == 0:
            return b""
        n = len(data) // csum_block_size
        if self.algorithm.startswith("crc32c") and n >= 8:
            arr = np.frombuffer(memoryview(data), dtype=np.uint8).reshape(
                n, csum_block_size)
            vals = np.asarray(_crc.crc32c_batch(arr)).astype(np.uint64)
        else:
            vals = np.array(
                [self._one(data[i * csum_block_size : (i + 1) * csum_block_size])
                 for i in range(n)], dtype=np.uint64)
        out = np.zeros((n, vsize), dtype=np.uint8)
        for b in range(vsize):
            out[:, b] = (vals >> np.uint64(8 * b)).astype(np.uint8)
        return out.tobytes()

    def verify(self, csum_block_size: int, data: bytes,
               csum_data: bytes) -> Optional[int]:
        """Returns the byte offset of the first bad block, or None if OK
        (reference returns -1 offset convention via bad_csum)."""
        want = self.calculate(csum_block_size, data)
        vsize = self.VALUE_SIZE[self.algorithm]
        if vsize == 0:
            return None
        for i in range(len(want) // vsize):
            if want[i * vsize : (i + 1) * vsize] != \
                    csum_data[i * vsize : (i + 1) * vsize]:
                return i * csum_block_size
        return None
